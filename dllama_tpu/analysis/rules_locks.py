"""Static lock discipline (rules ``lock-order`` / ``lock-leaf`` /
``lock-unranked``) — the compile-time half of the utils/locks sanitizer.

Extraction, over every ``dllama_tpu/`` module:

1. **Lock identities.** Every ``locks.make_lock("name")`` /
   ``make_rlock("name")`` binding is collected — class attributes
   (``self._mu = locks.make_rlock("engine.pool")``), module globals, and
   dataclass ``field(default_factory=lambda: locks.make_lock(...))``.
   Aliases (``self._mu = pool._mu`` — the radix tree riding the pool's
   RLock) resolve by attribute name against the collected bindings;
   ambiguous names resolve only when every candidate agrees.
2. **Acquisitions.** ``with <lock>:`` statements, resolved to a lock name
   via the enclosing class, the module globals, or the alias table.
3. **Edges.** Inside a with-block holding L: a nested with acquiring M is
   an edge L->M; every call contributes edges L->X for each lock X the
   callee may (transitively) acquire. Callees resolve within the analyzed
   modules (same-class methods, same-module functions) plus a small
   builtin table for the observability surface (instrument mutations ->
   the metrics leaf, tracer emissions -> the tracer leaf, fault hooks,
   ``note_transfer``, ``LEDGER.scope``).

Verdicts: every edge must STRICTLY ascend ``utils/locks.LOCK_RANKS``
(same-lock re-entry is legal only for reentrant locks); any edge out of a
leaf lock (metrics/tracer) is ``lock-leaf`` — the scrape-path deadlock
shape; a name outside the rank table (or a ranked name no lock uses) is
``lock-unranked``. With all edges ascending, the graph is acyclic by
construction — the acceptance criterion's "static lock-order graph is
acyclic" falls out of the rank check.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from dllama_tpu.analysis.core import Diagnostic, dotted, str_arg
from dllama_tpu.utils.locks import LEAF_LOCKS, LOCK_RANKS

#: call-pattern knowledge for the observability surface the whole stack
#: leans on — attribute method names that mutate metric families (all
#: paths end in the family lock) and tracer emissions
_METRIC_METHODS = {"inc", "dec", "set", "observe", "observe_n", "labels",
                   "value", "render", "sample", "names"}
_TRACER_METHODS = {"span", "span_at", "event", "req_submit", "req_admitted",
                   "req_prefill_done", "req_first_token", "req_chunk",
                   "req_mark", "req_end", "export_chrome",
                   "requests_summary", "request_timeline", "stats"}


@dataclass
class _Func:
    """Per-function lock facts: direct with-acquisitions, callee names,
    and (lock, inner-thing) containment for edge building."""

    qual: str  # module:Class.fn
    rel: str
    acquires: set = field(default_factory=set)  # lock names w/ sites
    calls: list = field(default_factory=list)  # (callee key tuple, line)
    # (lockname, line_of_with, [inner items]) where inner items are
    # ("lock", name, line) or ("call", callee_keys, line)
    regions: list = field(default_factory=list)


def _binding_value_lockname(value: ast.AST) -> tuple[str, bool] | None:
    """(name, reentrant) when `value` constructs a named lock."""
    if isinstance(value, ast.Call):
        d = dotted(value.func)
        if d is not None:
            leaf = d.split(".")[-1]
            if leaf in ("make_lock", "make_rlock"):
                name = str_arg(value, 0)
                if name is not None:
                    return name, leaf == "make_rlock"
            if leaf == "field":  # dataclass field(default_factory=...)
                for kw in value.keywords:
                    if kw.arg == "default_factory":
                        v = kw.value
                        if isinstance(v, ast.Lambda):
                            return _binding_value_lockname(v.body)
    return None


def _collect_bindings(project):
    """class_attr[(rel, Class)][attr] = name; mod_global[rel][var] = name;
    attr_names[attr] = set of names (for alias resolution); reentrant
    lock names; alias requests [(rel, Class, attr, src_attr, line)]."""
    class_attr: dict = {}
    mod_global: dict = {}
    attr_names: dict = {}
    reentrant: set = set()
    aliases: list = []
    for src in project.py_sources("dllama_tpu/"):
        mod_global.setdefault(src.rel, {})
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                got = _binding_value_lockname(node.value)
                if got:
                    mod_global[src.rel][node.targets[0].id] = got[0]
                    if got[1]:
                        reentrant.add(got[0])
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            key = (src.rel, cls.name)
            for node in ast.walk(cls):
                tgt = None
                val = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    tgt, val = node.target, node.value
                if tgt is None:
                    continue
                attr = None
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    attr = tgt.attr
                elif isinstance(tgt, ast.Name):
                    attr = tgt.id  # dataclass field at class level
                if attr is None:
                    continue
                got = _binding_value_lockname(val)
                if got:
                    class_attr.setdefault(key, {})[attr] = got[0]
                    attr_names.setdefault(attr, set()).add(got[0])
                    if got[1]:
                        reentrant.add(got[0])
                elif (isinstance(val, ast.Attribute)
                      and attr not in class_attr.get(key, {})):
                    # alias: self.X = <expr>.Y — resolve Y later
                    aliases.append((key, attr, val.attr))
    for key, attr, src_attr in aliases:
        names = attr_names.get(src_attr, set())
        if len(names) == 1:
            class_attr.setdefault(key, {}).setdefault(attr, next(iter(names)))
            attr_names.setdefault(attr, set()).add(next(iter(names)))
    return class_attr, mod_global, attr_names, reentrant


def _external_acquires(call: ast.Call) -> set:
    """Locks a call into the observability surface may take (the builtin
    knowledge table — see module docstring)."""
    out: set = set()
    d = dotted(call.func)
    f = call.func
    if isinstance(f, ast.Attribute):
        base = dotted(f.value) or ""
        segs = base.split(".")
        last = segs[-1] if segs else ""
        if f.attr in _METRIC_METHODS and "at" not in segs:
            caps = last.isupper() and len(last) > 1
            if caps or segs[0] in ("ins", "metrics") or last == "REGISTRY":
                out.add("obs.metrics")
            elif f.attr in ("labels", "observe", "observe_n", "inc", "dec"):
                # family handles travel under local names too (e.g. the
                # time ledger's injected counter): .labels/.observe/.inc
                # are metrics-family verbs in this codebase
                out.add("obs.metrics")
        if f.attr in _TRACER_METHODS and (
                last in ("TRACER", "tr", "tracer")
                or base.endswith(".TRACER")):
            out.add("obs.tracer")
    if d is not None:
        leaf = d.split(".")[-1]
        if d in ("faults.fire", "faults.flag"):
            out |= {"faults.point", "obs.metrics", "obs.tracer"}
        if leaf == "note_transfer":
            out |= {"obs.transfers", "obs.metrics"}
        if leaf in ("scope", "ensure_listener") and "LEDGER" in d.upper():
            out.add("obs.ledger")
    return out


class _FuncVisitor(ast.NodeVisitor):
    """Build the _Func table for one module."""

    def __init__(self, src, class_attr, mod_global, attr_names, funcs):
        self.src = src
        self.class_attr = class_attr
        self.mod_global = mod_global
        self.attr_names = attr_names
        self.funcs = funcs
        self.cls_stack: list[str] = []
        self.fn_stack: list[_Func] = []
        self.lock_stack: list[tuple[str, int]] = []

    # ------------------------------------------------------- lock naming

    def _lockname(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Name):
            return self.mod_global.get(self.src.rel, {}).get(expr.id)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and self.cls_stack:
                key = (self.src.rel, self.cls_stack[-1])
                name = self.class_attr.get(key, {}).get(attr)
                if name:
                    return name
            # non-self receiver (fam._lock, f.lock, ledger._lock): resolve
            # by attribute name when every known binding agrees
            names = self.attr_names.get(attr, set())
            if len(names) == 1:
                return next(iter(names))
        return None

    # --------------------------------------------------------- structure

    def visit_ClassDef(self, node):
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _qual(self, name: str) -> str:
        cls = self.cls_stack[-1] if self.cls_stack else ""
        return f"{self.src.rel}:{cls}.{name}" if cls \
            else f"{self.src.rel}:{name}"

    def visit_FunctionDef(self, node):
        fn = _Func(self._qual(node.name), self.src.rel)
        self.funcs.setdefault(self.src.rel, {})
        self.funcs[self.src.rel][
            (self.cls_stack[-1] if self.cls_stack else "", node.name)] = fn
        self.fn_stack.append(fn)
        outer_locks = self.lock_stack
        self.lock_stack = []  # lexical holds don't cross function bounds
        self.generic_visit(node)
        self.lock_stack = outer_locks
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            name = self._lockname(item.context_expr)
            if name is not None and self.fn_stack:
                fn = self.fn_stack[-1]
                fn.acquires.add(name)
                line = getattr(item.context_expr, "lineno", node.lineno)
                for outer, _oline in self.lock_stack:
                    fn.regions.append((outer, _oline, ("lock", name, line)))
                # push per item so `with A, B:` records the A->B edge
                self.lock_stack.append((name, node.lineno))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            del self.lock_stack[-pushed:]

    def visit_Call(self, node):
        if self.fn_stack and self.lock_stack:
            fn = self.fn_stack[-1]
            keys = self._callee_keys(node)
            ext = _external_acquires(node)
            for outer, line in self.lock_stack:
                if keys or ext:
                    fn.regions.append((outer, line,
                                       ("call", keys, ext, node.lineno)))
        if self.fn_stack:
            self.fn_stack[-1].calls.append((self._callee_keys(node),
                                            _external_acquires(node)))
        self.generic_visit(node)

    def _callee_keys(self, call: ast.Call):
        """Possible (rel, class, name) resolutions inside the project."""
        f = call.func
        keys = []
        if isinstance(f, ast.Name):
            keys.append((self.src.rel, "", f.id))
        elif isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and self.cls_stack:
                keys.append((self.src.rel, self.cls_stack[-1], f.attr))
            else:
                keys.append((self.src.rel, "*", f.attr))  # same-module scan
        return keys


def _resolve(funcs, rel, cls, name):
    mod = funcs.get(rel, {})
    if cls == "*":
        # attribute call on a non-self receiver: only CLASS methods can
        # match — a module-level function is called by bare name, and
        # matching it here confuses builtin container methods (dict.clear)
        # with same-named module functions
        hits = [fn for (c, n), fn in mod.items() if n == name and c]
        return hits if len(hits) == 1 else []
    fn = mod.get((cls, name))
    if fn is not None:
        return [fn]
    if cls:  # fall back: method defined on another class in the module
        hits = [f for (c, n), f in mod.items() if n == name]
        return hits if len(hits) == 1 else []
    return []


def _may_acquire(funcs):
    """Transitive closure: function -> set of lock names it may take."""
    memo: dict = {}

    def go(fn, stack):
        if fn.qual in memo:
            return memo[fn.qual]
        if fn.qual in stack:
            return set()
        stack = stack | {fn.qual}
        out = set(fn.acquires)
        for keys, ext in fn.calls:
            out |= ext
            for rel, cls, name in keys:
                for callee in _resolve(funcs, rel, cls, name):
                    out |= go(callee, stack)
        memo[fn.qual] = out
        return out

    for mod in funcs.values():
        for fn in mod.values():
            go(fn, frozenset())
    return memo


def build_graph(project):
    """[(holder, acquired, rel, line)] — the static lock-order edges.
    Exposed for ``--lock-graph`` and the README's rank-table docs."""
    class_attr, mod_global, attr_names, reentrant = _collect_bindings(project)
    funcs: dict = {}
    for src in project.py_sources("dllama_tpu/"):
        _FuncVisitor(src, class_attr, mod_global, attr_names,
                     funcs).visit(src.tree)
    may = _may_acquire(funcs)
    edges = []
    seen = set()
    for mod in funcs.values():
        for fn in mod.values():
            for region in fn.regions:
                outer = region[0]
                inner = region[2] if len(region) == 3 else None
                if inner is None:
                    continue
                if inner[0] == "lock":
                    _, name, line = inner
                    key = (outer, name, fn.rel, line)
                    if key not in seen:
                        seen.add(key)
                        edges.append((outer, name, fn.rel, line))
                else:
                    _, keys, ext, line = inner
                    targets = set(ext)
                    for rel, cls, name in keys:
                        for callee in _resolve(funcs, rel, cls, name):
                            targets |= may.get(callee.qual, set())
                    for t in sorted(targets):
                        key = (outer, t, fn.rel, line)
                        if key not in seen:
                            seen.add(key)
                            edges.append((outer, t, fn.rel, line))
    return edges, reentrant, class_attr, mod_global


def check(project) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    edges, reentrant, class_attr, mod_global = build_graph(project)

    # unranked names at their construction site
    for src in project.py_sources("dllama_tpu/"):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                got = _binding_value_lockname(node)
                if got and got[0] not in LOCK_RANKS:
                    diags.append(Diagnostic(
                        src.rel, node.lineno, "lock-unranked",
                        f"lock name {got[0]!r} is not in "
                        "utils/locks.LOCK_RANKS — rank it (and the README "
                        "table) before using it"))
    used = {n for names in
            ([list(v.values()) for v in class_attr.values()]
             + [list(v.values()) for v in mod_global.values()])
            for n in names}
    locks_src = project.source("dllama_tpu/utils/locks.py")
    if locks_src is not None:
        for name in sorted(set(LOCK_RANKS) - used):
            line = next((i for i, ln in enumerate(locks_src.lines, 1)
                         if f'"{name}"' in ln), 1)
            diags.append(Diagnostic(
                "dllama_tpu/utils/locks.py", line, "lock-unranked",
                f"LOCK_RANKS entry {name!r} is bound by no "
                "make_lock/make_rlock site — stale rank rows hide real "
                "order bugs"))

    for holder, acquired, rel, line in edges:
        if holder not in LOCK_RANKS or acquired not in LOCK_RANKS:
            continue  # unranked already reported at the binding
        if holder == acquired:
            if acquired in reentrant:
                continue
            diags.append(Diagnostic(
                rel, line, "lock-order",
                f"re-acquisition of non-reentrant lock {acquired!r} while "
                "holding it — self-deadlock"))
            continue
        if holder in LEAF_LOCKS:
            diags.append(Diagnostic(
                rel, line, "lock-leaf",
                f"acquiring {acquired!r} while holding leaf lock "
                f"{holder!r} — the scrape-path deadlock shape; leaf locks "
                "(metrics registry, tracer) must do pure work only"))
        elif LOCK_RANKS[holder] >= LOCK_RANKS[acquired]:
            diags.append(Diagnostic(
                rel, line, "lock-order",
                f"lock-order inversion: {acquired!r} "
                f"(rank {LOCK_RANKS[acquired]}) acquired while holding "
                f"{holder!r} (rank {LOCK_RANKS[holder]}) — edges must "
                "strictly ascend utils/locks.LOCK_RANKS"))
    return diags
