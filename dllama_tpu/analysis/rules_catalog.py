"""Single-site catalog discipline (``catalog-*`` rules).

The observability stack's whole design is "one definition site per
catalog": every metric family in ``obs/instruments.py``, every span/event
name in ``obs/trace.{SPAN,EVENT}_CATALOG``, every fault point in
``utils/faults.POINTS``. scripts/checks.sh keeps the README tables synced
to those catalogs; these rules close the other half of the loop — CODE
that registers or emits outside the catalog fails at the callsite with a
real location (the grep gates this replaces could only say "something,
somewhere").
"""

from __future__ import annotations

import ast

from dllama_tpu.analysis.core import Diagnostic, dotted, str_arg
from dllama_tpu.obs.trace import EVENT_CATALOG, SPAN_CATALOG
from dllama_tpu.utils.faults import POINTS

#: the only modules allowed to create metric families (metrics.py defines
#: the registry helpers themselves)
METRIC_SITES = ("dllama_tpu/obs/instruments.py", "dllama_tpu/obs/metrics.py")

_FACTORIES = {"counter", "gauge", "histogram"}

#: receivers whose .span/.span_at/.event calls are tracer emissions
_TRACER_BASES = {"tr", "tracer", "TRACER"}


def _is_metric_factory(call: ast.Call) -> bool:
    d = dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    if parts[-1] not in _FACTORIES or len(parts) < 2:
        return False
    return parts[-2] in ("metrics", "REGISTRY") or parts[0] == "REGISTRY"


def _is_tracer_call(call: ast.Call, src_rel: str) -> str | None:
    """'span' | 'event' when the call is a tracer emission."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    kind = {"span": "span", "span_at": "span", "event": "event"}.get(f.attr)
    if kind is None:
        return None
    base = dotted(f.value)
    if base is None:
        return None
    last = base.split(".")[-1]
    if last in _TRACER_BASES:
        return kind
    if base == "self" and src_rel == "dllama_tpu/obs/trace.py":
        return kind  # the tracer's own catalog-named emissions
    return None


def check(project) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    for src in project.py_sources("dllama_tpu/"):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_metric_factory(node) and src.rel not in METRIC_SITES:
                name = str_arg(node, 0)
                diags.append(Diagnostic(
                    src.rel, node.lineno, "catalog-metric",
                    f"metric family {name or '<dynamic>'!r} created outside "
                    "obs/instruments.py — the catalog (and its README "
                    "drift check) is the single registration site"))
            kind = _is_tracer_call(node, src.rel)
            if kind is not None:
                name = str_arg(node, 0)
                if name is not None:
                    catalog = SPAN_CATALOG if kind == "span" \
                        else EVENT_CATALOG
                    if name not in catalog:
                        which = "SPAN_CATALOG" if kind == "span" \
                            else "EVENT_CATALOG"
                        diags.append(Diagnostic(
                            src.rel, node.lineno, f"catalog-{kind}",
                            f"{kind} name {name!r} is not in "
                            f"obs/trace.{which} — add the catalog row "
                            "(and its README entry) with the emit site"))
            d = dotted(node.func)
            if d in ("faults.fire", "faults.flag"):
                point = str_arg(node, 0)
                if point is not None and point not in POINTS:
                    diags.append(Diagnostic(
                        src.rel, node.lineno, "catalog-fault",
                        f"fault point {point!r} is not in "
                        "utils/faults.POINTS — an undeclared point can "
                        "never be armed, so the drill silently never "
                        "fires"))
    return diags
