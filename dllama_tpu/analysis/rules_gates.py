"""Repo-contract gates (``gate-*``) and doc drift (``doc-*``) — the
checks scripts/checks.sh used to enforce with greps, upgraded to real
``file:line`` diagnostics, plus the analyzer's own documentation loop.

checks.sh keeps only what genuinely needs a live import (the metric/span/
event/fault/ledger-state/compile-fn README syncs read the registry);
everything textual moved here:

* ``gate-routes`` — ``engine/kernel_select.PAGED_ROUTES`` and the README
  "Paged KV cache" routing table must match both directions (a route the
  docs don't name, or a doc row for a route kernel_select cannot
  resolve, is the operator-facing contract lying).
* ``gate-bench`` / ``gate-perfdiff`` / ``gate-aot`` — the hybrid/compile
  bench records, the perfdiff regression rules (stall/TTFT ratios, the
  zero-recompile/zero-upload ceilings), and the paged-kernel AOT
  inventory must keep existing: deleting any of them un-gates a shipped
  invariant silently.
* ``gate-scripts`` — the smoke entry points those gates cite stay
  present and executable.
* ``doc-rules`` / ``doc-ranks`` — the README rule-catalog table matches
  :data:`~dllama_tpu.analysis.core.RULE_CATALOG` and the README lock-rank
  table matches ``utils/locks.LOCK_RANKS``, both directions — the same
  discipline LEDGER_STATES already gets.
"""

from __future__ import annotations

import ast
import re

from dllama_tpu.analysis.core import RULE_CATALOG, Diagnostic
from dllama_tpu.utils.locks import LOCK_RANKS

_KSEL = "dllama_tpu/engine/kernel_select.py"

#: routes that must keep EXISTING (the old checks.sh loop pinned these by
#: name — a commit deleting a shipped route from both the tuple and the
#: README must still fail, not pass as "consistent")
REQUIRED_ROUTES = ("paged_kernel", "paged_gather")

#: perfdiff regression-rule keys whose deletion un-gates a shipped
#: invariant (ISSUE 12/13 acceptance surfaces)
PERFDIFF_KEYS = ("hybrid.stall_reduction_x", "hybrid.ttft_overhead_x",
                 "compile.steady.unexpected_compiles",
                 "compile.steady.upload_bytes",
                 "compile.warmup_ttft_ratio",
                 # ISSUE 15: the router's affinity warm-TTFT win and the
                 # 2-vs-1-replica scaling ratio stay gated
                 "router.affinity.warm_ttft_ratio_on_off",
                 "router.scale.agg_tok_s_ratio_2_1",
                 # ISSUE 17: the observability plane stays ~free on the
                 # proxy path and every merged replica stays clock-aligned
                 "fleet_obs.tok_s_ratio_on_off",
                 "fleet_obs.trace.unaligned_replicas",
                 # ISSUE 19: the acceptance pin — proxy overhead with the
                 # plane on vs off, ceiling 1.03x
                 "fleet_obs.proxy_overhead_x")

#: aot_check.py markers: the paged flash-decode op inventory + its fused-
#: scatter cases (ISSUE 8)
AOT_MARKERS = ("paged_decode_attention", "fused scatter")

#: bench records the perf gate rules read
BENCH_DEFS = ("bench_hybrid", "bench_compile", "bench_router",
              "bench_fleet_obs")

#: smoke scripts the gates cite (path, must-be-executable)
GATED_SCRIPTS = ("scripts/hybrid_smoke.sh", "scripts/compile_smoke.sh",
                 "scripts/analysis_smoke.sh", "scripts/router_smoke.sh",
                 "scripts/failover_smoke.sh", "scripts/chaos_soak.sh",
                 "scripts/fleet_smoke.sh")


def _line_of(src, needle: str, default: int = 1) -> int:
    for i, ln in enumerate(src.lines, 1):
        if needle in ln:
            return i
    return default


def _table_rows(src, header_prefix: str) -> list[tuple[int, str]]:
    """(line, id) rows of the first README table whose header row starts
    with `header_prefix` — same parse as checks.sh's ledger-state check."""
    rows, in_table = [], False
    for i, line in enumerate(src.lines, 1):
        if line.startswith(header_prefix):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            m = re.match(r"^\| `([a-zA-Z0-9_.-]+)` \|", line)
            if m:
                rows.append((i, m.group(1)))
    return rows


def _check_routes(project, diags):
    ksel = project.source(_KSEL)
    readme = project.source("README.md")
    if ksel is None or ksel.parse_error() is not None:
        return  # a broken file is reported once as parse-error
    routes: list[str] = []
    route_line = 1
    for node in ksel.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "PAGED_ROUTES":
            route_line = node.lineno
            if isinstance(node.value, (ast.Tuple, ast.List)):
                routes = [e.value for e in node.value.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
    if not routes:
        diags.append(Diagnostic(
            _KSEL, route_line, "gate-routes",
            "PAGED_ROUTES tuple missing — it is the single definition "
            "site of the paged attention routes"))
        return
    for r in REQUIRED_ROUTES:
        if r not in routes:
            diags.append(Diagnostic(
                _KSEL, route_line, "gate-routes",
                f"shipped route {r!r} missing from PAGED_ROUTES — "
                "kernel_select can no longer resolve it (ISSUE 8's "
                "serving contract)"))
    if readme is None:
        diags.append(Diagnostic(
            "README.md", 1, "gate-routes",
            "README.md missing — the paged-routing table cannot be "
            "drift-checked"))
        return
    readme_rows = re.findall(r"^\| `([a-z_]+)` \|", readme.text, re.M)
    for r in routes:
        if r not in readme_rows:
            diags.append(Diagnostic(
                "README.md", _line_of(readme, "Paged KV cache"),
                "gate-routes",
                f"README 'Paged KV cache' routing table lost its "
                f"`{r}` row (kernel_select.PAGED_ROUTES names it)"))
    for r in set(readme_rows):
        if r.startswith("paged_") and r not in routes:
            diags.append(Diagnostic(
                "README.md", _line_of(readme, f"| `{r}` |"),
                "gate-routes",
                f"README routing row `{r}` names a route "
                "kernel_select.PAGED_ROUTES cannot resolve"))


def _check_texts(project, diags):
    bench = project.source("bench.py")
    if bench is None:
        diags.append(Diagnostic("bench.py", 1, "gate-bench",
                                "bench.py missing from the tree"))
    elif bench.parse_error() is not None:
        pass  # reported once as parse-error
    else:
        defs = {n.name for n in ast.walk(bench.tree)
                if isinstance(n, ast.FunctionDef)}
        for name in BENCH_DEFS:
            if name not in defs:
                diags.append(Diagnostic(
                    "bench.py", 1, "gate-bench",
                    f"bench.py lost its gated record (def {name})"))
    pd = project.source("experiments/perfdiff.py")
    if pd is None:
        diags.append(Diagnostic("experiments/perfdiff.py", 1,
                                "gate-perfdiff", "perfdiff.py missing"))
    else:
        for key in PERFDIFF_KEYS:
            if key not in pd.text:
                diags.append(Diagnostic(
                    "experiments/perfdiff.py", 1, "gate-perfdiff",
                    f"perfdiff rules lost {key!r} — that regression "
                    "surface is no longer gated"))
    aot = project.source("experiments/aot_check.py")
    if aot is None:
        diags.append(Diagnostic("experiments/aot_check.py", 1, "gate-aot",
                                "aot_check.py missing"))
    else:
        for marker in AOT_MARKERS:
            if marker not in aot.text:
                diags.append(Diagnostic(
                    "experiments/aot_check.py", 1, "gate-aot",
                    f"AOT gate lost its {marker!r} cases — a Mosaic "
                    "rejection could reach a live window unflagged"))


def _check_scripts(project, diags):
    if project.root is None:
        return  # in-memory fixture projects have no filesystem facts
    import os

    for rel in GATED_SCRIPTS:
        full = os.path.join(project.root, rel)
        if not os.path.exists(full):
            diags.append(Diagnostic(rel, 1, "gate-scripts",
                                    f"{rel} missing"))
        elif not os.access(full, os.X_OK):
            diags.append(Diagnostic(rel, 1, "gate-scripts",
                                    f"{rel} is not executable"))


def _check_docs(project, diags):
    readme = project.source("README.md")
    if readme is None:
        return
    rule_rows = _table_rows(readme, "| Rule |")
    doc_rules = {r for _, r in rule_rows}
    cat = set(RULE_CATALOG)
    anchor = _line_of(readme, "| Rule |")
    for r in sorted(cat - doc_rules):
        diags.append(Diagnostic(
            "README.md", anchor, "doc-rules",
            f"analyzer rule `{r}` has no row in the README rule-catalog "
            "table"))
    for line, r in rule_rows:
        if r not in cat:
            diags.append(Diagnostic(
                "README.md", line, "doc-rules",
                f"README rule-catalog row `{r}` names no analyzer rule "
                "(analysis.RULE_CATALOG is the definition site)"))
    rank_rows = _table_rows(readme, "| Lock |")
    doc_ranks = {r for _, r in rank_rows}
    anchor = _line_of(readme, "| Lock |")
    for name in sorted(set(LOCK_RANKS) - doc_ranks):
        diags.append(Diagnostic(
            "README.md", anchor, "doc-ranks",
            f"lock `{name}` (rank {LOCK_RANKS[name]}) has no row in the "
            "README lock-rank table"))
    for line, name in rank_rows:
        if name not in LOCK_RANKS:
            diags.append(Diagnostic(
                "README.md", line, "doc-ranks",
                f"README lock-rank row `{name}` names no "
                "utils/locks.LOCK_RANKS entry"))


def check(project) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    _check_routes(project, diags)
    _check_texts(project, diags)
    _check_scripts(project, diags)
    _check_docs(project, diags)
    return diags
