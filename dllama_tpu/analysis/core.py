"""Analyzer core: sources, suppressions, the rule registry, the runner.

Design constraints (the module docstring of :mod:`dllama_tpu.analysis`
has the why): stdlib-only, sub-5s on the whole tree, one ``ast.parse``
per file shared by every rule, and diagnostics that are plain data so
``--json`` is a dump, not a second code path.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass

#: rule-id -> one-line description. The SINGLE definition site of the rule
#: catalog: the README table is drift-checked against this (rule
#: ``doc-rules``), and a suppression naming an unknown rule is itself a
#: finding (``suppress-unknown``).
RULE_CATALOG = {
    "jit-scope": "cached-jit dispatch in dllama_tpu/engine/ outside a "
                 "LEDGER.scope(...) bracket",
    "jit-label": "LEDGER.scope(fn, ...) whose fn label is not an "
                 "obs/compile.COMPILE_FNS literal",
    "dev-state": "whole-array rebind of a device-authoritative engine "
                 "array (_pos_dev/_last_dev/_keys_dev) outside the "
                 "sanctioned boundary sites",
    "catalog-metric": "metric family created outside obs/instruments.py",
    "catalog-span": "span name not in obs/trace.SPAN_CATALOG",
    "catalog-event": "event name not in obs/trace.EVENT_CATALOG",
    "catalog-fault": "faults.fire/flag point not in utils/faults.POINTS",
    "transfer-note": "host<->device transfer in a steady-state decode/spec "
                     "path without note_transfer accounting",
    "lock-order": "static lock-graph edge that descends or re-enters "
                  "utils/locks.LOCK_RANKS",
    "lock-leaf": "lock acquired while holding a leaf lock (metrics "
                 "registry / tracer)",
    "lock-unranked": "named lock whose name is missing from LOCK_RANKS "
                     "(or a rank no lock uses)",
    "gate-routes": "engine/kernel_select.PAGED_ROUTES drifted from the "
                   "README paged-routing table",
    "gate-bench": "bench.py lost a gated record (bench_hybrid / "
                  "bench_compile / bench_router)",
    "gate-perfdiff": "experiments/perfdiff.py lost a gated regression rule",
    "gate-aot": "experiments/aot_check.py lost the paged-kernel AOT "
                "inventory",
    "gate-scripts": "a gated smoke script is missing or not executable",
    "doc-rules": "README rule-catalog table drifted from "
                 "analysis.RULE_CATALOG",
    "doc-ranks": "README lock-rank table drifted from "
                 "utils/locks.LOCK_RANKS",
    "suppress-reason": "# dllama: allow[...] suppression without a reason",
    "suppress-unknown": "# dllama: allow[...] naming an unknown rule id",
    "parse-error": "a .py file under analysis does not parse (the file is "
                   "excluded from every other rule)",
}


@dataclass(frozen=True)
class Diagnostic:
    path: str  # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*dllama:\s*allow\[([A-Za-z0-9_,\s-]+)\]\s*(.*?)\s*$")


class Source:
    """One analyzed file: text + (for .py) a lazily-parsed AST, the
    suppression map, and the function-extent index that lets a suppression
    on a ``def`` line cover the whole function body."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self._tree: ast.Module | None = None
        self._def_spans: list[tuple[int, int]] | None = None
        # line -> set of allowed rule ids; bare entries recorded separately
        self.suppressions: dict[int, set[str]] = {}
        self.bare_suppressions: list[tuple[int, str]] = []
        self.unknown_suppressions: list[tuple[int, str]] = []
        for i, ln in self._comments():
            m = _SUPPRESS_RE.search(ln)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            for r in rules:
                if r not in RULE_CATALOG:
                    self.unknown_suppressions.append((i, r))
            self.suppressions[i] = rules
            if not m.group(2):
                self.bare_suppressions.append((i, ",".join(sorted(rules))))

    def _comments(self):
        """(line, comment_text) for REAL comment tokens only — a
        suppression spelled inside a docstring or string literal is prose,
        not policy (tokenize, not a line regex)."""
        if not self.rel.endswith(".py"):
            return
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, SyntaxError,
                IndentationError):  # broken source: no comments to scan
            return

    @property
    def is_py(self) -> bool:
        return self.rel.endswith(".py")

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.rel)
        return self._tree

    def parse_error(self) -> SyntaxError | None:
        """The file's SyntaxError, or None when it parses — broken files
        become ONE ``parse-error`` diagnostic instead of an analyzer
        traceback (the documented file:line / --json contracts must
        degrade per file, never abort the run)."""
        try:
            self.tree
        except SyntaxError as e:
            return e
        return None

    def _spans(self) -> list[tuple[int, int]]:
        if self._def_spans is None:
            spans = []
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    spans.append((node.lineno,
                                  node.end_lineno or node.lineno))
            self._def_spans = spans
        return self._def_spans

    def suppressed(self, rule: str, line: int) -> bool:
        """True when `rule` is allowed at `line` — by a comment on the line
        itself or on the ``def`` line of any enclosing function."""
        s = self.suppressions.get(line)
        if s and rule in s:
            return True
        if not self.is_py or not self.suppressions:
            return False
        for start, end in self._spans():
            if start <= line <= end:
                s = self.suppressions.get(start)
                if s and rule in s:
                    return True
        return False


class Project:
    """The analyzed file set: repo-relative path -> :class:`Source`.

    ``from_disk`` walks the real tree; tests build in-memory projects from
    ``{relpath: text}`` mappings so every red fixture is a tiny literal.
    ``root`` (optional for in-memory projects) lets filesystem-facts rules
    (executable bits) run."""

    #: non-package files some rules read (gates/docs); missing entries are
    #: each rule's problem to report
    EXTRA_FILES = ("README.md", "bench.py", "experiments/perfdiff.py",
                   "experiments/aot_check.py")

    def __init__(self, files: dict[str, str], root: str | None = None):
        self.root = root
        self.sources: dict[str, Source] = {
            rel.replace("\\", "/"): Source(rel.replace("\\", "/"), text)
            for rel, text in files.items()
        }

    @classmethod
    def from_disk(cls, root: str) -> "Project":
        import os

        files: dict[str, str] = {}
        pkg = os.path.join(root, "dllama_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    files[rel] = f.read()
        for rel in cls.EXTRA_FILES:
            full = os.path.join(root, rel)
            if os.path.exists(full):
                with open(full, encoding="utf-8") as f:
                    files[rel] = f.read()
        return cls(files, root=root)

    def source(self, rel: str) -> Source | None:
        return self.sources.get(rel)

    def py_sources(self, prefix: str = "dllama_tpu/") -> list[Source]:
        """Parseable .py sources under `prefix` — files with syntax errors
        are excluded here and reported once by run() as ``parse-error``."""
        return [s for rel, s in sorted(self.sources.items())
                if s.is_py and rel.startswith(prefix)
                and s.parse_error() is None]


# --------------------------------------------------------------- helpers

def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_arg(call: ast.Call, index: int = 0) -> str | None:
    """The index-th positional argument when it is a string literal."""
    if len(call.args) > index:
        a = call.args[index]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


def parent_map(tree: ast.Module) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


# ---------------------------------------------------------------- runner

def run(project: Project) -> list[Diagnostic]:
    """Run every rule; returns unsuppressed diagnostics sorted by
    (path, line, rule). Suppressions without a reason, or naming unknown
    rules, are findings themselves — a silent blanket allow is exactly
    the drift this analyzer exists to stop."""
    from dllama_tpu.analysis import rules_catalog, rules_gates, rules_jit
    from dllama_tpu.analysis import rules_locks, rules_state

    diags: list[Diagnostic] = []
    for rel, src in sorted(project.sources.items()):
        if src.is_py:
            err = src.parse_error()
            if err is not None:
                diags.append(Diagnostic(
                    rel, err.lineno or 1, "parse-error",
                    f"file does not parse ({err.msg}); excluded from every "
                    "other rule"))
    for checker in (rules_jit.check, rules_state.check, rules_catalog.check,
                    rules_locks.check, rules_gates.check):
        diags.extend(checker(project))
    out = []
    for d in diags:
        src = project.source(d.path)
        if src is not None and src.suppressed(d.rule, d.line):
            continue
        out.append(d)
    for rel, src in sorted(project.sources.items()):
        for line, rules in src.bare_suppressions:
            out.append(Diagnostic(
                rel, line, "suppress-reason",
                f"suppression allow[{rules}] has no reason — say why the "
                "rule does not apply here"))
        for line, rule in src.unknown_suppressions:
            out.append(Diagnostic(
                rel, line, "suppress-unknown",
                f"suppression names unknown rule {rule!r} "
                f"(catalog: {', '.join(sorted(RULE_CATALOG))})"))
    out.sort(key=lambda d: (d.path, d.line, d.rule))
    return out
