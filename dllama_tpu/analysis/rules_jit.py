"""jit-dispatch discipline (rules ``jit-scope`` / ``jit-label``).

Every *dispatch* of a cached-jit callable in ``dllama_tpu/engine/`` must
be lexically inside a ``with LEDGER.scope(fn, key):`` bracket whose fn
label is an ``obs/compile.COMPILE_FNS`` literal — the compile ledger can
only attribute what the callsite scopes, and an unscoped dispatch is a
future "untracked compile mid-traffic" nobody can bill (the PR 12 ledger
catches that only when the path runs; this fails CI at the callsite).

What counts as a cached-jit callable (collected over all engine modules):

* ``self.X = jax.jit(...)`` attribute bindings (and ``@jax.jit``-decorated
  methods — called as ``self.X(...)``);
* ``self.X[...] = factory(...)`` where `factory` is an engine function
  whose body returns ``jax.jit(...)`` (the spec-decoder table);
* ``@jax.jit``-decorated module-level functions, including when imported
  into a sibling engine module.

Calls inside *impl* functions — functions handed TO ``jax.jit`` (directly,
via ``functools.partial``, or decorated) — are traced code, not dispatch
sites, and are skipped.
"""

from __future__ import annotations

import ast

from dllama_tpu.analysis.core import Diagnostic, dotted, str_arg
from dllama_tpu.obs.compile import COMPILE_FNS

ENGINE_PREFIX = "dllama_tpu/engine/"

#: dotted receivers that ARE the compile ledger (scope() brackets)
_SCOPE_CALLS = ("LEDGER.scope", "ledger.scope")


def _is_scope_call(call: ast.Call) -> bool:
    d = dotted(call.func)
    return d is not None and (d in _SCOPE_CALLS
                              or d.endswith(".LEDGER.scope"))


def _is_jax_jit(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted(node.func) == "jax.jit"


def _decorated_jit(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if dotted(dec) == "jax.jit":
            return True
        if isinstance(dec, ast.Call) and any(
                dotted(a) == "jax.jit" for a in dec.args):
            return True  # @functools.partial(jax.jit, ...)
    return False


def _collect(project):
    """(jit_attrs, module_callables, impl_names) over engine/ —
    impl_names is PER MODULE: a function handed to jax.jit in one module
    must not shadow a same-named dispatch method elsewhere."""
    factories: set[str] = set()
    impl_names: dict[str, set[str]] = {}  # rel -> traced fn names
    for src in project.py_sources(ENGINE_PREFIX):
        impls = impl_names.setdefault(src.rel, set())
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                if _decorated_jit(node):
                    impls.add(node.name)
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Return)
                            and sub.value is not None
                            and _is_jax_jit(sub.value)):
                        factories.add(node.name)
            if _is_jax_jit(node):
                # functions handed to jax.jit are impls (self._decode_impl,
                # partial(self._x_impl, ...), plain names)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Attribute):
                        impls.add(sub.attr)
                    elif isinstance(sub, ast.Name):
                        impls.add(sub.id)
    jit_attrs: dict[str, set[str]] = {}  # module rel -> tracked attr names
    mod_callables: dict[str, set[str]] = {}  # rel -> callable bare names
    for src in project.py_sources(ENGINE_PREFIX):
        attrs: set[str] = set()
        names: set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef) and _decorated_jit(node):
                # class-level: self.NAME(...); module-level: NAME(...)
                attrs.add(node.name)
                names.add(node.name)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self" and _is_jax_jit(node.value)):
                    attrs.add(t.attr)
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                        and isinstance(node.value, ast.Call)):
                    fname = dotted(node.value.func)
                    if fname and fname.split(".")[-1] in factories:
                        attrs.add(t.value.attr)
                if (isinstance(t, ast.Name) and _is_jax_jit(node.value)):
                    names.add(t.id)
        jit_attrs[src.rel] = attrs
        mod_callables[src.rel] = names
    # imported jit-decorated module functions count in the importing module
    all_names = set().union(*mod_callables.values()) if mod_callables else set()
    for src in project.py_sources(ENGINE_PREFIX):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name in all_names:
                        mod_callables[src.rel].add(
                            alias.asname or alias.name)
    return jit_attrs, mod_callables, impl_names


class _Visitor(ast.NodeVisitor):
    def __init__(self, src, attrs, names, impl_names, diags):
        self.src = src
        self.attrs = attrs
        self.names = names
        self.impl_names = impl_names
        self.diags = diags
        self.scope_depth = 0
        self.impl_depth = 0

    def visit_FunctionDef(self, node: ast.FunctionDef):
        impl = node.name in self.impl_names or _decorated_jit(node)
        if impl:
            self.impl_depth += 1
        self.generic_visit(node)
        if impl:
            self.impl_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        # lambdas handed to jax.vmap / inside jit args are traced; a
        # dispatch inside a bare lambda is rare enough to skip safely
        self.impl_depth += 1
        self.generic_visit(node)
        self.impl_depth -= 1

    def visit_With(self, node: ast.With):
        scoped = any(isinstance(item.context_expr, ast.Call)
                     and _is_scope_call(item.context_expr)
                     for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if scoped:
            self.scope_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if scoped:
            self.scope_depth -= 1

    def _dispatch_name(self, call: ast.Call) -> str | None:
        f = call.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and f.attr in self.attrs):
            return f"self.{f.attr}"
        if isinstance(f, ast.Subscript):
            base = f.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr in self.attrs):
                return f"self.{base.attr}[...]"
        if isinstance(f, ast.Name) and f.id in self.names:
            return f.id
        return None

    def visit_Call(self, node: ast.Call):
        name = self._dispatch_name(node)
        if name is not None and self.impl_depth == 0 \
                and self.scope_depth == 0:
            self.diags.append(Diagnostic(
                self.src.rel, node.lineno, "jit-scope",
                f"cached-jit dispatch {name}(...) outside a "
                "LEDGER.scope(fn, key) bracket — the compile ledger "
                "cannot attribute its compiles (obs/compile.COMPILE_FNS "
                "has the labels)"))
        self.generic_visit(node)


def check(project) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    jit_attrs, mod_callables, impl_names = _collect(project)
    for src in project.py_sources(ENGINE_PREFIX):
        _Visitor(src, jit_attrs.get(src.rel, set()),
                 mod_callables.get(src.rel, set()),
                 impl_names.get(src.rel, set()), diags).visit(src.tree)
    # jit-label: every literal scope label anywhere in the package must be
    # a COMPILE_FNS member (non-literal labels — warmup's loop variable —
    # are runtime-checked by ShapeContract.declare instead)
    for src in project.py_sources("dllama_tpu/"):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _is_scope_call(node):
                label = str_arg(node, 0)
                if label is not None and label not in COMPILE_FNS:
                    diags.append(Diagnostic(
                        src.rel, node.lineno, "jit-label",
                        f"LEDGER.scope fn label {label!r} is not in "
                        f"obs/compile.COMPILE_FNS "
                        f"({', '.join(sorted(COMPILE_FNS))})"))
    return diags
