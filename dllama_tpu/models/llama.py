"""Llama decoder forward pass — the graph the reference builds as data
(buildLlmNet, llm.cpp:125-436) expressed as one scanned, jittable function.

Per layer (mirrors the reference's att+ff segments, SURVEY.md §3.4):
  x += wo( attention( rope(q), rope(k)→cache, v→cache ) )   [att segment]
  x += w2( act(w1 h) * w3 h )                               [ff segment]
with pre-RMSNorm before each block. The reference's SYNC_NODE_SLICES
all-gathers don't appear here — under pjit the tensor-parallel collectives are
inserted by XLA from the weight/cache shardings (parallel/sharding.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.ops.layers import activation, apply_rope, gqa_attention, moe_ffn, rms_norm
from dllama_tpu.ops.matmul import matmul


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class KVCache:
    """[n_layers, batch, n_kv_heads, seq_len, head_size] per tensor.

    Functional stand-in for the reference's per-layer k/v buffers written
    through position-indexed dynamic pointers (nn-cpu.cpp:198-222); here the
    write is a donated dynamic_update_slice at pos, which XLA turns into an
    in-place HBM update.
    """

    k: jax.Array
    v: jax.Array

    def tree_flatten(self):
        return (self.k, self.v), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, cfg: LlamaConfig, batch: int, dtype=jnp.bfloat16, seq_len: int | None = None):
        shape = (cfg.n_layers, batch, cfg.n_kv_heads, seq_len or cfg.seq_len, cfg.head_size)
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    @property
    def seq_len(self) -> int:
        return self.k.shape[3]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVCache:
    """Paged KV layout: one global page pool
    [n_layers, n_pages + 1, n_kv_heads, page_size, head_size] per tensor plus
    per-slot block tables [n_slots, max_blocks] (i32 page indices, logical
    block b of slot s lives in pool page tables[s, b]).

    A slot reserves nothing up front — the engine-side allocator
    (engine/batch.PagePool) hands out pages as positions advance and
    refcounts them, so idle context windows cost no HBM and a shared prefix
    is ONE set of pages referenced by many tables (vLLM's PagedAttention
    layout, Kwon et al. 2023). The LAST pool page is the trash page: masked
    writes (inactive slots) scatter there instead of paying a
    whole-pool ``where``; the allocator never hands it out.

    Unallocated table entries point at page 0: reads through them surface
    whatever that page holds, which the causal mask zeroes exactly (stale
    pool values are finite, and softmax assigns masked positions
    probability 0.0 — so paged attention is bit-exact vs dense)."""

    k: jax.Array
    v: jax.Array
    tables: jax.Array  # i32 [n_slots, max_blocks]

    def tree_flatten(self):
        return (self.k, self.v, self.tables), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, cfg: LlamaConfig, n_slots: int, n_pages: int,
               page_size: int, dtype=jnp.bfloat16, max_blocks: int = 0):
        shape = (cfg.n_layers, n_pages + 1, cfg.n_kv_heads, page_size,
                 cfg.head_size)
        tables = jnp.zeros((n_slots, max_blocks or 1), jnp.int32)
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), tables)

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def n_pages(self) -> int:
        """Usable pages (the +1 trash page is excluded)."""
        return self.k.shape[1] - 1


def _cache_update(cache, new, pos_base, active):
    """Write [B, H, T, hd] rows at pos (scalar, or [B] per-row scatter); rows
    with active==False keep their old contents (continuous batching: frozen
    finished slots, masked prefill of a single slot)."""
    new = new.astype(cache.dtype)
    if jnp.ndim(pos_base) == 1:
        upd = jax.vmap(
            lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0))
        )(cache, new, pos_base)
    else:
        upd = jax.lax.dynamic_update_slice(cache, new, (0, 0, pos_base, 0))
    if active is not None:
        upd = jnp.where(active[:, None, None, None], upd, cache)
    return upd


def _paged_cache_update(pool, new, tables, pos_base, active):
    """Write [B, H, T, hd] rows into the page pool at block-table positions.

    pool: one layer's [P, H, page, hd] slice. Row pos+t of slot b lands in
    pool page tables[b, (pos+t) // page] at offset (pos+t) % page. Rows with
    active==False are routed to the TRASH page (index P-1, never allocated)
    — a per-row index swap instead of the dense path's whole-cache where().
    """
    from dllama_tpu.ops.layers import paged_write_targets

    new = new.astype(pool.dtype)
    b, h, t, hd = new.shape
    pages, off = paged_write_targets(tables, pos_base, t, pool.shape[2],
                                     pool.shape[0], active)
    return pool.at[pages, :, off, :].set(new.transpose(0, 2, 1, 3))


from dllama_tpu.ops.quant import slice_leaf as _slice_layer


def _layer(cfg: LlamaConfig, x, layers, li, k_cache, v_cache, rope, pos_base, attn_fn,
           active=None, col_fn=None, mm=None, mm_in=None, moe_impl="auto",
           tables=None):
    """One decoder layer. `layers` is the full stacked params dict and `li`
    the traced layer index — quantized weights are NOT sliced here: the matmul
    dispatcher either DMA-indexes the stack (Pallas scalar prefetch) or slices
    lazily (XLA path). Slicing stacked weights before a pallas_call would make
    XLA materialize a full HBM copy of every weight, every layer, every token.

    `mm_in` is the matmul for the INPUT-dim-sharded weights (wo/w2 — the
    reference's col slices with merge-add): under sharded-Pallas it psums
    partials inside shard_map; default is plain `mm` (GSPMD inserts the
    collective itself on the XLA path).
    """
    mm = mm or matmul
    if col_fn is None:
        colmm = mm_in or mm  # `--sync q80` swaps in the Q80-exchange
        # shard_map instead (parallel/collectives.make_q80_col_matmul)
    else:
        def colmm(h, w, layer=None):
            return col_fn(h, _slice_layer(w, layer) if layer is not None else w)
    b, t, d = x.shape
    kvd = cfg.kv_dim
    # --- attention block (reference "att" segment, llm.cpp:198-312)
    h = rms_norm(x, layers["rms_att"][li], cfg.norm_epsilon)
    if "wqkv" in layers:  # fused launch (fuse_layer_weights)
        qkv = mm(h, layers["wqkv"], li)
        q, k, v = qkv[..., :d], qkv[..., d : d + kvd], qkv[..., d + kvd :]
    else:
        q = mm(h, layers["wq"], li)
        k = mm(h, layers["wk"], li)
        v = mm(h, layers["wv"], li)
    q = q.reshape(b, t, cfg.n_heads, cfg.head_size)
    k = k.reshape(b, t, cfg.n_kv_heads, cfg.head_size)
    v = v.reshape(b, t, cfg.n_kv_heads, cfg.head_size)
    q = apply_rope(q, rope)
    k = apply_rope(k, rope)
    if tables is None:
        k_cache = _cache_update(k_cache, k.transpose(0, 2, 1, 3), pos_base, active)
        v_cache = _cache_update(v_cache, v.transpose(0, 2, 1, 3), pos_base, active)
        att = attn_fn(q, k_cache, v_cache, pos_base).reshape(b, t, d)
    elif getattr(attn_fn, "fused_kv_scatter", False):
        # paged flash-decode kernel: the new rows' scatter write is fused
        # into the attention launch (ops/pallas/paged_attention) — no
        # separate per-layer scatter dispatch, identical pool contents
        att, k_cache, v_cache = attn_fn(
            q, k_cache, v_cache, tables, pos_base,
            k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), active)
        att = att.reshape(b, t, d)
    else:  # paged layout: scatter at block-table positions, same math
        k_cache = _paged_cache_update(k_cache, k.transpose(0, 2, 1, 3),
                                      tables, pos_base, active)
        v_cache = _paged_cache_update(v_cache, v.transpose(0, 2, 1, 3),
                                      tables, pos_base, active)
        att = attn_fn(q, k_cache, v_cache, tables, pos_base).reshape(b, t, d)
    x = x + colmm(att, layers["wo"], li)
    # --- feed-forward block (reference "ff" segment, llm.cpp:314-385);
    # sparse-MoE variant when the header carries N_EXPERTS (llm.hpp:17-18 —
    # a key the reference parses but never executes)
    h = rms_norm(x, layers["rms_ffn"][li], cfg.norm_epsilon)
    if "moe_gate" in layers:
        x = x + moe_ffn(
            cfg, h, layers["moe_gate"][li],
            _slice_layer(layers["moe_w1"], li),
            _slice_layer(layers["moe_w2"], li),
            _slice_layer(layers["moe_w3"], li),
            impl=moe_impl,
        )
    elif "w13" in layers:  # fused launch (fuse_layer_weights)
        gu = mm(h, layers["w13"], li)
        f = cfg.hidden_dim
        gate = activation(gu[..., :f].astype(jnp.float32), cfg.hidden_act).astype(x.dtype)
        x = x + colmm(gate * gu[..., f:], layers["w2"], li)
    else:
        gate = activation(mm(h, layers["w1"], li).astype(jnp.float32), cfg.hidden_act).astype(x.dtype)
        up = mm(h, layers["w3"], li)
        x = x + colmm(gate * up, layers["w2"], li)
    return x, k_cache, v_cache


def fuse_layer_weights(layers: dict) -> dict:
    """wq/wk/wv -> wqkv and w1/w3 -> w13, concatenated on the OUTPUT dim.

    The attention and gate/up matmuls share their input activation; fusing
    them turns 5 kernel launches per layer into 2 (decode at 1B runs ~113
    Pallas calls per token — launch count is real money at 1 ms/token). The
    reference issues q/k/v and w1/w3 as separate MATMUL ops in its segment
    graph (llm.cpp:198-312, 314-385) because each op is a unit of its
    executor's thread-pool scheduling; here the unit is a kernel launch, so
    concatenation is the analogous batching lever.
    QTensor concat is exact: packed nibbles and f16 scales both carry the
    output dim last. Unsharded engines only — under tp the q and kv blocks
    shard at different granularity, so fused weights would mis-slice.
    Dense (unquantized) leaves concatenate the same way."""
    from dllama_tpu.ops.quant import Q8Tensor, QTensor

    def cat(*ws):
        if isinstance(ws[0], QTensor):
            return QTensor(
                jnp.concatenate([w.packed for w in ws], axis=-1),
                jnp.concatenate([w.scales for w in ws], axis=-1),
            )
        if isinstance(ws[0], Q8Tensor):
            # same output-dim-last layout argument as QTensor
            return Q8Tensor(
                jnp.concatenate([w.codes for w in ws], axis=-1),
                jnp.concatenate([w.scales for w in ws], axis=-1),
            )
        return jnp.concatenate(ws, axis=-1)

    out = dict(layers)
    if all(k in out for k in ("wq", "wk", "wv")):
        out["wqkv"] = cat(out.pop("wq"), out.pop("wk"), out.pop("wv"))
    if all(k in out for k in ("w1", "w3")):
        out["w13"] = cat(out.pop("w1"), out.pop("w3"))
    return out


def run_layers(
    cfg: LlamaConfig,
    layer_params: dict,  # stacked [L, ...] leaves
    x: jax.Array,  # [B, T, D]
    pos_base: jax.Array,  # scalar, or [B] per-row positions
    k_cache: jax.Array,  # [L, B, Hkv, S, hd]
    v_cache: jax.Array,
    rope: jax.Array,  # [T, head_size/2, 2] rope rows (or [B, T, ...] per-row)
    attn_fn=None,
    active: jax.Array | None = None,  # [B] bool: rows allowed to write cache
    unroll: int | bool = 1,
    col_fn=None,  # wo/w2 matmul override (Q80 quantized exchange)
    mm=None,  # quantized-matmul fn (x, w, layer) -> out; default ops.matmul
    mm_in=None,  # matmul for input-dim-sharded weights (see _layer)
    moe_impl: str = "auto",  # MoE compute scheme (ops.layers.moe_ffn)
    tables: jax.Array | None = None,  # i32 [B, max_blocks] block tables —
    # presence selects the paged cache layout (k/v are then page pools)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Scan the decoder layers (any contiguous stack — the full model, or one
    pipeline stage's slice). Returns (x, k_cache, v_cache).

    The scan carries only the layer INDEX (plus the per-layer cache slices) —
    the stacked weights stay closed-over and un-sliced, so the Pallas kernels
    can DMA-index them with zero copies (ops/pallas/q40_matmul.py docstring).

    `unroll`: passed to lax.scan — trades compile time for cross-layer
    scheduling freedom."""
    if attn_fn is None:
        if tables is None:
            attn_fn = gqa_attention
        else:
            from dllama_tpu.ops.layers import paged_gqa_attention

            attn_fn = paged_gqa_attention
    n_layers = k_cache.shape[0]

    def scan_fn(carry, xs):
        x = carry
        li, kc, vc = xs
        x, kc, vc = _layer(cfg, x, layer_params, li, kc, vc, rope, pos_base, attn_fn,
                           active, col_fn, mm, mm_in, moe_impl, tables)
        return x, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        scan_fn, x, (jnp.arange(n_layers, dtype=jnp.int32), k_cache, v_cache),
        unroll=unroll,
    )
    return x, k_new, v_new


def forward(
    cfg: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # i32 [B, T]
    pos_base: jax.Array,  # scalar i32
    cache: KVCache,
    rope_cache: jax.Array,  # [seq, head_size/2, 2]
    attn_fn=None,  # (q, k_cache, v_cache, pos) -> out; default full-cache GQA.
    # A sequence-parallel mesh passes the shard_map'd LSE-merge attention here
    # (parallel/ring_attention.sp_cache_attention).
    active: jax.Array | None = None,  # [B] bool cache-write mask (batch mode)
    unroll: int | bool = 1,  # lax.scan unroll over layers (see run_layers)
    col_fn=None,  # wo/w2 matmul override (Q80 quantized exchange)
    mm=None,  # quantized-matmul fn (x, w, layer) -> out; default ops.matmul
    mm_in=None,  # matmul for input-dim-sharded weights (see _layer)
    moe_impl: str = "auto",  # MoE compute scheme (ops.layers.moe_ffn)
    last_only: bool = False,  # project logits for the last position only
) -> tuple[jax.Array, KVCache]:
    """Returns (logits f32 [B, T, vocab], updated cache).

    pos_base may be a scalar (all rows at one position — the single-sequence
    fast path) or an i32[B] vector giving each row its own position
    (continuous batching; rope rows are then gathered per row).

    ``last_only=True`` slices x to the final position before the lm-head
    matmul — prefill only needs next-token logits, and XLA cannot DCE rows of
    a dot, so without this a 128-token chunk would pay 128x the lm-head cost
    (the reference has the same shape: logits only materialize for the last
    token of a batch, dllama.cpp:69-88).

    `cache` may be a dense KVCache or a PagedKVCache — the paged layout
    threads its block tables through the layer scan (scatter writes at
    table positions, gather/block-indexed attention; identical math)."""
    x = params["embedding"][tokens]  # [B, T, D]
    t = tokens.shape[1]
    pos_base = jnp.asarray(pos_base, jnp.int32)
    if pos_base.ndim == 1:
        idx = pos_base[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # [B, T]
        rope = rope_cache[jnp.clip(idx, 0, rope_cache.shape[0] - 1)]
    else:
        rope = jax.lax.dynamic_slice_in_dim(rope_cache, pos_base, t, axis=0)
    paged = isinstance(cache, PagedKVCache)
    x, k_new, v_new = run_layers(
        cfg, params["layers"], x, pos_base, cache.k, cache.v, rope, attn_fn, active,
        unroll=unroll, col_fn=col_fn, mm=mm, mm_in=mm_in, moe_impl=moe_impl,
        tables=cache.tables if paged else None,
    )
    if last_only:
        x = x[:, -1:]
    x = rms_norm(x, params["final_norm"], cfg.norm_epsilon)
    logits = (mm or matmul)(x, params["wcls"]).astype(jnp.float32)
    if paged:
        return logits, PagedKVCache(k_new, v_new, cache.tables)
    return logits, KVCache(k_new, v_new)


def random_params_fast(cfg: LlamaConfig, seed: int = 0, dtype=jnp.bfloat16):
    """Synthetic Q40 params built from random *packed bytes* directly — no
    float weights, no quantization pass. ~30x faster than random_params for
    benchmark-sized models (an 8B preset materializes in seconds instead of
    minutes); the decoded values are valid Q40 numerics, just not
    normally-distributed. Perf benchmarks only — logits are meaningless."""
    import numpy as np

    from dllama_tpu.ops.quant import Q_BLOCK, QTensor

    rng = np.random.default_rng(seed)

    def qw(lead, k, n):
        packed = rng.integers(0, 256, (*lead, k // 2, n), dtype=np.uint8)
        # f16 scales like the file format; small positive spread
        scales = rng.random((*lead, k // Q_BLOCK, n), np.float32) * 0.02 + 1e-3
        return QTensor(jnp.asarray(packed), jnp.asarray(scales.astype(np.float16)))

    L = cfg.n_layers
    layers: dict = {
        "wq": qw((L,), cfg.dim, cfg.dim),
        "wk": qw((L,), cfg.dim, cfg.kv_dim),
        "wv": qw((L,), cfg.dim, cfg.kv_dim),
        "wo": qw((L,), cfg.dim, cfg.dim),
        "w1": qw((L,), cfg.dim, cfg.hidden_dim),
        "w2": qw((L,), cfg.hidden_dim, cfg.dim),
        "w3": qw((L,), cfg.dim, cfg.hidden_dim),
        "rms_att": jnp.ones((L, cfg.dim), jnp.float32),
        "rms_ffn": jnp.ones((L, cfg.dim), jnp.float32),
    }
    emb = (rng.random((cfg.vocab_size, cfg.dim), np.float32) - 0.5) * 0.04
    return {
        "embedding": jnp.asarray(emb, dtype),
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "wcls": qw((), cfg.dim, cfg.vocab_size),
        "layers": layers,
    }


def random_params(cfg: LlamaConfig, seed: int = 0, dtype=jnp.bfloat16, quantize: bool = True):
    """Random-initialized parameter pytree in the same structure load_params
    produces — for tests and synthetic benchmarks (no real checkpoint needed)."""
    import numpy as np

    from dllama_tpu.ops.quant import QTensor

    rng = np.random.default_rng(seed)

    def w(k, n):
        x = (rng.standard_normal((k, n)) * 0.02).astype(np.float32)
        return QTensor.quantize(x) if quantize else jnp.asarray(x, dtype)

    def stack(fn):
        leaves = [fn() for _ in range(cfg.n_layers)]
        return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *leaves)

    layers: dict = {
        "wq": stack(lambda: w(cfg.dim, cfg.dim)),
        "wk": stack(lambda: w(cfg.dim, cfg.kv_dim)),
        "wv": stack(lambda: w(cfg.dim, cfg.kv_dim)),
        "wo": stack(lambda: w(cfg.dim, cfg.dim)),
        "rms_att": stack(lambda: jnp.ones((cfg.dim,), jnp.float32)),
        "rms_ffn": stack(lambda: jnp.ones((cfg.dim,), jnp.float32)),
    }
    if cfg.n_experts:
        def expert_stack(k, n):
            leaves = [w(k, n) for _ in range(cfg.n_experts)]
            return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *leaves)

        layers["moe_gate"] = stack(
            lambda: jnp.asarray(rng.standard_normal((cfg.dim, cfg.n_experts)), jnp.float32)
        )
        layers["moe_w1"] = stack(lambda: expert_stack(cfg.dim, cfg.hidden_dim))
        layers["moe_w2"] = stack(lambda: expert_stack(cfg.hidden_dim, cfg.dim))
        layers["moe_w3"] = stack(lambda: expert_stack(cfg.dim, cfg.hidden_dim))
    else:
        layers["w1"] = stack(lambda: w(cfg.dim, cfg.hidden_dim))
        layers["w2"] = stack(lambda: w(cfg.hidden_dim, cfg.dim))
        layers["w3"] = stack(lambda: w(cfg.dim, cfg.hidden_dim))
    params = {
        "embedding": jnp.asarray(rng.standard_normal((cfg.vocab_size, cfg.dim)) * 0.02, dtype),
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "wcls": w(cfg.dim, cfg.vocab_size),
        "layers": layers,
    }
    return params
