"""Model configuration — the `.m` header schema as a dataclass.

Key ids and semantics mirror the reference header kv-list (llm.hpp:8-28,
llm.cpp:26-98) for drop-in model-file compatibility: same magic, same keys,
same int-valued floats, same derived quantities (head_size, kv_dim), and the
same `--max-seq-len` clamping rule (llm.cpp:89-91).
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum

from dllama_tpu.ops.quant import FloatType

MODEL_MAGIC = 0x0A00ABCD  # llm.cpp:46-48 (magic 0xA00ABCD)


class ArchType(IntEnum):
    LLAMA = 0xABCD00


class HiddenAct(IntEnum):
    GELU = 0
    SILU = 1


class RopeType(IntEnum):
    LLAMA = 0
    FALCON = 1  # present in the reference enum order (nn-core.hpp), unused
    LLAMA3_1 = 2


class HeaderKey(IntEnum):
    """llm.hpp:8-28."""

    VERSION = 0
    ARCH_TYPE = 1
    DIM = 2
    HIDDEN_DIM = 3
    N_LAYERS = 4
    N_HEADS = 5
    N_KV_HEADS = 6
    N_EXPERTS = 7
    N_ACTIVE_EXPERTS = 8
    VOCAB_SIZE = 9
    SEQ_LEN = 10
    HIDDEN_ACT = 11
    ROPE_THETA = 12
    WEIGHT_FLOAT_TYPE = 13
    ROPE_SCALING_FACTOR = 14
    ROPE_SCALING_LOW_FREQ_FACTOR = 15
    ROPE_SCALING_HIGH_FREQ_FACTORY = 16
    ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
    ROPE_TYPE = 18
    # dllama-tpu extension (not in the reference schema, which hardcodes
    # normEpsilon=1e-5, llm.cpp:33): written only when eps != 1e-5, value is
    # eps * 1e12 as an int. Reference binaries reject files carrying it.
    NORM_EPSILON_X1E12 = 100


@dataclasses.dataclass
class LlamaConfig:
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    version: int = 0
    arch: ArchType = ArchType.LLAMA
    n_experts: int = 0
    n_active_experts: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_theta: float = 10000.0
    rope_type: RopeType = RopeType.LLAMA
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    norm_epsilon: float = 1e-5
    weight_type: FloatType = FloatType.Q40
    orig_seq_len: int = 0  # pre-clamp seq len from the file

    def __post_init__(self):
        if self.orig_seq_len == 0:
            self.orig_seq_len = self.seq_len

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    def describe(self) -> str:
        """One-line summary in the spirit of the reference's header dump
        (llm.cpp:100-123)."""
        return (
            f"{self.arch.name} dim={self.dim} hidden={self.hidden_dim} "
            f"layers={self.n_layers} heads={self.n_heads}/{self.n_kv_heads} "
            f"vocab={self.vocab_size} seq={self.seq_len} "
            f"act={self.hidden_act.name} rope={self.rope_type.name} "
            f"weights={self.weight_type.name}"
            + (f" experts={self.n_experts}/{self.n_active_experts}" if self.n_experts else "")
        )

    def clamp_seq_len(self, max_seq_len: int | None) -> "LlamaConfig":
        """The reference's --max-seq-len RAM clamp (llm.cpp:89-91)."""
        if max_seq_len and self.seq_len > max_seq_len:
            return dataclasses.replace(self, seq_len=max_seq_len, orig_seq_len=self.orig_seq_len)
        return self

    def to_header_kv(self) -> list[tuple[int, int]]:
        """Serialize to the `.m` kv pairs (float values stored as ints, as the
        reference converter does — writer.py:109-143)."""
        kv = [
            (HeaderKey.VERSION, self.version),
            (HeaderKey.ARCH_TYPE, int(self.arch)),
            (HeaderKey.DIM, self.dim),
            (HeaderKey.HIDDEN_DIM, self.hidden_dim),
            (HeaderKey.N_LAYERS, self.n_layers),
            (HeaderKey.N_HEADS, self.n_heads),
            (HeaderKey.N_KV_HEADS, self.n_kv_heads),
            (HeaderKey.N_EXPERTS, self.n_experts),
            (HeaderKey.N_ACTIVE_EXPERTS, self.n_active_experts),
            (HeaderKey.VOCAB_SIZE, self.vocab_size),
            (HeaderKey.SEQ_LEN, self.orig_seq_len),
            (HeaderKey.HIDDEN_ACT, int(self.hidden_act)),
            (HeaderKey.ROPE_THETA, int(self.rope_theta)),
            (HeaderKey.WEIGHT_FLOAT_TYPE, int(self.weight_type)),
        ]
        if self.rope_type == RopeType.LLAMA3_1:
            kv += [
                (HeaderKey.ROPE_SCALING_FACTOR, int(self.rope_scaling_factor)),
                (HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR, int(self.rope_scaling_low_freq_factor)),
                (HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTORY, int(self.rope_scaling_high_freq_factor)),
                (HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN, self.rope_scaling_orig_max_seq_len),
                (HeaderKey.ROPE_TYPE, int(self.rope_type)),
            ]
        if abs(self.norm_epsilon - 1e-5) > 1e-12:
            kv.append((HeaderKey.NORM_EPSILON_X1E12, int(round(self.norm_epsilon * 1e12))))
        return [(int(k), int(v)) for k, v in kv]

    @classmethod
    def from_header_kv(cls, kv: list[tuple[int, int]]) -> "LlamaConfig":
        vals: dict = {}
        for key, value in kv:
            key = HeaderKey(key)
            if key == HeaderKey.VERSION:
                vals["version"] = value
            elif key == HeaderKey.ARCH_TYPE:
                vals["arch"] = ArchType(value)
            elif key == HeaderKey.DIM:
                vals["dim"] = value
            elif key == HeaderKey.HIDDEN_DIM:
                vals["hidden_dim"] = value
            elif key == HeaderKey.N_LAYERS:
                vals["n_layers"] = value
            elif key == HeaderKey.N_HEADS:
                vals["n_heads"] = value
            elif key == HeaderKey.N_KV_HEADS:
                vals["n_kv_heads"] = value
            elif key == HeaderKey.N_EXPERTS:
                vals["n_experts"] = value
            elif key == HeaderKey.N_ACTIVE_EXPERTS:
                vals["n_active_experts"] = value
            elif key == HeaderKey.VOCAB_SIZE:
                vals["vocab_size"] = value
            elif key == HeaderKey.SEQ_LEN:
                vals["seq_len"] = value
            elif key == HeaderKey.HIDDEN_ACT:
                vals["hidden_act"] = HiddenAct(value)
            elif key == HeaderKey.ROPE_THETA:
                vals["rope_theta"] = float(value)
            elif key == HeaderKey.WEIGHT_FLOAT_TYPE:
                vals["weight_type"] = FloatType(value)
            elif key == HeaderKey.ROPE_SCALING_FACTOR:
                vals["rope_scaling_factor"] = float(value)
            elif key == HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR:
                vals["rope_scaling_low_freq_factor"] = float(value)
            elif key == HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTORY:
                vals["rope_scaling_high_freq_factor"] = float(value)
            elif key == HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN:
                vals["rope_scaling_orig_max_seq_len"] = value
            elif key == HeaderKey.ROPE_TYPE:
                vals["rope_type"] = RopeType(value)
            elif key == HeaderKey.NORM_EPSILON_X1E12:
                vals["norm_epsilon"] = value / 1e12
        return cls(**vals)
