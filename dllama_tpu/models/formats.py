"""`.m` model-file reader/writer.

File schema (llm.cpp:26-98, converter/writer.py:109-143): i32 magic
0xA00ABCD, i32 headerSize, (key,value) i32 pairs, then raw tensors in fixed
order (llm.cpp:453-468):

  embedding f32 [vocab, dim]
  per layer: q [dim,dim] k [kv_dim,dim] v [kv_dim,dim] wo [dim,dim]
             w1 [hidden,dim] w2 [dim,hidden] w3 [hidden,dim]   (weight_type)
             rms_norm_0 f32 [dim], rms_norm_1 f32 [dim]
  final_rms_norm f32 [dim]
  wcls [vocab, dim]                                            (weight_type)

Matmul tensors are stored [out, in] row-major; we load them as transposed
``x @ W`` operands ([in, out]) — Q40 becomes a :class:`QTensor`, f32/f16
become dense arrays. Where the reference root slices each tensor and ships
shards to workers over TCP (nn-network.cpp:775-869), here every tensor is
`jax.device_put` with its mesh sharding — XLA/ICI replaces the wire protocol.
"""

from __future__ import annotations

import struct
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.models.config import MODEL_MAGIC, LlamaConfig
from dllama_tpu.ops.quant import (
    FloatType,
    Q_BLOCK,
    Q8Tensor,
    QTensor,
    dequantize_q40_np,
    dequantize_q80_np,
    quantize_q40_np,
    quantize_q80_np,
)


class ModelFileError(ValueError):
    """A .m file that cannot be what it claims: wrong magic, truncated
    header, or fewer/more tensor bytes than the header's config implies.
    Every message names the file and the expected-vs-actual numbers — the
    raw struct/mmap errors these replace said neither."""


def read_header(path: str, max_seq_len: int | None = None) -> tuple[LlamaConfig, int]:
    """Returns (config, header_size_bytes). Mirrors loadLlmHeader (llm.cpp:26-98)."""
    from dllama_tpu.utils import faults

    faults.fire("loader.read")
    with open(path, "rb") as f:
        head = f.read(8)
        if len(head) < 8:
            raise ModelFileError(
                f"{path}: not a .m model file — {len(head)} bytes on disk, "
                f"need at least the 8-byte magic+size header")
        magic, header_size = struct.unpack("<ii", head)
        if magic in (0xABCD00, 0xABCD01):
            raise ModelFileError(f"{path}: old model format is not supported")
        if magic != MODEL_MAGIC:
            raise ModelFileError(
                f"{path}: unsupported magic number {magic:#x} "
                f"(expected {MODEL_MAGIC:#x}) — not a .m model file, or corrupt")
        if header_size < 8 or (header_size - 8) % 8 != 0:
            raise ModelFileError(
                f"{path}: corrupt header: headerSize={header_size} "
                f"(want 8 + a multiple of 8 key/value bytes)")
        body = f.read(header_size - 8)
        if len(body) < header_size - 8:
            raise ModelFileError(
                f"{path}: truncated header: declares {header_size} bytes but "
                f"only {8 + len(body)} are on disk")
        n_kv = (header_size - 8) // 4 // 2
        kv = []
        for i in range(n_kv):
            key, value = struct.unpack_from("<ii", body, i * 8)
            kv.append((key, value))
    config = LlamaConfig.from_header_kv(kv)
    return config.clamp_seq_len(max_seq_len), header_size


def write_header(f, config: LlamaConfig) -> int:
    kv = config.to_header_kv()
    header = struct.pack("<ii", MODEL_MAGIC, 8 + len(kv) * 8)
    body = b"".join(struct.pack("<ii", k, v) for k, v in kv)
    f.write(header + body)
    return len(header) + len(body)


def tensor_plan(config: LlamaConfig) -> list[tuple[str, tuple[int, int] | tuple[int], FloatType]]:
    """(name, file_shape, float_type) in on-disk order (llm.cpp:453-468)."""
    wt = config.weight_type
    plan: list = [("embedding", (config.vocab_size, config.dim), FloatType.F32)]
    for layer in range(config.n_layers):
        plan += [
            (f"layers.{layer}.wq", (config.dim, config.dim), wt),
            (f"layers.{layer}.wk", (config.kv_dim, config.dim), wt),
            (f"layers.{layer}.wv", (config.kv_dim, config.dim), wt),
            (f"layers.{layer}.wo", (config.dim, config.dim), wt),
        ]
        if config.n_experts:
            # MoE extension: the reference header carries N_EXPERTS
            # (llm.hpp:17-18) and its HF converter emits expert tensors
            # (convert-hf.py:66-73), but its runtime never reads them; this
            # is the layout our converter writes — router gate then
            # expert-stacked w1/w2/w3 blobs.
            plan += [
                (f"layers.{layer}.moe_gate", (config.n_experts, config.dim), FloatType.F32),
                (f"layers.{layer}.moe_w1",
                 (config.n_experts, config.hidden_dim, config.dim), wt),
                (f"layers.{layer}.moe_w2",
                 (config.n_experts, config.dim, config.hidden_dim), wt),
                (f"layers.{layer}.moe_w3",
                 (config.n_experts, config.hidden_dim, config.dim), wt),
            ]
        else:
            plan += [
                (f"layers.{layer}.w1", (config.hidden_dim, config.dim), wt),
                (f"layers.{layer}.w2", (config.dim, config.hidden_dim), wt),
                (f"layers.{layer}.w3", (config.hidden_dim, config.dim), wt),
            ]
        plan += [
            (f"layers.{layer}.rms_att", (config.dim,), FloatType.F32),
            (f"layers.{layer}.rms_ffn", (config.dim,), FloatType.F32),
        ]
    plan += [
        ("final_norm", (config.dim,), FloatType.F32),
        ("wcls", (config.vocab_size, config.dim), wt),
    ]
    return plan


def write_tensor(f, x: np.ndarray, float_type: FloatType) -> int:
    """Serialize a tensor in the reference byte format (writer.py:29-107).

    Q40 quantization runs in C++ when the native library is available
    (bit-identical to quantize_q40_np; tests/test_native.py pins it)."""
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if float_type == FloatType.F32:
        buf = flat.tobytes()
    elif float_type == FloatType.F16:
        buf = flat.astype(np.float16).tobytes()
    elif float_type == FloatType.Q40:
        from dllama_tpu.utils import native

        if native.available():
            packed, scales = native.quantize_q40(flat)
        else:
            packed, scales = quantize_q40_np(flat)
        rec = np.zeros((packed.shape[0], 2 + Q_BLOCK // 2), dtype=np.uint8)
        rec[:, :2] = scales.reshape(-1, 1).view(np.uint8)
        rec[:, 2:] = packed
        buf = rec.tobytes()
    elif float_type == FloatType.Q80:
        # reference record: f16 delta + 32 int8 codes (writer.py:55-74)
        codes, scales = quantize_q80_np(flat)
        rec = np.zeros((codes.shape[0], 2 + Q_BLOCK), dtype=np.uint8)
        rec[:, :2] = scales.reshape(-1, 1).view(np.uint8)
        rec[:, 2:] = codes.view(np.uint8)
        buf = rec.tobytes()
    else:
        raise ValueError(f"unsupported weight type: {float_type}")
    f.write(buf)
    return len(buf)


def save_model(path: str, config: LlamaConfig, tensors: dict[str, np.ndarray]) -> None:
    """Write a complete `.m` file; `tensors` maps plan names to file-shape arrays."""
    with open(path, "wb") as f:
        write_header(f, config)
        for name, shape, ft in tensor_plan(config):
            x = tensors[name]
            assert tuple(x.shape) == tuple(shape), (name, x.shape, shape)
            write_tensor(f, x, ft)


def iter_tensors(path: str, config: LlamaConfig, header_size: int) -> Iterator[tuple[str, tuple, FloatType, np.ndarray]]:
    """Yield (name, file_shape, float_type, raw_bytes_view) per plan entry.

    Uses a read-only memmap — the analog of the reference's mmap weight load
    (mmap.hpp:35-70); no copy happens until a tensor is decoded.
    """
    data = np.memmap(path, dtype=np.uint8, mode="r")
    plan = tensor_plan(config)
    # validate the WHOLE plan against the on-disk size up front: a truncated
    # download/copy fails here with the offending tensor named, not as an
    # opaque out-of-bounds view (or worse, a SIGBUS on the mmap) deep inside
    # the layer-stacking loop
    total = header_size + sum(ft.nbytes(int(np.prod(shape))) for _, shape, ft in plan)
    if data.shape[0] < total:
        offset = header_size
        for name, shape, ft in plan:
            nbytes = ft.nbytes(int(np.prod(shape)))
            if offset + nbytes > data.shape[0]:
                raise ModelFileError(
                    f"{path}: truncated .m file: {data.shape[0]:,} bytes on "
                    f"disk, {total:,} expected for this header's config; "
                    f"first incomplete tensor is {name!r} "
                    f"(needs bytes [{offset:,}, {offset + nbytes:,}))")
            offset += nbytes
    if data.shape[0] > total:
        raise ModelFileError(
            f"{path}: .m file has {data.shape[0]:,} bytes but this header's "
            f"config accounts for {total:,} — corrupt header or mismatched "
            f"weight type")
    offset = header_size
    for name, shape, ft in plan:
        nbytes = ft.nbytes(int(np.prod(shape)))
        yield name, shape, ft, data[offset : offset + nbytes]
        offset += nbytes


def decode_dense(raw: np.ndarray, shape: tuple, ft: FloatType) -> np.ndarray:
    """Decode raw bytes to an f32 array of `shape`."""
    if ft == FloatType.F32:
        return raw.view(np.float32).reshape(shape)
    if ft == FloatType.F16:
        return raw.view(np.float16).reshape(shape).astype(np.float32)
    if ft == FloatType.Q40:
        n = int(np.prod(shape))
        rec = raw.reshape(n // Q_BLOCK, 2 + Q_BLOCK // 2)
        scales = rec[:, :2].copy().view(np.float16).reshape(-1)
        packed = rec[:, 2:]
        return dequantize_q40_np(packed, scales).reshape(shape)
    if ft == FloatType.Q80:
        n = int(np.prod(shape))
        rec = raw.reshape(n // Q_BLOCK, 2 + Q_BLOCK)
        scales = rec[:, :2].copy().view(np.float16).reshape(-1)
        codes = rec[:, 2:].view(np.int8)  # same-itemsize view: no copy
        return dequantize_q80_np(codes, scales).reshape(shape)
    raise ValueError(f"unsupported weight type: {ft}")


class LazyQ40:
    """A Q40 matmul weight still living as bytes on the `.m` memmap.

    Shards decode ON DEMAND in the device layout (packed u8[k/2, n], scales
    f16[k/32, n]): `jax.make_array_from_callback` asks only for the shards a
    host's devices own, so a model bigger than one host's RAM never fully
    decodes anywhere — the byte-range analog of the reference's
    slice-then-ship (nn-network.cpp:775-869), with the mmap as the wire.
    Both device dims map to contiguous/strided ranges of the file's
    [n_out, k_in/32, 18-byte-block] record array, so a shard read touches
    only its own byte ranges.
    """

    def __init__(self, raw: np.ndarray, n_out: int, k_in: int):
        self.rec = raw.reshape(n_out, k_in // Q_BLOCK, 2 + Q_BLOCK // 2)
        self.n_out = n_out
        self.k_in = k_in

    @property
    def packed_shape(self) -> tuple[int, ...]:
        return (self.k_in // 2, self.n_out)

    @property
    def scales_shape(self) -> tuple[int, ...]:
        return (self.k_in // Q_BLOCK, self.n_out)

    @staticmethod
    def _aligned(sl: slice, total: int, unit: int) -> tuple[int, int]:
        start = sl.start or 0
        stop = total if sl.stop is None else sl.stop
        assert start % unit == 0 and stop % unit == 0, (sl, unit)
        return start // unit, stop // unit

    def packed_shard(self, k2_sl: slice, n_sl: slice) -> np.ndarray:
        """Device-layout packed rows [k2_sl, n_sl] (k2 units of half-blocks)."""
        b0, b1 = self._aligned(k2_sl, self.k_in // 2, Q_BLOCK // 2)
        n0, n1 = self._aligned(n_sl, self.n_out, 1)
        from dllama_tpu.utils import native

        if native.has_q40_shard():
            return native.q40_shard(self.rec, n0, n1, b0, b1, True, False)[0]
        sub = np.ascontiguousarray(self.rec[n0:n1, b0:b1, 2:])  # [n, nb, 16]
        return np.transpose(sub, (1, 2, 0)).reshape(-1, sub.shape[0])

    def scales_shard(self, kb_sl: slice, n_sl: slice) -> np.ndarray:
        b0, b1 = self._aligned(kb_sl, self.k_in // Q_BLOCK, 1)
        n0, n1 = self._aligned(n_sl, self.n_out, 1)
        from dllama_tpu.utils import native

        if native.has_q40_shard():
            # the C++ twin emits f32; narrowing back to f16 is exact
            return native.q40_shard(self.rec, n0, n1, b0, b1, False, True)[1].astype(np.float16)
        sub = np.ascontiguousarray(self.rec[n0:n1, b0:b1, :2])  # [n, nb, 2]
        return np.ascontiguousarray(sub.view(np.float16)[..., 0].T)  # f16 [nb, n]

    def eager(self) -> QTensor:
        full = slice(None)
        return QTensor(self.packed_shard(full, full), self.scales_shard(full, full))


class LazyQ40Stack:
    """Layer-stacked LazyQ40s: one more leading axis on every shard request
    (sharded over 'pp' on pipeline meshes — a host decodes only its stage)."""

    def __init__(self, members: list[LazyQ40]):
        self.members = members

    @property
    def packed_shape(self) -> tuple[int, ...]:
        return (len(self.members), *self.members[0].packed_shape)

    @property
    def scales_shape(self) -> tuple[int, ...]:
        return (len(self.members), *self.members[0].scales_shape)

    def packed_shard(self, l_sl: slice, k2_sl: slice, n_sl: slice) -> np.ndarray:
        return np.stack([m.packed_shard(k2_sl, n_sl) for m in self.members[l_sl]])

    def scales_shard(self, l_sl: slice, kb_sl: slice, n_sl: slice) -> np.ndarray:
        return np.stack([m.scales_shard(kb_sl, n_sl) for m in self.members[l_sl]])

    def eager(self) -> QTensor:
        parts = [m.eager() for m in self.members]
        return QTensor(
            np.stack([p.packed for p in parts]), np.stack([p.scales for p in parts])
        )


def _load_matmul(raw: np.ndarray, shape: tuple[int, int], ft: FloatType, dtype, dequantize: bool,
                 lazy: bool = False, q80_packed: bool = False):
    """File [out, in] -> host-resident x@W operand: QTensor/Q8Tensor or dense [in, out]."""
    n_out, k_in = shape
    if ft == FloatType.Q40 and not dequantize:
        if lazy:
            return LazyQ40(raw, n_out, k_in)
        rec = raw.reshape(n_out * k_in // Q_BLOCK, 2 + Q_BLOCK // 2)
        scales = rec[:, :2].copy().view(np.float16)
        packed = rec[:, 2:]
        return QTensor.from_file_layout(packed, scales, n_out, k_in, device=False)
    if ft == FloatType.Q80 and q80_packed and not dequantize:
        # keep Q80 weights packed on device (int8 + f16 scales, 1.0625
        # bytes/weight vs 2 for the dense fallback); unsharded engines only —
        # the mesh slicers know QTensor/dense layouts, not Q8Tensor
        rec = raw.reshape(n_out * k_in // Q_BLOCK, 2 + Q_BLOCK)
        scales = rec[:, :2].copy().view(np.float16)
        codes = rec[:, 2:].view(np.int8)
        return Q8Tensor.from_file_layout(codes, scales, n_out, k_in, device=False)
    return decode_dense(raw, shape, ft).T.astype(dtype, order="C")


def _load_expert_matmul(raw: np.ndarray, shape: tuple[int, int, int], ft: FloatType, dtype, dequantize: bool):
    """File [E, out, in] blob -> expert-stacked host x@W operand [E, in, out]."""
    e, n_out, k_in = shape
    per = ft.nbytes(n_out * k_in)
    leaves = [
        _load_matmul(raw[i * per : (i + 1) * per], (n_out, k_in), ft, dtype, dequantize)
        for i in range(e)
    ]
    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *leaves)


def load_params(
    path: str,
    config: LlamaConfig,
    header_size: int,
    dtype=jnp.bfloat16,
    dequantize: bool = False,
    put: Callable[[str, object], object] | None = None,
    q80_packed: bool = False,
):
    """Load the full parameter pytree.

    Per-layer tensors are stacked on a leading layer axis so the model can
    `lax.scan` over layers (one XLA while-loop instead of n_layers copies of
    the graph — the TPU analog of the reference's per-layer segment list).

    `put(name, leaf)` receives each finished leaf as a *host* (numpy-backed or
    :class:`LazyQ40`/:class:`LazyQ40Stack`) pytree and decides device
    placement — the shard-direct path passes LlamaShardings.param_put so every
    tensor goes straight from the memmap to its device shards, and Q40 matmul
    weights stay LAZY: only the byte ranges of a host's own shards are ever
    decoded (no whole-model staging on any host or device; the reference's
    analog is slice-then-ship, nn-network.cpp:775-869). Default: eager
    host->default-device.
    """
    def default_put(name, x):
        if isinstance(x, (LazyQ40, LazyQ40Stack)):
            x = x.eager()
        return jax.tree.map(jnp.asarray, x)

    put = put or default_put
    layer_acc: dict[str, list] = {}
    params: dict = {}
    for name, shape, ft, raw in iter_tensors(path, config, header_size):
        if name in ("embedding",):
            params["embedding"] = put(name, decode_dense(raw, shape, ft).astype(dtype))
        elif name in ("final_norm",):
            params["final_norm"] = put(name, decode_dense(raw, shape, ft))
        elif name == "wcls":
            params["wcls"] = put(name, _load_matmul(raw, shape, ft, dtype, dequantize,
                                                    lazy=True, q80_packed=q80_packed))
        else:
            _, _, short = name.split(".")
            if short in ("rms_att", "rms_ffn"):
                leaf = decode_dense(raw, shape, ft)
            elif short == "moe_gate":
                # router stays f32; file [E, dim] -> h@gate operand [dim, E]
                leaf = decode_dense(raw, shape, ft).T.astype(np.float32, order="C")
            elif short.startswith("moe_"):
                leaf = _load_expert_matmul(raw, shape, ft, dtype, dequantize)
            else:
                leaf = _load_matmul(raw, shape, ft, dtype, dequantize, lazy=True,
                                    q80_packed=q80_packed)
            layer_acc.setdefault(short, []).append(leaf)

    layers = {}
    for short, leaves in layer_acc.items():
        if isinstance(leaves[0], LazyQ40):
            stacked = LazyQ40Stack(leaves)
        else:
            stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *leaves)
        layers[short] = put(f"layers.{short}", stacked)
    params["layers"] = layers
    return params
