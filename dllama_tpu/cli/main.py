"""CLI frontend — the analog of the reference's `dllama` binary
(dllama.cpp:207-229, app.cpp:21-110).

Modes:
  inference  one-shot generation from --prompt, with per-token timing and the
             tok/s summary (dllama.cpp:10-105's report shape)
  chat       REPL with chat template + streaming EOS detection
             (dllama.cpp:121-205)
  serve      OpenAI-compatible HTTP server (the `dllama-api` binary's role)
  router     multi-replica front: one address over N `serve` replicas with
             config handshake, health/drain polling, prefix-affinity
             routing, and failover (the reference ROOT node's role over
             its worker mesh, serve/router.py)
  info       print the model header (llm.cpp:100-123's dump)

There is no `worker` mode: the reference needs one process per node because
its nodes are TCP peers; here multi-chip is a jax.sharding.Mesh inside one
process (use --mesh tp=8 etc.), and multi-host runs launch the same command
on every host via jax.distributed.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dllama-tpu",
        description="TPU-native distributed-llama: tensor/sequence/data-parallel LLM inference",
    )
    p.add_argument("mode", choices=["inference", "chat", "serve", "info",
                                    "router"])
    # required for every mode except `router` (which owns no engine —
    # replicas own their weights); main() enforces it per mode
    p.add_argument("--model", default=None, help=".m model file "
                   "(required for every mode except router)")
    p.add_argument("--tokenizer", help=".t tokenizer file")
    p.add_argument("--prompt", help="prompt text (inference mode)")
    p.add_argument("--steps", type=int, default=64, help="max tokens to generate")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument("--presence-penalty", type=float, default=0.0,
                   help="subtract this from logits of any already-seen token "
                        "(OpenAI presence_penalty semantics)")
    p.add_argument("--frequency-penalty", type=float, default=0.0,
                   help="subtract count*this from logits per occurrence "
                        "(OpenAI frequency_penalty semantics)")
    p.add_argument("--exact-topp", action="store_true",
                   help="reference-exact nucleus: full-vocab sort per step instead "
                        "of the approx-top-256 candidate set (slower on big vocabs)")
    p.add_argument("--seed", type=int, default=None, help="sampler seed (default: time)")
    p.add_argument("--spec", type=int, default=0, metavar="K",
                   help="prompt-lookup speculative decoding with K-token drafts "
                        "(greedy runs only — bit-identical output, fewer forwards "
                        "on repetitive text; 0 = off). In serve mode this is the "
                        "legacy alias for --spec-k")
    p.add_argument("--spec-k", type=int, default=None, metavar="K",
                   help="serve mode, needs --slots > 0: per-request speculative "
                        "decoding capacity AND default — the engine compiles a "
                        "K-draft verify cycle, every request speculates at K "
                        "unless its body passes its own spec_k (0..K; 0 opts "
                        "out). Greedy token streams are BIT-IDENTICAL spec on "
                        "or off; sampled/penalized requests ride the cycles "
                        "one exact token at a time, so mixed traffic batches "
                        "together. Telemetry: dllama_spec_* series, spec "
                        "objects in timings//debug/perf (default: --spec, "
                        "else 0 = off)")
    p.add_argument("--max-seq-len", type=int, default=None, help="clamp context length (RAM cap)")
    p.add_argument(
        "--mesh",
        default="auto",
        help="device mesh spec 'tp=4,dp=2,sp=1' or 'auto' (all devices on tp)",
    )
    p.add_argument("--no-mesh", action="store_true", help="single-device even if more exist")
    p.add_argument("--cache-dtype", choices=["bf16", "f32", "f8"], default="bf16",
                   help="KV cache element type; f8 (e4m3) halves cache HBM "
                        "traffic/footprint — 2x the slots or context per chip "
                        "at a small accuracy cost")
    p.add_argument("--kv-layout", choices=["auto", "dense", "paged"],
                   default="auto",
                   help="serve mode, needs --slots > 0: KV cache layout. "
                        "'paged' backs slots with a refcounted page pool + "
                        "block tables instead of a full per-slot context "
                        "reservation — bit-exact token streams, prefix reuse "
                        "shares pages copy-free, and admission becomes "
                        "capacity-aware (defers when the pool can't cover "
                        "prompt + one decode page). 'auto' (default) picks "
                        "'paged' on unsharded engines where the paged "
                        "flash-decode kernel's capability check passes "
                        "(any 8-row-aligned page size; f8 caches and "
                        "meshes stay 'dense'). Pin 'dense' to opt out, or "
                        "'paged' to force the layout regardless of kernel "
                        "capability (see MIGRATION.md)")
    p.add_argument("--page-size", type=int, default=128,
                   help="paged KV cache: rows per page (must divide the "
                        "context length; kv-layout auto shrinks it to "
                        "gcd(page-size, context) so short contexts stay "
                        "paged; any multiple of 8 rides the Pallas paged "
                        "kernel — no 64-row tileability requirement)")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="paged KV cache: pool size in pages; 0 = full "
                        "coverage (slots x context / page-size — same "
                        "capacity as dense). Smaller pools overcommit "
                        "capacity: more slots than HBM could densely hold, "
                        "admission-gated by actual page demand")
    p.add_argument("--kv-host-pages", type=int, default=0,
                   help="paged KV cache + radix cache: host-RAM spill tier "
                        "size in pages (0 = off). Radix LRU eviction swaps "
                        "cold pages device-to-host instead of discarding; a "
                        "returning prompt re-uploads them at admission and "
                        "re-prefills only what the tiers can't cover. "
                        "Transfers are billed (dllama_kv_spill_total, "
                        "kv_spill/kv_restore transfer sites); occupancy at "
                        "dllama_kv_host_pages_{total,used}")
    p.add_argument("--radix-cache", choices=["auto", "on", "off"],
                   default="auto",
                   help="serve mode, needs --slots > 0: cross-request radix "
                        "prefix cache over the paged KV pool — a global tree "
                        "keyed on token ids whose nodes hold refcounted page "
                        "references; admissions map the longest shared "
                        "prefix for free (shared system prompts, few-shot "
                        "templates, multi-turn chat become O(new tokens) "
                        "prefill), LRU leaves are reclaimed under capacity "
                        "pressure. 'auto' (default) = on whenever the KV "
                        "layout is paged; token streams are bit-exact on or "
                        "off. Telemetry: dllama_radix_* series, "
                        "GET /debug/radix")
    p.add_argument("--max-prefill-chunk", type=int, default=256,
                   help="prefill chunk cap (pow-2 chunks; larger = better MXU "
                        "utilization, more HBM for activations)")
    p.add_argument("--dequantize", action="store_true", help="load Q40 weights as bf16 (faster prefill, 4x HBM)")
    p.add_argument("--port", type=int, default=None,
                   help="HTTP port (default: 9990 in serve mode, 9980 in "
                        "router mode)")
    p.add_argument("--host", default="127.0.0.1",
                   help="HTTP bind address (serve/router modes)")
    p.add_argument("--frontend", choices=["aio", "threads"], default="aio",
                   help="serve mode: connection transport. 'aio' (default) "
                        "multiplexes every connection — accept, parse, SSE "
                        "fan-out, disconnect detection — on one selectors "
                        "event loop with a small fixed worker pool and one "
                        "SSE pump thread, so thousands of streams cost "
                        "thousands of sockets, not thousands of threads "
                        "(dllama_process_threads stays flat). 'threads' "
                        "keeps the thread-per-connection stdlib server as "
                        "the A/B baseline. Routes and HTTP semantics are "
                        "identical")
    p.add_argument("--aio-workers", type=int, default=0,
                   help="serve mode, --frontend aio: request-handling "
                        "worker threads (0 = min(8, cores); streams don't "
                        "occupy workers — only non-streaming completions "
                        "and probe/debug endpoints do)")
    p.add_argument("--sse-heartbeat-s", type=float, default=15.0,
                   help="serve mode: emit a `: keep-alive` SSE comment "
                        "frame on streams idle this long, so router/LB "
                        "idle timeouts can't kill a slow-decode stream "
                        "(0 = off; default 15)")
    p.add_argument("--replica-id", default=None,
                   help="serve mode: identity stamped on every response "
                        "(X-Replica-Id header + timings.replica) for "
                        "end-to-end attribution through the router "
                        "(default: host:port of the bound socket)")
    p.add_argument("--replica", action="append", default=None,
                   metavar="HOST:PORT",
                   help="router mode (repeatable, at least one): an engine "
                        "replica to front — a normal `dllama-tpu serve` "
                        "process; the router handshakes its config, polls "
                        "its health, and routes/fails-over across the set")
    p.add_argument("--affinity", choices=["on", "off"], default="on",
                   help="router mode: prefix-affinity routing — pin each "
                        "request's prefix fingerprint (shared system "
                        "prompt / leading prompt bytes) to the replica "
                        "that served it last, so the radix prefix cache "
                        "is warm (off = pure least-loaded, the A/B "
                        "baseline)")
    p.add_argument("--poll-s", type=float, default=0.5,
                   help="router mode: replica /health poll cadence in "
                        "seconds")
    p.add_argument("--router-workers", type=int, default=16,
                   help="router mode: worker threads (each in-flight "
                        "proxied request occupies one for its upstream "
                        "I/O)")
    p.add_argument("--failover-max", type=int, default=2,
                   help="router mode: mid-stream failover budget — resume "
                        "attempts per journaled stream when its replica "
                        "dies mid-SSE (capped exponential backoff with "
                        "jitter; 0 = fail the stream exactly once with "
                        "finish_reason=error, the pre-failover contract)")
    p.add_argument("--fleet-obs", choices=["on", "off"], default="on",
                   help="router mode: the mesh observability plane — "
                        "distributed trace propagation (X-Dllama-Trace hop "
                        "header + router-side spans), per-replica clock-"
                        "offset estimation, and the /router/trace|metrics|"
                        "fleet|requests/{id} fleet endpoints stay up but "
                        "empty of router spans when off (the bench A/B "
                        "baseline)")
    p.add_argument("--slots", type=int, default=0,
                   help="serve mode: continuous-batching slots (0 = single-request + prefix cache)")
    p.add_argument("--overlap", choices=["on", "off"], default="on",
                   help="serve mode, needs --slots > 0: overlapped decode "
                        "pipeline — dispatch chunk N+1 off device-resident "
                        "state before chunk N's tokens are consumed, so host "
                        "scheduling runs concurrently with device compute "
                        "(token-level stops lag at most one chunk; overrun "
                        "tokens are discarded). 'off' restores the lockstep "
                        "loop for A/B — token streams are identical")
    p.add_argument("--admit-budget-ms", type=float, default=None,
                   help="serve mode, needs --slots > 0: LEGACY phase-split "
                        "admission only (--prefill-budget 0): max decode "
                        "stall (ms) a joining prompt's prefill may insert per "
                        "visit (default 250; 0 = strict one-chunk-per-decode "
                        "interleaving). With the hybrid step (the default) "
                        "admissions ride the decode chunks and this knob is "
                        "inert")
    p.add_argument("--prefill-budget", default="auto", metavar="{auto,N,0}",
                   help="serve mode, needs --slots > 0: hybrid chunked "
                        "prefill — each fused decode chunk co-processes up "
                        "to this many prompt tokens of an admitting request "
                        "in the SAME device launch, so a long prompt never "
                        "stalls running streams. 'auto' (default) steers the "
                        "budget online from the windowed ITL headroom "
                        "against --slo-itl-ms (holds 64 with no target); an "
                        "integer pins it; 0 restores the legacy phase-split "
                        "admission (the A/B baseline). Token streams are "
                        "bit-exact across all settings")
    p.add_argument("--preempt", choices=["auto", "on", "off"], default="auto",
                   help="serve mode, needs --slots > 0: preempt-to-pages — "
                        "a running lower-priority request may be suspended "
                        "at a chunk boundary when a strictly higher-priority "
                        "request is blocked (no free slot / KV capacity); "
                        "its pages stay referenced (radix tree) and the "
                        "stream later resumes byte-identical with near-zero "
                        "recompute. auto = on (default)")
    p.add_argument("--tenant-weight", action="append", default=None,
                   metavar="NAME=W",
                   help="serve mode, needs --slots > 0: weighted fair "
                        "queueing across tenants (the `tenant` request body "
                        "field) within each priority class — repeatable, "
                        "e.g. --tenant-weight paid=4 --tenant-weight free=1; "
                        "unlisted tenants weigh 1")
    p.add_argument("--warmup", choices=["auto", "off"], default="off",
                   help="serve mode, needs --slots > 0: precompile the "
                        "declared compiled-shape universe at boot (decode/"
                        "spec scans, pow2 prefill chunks, pow2 hybrid "
                        "budget slices, the commit sample — each x plain/"
                        "penalized) BEFORE the scheduler takes traffic, so "
                        "the first real request pays zero XLA compile. "
                        "Coverage + timings at GET /debug/compile; default "
                        "off (opt-in — boot takes the compile time instead)")
    p.add_argument("--transfer-guard", choices=["off", "log", "strict"],
                   default="off",
                   help="serve mode, needs --slots > 0: guard the steady-"
                        "state decode/spec dispatch window with "
                        "jax.transfer_guard — every operand there is a "
                        "device-resident carry, so 'strict' turns an "
                        "unexpected implicit host->device upload (the PR 3 "
                        "invariant breaking) into an error instead of a "
                        "silently serialized pipeline; 'log' logs them. "
                        "Transfer accounting (dllama_transfers_total) is "
                        "always on regardless")
    p.add_argument("--admit-ttft-deadline-ms", type=float, default=None,
                   help="serve mode, needs --slots > 0: joiners older than this "
                        "pump their prefill to completion despite the stall "
                        "budget (hard TTFT bound; default off)")
    p.add_argument("--max-queue", type=int, default=0,
                   help="serve mode, needs --slots > 0: bound the admission "
                        "queue — requests beyond this depth are shed with "
                        "HTTP 429 + Retry-After (0 = unbounded)")
    p.add_argument("--stall-deadline-s", type=float, default=0.0,
                   help="serve mode, needs --slots > 0: watchdog deadline — a "
                        "device chunk silent for longer flips /health to "
                        "unhealthy (0 = watchdog off)")
    p.add_argument("--restart-max", type=int, default=0,
                   help="serve mode, needs --slots > 0: self-healing — on a "
                        "worker crash, warm-restart the engine in-process "
                        "(decode state + KV pool rebuilt against resident "
                        "weights, NO model reload; queued requests survive, "
                        "in-flight ones resume bit-exact) at most this many "
                        "times per --restart-window-s, with exponential "
                        "backoff. 0 = any crash is permanently unhealthy "
                        "(external supervisor owns the restart)")
    p.add_argument("--restart-window-s", type=float, default=60.0,
                   help="serve mode: the sliding window the --restart-max "
                        "budget counts warm restarts in; budget exhausted "
                        "within the window = stay down (default 60)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="serve mode: TTFT SLO target in ms — terminal "
                        "requests over it burn dllama_slo_violations_total"
                        "{kind=ttft} and drop out of goodput; windowed "
                        "attainment at /debug/perf and "
                        "dllama_slo_attainment. Router mode: same target "
                        "judged from the CLIENT's seat (failover gaps "
                        "included) into dllama_router_slo_attainment and "
                        "GET /router/fleet (default: no target)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="serve AND router mode: inter-token-latency SLO "
                        "target in ms "
                        "(mean ITL per request, same derivation as the "
                        "itl_ms metrics); violations burn "
                        "dllama_slo_violations_total{kind=itl} "
                        "(default: no target)")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="serve mode: on SIGTERM, stop admission (503) and "
                        "give in-flight requests this long to finish before "
                        "shutting down")
    p.add_argument("--faults", default=None, metavar="SPEC",
                   help="arm deterministic fault injection (testing/drills): "
                        "comma-separated point:action[:k=v...] clauses, e.g. "
                        "'engine.decode:raise:after=2' — see "
                        "dllama_tpu/utils/faults.py (also: $DLLAMA_FAULTS)")
    p.add_argument("--kernels", choices=["auto", "pallas", "xla"], default="auto")
    p.add_argument("--fuse-weights", action="store_true",
                   help="fused wqkv/w13 kernel launches (single-device engines; "
                        "ignored on a mesh)")
    p.add_argument("--moe", choices=["auto", "dispatch", "sort", "dense"], default="auto",
                   help="MoE compute: capacity-bucketed dispatch (O(k) FLOPs, rare "
                        "capacity drops), sort (grouped-GEMM ragged segments — "
                        "O(k) FLOPs AND exact), or exact dense all-experts")
    p.add_argument("--sync", choices=["bf16", "q80", "auto"], default="bf16",
                   help="tp activation exchange: bf16 (exact, default), q80 "
                        "(the reference's quantized payload), or auto — the "
                        "measured recommendation: q80 only at tp=2, where it "
                        "wins on BOTH byte accountings; at tp>=4 the compiled "
                        "HLO says the gather formulation costs more "
                        "(COLLECTIVES.md)")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host: jax.distributed.initialize (run the same command on every host)")
    p.add_argument("--coordinator", default=None, help="host:port rendezvous (omit on TPU pods)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--log-format", choices=["text", "json"], default="text",
                   help="log line format: text (human) or json (one structured "
                        "object per line — request_id and other context as "
                        "fields; see dllama_tpu/utils/logs.py for the schema)")
    p.add_argument("--trace", metavar="DIR", help="write a jax.profiler trace "
                   "(XProf/TensorBoard; serve mode can instead capture on "
                   "demand via POST /debug/profile)")
    p.add_argument("--trace-buffer", type=int, default=2048, metavar="N",
                   help="request-flow span tracer: ring capacity in events "
                        "(serve mode exports it at GET /debug/trace — loads "
                        "in Perfetto — and GET /debug/requests, the "
                        "per-request flight recorder). 0 disables tracing "
                        "entirely: a no-op tracer, nothing recorded or "
                        "allocated (default 2048)")
    p.add_argument("--report", action="store_true",
                   help="print memory + per-token latency + collective-payload report")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def _load(args):
    import jax.numpy as jnp

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.ops import matmul

    if args.distributed:
        from dllama_tpu.parallel.multihost import initialize

        initialize(args.coordinator, args.num_processes, args.process_id)
    matmul.BACKEND = args.kernels
    if args.exact_topp:
        # must land before the first sampler trace — NUCLEUS_K is a
        # trace-time constant of the fused decode step
        from dllama_tpu.engine import sampling

        sampling.NUCLEUS_K = None
    return load_model(
        args.model,
        args.tokenizer,
        max_seq_len=args.max_seq_len,
        mesh=None if args.no_mesh else args.mesh,
        cache_dtype={"bf16": jnp.bfloat16, "f32": jnp.float32,
                     "f8": jnp.float8_e4m3fn}[args.cache_dtype],
        dequantize=args.dequantize,
        max_prefill_chunk=args.max_prefill_chunk,
        sync=args.sync,
        kernels=args.kernels,
        moe_impl=args.moe,
        fuse_weights=args.fuse_weights,
    )


def cmd_info(args) -> int:
    from dllama_tpu.models.formats import read_header, tensor_plan

    cfg, header_size = read_header(args.model, args.max_seq_len)
    print(cfg.describe())
    total = sum(
        cfg.weight_type.nbytes(int(np.prod(shape))) if ft == cfg.weight_type else ft.nbytes(int(np.prod(shape)))
        for _, shape, ft in tensor_plan(cfg)
    )
    print(f"header: {header_size} B, weights: {total / 1e9:.2f} GB on disk")
    # what would actually run here: resolved matmul backend / attention impl
    # (the reference prints its CPU features at startup, nn-cpu-ops.cpp:
    # 1276-1294 — this is the TPU-side equivalent)
    try:
        from dllama_tpu.engine.kernel_select import resolve_kernels

        sel = resolve_kernels(cfg, cfg.seq_len, 1, args.kernels)
        attn = "flash" if sel.attn_fn is not None else "jnp"
        import jax

        print(f"this host: {len(jax.devices())}x {jax.devices()[0].platform}; "
              f"kernels={sel.backend} attention={attn}")
    except Exception as e:  # info must never fail on backend trouble
        print(f"this host: backend unavailable ({e!r})"[:120])
    return 0


def cmd_inference(args) -> int:
    from dllama_tpu.engine.engine import GenerationStats
    from dllama_tpu.engine.sampling import Sampler

    if not args.prompt:
        print("inference mode requires --prompt", file=sys.stderr)
        return 1
    if not args.tokenizer:
        print("inference mode requires --tokenizer", file=sys.stderr)
        return 1
    m = _load(args)
    tok = m.tokenizer
    sampler = Sampler(args.temperature, args.topp,
                      args.seed if args.seed is not None else int(time.time()),
                      presence=args.presence_penalty, frequency=args.frequency_penalty)
    prompt_tokens = tok.encode(args.prompt, add_bos=True)
    max_tokens = min(args.steps, m.engine.seq_len - len(prompt_tokens))
    stats = GenerationStats()

    from dllama_tpu.utils import profiling

    timer = profiling.TokenTimer()
    tok.reset_decoder()
    with profiling.trace(args.trace):
        timer.start()
        for t in m.engine.generate(
            prompt_tokens, max_tokens, sampler, stop_fn=tok.is_eos, stats=stats,
            spec=args.spec,
        ):
            timer.stop()
            piece = tok.decode(t)
            if piece:
                print(piece, end="", flush=True)
            timer.start()
    print()
    print(stats.summary(), file=sys.stderr)
    if args.report:
        print(profiling.memory_report(m.config, m.engine.params, m.engine.cache), file=sys.stderr)
        print(f"⏱  {timer.summary()}", file=sys.stderr)
        shape = dict(m.shardings.mesh.shape) if m.shardings else {}
        tp, sp = shape.get("tp", 1), shape.get("sp", 1)
        est = profiling.collective_bytes_per_token(m.config, tp=tp, sp=sp)
        print(
            f"🔗 est. inter-chip payload: {est['kb_per_token_per_chip']:.0f} kB/token/chip "
            f"(tp={tp} sp={sp})",
            file=sys.stderr,
        )
        # measured counterpart: the collective ops in the compiled step
        # (nn-network.cpp:483-492 counts real socket bytes; this counts the
        # real HLO collectives — scan bodies once per trip, see docstring)
        meas = m.engine.measured_collective_report()
        ops = ", ".join(f"{k}={v / 1024:.1f}kB" for k, v in meas["per_op"].items()) or "none"
        print(
            f"🔗 measured in compiled step: {meas['total_bytes'] / 1024:.1f} kB ({ops})",
            file=sys.stderr,
        )
    return 0


def cmd_chat(args) -> int:
    from dllama_tpu.engine.sampling import Sampler
    from dllama_tpu.tokenizer.chat import (
        ChatItem,
        ChatTemplate,
        ChatTemplateType,
        EosDetector,
        EosResult,
        chat_stops,
    )

    if not args.tokenizer:
        print("chat mode requires --tokenizer", file=sys.stderr)
        return 1
    m = _load(args)
    tok = m.tokenizer
    template = ChatTemplate(ChatTemplateType.UNKNOWN, tok.chat_template, "")
    stops = chat_stops(tok)
    sampler = Sampler(args.temperature, args.topp,
                      args.seed if args.seed is not None else int(time.time()),
                      presence=args.presence_penalty, frequency=args.frequency_penalty)

    print("💬 chat mode — empty line or Ctrl-D to exit")
    try:
        system = input("📢 system: ").strip()
    except EOFError:
        return 0
    items: list[ChatItem] = []
    if system:
        items.append(ChatItem("system", system))

    first = True
    while True:
        try:
            user = input("👱 user: ").strip()
        except EOFError:
            break
        if not user:
            break
        items.append(ChatItem("user", user))
        generated = template.generate(items, append_generation_prompt=True)
        # feed only the delta since the engine's KV cache holds the history
        prompt_tokens = tok.encode(generated.content, add_bos=first)
        items = []  # history lives in the KV cache from here on
        first = False
        if generated.public_prompt:
            print(generated.public_prompt, end="")

        detector = EosDetector(tok.eos_ids, stops, padding_left=2, padding_right=2)
        tok.reset_decoder()
        print("🤖 assistant: ", end="", flush=True)
        budget = m.engine.seq_len - m.engine.pos - len(prompt_tokens) - 1
        if budget <= 0:
            print("(context window exhausted)")
            break
        for t in m.engine.generate(prompt_tokens, budget, sampler, spec=args.spec):
            piece = tok.decode(t)
            res = detector.append(t, piece)
            delta = detector.get_delta()
            if delta:
                print(delta, end="", flush=True)
            if res == EosResult.EOS:
                break
        else:
            delta = detector.flush()
            if delta:
                print(delta, end="", flush=True)
        print()
    return 0


def _parse_tenant_weights(specs) -> dict[str, float] | None:
    """--tenant-weight NAME=W (repeatable) -> {name: weight}; malformed
    specs fail startup with a clear message instead of silently weighing 1."""
    if not specs:
        return None
    import math

    out: dict[str, float] = {}
    for spec in specs:
        name, sep, w = str(spec).partition("=")
        try:
            weight = float(w)
        except ValueError:
            weight = 0.0
        # non-finite weights corrupt the fair queue silently (NaN poisons
        # every tag comparison, inf zeroes a tenant's cost and starves the
        # rest) — reject them with the same startup error as w <= 0
        if not sep or not name or not math.isfinite(weight) or weight <= 0:
            raise SystemExit(
                f"--tenant-weight {spec!r}: expected NAME=W with finite "
                "W > 0")
        out[name] = weight
    return out


def cmd_serve(args) -> int:
    from dllama_tpu.serve.api import run_server

    m = _load(args)
    if m.tokenizer is None:
        print("serve mode requires --tokenizer", file=sys.stderr)
        return 1
    prefill_budget = args.prefill_budget
    if prefill_budget != "auto":
        try:
            prefill_budget = int(prefill_budget)
        except ValueError:
            print(f"--prefill-budget must be 'auto' or an integer, got "
                  f"{prefill_budget!r}", file=sys.stderr)
            return 1
        if prefill_budget < 0:
            print("--prefill-budget must be >= 0", file=sys.stderr)
            return 1
    return run_server(
        m,
        host=args.host,
        port=args.port if args.port is not None else 9990,
        n_slots=args.slots,
        default_temperature=args.temperature,
        default_topp=args.topp,
        # --spec-k is the serving-tier knob; --spec remains the legacy
        # alias (and the single-engine tier's greedy spec toggle)
        spec=args.spec_k if args.spec_k is not None else args.spec,
        default_seed=args.seed,
        admit_stall_budget_ms=args.admit_budget_ms,
        admit_ttft_deadline_ms=args.admit_ttft_deadline_ms,
        max_queue=args.max_queue,
        stall_deadline_s=args.stall_deadline_s,
        restart_max=args.restart_max,
        restart_window_s=args.restart_window_s,
        drain_timeout_s=args.drain_timeout_s,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_itl_ms=args.slo_itl_ms,
        overlap=args.overlap == "on",
        kv_layout=args.kv_layout,
        page_size=args.page_size,
        kv_pages=args.kv_pages,
        kv_host_pages=args.kv_host_pages,
        radix_cache=args.radix_cache,
        prefill_budget=prefill_budget,
        preempt=args.preempt,
        tenant_weights=_parse_tenant_weights(args.tenant_weight),
        warmup=args.warmup,
        transfer_guard=args.transfer_guard,
        frontend=args.frontend,
        aio_workers=args.aio_workers,
        sse_heartbeat_s=args.sse_heartbeat_s,
        replica_id=args.replica_id,
    )


def cmd_router(args) -> int:
    from dllama_tpu.serve.router import run_router

    if not args.replica:
        print("router mode requires at least one --replica HOST:PORT",
              file=sys.stderr)
        return 1
    port = args.port if args.port is not None else 9980  # router's default
    return run_router(
        args.replica,
        host=args.host,
        port=port,
        poll_s=args.poll_s,
        affinity=args.affinity == "on",
        workers=args.router_workers,
        drain_timeout_s=args.drain_timeout_s,
        failover_max=args.failover_max,
        fleet_obs=args.fleet_obs == "on",
        trace_capacity=args.trace_buffer,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_itl_ms=args.slo_itl_ms,
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.mode != "router" and not args.model:
        print(f"{args.mode} mode requires --model", file=sys.stderr)
        return 1
    from dllama_tpu.utils.logs import setup_logging

    # shared logger setup (utils/logs.py): --log-format json switches every
    # line to one structured object with request_id/fault_point/... fields
    setup_logging(fmt=args.log_format, verbose=args.verbose)
    # request-flow tracing rides every mode (serve exposes it over /debug/*;
    # inference/chat record into the same in-process ring) — configured
    # before anything that could emit a span
    from dllama_tpu.obs import trace

    trace.configure(args.trace_buffer)
    from dllama_tpu.utils import faults

    # $DLLAMA_FAULTS first, --faults wins when both are set; a bad spec
    # fails startup here, not by silently never firing
    faults.configure_from_env()
    if args.faults:
        faults.configure(args.faults)
    return {
        "info": cmd_info,
        "inference": cmd_inference,
        "chat": cmd_chat,
        "serve": cmd_serve,
        "router": cmd_router,
    }[args.mode](args)


if __name__ == "__main__":
    raise SystemExit(main())
