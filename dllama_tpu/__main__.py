"""`python -m dllama_tpu` — the `dllama` binary equivalent."""

from dllama_tpu.cli.main import main

raise SystemExit(main())
