"""OpenAI-compatible API client — the reference's examples/chat-api-client.js
in stdlib Python, against `python -m dllama_tpu serve`.

Usage: python examples/api_client.py [--port 9990] [--stream] "your message"
"""

import argparse
import json
import sys
import urllib.request


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("message", nargs="?", default="What is the capital of France?")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--stream", action="store_true")
    p.add_argument("--max-tokens", type=int, default=128)
    args = p.parse_args()

    body = {
        "model": "dllama-tpu",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user", "content": args.message},
        ],
        "temperature": 0.7,
        "max_tokens": args.max_tokens,
        "stream": args.stream,
    }
    req = urllib.request.Request(
        f"http://{args.host}:{args.port}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        if not args.stream:
            out = json.load(r)
            print(out["choices"][0]["message"]["content"])
            print(f"usage: {out.get('usage')}", file=sys.stderr)
            return 0
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data:"):
                continue
            payload = line[5:].strip()
            if payload == "[DONE]":
                break
            delta = json.loads(payload)["choices"][0]["delta"]
            print(delta.get("content", ""), end="", flush=True)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
