"""OpenAI-compatible API client — the reference's examples/chat-api-client.js
in stdlib Python, against `python -m dllama_tpu serve`.

Usage: python examples/api_client.py [--port 9990] [--stream] "your message"

`--concurrency N` sends the request N times at once and prints per-request
TTFT / total latency — against a `--slots` server the requests share the
device through the continuous-batching scheduler (aggregate wall time well
under N * single-request time); against the single-engine tier they
serialize. The reference's server is single-request blocking
(dllama-api.cpp:522-533), so this demo has no counterpart there.
"""

import argparse
import json
import sys
import threading
import time
import urllib.request


def iter_sse_content(resp):
    """Yield the content string of each SSE delta chunk until [DONE]."""
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        payload = line[5:].strip()
        if payload == "[DONE]":
            break
        delta = json.loads(payload)["choices"][0]["delta"]
        yield delta.get("content", "")


def _one_request(url: str, body: dict, idx: int, results: list) -> None:
    t0 = time.perf_counter()
    req = urllib.request.Request(
        url, data=json.dumps(dict(body, stream=True)).encode(),
        headers={"Content-Type": "application/json"},
    )
    ttft = None
    chars = 0
    try:
        with urllib.request.urlopen(req, timeout=600) as r:
            for text in iter_sse_content(r):
                if text and ttft is None:
                    ttft = time.perf_counter() - t0
                chars += len(text)
    except Exception as e:  # server down/stalled: keep the FAILED path clean
        print(f"req {idx}: {e!r}"[:200], file=sys.stderr)
        return
    results[idx] = (ttft, time.perf_counter() - t0, chars)


def run_concurrent(url: str, body: dict, n: int) -> int:
    results: list = [None] * n
    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=_one_request, args=(url, body, i, results))
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for i, r in enumerate(results):
        if r is None:
            print(f"req {i}: FAILED")
            continue
        ttft, total, chars = r
        ttft_s = "n/a" if ttft is None else f"{ttft:.2f}s"  # zero visible
        # text (held-back stop bytes, instant EOS) leaves ttft unset
        print(f"req {i}: ttft={ttft_s} total={total:.2f}s chars={chars}")
    done = [r for r in results if r is not None]
    if done:
        print(f"aggregate: {n} requests in {wall:.2f}s wall "
              f"(sum of individual times {sum(r[1] for r in done):.2f}s — "
              f"well under it means the batch shared the device)")
    return 0 if len(done) == n else 1


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("message", nargs="?", default="What is the capital of France?")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--stream", action="store_true")
    p.add_argument("--max-tokens", type=int, default=128)
    p.add_argument("--concurrency", type=int, default=0, metavar="N",
                   help="send the request N times at once (serve --slots M "
                        "shows continuous batching: N requests share the device)")
    args = p.parse_args()

    body = {
        "model": "dllama-tpu",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user", "content": args.message},
        ],
        "temperature": 0.7,
        "max_tokens": args.max_tokens,
        "stream": args.stream,
    }
    url = f"http://{args.host}:{args.port}/v1/chat/completions"
    if args.concurrency > 0:
        return run_concurrent(url, body, args.concurrency)
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        if not args.stream:
            out = json.load(r)
            print(out["choices"][0]["message"]["content"])
            print(f"usage: {out.get('usage')}", file=sys.stderr)
            return 0
        for text in iter_sse_content(r):
            print(text, end="", flush=True)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
