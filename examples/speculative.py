"""Speculative-decoding demo: same tokens, fewer forwards.

Runs plain fused greedy decode and prompt-lookup speculative decode
(engine/speculative.py) on a repetitive prompt and a random prompt, prints
tokens/forward and agreement. Synthetic weights — output ids are noise, the
point is the EXACTNESS (identical streams) and the forward-count accounting.

    env PYTHONPATH= JAX_PLATFORMS=cpu python examples/speculative.py
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params

cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=256, seq_len=256)
params = random_params(cfg, seed=0, dtype=jnp.bfloat16, quantize=True)

for label, prompt in (
    ("repetitive", ([17, 23, 5, 9] * 10)[:40]),
    ("random", list(np.random.default_rng(0).integers(1, cfg.vocab_size, 40))),
):
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16)
    logits = eng.prefill(np.asarray([prompt], np.int32))
    first = int(np.argmax(np.asarray(logits)[0]))
    ref = [int(t) for t in eng.decode_greedy_n(np.array([[first]]), 48)[:, 0]]

    eng2 = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16)
    eng2.prefill(np.asarray([prompt], np.int32))
    got = [int(t) for t in eng2.decode_spec_greedy_n(list(prompt), first, 48, k=8)]
    st = eng2._spec_stats
    print(f"{label:>10}: identical={got == ref}  "
          f"tokens/forward={st['emitted'] / st['cycles']:.2f}  "
          f"({st['emitted']} tokens in {st['cycles']} forwards vs 48 plain)")
