"""Determinism regression — the reference's examples/macbeth.sh role.

macbeth.sh feeds a long fixed prompt at temp~0 and diffs the continuation
against an expected text (it notes the output is only stable per CPU family).
This version needs no model download and is stable per *backend*: it builds a
synthetic Q40 model on disk, generates twice greedily through the full stack
(.m/.t load -> jit'd forward -> KV cache -> sampler), and also replays the
same prompt through a fresh engine — all three must agree token-for-token.

Run: python examples/determinism.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main() -> int:
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.engine.sampling import Sampler
    from dllama_tpu.models import formats
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.ops.quant import FloatType

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
                      vocab_size=512, seq_len=256, weight_type=FloatType.Q40)
    rng = np.random.default_rng(1234)
    tensors = {n: (rng.standard_normal(s) * 0.08).astype(np.float32)
               for n, s, _ in formats.tensor_plan(cfg)}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "det.m")
        formats.save_model(path, cfg, tensors)
        cfg2, hs = formats.read_header(path)
        params = formats.load_params(path, cfg2, hs, dtype=jnp.bfloat16)

        prompt = list(rng.integers(1, cfg.vocab_size, 48))
        sampler = Sampler(temperature=0.0, topp=0.9, seed=7)

        runs = []
        for _ in range(2):
            eng = InferenceEngine(cfg2, params, cache_dtype=jnp.bfloat16)
            runs.append(list(eng.generate(prompt, 64, sampler)))
        # same engine, rewound via reset (prefix-cache path)
        eng = InferenceEngine(cfg2, params, cache_dtype=jnp.bfloat16)
        first = list(eng.generate(prompt, 64, sampler))
        eng.reset(0)
        second = list(eng.generate(prompt, 64, sampler))

    ok = runs[0] == runs[1] == first == second
    print(f"tokens: {runs[0][:12]} ...")
    print("✅ deterministic" if ok else "❌ NONDETERMINISTIC")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
