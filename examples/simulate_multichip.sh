#!/bin/sh
# Multi-chip without a pod — the analog of the reference's examples/n-workers.sh
# (which spawns W localhost worker processes under `screen`). On TPU the mesh
# lives in one process, so simulation is just XLA's host-device splitting:
# 8 virtual devices, tensor-parallel over 'tp' (or any --mesh spec).
#
# Usage: sh examples/simulate_multichip.sh model.m tokenizer.t "prompt" [mesh]
set -e
MODEL=${1:?model.m}
TOK=${2:?tokenizer.t}
PROMPT=${3:-"Hello"}
MESH=${4:-tp=8}

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -m dllama_tpu inference \
    --model "$MODEL" --tokenizer "$TOK" --prompt "$PROMPT" \
    --mesh "$MESH" --steps 32 --temperature 0 --report
