"""Turn a TPU window's logs into concrete default recommendations.

The measurement session (tpu_session.sh) is fully unattended; this closes
the loop on the other side: parse the kbench/ebench/bench logs it left in
experiments/logs/ and print, mechanically, the decisions PLAYBOOK.md
describes in prose — decode style ranking, blockdot tile override, prefill
GEMM routing, flash bucketing flip, unroll choice, MoE scheme. Every
recommendation cites the numbers it derives from, so a round's
"committed with data" defaults are reproducible from the logs alone.

Usage: python experiments/decide.py [LOGS_DIR]   (default experiments/logs)
Exit 0 always; prints NO-DATA sections for stages that never ran. Pure
stdlib/regex — safe to run anywhere, no JAX import.
"""

from __future__ import annotations

import glob
import os
import re
import sys


def _latest(d: str, pat: str, must_contain: str | None = None) -> str | None:
    # by mtime, not name: session logs use time-of-day-only timestamps, so
    # a lexically-late log from yesterday must not shadow today's; filename
    # tiebreak keeps equal-mtime checkouts deterministic. must_contain skips
    # newer-but-empty logs (e.g. a wedged full bench must not hide the
    # window's earlier quick-bench record)
    files = sorted(glob.glob(os.path.join(d, pat)),
                   key=lambda p: (os.path.getmtime(p), p), reverse=True)
    for p in files:
        if must_contain is None or must_contain in _read(p):
            return p
    return files[0] if files else None


def _read(path: str | None) -> str:
    if path is None:
        return ""
    with open(path, errors="replace") as f:
        return f.read()


_ROW = re.compile(r"(\w[\w-]*) ([\w.-]+)=(\d+)us\((\d+)GB/s\)")


def parse_kbench_rows(text: str) -> dict[str, dict[str, tuple[int, int]]]:
    """-> {"m=8 w1": {"BD": (us, gbs), ...}, ...} from run_one output."""
    out: dict[str, dict[str, tuple[int, int]]] = {}
    for line in text.splitlines():
        m = re.match(r"(m=\d+ \w+): (.*)", line)
        if not m:
            continue
        rows = {}
        for code, _name, us, gbs in _ROW.findall(m.group(2)):
            rows[code] = (int(us), int(gbs))
        if rows:
            out[m.group(1)] = rows
    return out


def decide_kbench(text: str) -> list[str]:
    rec: list[str] = []
    rows = parse_kbench_rows(text)

    dec = rows.get("m=8 w1", {})
    styles = {c: dec[c] for c in ("BD", "LD", "MD", "DQ") if c in dec}
    if styles:
        best = min(styles, key=lambda c: styles[c][0])
        name = {"BD": "blockdot", "LD": "loopdot", "MD": "maskdot", "DQ": "deq"}[best]
        detail = " ".join(f"{c}={styles[c][0]}us" for c in styles)
        if best == "BD":
            rec.append(f"decode STYLE: keep 'auto' (blockdot fastest: {detail})")
        else:
            rec.append(f"decode STYLE: set q40_matmul.STYLE='{name}' ({detail})")
        if "D" in dec and dec[best][1] and dec["D"][1]:
            ratio = dec[best][1] / dec["D"][1]
            rec.append(f"  decode GB/s vs bf16 roofline kernel: {ratio:.2f}x "
                       f"({dec[best][1]} vs {dec['D'][1]} GB/s; >=0.7x is healthy)")
    else:
        rec.append("decode STYLE: NO-DATA (no m=8 w1 rows)")

    m_sweep = re.search(r"tile sweep m=\d+ \w+ best-first: (\S+)=(\d+)us", text)
    if m_sweep and styles and "BD" in styles:
        tk_tn = re.match(r"tk(\d+)/tn(\d+)", m_sweep.group(1))
        if tk_tn and int(m_sweep.group(2)) < 0.9 * styles["BD"][0]:
            rec.append(f"blockdot tiles: set BLOCKDOT_TK={tk_tn.group(1)}, "
                       f"BLOCKDOT_TN={tk_tn.group(2)} "
                       f"({m_sweep.group(2)}us vs default {styles['BD'][0]}us, >10% win)")
        elif tk_tn:
            rec.append("blockdot tiles: keep defaults (sweep best "
                       f"{m_sweep.group(2)}us is not >10% under the default pick)")

    for label in ("m=256 w1", "m=512 w1", "m=32 w1"):
        pf = rows.get(label, {})
        if "DQ" in pf and "E" in pf:
            if pf["E"][0] < 0.9 * pf["DQ"][0]:
                rec.append(f"prefill route: set matmul.XLA_PREFILL_MIN_M={label.split()[0][2:]} "
                           f"(E={pf['E'][0]}us beats DQ={pf['DQ'][0]}us at {label})")
            else:
                rec.append(f"prefill route: keep fused (DQ={pf['DQ'][0]}us vs "
                           f"E={pf['E'][0]}us at {label})")
            break
    if "Q8" in dec and "D" in dec:
        rec.append(f"q80 fused path: {dec['Q8'][1]} GB/s vs bf16 {dec['D'][1]} GB/s "
                   f"(informational — Q80-file models only)")

    # flash depth sweep: static vs bucketed at the shallowest position
    stat = {int(p): int(us) for p, us in
            re.findall(r"flash decode S=\d+ pos=(\d+): (\d+)us", text)}
    buck = {int(p): int(us) for p, us in
            re.findall(r"flash decode BUCKETED S=\d+ pos=(\d+): (\d+)us", text)}
    common = sorted(set(stat) & set(buck))
    if common:
        p0, p1 = common[0], common[-1]
        win = stat[p0] / buck[p0] if buck[p0] else 0.0
        deep_ok = buck[p1] <= 1.15 * stat[p1]
        if win >= 1.3 and deep_ok:
            rec.append(f"flash buckets: FLIP DLLAMA_FLASH_BUCKETS=1 default "
                       f"(pos={p0}: {stat[p0]}us -> {buck[p0]}us, {win:.1f}x; "
                       f"deep pos={p1} within 15%: {stat[p1]} vs {buck[p1]}us)")
        else:
            rec.append(f"flash buckets: keep off (pos={p0} win {win:.2f}x, "
                       f"deep pos={p1}: static {stat[p1]}us vs bucketed {buck[p1]}us)")
    return rec


def decide_ebench(text: str) -> list[str]:
    rec = []
    rows = {m.group(1).strip(): float(m.group(2)) for m in
            re.finditer(r"^([\w+ -]+): decode=[\d.]+ms/tok \((\d+)tok/s\)",
                        text, re.M)}
    if rows:
        best = max(rows, key=rows.get)
        rec.append(f"engine knobs: best decode config '{best}' "
                   f"({rows[best]:.1f} tok/s; all: "
                   + " ".join(f"{k}={v:.1f}" for k, v in sorted(rows.items())) + ")")
    else:
        rec.append("engine knobs: NO-DATA (no ebench decode rows parsed)")
    return rec


def decide_bench(text: str) -> list[str]:
    rec = []
    m = re.search(r'\{.*"vs_baseline".*\}', text)
    if not m:
        return ["bench: NO-DATA (no JSON record line)"]
    import json

    try:
        r = json.loads(m.group(0))
    except ValueError:
        return ["bench: JSON record unparsable"]
    rec.append(f"bench headline: {r.get('value')} {r.get('unit')} "
               f"(vs_baseline {r.get('vs_baseline')}, "
               f"tpu={'NO' if r.get('tpu_unavailable') else 'yes'})")
    moe = r.get("moe") or {}
    times = {k: moe[k] for k in ("sort_ms", "dispatch_ms", "dense_ms") if k in moe}
    if times:
        best = min(times, key=times.get)
        rec.append(f"moe auto: '{best.split('_')[0]}' is fastest "
                   + " ".join(f"{k}={v}" for k, v in times.items())
                   + (" — matches the shipped default" if best == "sort_ms"
                      else " — flip ops.layers auto accordingly"))
    pre = r.get("presets") or {}
    base = pre.get("8b_long") or {}
    ab = pre.get("8b_long_bucketed") or {}
    if "decode_ms_per_token" in base and "decode_ms_per_token" in ab:
        rec.append(f"8b_long bucketed A/B: {base['decode_ms_per_token']}ms -> "
                   f"{ab['decode_ms_per_token']}ms per token "
                   + ("(flip DLLAMA_FLASH_BUCKETS=1)"
                      if ab["decode_ms_per_token"] < 0.9 * base["decode_ms_per_token"]
                      else "(keep off)"))
    return rec


def decide_abench(text: str) -> list[str]:
    """Three-mode admission record (sync/strict/paced) -> budget decision."""
    import ast

    rec = []
    rows: dict[str, dict] = {}
    for line in text.splitlines():
        if not line.startswith("{'mode': "):
            continue
        try:
            r = ast.literal_eval(line.strip())  # abench prints dict reprs
        except (ValueError, SyntaxError):
            continue
        if isinstance(r, dict) and "mode" in r:
            rows[r["mode"]] = r
    if not rows:
        return ["admission: NO-DATA (no abench mode rows)"]
    for mode, r in rows.items():
        stall = r.get("sched_stall_ms_max")
        rec.append(f"  {mode}: stall_max={'n/a' if stall is None else f'{stall}ms'} "
                   f"ttft={r.get('long_ttft_ms')}ms")
    verdict = re.search(r"paced within 2x of best on stall .*: (PASS|FAIL)", text)
    if verdict:
        if verdict.group(1) == "PASS":
            rec.append("admission: keep 'paced' default (2x acceptance bar PASS)")
        else:
            rec.append("admission: paced FAILED the 2x bar — tune "
                       "admit_stall_budget_ms toward whichever metric "
                       "regressed (raise for TTFT, lower for stall)")
    return rec


def decide_wedge(d: str) -> list[str]:
    """Surface any WEDGE_DIAG verdict from the latest canary/control logs."""
    rec = []
    for pat in ("control_*.log", "canary_*.log"):
        text = _read(_latest(d, pat))
        for m in re.finditer(r"WEDGE_DIAG (verdict=\S+.*)", text):
            rec.append(f"{pat.split('_')[0]}: {m.group(1)}")
    return rec or ["wedge: no WEDGE_DIAG lines (canaries passed or never ran)"]


def main() -> None:
    d = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "logs")
    print("== wedge:")
    for line in decide_wedge(d):
        print("  " + line)
    for title, pat, fn, need in (
        ("kbench", "kbench_*.log", decide_kbench, None),
        ("ebench", "ebench_*.log", decide_ebench, None),
        ("abench", "abench_*.log", decide_abench, None),
        # newest bench log WITH a JSON record: a wedged full bench must not
        # hide the same window's quick-bench record
        ("bench", "bench_*.log", decide_bench, '"vs_baseline"'),
    ):
        path = _latest(d, pat, must_contain=need)
        print(f"== {title}: {os.path.basename(path) if path else 'NO LOG'}")
        for line in fn(_read(path)) if path else ():
            print("  " + line)
    print("DECIDE DONE")


if __name__ == "__main__":
    main()
