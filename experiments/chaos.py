"""Seeded chaos soak for the self-healing serving stack (ISSUE 6).

Drives mixed traffic — greedy / sampled / penalized, staggered submission,
a fraction carrying per-request deadlines — through a warm-restart-enabled
Scheduler while a seeded injector keeps arming random faults across the
catalog (engine.decode, engine.prefill, scheduler.loop, scheduler.queue,
pool.alloc, decode.nan; raise and delay actions). Every request streams
through its token queue, exactly like an SSE client.

What a passing soak proves, asserted at the end:

* **100% terminal**: every submitted request reaches a terminal state
  (stop/length/timeout/error/cancelled — or a clean admission shed); no
  client queue ever hangs;
* **allocator integrity**: ``PagePool.audit()`` is clean — including the
  radix prefix tree's page references reconciling exactly against the pool
  refcounts (the engine runs with the paged-default radix cache ON) — and,
  after idle prefix caches and the tree are dropped, ZERO pages remain
  referenced (no leaks across hundreds of crash/restart/timeout/error
  paths);
* **self-healing**: ``/health`` is back to live=true/ready=true once the
  fault schedule stops;
* **counter/trace reconciliation**: dllama_engine_restarts_total,
  dllama_requests_recovered_total and finished{reason="timeout"} deltas
  each equal their flight-recorder event counts (engine.restart /
  request.recovered / request.timeout), and the timeout counter matches
  what clients actually observed.

CLI::

    JAX_PLATFORMS=cpu python experiments/chaos.py --requests 200 --seed 0

(scripts/chaos_soak.sh wraps exactly that). tests/test_chaos.py runs a
bounded mini-soak through the same run_chaos() entry point in tier-1.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: fault points the injector cycles through. engine.restart is deliberately
#: NOT in the schedule: a raise there makes the restart itself die, which is
#: the budget-exhaustion drill (tests/test_faults.py), not a soak the stack
#: is supposed to survive.
FAULT_MENU = (
    ("engine.decode", "raise"),
    ("engine.decode", "delay"),
    ("engine.prefill", "raise"),
    ("scheduler.loop", "raise"),
    ("scheduler.queue", "raise"),
    ("pool.alloc", "raise"),
    ("decode.nan", "raise"),
    # host spill tier (ISSUE 16): a failed spill degrades to a discard, a
    # failed restore degrades to a re-prefill — never a corrupt page
    ("pool.spill", "raise"),
    ("pool.restore", "raise"),
)

#: finish reasons that count as "reached a terminal state"
TERMINAL = {"stop", "length", "timeout", "error", "cancelled", "shutdown"}


def _sample(name, labels=None) -> float:
    from dllama_tpu.obs import metrics

    v = metrics.REGISTRY.sample(name, labels)
    return float(v or 0.0)


def run_chaos(n_requests: int = 200, seed: int = 0, n_slots: int = 3,
              kv_pages: int = 12, page_size: int = 8, chunk: int = 3,
              clients: int = 4, fault_gap_s: tuple = (0.02, 0.15),
              timeout_frac: float = 0.15, client_deadline_s: float = 120.0,
              kv_host_pages: int = 6, verbose: bool = False) -> dict:
    """Run one seeded soak; returns a report dict with ``ok`` plus every
    assertion's inputs. Raises AssertionError on any robustness violation."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.obs import trace
    from dllama_tpu.serve.scheduler import Scheduler, SchedulerRejected
    from dllama_tpu.utils import faults

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
    rng = np.random.default_rng(seed)
    rng_inj = np.random.default_rng(seed + 1)

    # a soak-sized tracer: reconciliation counts flight-recorder events, so
    # nothing relevant may fall off the ring (restored in the finally)
    prev_tracer = trace.TRACER
    tracer = trace.configure(1 << 16, max_requests=max(256, 2 * n_requests))

    # kv_host_pages > 0 puts the ISSUE 16 host spill tier under the fault
    # schedule too: the undersized device pool forces radix evictions all
    # soak long, so spills/restores interleave with crashes and restarts
    eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=jnp.float32,
                      kv_layout="paged", page_size=page_size,
                      kv_pages=kv_pages, spec=4,
                      kv_host_pages=kv_host_pages)
    eng.pool.audit_on_release = True  # every release audited, crash-adjacent
    sched = Scheduler(eng, chunk=chunk, restart_max=1_000_000,
                      restart_window_s=2.0, restart_backoff_s=0.005)
    sched.restart_backoff_max_s = 0.05

    # metric baselines (the registry is process-global; soak asserts deltas)
    base = {
        "restarts": _sample("dllama_engine_restarts_total"),
        "recovered": _sample("dllama_requests_recovered_total"),
        "fin_timeout": _sample("dllama_requests_finished_total",
                               {"reason": "timeout"}),
        "shed_timeout": _sample("dllama_requests_shed_total",
                                {"reason": "timeout"}),
        "audit_fail": _sample("dllama_kv_audit_failures_total"),
    }

    # seeded request mix: ~half greedy, a sampled band, a penalized band, a
    # deadline band; prompts and budgets sized for the tiny pool
    specs = []
    for i in range(n_requests):
        plen = int(rng.integers(2, 14))
        greedy = rng.random() < 0.5
        specs.append(dict(
            prompt=rng.integers(1, cfg.vocab_size - 1, size=plen).tolist(),
            temperature=0.0 if greedy else float(rng.uniform(0.7, 1.2)),
            topp=float(rng.uniform(0.8, 0.95)),
            max_tokens=int(rng.integers(2, 12)),
            seed=int(rng.integers(0, 2**31)),
            presence=0.5 if rng.random() < 0.15 else 0.0,
            frequency=0.25 if rng.random() < 0.10 else 0.0,
            timeout_s=(float(rng.uniform(0.05, 0.5))
                       if rng.random() < timeout_frac else None),
            # per-request speculation (ISSUE 11): roughly half the greedy
            # band speculates, so spec cycles + plain chunks + restarts +
            # deadlines + penalties all interleave under fault injection —
            # and the release-time pool audits run with draft rows landing
            # k+1 past live positions the whole soak
            spec_k=(4 if greedy and rng.random() < 0.5 else 0),
        ))

    results: list[dict] = [None] * n_requests  # type: ignore[list-item]
    next_idx = {"i": 0}
    idx_lock = threading.Lock()
    stop_inj = threading.Event()
    fault_log: list[tuple] = []

    def injector() -> None:
        while not stop_inj.is_set():
            time.sleep(float(rng_inj.uniform(*fault_gap_s)))
            point, action = FAULT_MENU[int(rng_inj.integers(len(FAULT_MENU)))]
            kw = {"times": 1}
            if action == "delay":
                kw["ms"] = float(rng_inj.uniform(5, 40))
            faults.install(point, action, **kw)
            fault_log.append((time.monotonic(), point, action))

    def client() -> None:
        while True:
            with idx_lock:
                i = next_idx["i"]
                if i >= n_requests:
                    return
                next_idx["i"] = i + 1
            s = specs[i]
            try:
                req = sched.submit(s["prompt"], s["temperature"], s["topp"],
                                   s["max_tokens"], frozenset(),
                                   seed=s["seed"], presence=s["presence"],
                                   frequency=s["frequency"],
                                   req_id=f"req_chaos{i:05d}",
                                   timeout_s=s["timeout_s"],
                                   spec_k=s["spec_k"])
            except SchedulerRejected as e:
                # admission shed (injected queue overflow, restart-depth
                # backpressure): a clean, client-visible terminal outcome
                results[i] = {"finish": "shed", "tokens": 0,
                              "error": type(e).__name__}
                continue
            toks: list[int] = []
            err = None
            deadline = time.monotonic() + client_deadline_s
            try:
                while True:
                    item = req.out.get(
                        timeout=max(0.01, deadline - time.monotonic()))
                    if isinstance(item, BaseException):
                        err = type(item).__name__
                        break
                    if isinstance(item, int):
                        toks.append(item)
                    else:
                        break  # _END
            except Exception:
                results[i] = {"finish": "HUNG", "tokens": len(toks),
                              "error": "client drain deadline"}
                continue
            results[i] = {"finish": req.finish_reason, "tokens": len(toks),
                          "error": err}

    report: dict = {"ok": False, "requests": n_requests, "seed": seed}
    t0 = time.monotonic()
    inj = threading.Thread(target=injector, name="chaos-injector", daemon=True)
    workers = [threading.Thread(target=client, name=f"chaos-client-{c}",
                                daemon=True) for c in range(clients)]
    try:
        # compile warm-up BEFORE the fault schedule starts: the soak times
        # supervision and recovery, not XLA
        warm = sched.submit([1, 2, 3], 0.0, 0.9, 2, frozenset(), seed=0)
        for _ in warm.tokens():
            pass
        pen = sched.submit([4, 5], 0.9, 0.9, 2, frozenset(), seed=1,
                           presence=0.5)
        for _ in pen.tokens():
            pass
        inj.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=client_deadline_s + 30.0)
        stop_inj.set()
        inj.join(timeout=5.0)
        faults.clear()

        problems: list[str] = []
        hung = [w for w in workers if w.is_alive()]
        if hung:
            problems.append(f"{len(hung)} client thread(s) never finished")

        # --- 1) every request terminal
        finishes: dict[str, int] = {}
        for i, r in enumerate(results):
            if r is None:
                problems.append(f"request {i} has no result record")
                continue
            finishes[r["finish"] or "none"] = finishes.get(
                r["finish"] or "none", 0) + 1
            if r["finish"] not in TERMINAL and r["finish"] != "shed":
                problems.append(
                    f"request {i} non-terminal: {r}")
        report["finish_reasons"] = finishes

        # --- 2) /health recovers once the fault schedule stops
        deadline = time.monotonic() + 15.0
        h = sched.health()
        while time.monotonic() < deadline:
            h = sched.health()
            if h["live"] and h["ready"]:
                break
            time.sleep(0.02)
        report["health"] = {k: h[k] for k in
                            ("live", "ready", "restarts", "crashed")}
        if not (h["live"] and h["ready"]):
            problems.append(f"/health did not recover: {report['health']}")
        else:
            # post-chaos probe: the healed engine still serves, end to end
            probe = sched.submit([9, 8, 7], 0.0, 0.9, 3, frozenset(), seed=7)
            got = sum(1 for _ in probe.tokens())
            if probe.finish_reason != "length" or got != 3:
                problems.append(
                    f"post-chaos probe broken: {probe.finish_reason}/{got}")

        # --- 3) allocator integrity: audit clean (incl. the radix prefix
        # tree's page refs reconciling against the pool refcounts), zero
        # pages leaked once idle prefix caches AND the tree are dropped
        audit = eng.pool.audit(raise_on_fail=False)
        report["audit"] = audit
        if not audit["ok"]:
            problems.append(f"pool audit failed: {audit['problems']}")
        report["radix"] = eng.radix_stats()
        report["spec"] = eng.spec_stats()  # acceptance record of the soak's
        # speculative band (cycles > 0 proves spec ran under the faults)
        for s in range(n_slots):
            if not eng.active[s]:
                eng.drop_slot_pages(s)
        if eng.radix is not None:
            eng.radix.clear()  # the tree's refs are cache, not leaks
        leaked = eng.pool.stats()["used"]
        report["pages_leaked"] = leaked
        if eng.active.any():
            problems.append("slots still active after all clients finished")
        elif leaked:
            problems.append(f"{leaked} page(s) leaked after dropping caches")
        host = eng.pool.host
        if host is not None:
            hs = host.stats()
            report["host"] = hs
            # put/take/drop bookkeeping must close: what went down minus
            # what came back (or was LRU-dropped) is exactly what's resident
            if hs["spilled"] != hs["used"] + hs["restored"] + hs["dropped"]:
                problems.append(f"host tier counters do not reconcile: {hs}")
        audit_fails = _sample("dllama_kv_audit_failures_total") - base["audit_fail"]
        report["audit_failures"] = audit_fails
        if audit_fails:
            problems.append(f"{audit_fails:.0f} audit failure(s) during soak")

        # --- 4) counters reconcile with the flight recorder
        events: dict[str, int] = {}
        for ev in tracer.export_chrome()["traceEvents"]:
            if ev.get("ph") == "i":
                events[ev["name"]] = events.get(ev["name"], 0) + 1
        d_restart = _sample("dllama_engine_restarts_total") - base["restarts"]
        d_recovered = (_sample("dllama_requests_recovered_total")
                       - base["recovered"])
        d_fin_tmo = (_sample("dllama_requests_finished_total",
                             {"reason": "timeout"}) - base["fin_timeout"])
        d_shed_tmo = (_sample("dllama_requests_shed_total",
                              {"reason": "timeout"}) - base["shed_timeout"])
        report["reconcile"] = {
            "restarts": d_restart,
            "restart_events": events.get("engine.restart", 0),
            "recovered": d_recovered,
            "recovered_events": events.get("request.recovered", 0),
            "finished_timeout": d_fin_tmo,
            "shed_timeout": d_shed_tmo,
            "timeout_events": events.get("request.timeout", 0),
            "client_timeouts": finishes.get("timeout", 0),
        }
        if d_restart != events.get("engine.restart", 0):
            problems.append("restart counter != engine.restart events: "
                            f"{report['reconcile']}")
        if d_recovered != events.get("request.recovered", 0):
            problems.append("recovered counter != request.recovered events: "
                            f"{report['reconcile']}")
        if d_fin_tmo != events.get("request.timeout", 0):
            problems.append("finished{timeout} != request.timeout events: "
                            f"{report['reconcile']}")
        if d_fin_tmo != finishes.get("timeout", 0):
            problems.append("finished{timeout} != client-observed timeouts: "
                            f"{report['reconcile']}")
        if d_shed_tmo > d_fin_tmo:
            problems.append("shed{timeout} exceeds finished{timeout}: "
                            f"{report['reconcile']}")

        report["faults_injected"] = len(fault_log)
        report["elapsed_s"] = round(time.monotonic() - t0, 2)
        report["problems"] = problems
        report["ok"] = not problems
        if verbose or problems:
            print(f"chaos: {n_requests} requests, "
                  f"{report['faults_injected']} faults, "
                  f"{report['reconcile']['restarts']:.0f} restarts, "
                  f"{report['reconcile']['recovered']:.0f} recovered, "
                  f"finishes={finishes}, leaked={leaked}, "
                  f"{report['elapsed_s']}s")
            for p in problems:
                print(f"chaos VIOLATION: {p}")
        assert not problems, "; ".join(problems)
        return report
    finally:
        stop_inj.set()
        faults.clear()
        sched.shutdown()
        trace.TRACER = prev_tracer


def run_mesh_chaos(n_replicas: int = 3, n_requests: int = 30, seed: int = 0,
                   clients: int = 3, kv_host_pages: int = 4,
                   failover_max: int = 3, boot_deadline_s: float = 420.0,
                   verbose: bool = False) -> dict:
    """Multi-replica chaos mesh (ISSUE 16): one router subprocess over N
    real `dllama-tpu serve` CLI replicas (tiny fixture model, paged KV +
    host spill tier), while a seeded scheduler of process-level faults —
    SIGKILL (with respawn), SIGSTOP/SIGCONT stalls, slow-poll windows —
    runs against them. Streaming clients verify per stream:

    * a terminal outcome ALWAYS arrives (finish_reason/error + [DONE]);
    * token positions are exactly 0..n-1 — zero duplicated, zero dropped
      tokens across however many mid-stream failovers the stream ate.

    Afterwards: every live replica's /debug/kv audit must be clean (device
    AND host tier reconciled), and the router's failover counters must
    reconcile with what the clients observed (every error-finished stream
    is exactly one exhausted/unresumable failover verdict)."""
    import http.client
    import json
    import pathlib
    import re
    import signal
    import socket
    import subprocess
    import tempfile

    from tests.test_serve import make_tiny_files

    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix="dllama_chaos_mesh_")
    mpath, tpath, _cfg = make_tiny_files(pathlib.Path(tmp))

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port() for _ in range(n_replicas)]
    rport = free_port()

    def spawn_replica(port):
        return subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
             "--tokenizer", tpath, "--slots", "2", "--port", str(port),
             # 56 device pages: two concurrent ~170-token chat prompts
             # (~22 pages each) fit, but retained radix prefixes don't —
             # evictions (and with the host tier on, spills) all soak long
             "--kv-layout", "paged", "--page-size", "8",
             "--kv-pages", "56", "--kv-host-pages", str(kv_host_pages)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    replicas = {p: spawn_replica(p) for p in ports}
    router = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "router", "--port", str(rport),
         *[a for p in ports for a in ("--replica", f"127.0.0.1:{p}")],
         "--poll-s", "0.2", "--failover-max", str(failover_max)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def get(port, path, timeout=10):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request("GET", path)
        r = conn.getresponse()
        body = r.read().decode()
        conn.close()
        return r.status, body

    def wait_ready(deadline_s, want_all=False):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                st, body = get(rport, "/router/replicas")
                if st == 200:
                    reps = json.loads(body)["replicas"]
                    ok = [r for r in reps if r["ready"] and r["config_ok"]]
                    if (len(ok) == n_replicas) if want_all else ok:
                        return True
            except OSError:
                pass
            time.sleep(0.25)
        return False

    report: dict = {"ok": False, "mode": "mesh", "replicas": n_replicas,
                    "requests": n_requests, "seed": seed}
    stop_chaos = threading.Event()
    chaos_log: list[tuple] = []
    mu = threading.Lock()

    def chaos_agent():
        """Seeded process-level fault schedule. Never reduces the live set
        below 2 (someone must survive to resume onto)."""
        rng_c = np.random.default_rng(seed + 99)
        while not stop_chaos.is_set():
            time.sleep(float(rng_c.uniform(1.0, 2.5)))
            if stop_chaos.is_set():
                return
            action = ("kill", "stop", "slow")[int(rng_c.integers(3))]
            with mu:
                live = [p for p, proc in replicas.items()
                        if proc.poll() is None]
                if len(live) < 2:
                    continue
                victim = live[int(rng_c.integers(len(live)))]
                proc = replicas[victim]
            if action == "kill":
                proc.kill()
                proc.wait(timeout=10)
                chaos_log.append(("kill", victim))
                time.sleep(float(rng_c.uniform(0.5, 1.5)))
                with mu:
                    replicas[victim] = spawn_replica(victim)  # rejoin later
            elif action == "stop":
                # a frozen replica: in-flight reads stall, health polls
                # time out, then the world resumes mid-flight
                try:
                    proc.send_signal(signal.SIGSTOP)
                    chaos_log.append(("stop", victim))
                    time.sleep(float(rng_c.uniform(0.3, 1.2)))
                finally:
                    try:
                        proc.send_signal(signal.SIGCONT)
                    except OSError:
                        pass
            else:
                # slow-poll window: brief freeze, long enough to make the
                # router's next poll verdict stale but not to kill streams
                try:
                    proc.send_signal(signal.SIGSTOP)
                    chaos_log.append(("slow", victim))
                    time.sleep(0.15)
                finally:
                    try:
                        proc.send_signal(signal.SIGCONT)
                    except OSError:
                        pass

    results: list[dict] = [None] * n_requests  # type: ignore[list-item]
    next_idx = {"i": 0}
    idx_lock = threading.Lock()

    def stream_one(i):
        greedy = (i % 2 == 0)
        body = {"messages": [
                    {"role": "system",
                     "content": f"mesh soak shared preamble {i % 4}"},
                    {"role": "user", "content": f"request {i}"}],
                "stream": True, "max_tokens": int(6 + (i % 6)),
                "temperature": 0.0 if greedy else 0.9,
                "seed": 1000 + i}
        conn = http.client.HTTPConnection("127.0.0.1", rport, timeout=120)
        try:
            conn.request("POST", "/v1/chat/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                return {"finish": "shed", "status": resp.status,
                        "positions_ok": True}
            raw = resp.read().decode()
        except (OSError, http.client.HTTPException) as e:
            return {"finish": "HUNG", "error": repr(e), "positions_ok": False}
        finally:
            conn.close()
        finish, err, poss = None, False, []
        for line in raw.splitlines():
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            try:
                ev = json.loads(line[6:])
            except ValueError:
                continue
            if "error" in ev:
                err = True
                continue
            if "token_ids" in ev:
                poss.extend(range(ev["position"],
                                  ev["position"] + len(ev["token_ids"])))
            fr = (ev.get("choices") or [{}])[0].get("finish_reason")
            if fr:
                finish = fr
        return {"finish": finish or ("error" if err else "NONE"),
                "done": raw.rstrip().endswith("data: [DONE]"),
                "positions_ok": poss == list(range(len(poss))),
                "tokens": len(poss)}

    def client():
        while True:
            with idx_lock:
                i = next_idx["i"]
                if i >= n_requests:
                    return
                next_idx["i"] = i + 1
            results[i] = stream_one(i)

    t0 = time.monotonic()
    procs = lambda: list(replicas.values()) + [router]  # noqa: E731
    try:
        if not wait_ready(boot_deadline_s, want_all=True):
            raise AssertionError("mesh never became ready "
                                 f"(replica boot > {boot_deadline_s:.0f}s)")
        agent = threading.Thread(target=chaos_agent, name="chaos-mesh-agent",
                                 daemon=True)
        workers = [threading.Thread(target=client, daemon=True,
                                    name=f"mesh-client-{c}")
                   for c in range(clients)]
        agent.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=600.0)
        stop_chaos.set()
        agent.join(timeout=15.0)

        problems: list[str] = []
        if any(w.is_alive() for w in workers):
            problems.append("client thread(s) never finished")

        finishes: dict[str, int] = {}
        for i, r in enumerate(results):
            if r is None:
                problems.append(f"request {i} has no result record")
                continue
            finishes[r["finish"]] = finishes.get(r["finish"], 0) + 1
            if r["finish"] in ("NONE", "HUNG"):
                problems.append(f"request {i} non-terminal: {r}")
            if not r["positions_ok"]:
                problems.append(f"request {i} duplicated/dropped tokens: {r}")
        report["finish_reasons"] = finishes

        # the mesh heals: at least the floor of survivors is ready again
        if not wait_ready(60.0):
            problems.append("mesh did not recover after the fault schedule")

        # every live replica's device + host KV tiers audit clean
        audits = {}
        with mu:
            live = [p for p, proc in replicas.items() if proc.poll() is None]
        for p in live:
            # a replica respawned late in the schedule may still be
            # XLA-compiling (a cold serve boot is O(minutes) on CPU) —
            # connection-refused within its boot window is "booting", not
            # a violation, so give each live process the boot deadline
            audit_deadline = time.monotonic() + boot_deadline_s
            while True:
                try:
                    st, body = get(p, "/debug/kv", timeout=30)
                    kv = json.loads(body)
                    audits[p] = kv.get("audit", {}).get("ok")
                    if st != 200 or audits[p] is not True:
                        problems.append(f"replica :{p} KV audit not clean")
                    break
                except (OSError, ValueError) as e:
                    with mu:
                        gone = replicas[p].poll() is not None
                    if gone or time.monotonic() > audit_deadline:
                        problems.append(
                            f"replica :{p} /debug/kv unreachable: {e!r}")
                        break
                    time.sleep(1.0)
        report["audits"] = audits

        # ---- directed failover drill (ISSUE 19) -------------------------
        # The random schedule alone rarely lands a SIGKILL mid-stream with
        # an UNSATURATED survivor (a resume dispatched into a shedding
        # degraded mesh exhausts its budget instead of resuming), so the
        # cross-replica-trace assertion below would usually have no
        # subject. Drill it deterministically on the HEALED mesh, after
        # the random schedule has stopped: stream one request through the
        # router, SIGKILL whichever replica holds it once content frames
        # are on the wire, and require the stream to finish on the
        # survivor. Running it last also means nothing can SIGKILL the
        # survivor afterward and erase its tracer ring before the merged
        # trace is read. The drill's `resumed` verdict lands in the same
        # counters the reconciliation below scrapes.
        drill_killed = {"port": None}

        def _drill_assassin(n_frames):
            if drill_killed["port"] is None and n_frames >= 3:
                _st, body_r = get(rport, "/router/replicas")
                for rr in json.loads(body_r)["replicas"]:
                    if rr["inflight"] > 0:
                        p = int(rr["id"].rsplit(":", 1)[1])
                        with mu:
                            replicas[p].kill()
                        drill_killed["port"] = p
                        return

        if not wait_ready(boot_deadline_s, want_all=True):
            problems.append("mesh never FULLY healed — the directed "
                            "failover drill needs every replica back")
        else:
            drill_body = {"messages": [
                              {"role": "system",
                               "content": "mesh soak shared preamble drill"},
                              {"role": "user",
                               "content": "stream me a dozen tokens"}],
                          "stream": True, "max_tokens": 12,
                          "temperature": 0.0, "seed": seed + 7}
            conn = http.client.HTTPConnection("127.0.0.1", rport,
                                              timeout=120)
            try:
                conn.request("POST", "/v1/chat/completions",
                             json.dumps(drill_body),
                             {"Content-Type": "application/json",
                              "X-Request-Id": "req-mesh-drill"})
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    problems.append("failover drill shed on an idle mesh: "
                                    f"{resp.status}")
                else:
                    raw = b""
                    while True:
                        chunk = resp.read1(65536)
                        if not chunk:
                            break
                        raw += chunk
                        _drill_assassin(raw.count(b"data: "))
                    if drill_killed["port"] is None:
                        problems.append(
                            "failover drill never caught a replica inflight"
                            " — the stream finished too fast to interrupt")
                    elif (not raw.rstrip().endswith(b"data: [DONE]")
                            or b'"error"' in raw):
                        problems.append(
                            "failover drill stream did not resume cleanly "
                            f"on the survivor: {raw[-300:]!r}")
            except (OSError, http.client.HTTPException) as e:
                problems.append(f"failover drill stream broke: {e!r}")
            finally:
                conn.close()
            if drill_killed["port"] is not None:
                with mu:
                    replicas[drill_killed["port"]].wait(timeout=10)
                    replicas[drill_killed["port"]] = spawn_replica(
                        drill_killed["port"])
                chaos_log.append(("kill", drill_killed["port"]))

        # router counters reconcile with the client view: every
        # error-finished stream is exactly one exhausted/unresumable verdict
        st, mtext = get(rport, "/metrics", timeout=30)
        fov = {m.group(1): float(m.group(2)) for m in re.finditer(
            r'dllama_router_failovers_total\{outcome="(\w+)"\} ([0-9.e+-]+)',
            mtext)}
        report["failovers"] = fov
        errors_seen = finishes.get("error", 0)
        if errors_seen != fov.get("exhausted", 0) + fov.get("unresumable", 0):
            problems.append(
                f"error streams ({errors_seen}) != exhausted+unresumable "
                f"({fov})")
        if fov.get("resumed", 0) > fov.get("retried", 0):
            problems.append(f"resumed > retried: {fov}")

        # the fleet plane under fire (ISSUE 19): GET /router/fleet must
        # tell the SAME failover story as the raw counters and the client
        # view — its reconciliation block is only trustworthy if it holds
        # while replicas are dying, not just in a quiet mesh
        try:
            st, fbody = get(rport, "/router/fleet", timeout=30)
            fleet = json.loads(fbody) if st == 200 else {}
        except (OSError, ValueError) as e:
            st, fleet = 0, {}
            problems.append(f"/router/fleet unreachable: {e!r}")
        if st == 200:
            fblock = fleet.get("fleet") or {}
            ffov = fblock.get("failovers") or {}
            report["fleet_failovers"] = ffov
            for k in ("retried", "resumed", "exhausted", "unresumable"):
                if ffov.get(k) != fov.get(k, 0):
                    problems.append(
                        f"/router/fleet failovers[{k}]={ffov.get(k)} "
                        f"disagrees with /metrics ({fov.get(k, 0)})")
            cerr = fblock.get("client_errors") or {}
            if cerr.get("stream_error") != errors_seen:
                problems.append(
                    f"/router/fleet client_errors.stream_error="
                    f"{cerr.get('stream_error')} != client-observed error "
                    f"streams ({errors_seen})")
        elif st:
            problems.append(f"/router/fleet status {st}")

        # the merged mesh trace must hold >= 1 CROSS-REPLICA resumed
        # request: a `resume` span on the router track (pid 1) whose
        # req_id also has events on a replica track (pid > 1 — the
        # survivor; the original replica was SIGKILLed and respawned with
        # an empty ring, so its leg is gone by design)
        if fov.get("resumed", 0) < 1:
            problems.append("fault schedule produced no resumed stream — "
                            "the cross-replica trace check has no subject")
        else:
            try:
                st, tbody = get(rport, "/router/trace", timeout=60)
                merged = json.loads(tbody) if st == 200 else {}
            except (OSError, ValueError) as e:
                st, merged = 0, {}
                problems.append(f"/router/trace unreachable: {e!r}")
            evs = merged.get("traceEvents") or []
            resumed_ids = {e.get("args", {}).get("req_id")
                           for e in evs
                           if e.get("name") == "resume"
                           and e.get("pid") == 1
                           and e.get("args", {}).get("req_id")}
            cross = set()
            for e in evs:
                if (e.get("pid", 1) > 1 and e.get("ph") != "M"
                        and e.get("args", {}).get("req_id") in resumed_ids):
                    cross.add(e["args"]["req_id"])
            report["trace_resumed_req_ids"] = len(resumed_ids)
            report["trace_cross_replica_resumed"] = len(cross)
            if st == 200 and not cross:
                problems.append(
                    "merged /router/trace has no cross-replica resumed "
                    f"request (resume spans for {len(resumed_ids)} req_ids, "
                    "none with replica-track events)")

        report["chaos_events"] = len(chaos_log)
        report["elapsed_s"] = round(time.monotonic() - t0, 2)
        report["problems"] = problems
        report["ok"] = not problems
        if verbose or problems:
            print(f"chaos mesh: {n_requests} streams over {n_replicas} "
                  f"replicas, {len(chaos_log)} process faults "
                  f"({[e[0] for e in chaos_log]}), finishes={finishes}, "
                  f"failovers={fov}, {report['elapsed_s']}s")
            for p in problems:
                print(f"chaos mesh VIOLATION: {p}")
        assert not problems, "; ".join(problems)
        return report
    finally:
        stop_chaos.set()
        for proc in procs():
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGCONT)  # un-freeze first
                except OSError:
                    pass
                proc.kill()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--kv-pages", type=int, default=12)
    ap.add_argument("--kv-host-pages", type=int, default=6)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--timeout-frac", type=float, default=0.15)
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="run the MULTI-REPLICA mesh soak instead: a router "
                         "+ N real CLI replicas under randomized SIGKILL/"
                         "SIGSTOP/slow-poll (ISSUE 16); --requests then "
                         "means streamed requests through the router")
    ap.add_argument("--failover-max", type=int, default=3)
    args = ap.parse_args(argv)
    try:
        if args.mesh > 0:
            report = run_mesh_chaos(
                n_replicas=args.mesh, n_requests=args.requests,
                seed=args.seed, clients=args.clients,
                kv_host_pages=args.kv_host_pages,
                failover_max=args.failover_max, verbose=True)
            print(f"chaos mesh PASSED (seed {args.seed}): "
                  f"{report['requests']} streams 100% terminal with zero "
                  f"duplicate/dropped tokens, audits clean, "
                  f"failovers={report['failovers']}")
            return 0
        report = run_chaos(n_requests=args.requests, seed=args.seed,
                           n_slots=args.slots, kv_pages=args.kv_pages,
                           kv_host_pages=args.kv_host_pages,
                           clients=args.clients,
                           timeout_frac=args.timeout_frac, verbose=True)
    except AssertionError as e:
        print(f"chaos soak FAILED: {e}", file=sys.stderr)
        return 1
    print(f"chaos soak PASSED (seed {args.seed}): "
          f"{report['requests']} requests 100% terminal, audit clean, "
          f"health recovered, counters reconciled")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
