"""Seeded chaos soak for the self-healing serving stack (ISSUE 6).

Drives mixed traffic — greedy / sampled / penalized, staggered submission,
a fraction carrying per-request deadlines — through a warm-restart-enabled
Scheduler while a seeded injector keeps arming random faults across the
catalog (engine.decode, engine.prefill, scheduler.loop, scheduler.queue,
pool.alloc, decode.nan; raise and delay actions). Every request streams
through its token queue, exactly like an SSE client.

What a passing soak proves, asserted at the end:

* **100% terminal**: every submitted request reaches a terminal state
  (stop/length/timeout/error/cancelled — or a clean admission shed); no
  client queue ever hangs;
* **allocator integrity**: ``PagePool.audit()`` is clean — including the
  radix prefix tree's page references reconciling exactly against the pool
  refcounts (the engine runs with the paged-default radix cache ON) — and,
  after idle prefix caches and the tree are dropped, ZERO pages remain
  referenced (no leaks across hundreds of crash/restart/timeout/error
  paths);
* **self-healing**: ``/health`` is back to live=true/ready=true once the
  fault schedule stops;
* **counter/trace reconciliation**: dllama_engine_restarts_total,
  dllama_requests_recovered_total and finished{reason="timeout"} deltas
  each equal their flight-recorder event counts (engine.restart /
  request.recovered / request.timeout), and the timeout counter matches
  what clients actually observed.

CLI::

    JAX_PLATFORMS=cpu python experiments/chaos.py --requests 200 --seed 0

(scripts/chaos_soak.sh wraps exactly that). tests/test_chaos.py runs a
bounded mini-soak through the same run_chaos() entry point in tier-1.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: fault points the injector cycles through. engine.restart is deliberately
#: NOT in the schedule: a raise there makes the restart itself die, which is
#: the budget-exhaustion drill (tests/test_faults.py), not a soak the stack
#: is supposed to survive.
FAULT_MENU = (
    ("engine.decode", "raise"),
    ("engine.decode", "delay"),
    ("engine.prefill", "raise"),
    ("scheduler.loop", "raise"),
    ("scheduler.queue", "raise"),
    ("pool.alloc", "raise"),
    ("decode.nan", "raise"),
)

#: finish reasons that count as "reached a terminal state"
TERMINAL = {"stop", "length", "timeout", "error", "cancelled", "shutdown"}


def _sample(name, labels=None) -> float:
    from dllama_tpu.obs import metrics

    v = metrics.REGISTRY.sample(name, labels)
    return float(v or 0.0)


def run_chaos(n_requests: int = 200, seed: int = 0, n_slots: int = 3,
              kv_pages: int = 12, page_size: int = 8, chunk: int = 3,
              clients: int = 4, fault_gap_s: tuple = (0.02, 0.15),
              timeout_frac: float = 0.15, client_deadline_s: float = 120.0,
              verbose: bool = False) -> dict:
    """Run one seeded soak; returns a report dict with ``ok`` plus every
    assertion's inputs. Raises AssertionError on any robustness violation."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.obs import trace
    from dllama_tpu.serve.scheduler import Scheduler, SchedulerRejected
    from dllama_tpu.utils import faults

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
    rng = np.random.default_rng(seed)
    rng_inj = np.random.default_rng(seed + 1)

    # a soak-sized tracer: reconciliation counts flight-recorder events, so
    # nothing relevant may fall off the ring (restored in the finally)
    prev_tracer = trace.TRACER
    tracer = trace.configure(1 << 16, max_requests=max(256, 2 * n_requests))

    eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=jnp.float32,
                      kv_layout="paged", page_size=page_size,
                      kv_pages=kv_pages, spec=4)
    eng.pool.audit_on_release = True  # every release audited, crash-adjacent
    sched = Scheduler(eng, chunk=chunk, restart_max=1_000_000,
                      restart_window_s=2.0, restart_backoff_s=0.005)
    sched.restart_backoff_max_s = 0.05

    # metric baselines (the registry is process-global; soak asserts deltas)
    base = {
        "restarts": _sample("dllama_engine_restarts_total"),
        "recovered": _sample("dllama_requests_recovered_total"),
        "fin_timeout": _sample("dllama_requests_finished_total",
                               {"reason": "timeout"}),
        "shed_timeout": _sample("dllama_requests_shed_total",
                                {"reason": "timeout"}),
        "audit_fail": _sample("dllama_kv_audit_failures_total"),
    }

    # seeded request mix: ~half greedy, a sampled band, a penalized band, a
    # deadline band; prompts and budgets sized for the tiny pool
    specs = []
    for i in range(n_requests):
        plen = int(rng.integers(2, 14))
        greedy = rng.random() < 0.5
        specs.append(dict(
            prompt=rng.integers(1, cfg.vocab_size - 1, size=plen).tolist(),
            temperature=0.0 if greedy else float(rng.uniform(0.7, 1.2)),
            topp=float(rng.uniform(0.8, 0.95)),
            max_tokens=int(rng.integers(2, 12)),
            seed=int(rng.integers(0, 2**31)),
            presence=0.5 if rng.random() < 0.15 else 0.0,
            frequency=0.25 if rng.random() < 0.10 else 0.0,
            timeout_s=(float(rng.uniform(0.05, 0.5))
                       if rng.random() < timeout_frac else None),
            # per-request speculation (ISSUE 11): roughly half the greedy
            # band speculates, so spec cycles + plain chunks + restarts +
            # deadlines + penalties all interleave under fault injection —
            # and the release-time pool audits run with draft rows landing
            # k+1 past live positions the whole soak
            spec_k=(4 if greedy and rng.random() < 0.5 else 0),
        ))

    results: list[dict] = [None] * n_requests  # type: ignore[list-item]
    next_idx = {"i": 0}
    idx_lock = threading.Lock()
    stop_inj = threading.Event()
    fault_log: list[tuple] = []

    def injector() -> None:
        while not stop_inj.is_set():
            time.sleep(float(rng_inj.uniform(*fault_gap_s)))
            point, action = FAULT_MENU[int(rng_inj.integers(len(FAULT_MENU)))]
            kw = {"times": 1}
            if action == "delay":
                kw["ms"] = float(rng_inj.uniform(5, 40))
            faults.install(point, action, **kw)
            fault_log.append((time.monotonic(), point, action))

    def client() -> None:
        while True:
            with idx_lock:
                i = next_idx["i"]
                if i >= n_requests:
                    return
                next_idx["i"] = i + 1
            s = specs[i]
            try:
                req = sched.submit(s["prompt"], s["temperature"], s["topp"],
                                   s["max_tokens"], frozenset(),
                                   seed=s["seed"], presence=s["presence"],
                                   frequency=s["frequency"],
                                   req_id=f"req_chaos{i:05d}",
                                   timeout_s=s["timeout_s"],
                                   spec_k=s["spec_k"])
            except SchedulerRejected as e:
                # admission shed (injected queue overflow, restart-depth
                # backpressure): a clean, client-visible terminal outcome
                results[i] = {"finish": "shed", "tokens": 0,
                              "error": type(e).__name__}
                continue
            toks: list[int] = []
            err = None
            deadline = time.monotonic() + client_deadline_s
            try:
                while True:
                    item = req.out.get(
                        timeout=max(0.01, deadline - time.monotonic()))
                    if isinstance(item, BaseException):
                        err = type(item).__name__
                        break
                    if isinstance(item, int):
                        toks.append(item)
                    else:
                        break  # _END
            except Exception:
                results[i] = {"finish": "HUNG", "tokens": len(toks),
                              "error": "client drain deadline"}
                continue
            results[i] = {"finish": req.finish_reason, "tokens": len(toks),
                          "error": err}

    report: dict = {"ok": False, "requests": n_requests, "seed": seed}
    t0 = time.monotonic()
    inj = threading.Thread(target=injector, name="chaos-injector", daemon=True)
    workers = [threading.Thread(target=client, name=f"chaos-client-{c}",
                                daemon=True) for c in range(clients)]
    try:
        # compile warm-up BEFORE the fault schedule starts: the soak times
        # supervision and recovery, not XLA
        warm = sched.submit([1, 2, 3], 0.0, 0.9, 2, frozenset(), seed=0)
        for _ in warm.tokens():
            pass
        pen = sched.submit([4, 5], 0.9, 0.9, 2, frozenset(), seed=1,
                           presence=0.5)
        for _ in pen.tokens():
            pass
        inj.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=client_deadline_s + 30.0)
        stop_inj.set()
        inj.join(timeout=5.0)
        faults.clear()

        problems: list[str] = []
        hung = [w for w in workers if w.is_alive()]
        if hung:
            problems.append(f"{len(hung)} client thread(s) never finished")

        # --- 1) every request terminal
        finishes: dict[str, int] = {}
        for i, r in enumerate(results):
            if r is None:
                problems.append(f"request {i} has no result record")
                continue
            finishes[r["finish"] or "none"] = finishes.get(
                r["finish"] or "none", 0) + 1
            if r["finish"] not in TERMINAL and r["finish"] != "shed":
                problems.append(
                    f"request {i} non-terminal: {r}")
        report["finish_reasons"] = finishes

        # --- 2) /health recovers once the fault schedule stops
        deadline = time.monotonic() + 15.0
        h = sched.health()
        while time.monotonic() < deadline:
            h = sched.health()
            if h["live"] and h["ready"]:
                break
            time.sleep(0.02)
        report["health"] = {k: h[k] for k in
                            ("live", "ready", "restarts", "crashed")}
        if not (h["live"] and h["ready"]):
            problems.append(f"/health did not recover: {report['health']}")
        else:
            # post-chaos probe: the healed engine still serves, end to end
            probe = sched.submit([9, 8, 7], 0.0, 0.9, 3, frozenset(), seed=7)
            got = sum(1 for _ in probe.tokens())
            if probe.finish_reason != "length" or got != 3:
                problems.append(
                    f"post-chaos probe broken: {probe.finish_reason}/{got}")

        # --- 3) allocator integrity: audit clean (incl. the radix prefix
        # tree's page refs reconciling against the pool refcounts), zero
        # pages leaked once idle prefix caches AND the tree are dropped
        audit = eng.pool.audit(raise_on_fail=False)
        report["audit"] = audit
        if not audit["ok"]:
            problems.append(f"pool audit failed: {audit['problems']}")
        report["radix"] = eng.radix_stats()
        report["spec"] = eng.spec_stats()  # acceptance record of the soak's
        # speculative band (cycles > 0 proves spec ran under the faults)
        for s in range(n_slots):
            if not eng.active[s]:
                eng.drop_slot_pages(s)
        if eng.radix is not None:
            eng.radix.clear()  # the tree's refs are cache, not leaks
        leaked = eng.pool.stats()["used"]
        report["pages_leaked"] = leaked
        if eng.active.any():
            problems.append("slots still active after all clients finished")
        elif leaked:
            problems.append(f"{leaked} page(s) leaked after dropping caches")
        audit_fails = _sample("dllama_kv_audit_failures_total") - base["audit_fail"]
        report["audit_failures"] = audit_fails
        if audit_fails:
            problems.append(f"{audit_fails:.0f} audit failure(s) during soak")

        # --- 4) counters reconcile with the flight recorder
        events: dict[str, int] = {}
        for ev in tracer.export_chrome()["traceEvents"]:
            if ev.get("ph") == "i":
                events[ev["name"]] = events.get(ev["name"], 0) + 1
        d_restart = _sample("dllama_engine_restarts_total") - base["restarts"]
        d_recovered = (_sample("dllama_requests_recovered_total")
                       - base["recovered"])
        d_fin_tmo = (_sample("dllama_requests_finished_total",
                             {"reason": "timeout"}) - base["fin_timeout"])
        d_shed_tmo = (_sample("dllama_requests_shed_total",
                              {"reason": "timeout"}) - base["shed_timeout"])
        report["reconcile"] = {
            "restarts": d_restart,
            "restart_events": events.get("engine.restart", 0),
            "recovered": d_recovered,
            "recovered_events": events.get("request.recovered", 0),
            "finished_timeout": d_fin_tmo,
            "shed_timeout": d_shed_tmo,
            "timeout_events": events.get("request.timeout", 0),
            "client_timeouts": finishes.get("timeout", 0),
        }
        if d_restart != events.get("engine.restart", 0):
            problems.append("restart counter != engine.restart events: "
                            f"{report['reconcile']}")
        if d_recovered != events.get("request.recovered", 0):
            problems.append("recovered counter != request.recovered events: "
                            f"{report['reconcile']}")
        if d_fin_tmo != events.get("request.timeout", 0):
            problems.append("finished{timeout} != request.timeout events: "
                            f"{report['reconcile']}")
        if d_fin_tmo != finishes.get("timeout", 0):
            problems.append("finished{timeout} != client-observed timeouts: "
                            f"{report['reconcile']}")
        if d_shed_tmo > d_fin_tmo:
            problems.append("shed{timeout} exceeds finished{timeout}: "
                            f"{report['reconcile']}")

        report["faults_injected"] = len(fault_log)
        report["elapsed_s"] = round(time.monotonic() - t0, 2)
        report["problems"] = problems
        report["ok"] = not problems
        if verbose or problems:
            print(f"chaos: {n_requests} requests, "
                  f"{report['faults_injected']} faults, "
                  f"{report['reconcile']['restarts']:.0f} restarts, "
                  f"{report['reconcile']['recovered']:.0f} recovered, "
                  f"finishes={finishes}, leaked={leaked}, "
                  f"{report['elapsed_s']}s")
            for p in problems:
                print(f"chaos VIOLATION: {p}")
        assert not problems, "; ".join(problems)
        return report
    finally:
        stop_inj.set()
        faults.clear()
        sched.shutdown()
        trace.TRACER = prev_tracer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--kv-pages", type=int, default=12)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--timeout-frac", type=float, default=0.15)
    args = ap.parse_args(argv)
    try:
        report = run_chaos(n_requests=args.requests, seed=args.seed,
                           n_slots=args.slots, kv_pages=args.kv_pages,
                           clients=args.clients,
                           timeout_frac=args.timeout_frac, verbose=True)
    except AssertionError as e:
        print(f"chaos soak FAILED: {e}", file=sys.stderr)
        return 1
    print(f"chaos soak PASSED (seed {args.seed}): "
          f"{report['requests']} requests 100% terminal, audit clean, "
          f"health recovered, counters reconciled")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
