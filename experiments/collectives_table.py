"""Measured per-token collective bytes — the reference's Fig. 6 analog.

The reference publishes measured sync payload per token vs node count
(report.pdf Fig. 6, counted by its socket byte counters
nn-network.cpp:483-492). This produces the committed counterpart WITHOUT
TPU hardware (VERDICT r3 #3): for each (preset, tp, sync-wire) combo it
builds the sharded engine on the virtual 8-device CPU mesh, lowers the
T=1 decode step with layer_unroll=True (collectives inside the layer scan
would otherwise count once per loop trip), compiles, and sums the result
shapes of every collective op XLA actually emitted after SPMD partitioning
(utils.profiling.measured_collective_bytes).

Two columns, two meanings:
* measured — per-chip HLO collective op bytes (the data each chip's program
  materializes out of collectives per token; the compiled-program truth).
* analytic — the wire model (collective_bytes_per_token): send+recv bytes
  per chip for ring implementations, the reference's counter semantics.

Usage:  python experiments/collectives_table.py [--smoke] [--out COLLECTIVES.md]
Writes the markdown table + experiments/collectives.json (consumed by
bench.py to fill kb_per_token_per_chip when a mesh is active).
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import PRESETS
from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params_fast
from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
from dllama_tpu.parallel.sharding import LlamaShardings
from dllama_tpu.utils.profiling import collective_bytes_per_token


def measure(cfg: LlamaConfig, mesh_kw: dict, sync: str) -> dict:
    mesh = make_mesh(MeshConfig(**mesh_kw))
    sh = LlamaShardings(mesh, cfg)
    params = random_params_fast(cfg, seed=0, dtype=jnp.bfloat16)
    eng = InferenceEngine(
        cfg, params, cache_dtype=jnp.bfloat16, shardings=sh,
        layer_unroll=True, sync=sync,
    )
    rep = eng.measured_collective_report()
    wire = 34.0 / 32.0 if sync == "q80" else 2.0
    analytic = collective_bytes_per_token(
        cfg, tp=mesh_kw.get("tp", 1), sp=mesh_kw.get("sp", 1), exchange_bytes=wire
    )
    del eng, params
    return {
        "measured_bytes": rep["total_bytes"],
        "per_op": rep["per_op"],
        "analytic_wire_bytes": analytic["bytes_per_token_per_chip"],
    }


def main():
    smoke = "--smoke" in sys.argv
    out_md = "COLLECTIVES.md"
    if "--out" in sys.argv:
        out_md = sys.argv[sys.argv.index("--out") + 1]
    if smoke:
        combos = [("tiny", {"tp": 2}, "bf16"), ("tiny", {"tp": 2}, "q80"),
                  ("tiny", {"sp": 2}, "bf16")]
        out_md = os.path.join("experiments", "collectives_smoke.md")
    else:
        combos = [
            (name, {"tp": tp}, sync)
            for name in ("1b", "8b")
            for tp in (2, 4, 8)
            for sync in ("bf16", "q80")
        ] + [
            # sequence/context parallelism (the axis the reference lacks):
            # decode-path ring attention's per-step LSE-merge payload
            ("1b", {"sp": 8}, "bf16"),
            ("1b", {"sp": 2, "tp": 4}, "bf16"),
            ("8b", {"sp": 8}, "bf16"),
        ]

    rows, table_json = [], {}
    for name, mesh_kw, sync in combos:
        t0 = time.time()
        cfg = LlamaConfig(**PRESETS[name])
        mesh_label = ",".join(f"{k}{v}" for k, v in sorted(mesh_kw.items()))
        try:
            r = measure(cfg, mesh_kw, sync)
        except Exception as e:
            print(f"{name} {mesh_label} {sync}: FAILED {e!r}"[:300], flush=True)
            continue
        ops = " + ".join(
            f"{op} {b/1024:.1f}K" for op, b in sorted(r["per_op"].items())
        )
        rows.append(
            f"| {name} | {mesh_label} | {sync} | {r['measured_bytes']/1024:.1f} | "
            f"{r['analytic_wire_bytes']/1024:.1f} | {ops} |"
        )
        table_json[f"{name}/{mesh_label}/{sync}"] = {
            "measured_kb_per_token_per_chip": r["measured_bytes"] / 1024.0,
            "analytic_wire_kb_per_token_per_chip": r["analytic_wire_bytes"] / 1024.0,
            "per_op_bytes": r["per_op"],
        }
        print(rows[-1] + f"  ({time.time()-t0:.0f}s)", flush=True)

    header = (
        "# Measured per-token collective bytes (Fig. 6 analog)\n\n"
        "Per-chip collective payload of ONE decoded token (T=1 step, batch=1),\n"
        "counted from the compiled post-SPMD HLO on the virtual 8-device mesh\n"
        "(`experiments/collectives_table.py`; method in\n"
        "`dllama_tpu/utils/profiling.py:measured_collective_bytes`). The\n"
        "reference's counterpart is its socket byte counters\n"
        "(`nn-network.cpp:483-492`) and report.pdf Fig. 6.\n\n"
        "* **measured KB** — sum of collective-op result shapes in each chip's\n"
        "  compiled program (what XLA actually emitted, layer scan unrolled).\n"
        "* **analytic KB** — wire model (send+recv per chip, ring collectives):\n"
        "  `utils.profiling.collective_bytes_per_token`.\n"
        "* q80 rides the quantized exchange (u8 payload + f16 scales ≈ 1.06\n"
        "  bytes/elem on the wire) for the wo/w2 partial-sum syncs.\n\n"
        "| preset | mesh | sync | measured KB/tok/chip | analytic wire KB/tok/chip | measured per-op |\n"
        "|---|---|---|---|---|---|\n"
    )
    md = header + "\n".join(rows) + "\n"
    with open(out_md, "w") as f:
        f.write(md)
    jpath = os.path.join("experiments", "collectives_smoke.json" if smoke else "collectives.json")
    with open(jpath, "w") as f:
        json.dump(table_json, f, indent=1, sort_keys=True)
    print(f"wrote {out_md} + {jpath}")
    print("COLLECTIVES DONE")


if __name__ == "__main__":
    main()
