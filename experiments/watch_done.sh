#!/bin/sh
# Exit 0 iff a FULL real-TPU bench record exists in the given logs dir
# (default experiments/logs). The watcher keys "stop watching for windows"
# off this: a CPU-fallback record ("tpu_unavailable": true) or a wedge
# partial snapshot ("partial": true) keeps the watch armed — only a
# complete TPU bench run ends it. Tested by tests/test_window_scripts.py.
set -u
D="${1:-experiments/logs}"
grep -l '"vs_baseline"' "$D"/bench_*.log 2>/dev/null \
  | xargs -r grep -L '"tpu_unavailable": true' 2>/dev/null \
  | xargs -r grep -L '"partial": true' 2>/dev/null | grep -q .
