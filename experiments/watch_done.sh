#!/bin/sh
# Exit 0 iff a FULL real-TPU bench record exists in the given logs dir
# (default experiments/logs). The watcher keys "stop watching for windows"
# off this: a CPU-fallback record ("tpu_unavailable": true), a wedge
# partial snapshot ("partial": true), or the quick-bench 1b record (no
# "8b..." vs_baseline_config — vs_baseline is pinned to the 8b serving
# sweep, so a non-null config string IS the "north-star config measured"
# signal) keeps the watch armed — only a complete TPU bench run that
# measured the 8b serving sweep ends it. Tested by
# tests/test_window_scripts.py.
set -u
D="${1:-experiments/logs}"
grep -l '"vs_baseline_config": "8b' "$D"/bench_*.log 2>/dev/null \
  | xargs -r grep -L '"tpu_unavailable": true' 2>/dev/null \
  | xargs -r grep -L '"partial": true' 2>/dev/null | grep -q .
