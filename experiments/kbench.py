"""Microbench harness for Q40 matmul kernel variants on the real TPU.

Usage: python experiments/kbench.py suite
       python experiments/kbench.py M SHAPE [variant ...]
'suite' (what tpu_session.sh runs) benches the decode variants (m=8 on
w1/wcls), the prefill tier comparison (m=256/512: in-kernel deq vs XLA
dequant-dot), and a blockdot (tk, tn) tile autotune, all in one process.
'suite --smoke' runs the same code path on CPU (interpret-mode Pallas, tiny
shapes, 2 iters) so CI proves the harness cannot crash in a live TPU window
(VERDICT r3 #2); smoke numbers are meaningless, only completion matters.
  variants: A  production dispatch (q40_matmul auto: blockdot for m<=16, deq above)
            DQ forced deq-style kernel      BD forced blockdot kernel
            MD forced maskdot fallback      LD forced loopdot fallback
            B  legacy fma-f32 kernel        D  bf16-weights roofline reference
            E  XLA dequantize-then-dot
Measures achieved HBM GB/s (packed+scales bytes) on 1B-preset shapes.
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dllama_tpu.ops.quant import Q_BLOCK, QTensor
from dllama_tpu.ops.pallas import q40_matmul as qmod
from dllama_tpu.ops.pallas.tiling import COMPILER_PARAMS
from dllama_tpu.ops.pallas.tiling import pick_tile as _pick_tile

# --smoke flips these: interpret-mode Pallas, 2 timing iters (see docstring)
INTERPRET = False
ITERS = 30


# ---------------------------------------------------------------- variant B
# u8 unpack kept narrow, dequant via fma (w = f*s - 8s), f32 dot (no bf16 cast)
def _kernel_b(x_ref, packed_ref, scales_ref, out_ref, acc_ref, *, tk, tn):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    p = packed_ref[:].astype(jnp.int32)  # [tk/2, tn]
    lo = (p & 0x0F)
    hi = (p >> 4)
    codes = jnp.concatenate(
        [lo.reshape(tk // Q_BLOCK, Q_BLOCK // 2, tn), hi.reshape(tk // Q_BLOCK, Q_BLOCK // 2, tn)],
        axis=1,
    )  # i32 [tk/32, 32, tn]
    s = scales_ref[:].astype(jnp.float32)[:, None, :]
    f = codes.astype(jnp.float32)
    w = (f * s - 8.0 * s).reshape(tk, tn)
    acc_ref[:] += jnp.dot(x_ref[:].astype(jnp.float32), w, preferred_element_type=jnp.float32)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        out_ref[:] = acc_ref[:]


# ---------------------------------------------------------------- variant D
# bf16 weights materialized (roofline reference for unquantized): plain dot
def _kernel_d(x_ref, w_ref, out_ref, acc_ref, *, tk, tn):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def make_call(kernel, m, k, n, *, tiles=None, bf16=False):
    tm = _pick_tile(m, (256, 128, 64, 32, 16, 8))
    tn, tk = tiles or (_pick_tile(n, (512, 256, 128)), _pick_tile(k, (512, 256, 128, 64, 32)))
    grid = (m // tm, n // tn, k // tk)
    if bf16:
        in_specs = [
            pl.BlockSpec((tm, tk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((tk, tn), lambda i, j, kb: (kb, j)),
        ]
    else:
        in_specs = [
            pl.BlockSpec((tm, tk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((tk // 2, tn), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((tk // Q_BLOCK, tn), lambda i, j, kb: (kb, j)),
        ]
    return pl.pallas_call(
        functools.partial(kernel, tk=tk, tn=tn),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kb: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=INTERPRET,
    )


def bench(fn, args, iters=None):
    """Each iteration gets a DISTINCT x buffer (the tunnel appears to cache
    results for identical (executable, args) pairs); dispatch is async with a
    single block at the end."""
    iters = iters or ITERS
    x, *rest = args
    jfn = jax.jit(fn)
    xs = [x + jnp.float32(i).astype(x.dtype) for i in range(iters)]
    jax.block_until_ready(xs)
    out = jfn(xs[0], *rest)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = [jfn(xi, *rest) for xi in xs]
    jax.block_until_ready(outs)
    return (time.perf_counter() - t0) / iters


SHAPES = {
    "wq": (2048, 2048),
    "w1": (2048, 8192),
    "w2": (8192, 2048),
    "wcls": (2048, 128256),
}


def make_inputs(m, label):
    """Shared test data for run_one and the tile sweep — ONE definition so the
    sweep always benchmarks the same (w, x, qbytes) as the variant rows."""
    k, n = SHAPES[label]
    rng = np.random.default_rng(0)
    w = QTensor.quantize((rng.standard_normal((k, n)) * 0.02).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.bfloat16)
    qbytes = k * n // 2 + (k // Q_BLOCK) * n * 2  # packed + f16 scales
    return w, x, qbytes


def dispatch_closure(w, style, tk=None, tn=None):
    """Production-dispatch closure with forced style (+ optional blockdot tile
    overrides); a FRESH closure per combo so each re-traces its static args."""

    def prod(x, w=w, style=style, tk=tk, tn=tn):
        qmod.STYLE, qmod.BLOCKDOT_TK, qmod.BLOCKDOT_TN = style, tk, tn
        try:
            return qmod.q40_matmul(x, w, interpret=INTERPRET)
        finally:
            qmod.STYLE = "auto"
            qmod.BLOCKDOT_TK = qmod.BLOCKDOT_TN = None

    return prod


def run_one(m, label, variants):
    k, n = SHAPES[label]
    w, x, qbytes = make_inputs(m, label)
    rows = []
    for v in variants:
        # per-variant isolation: one Mosaic rejection (MD exists because the
        # batched dot_general might not lower) must not eat the row's other
        # timings in a one-shot TPU window
        try:
            if v in ("A", "DQ", "BD", "MD", "LD"):
                # NOTE: forced decode styles (BD/MD/LD) apply only when m <= 16;
                # larger m silently uses deq (the dispatcher's prefill rule)
                style = {"A": "auto", "DQ": "deq", "BD": "blockdot",
                         "MD": "maskdot", "LD": "loopdot"}[v]
                t = bench(dispatch_closure(w, style), (x,))
                rows.append((f"{v} {style}", t, qbytes))
            elif v == "B":
                call = make_call(_kernel_b, m, k, n)
                # legacy f32-scales kernel: feed widened scales (QTensor is f16 now)
                t = bench(call, (x, w.packed, w.scales.astype(jnp.float32)))
                rows.append(("B fma-f32", t, qbytes + (k // Q_BLOCK) * n * 2))  # f32 scales
            elif v == "D":
                wb = w.dequantize(jnp.bfloat16)
                call = make_call(_kernel_d, m, k, n, bf16=True)
                t = bench(call, (x, wb))
                rows.append(("D bf16-ref", t, k * n * 2))
            elif v == "E":
                t = bench(
                    lambda x, w: jnp.dot(x, w.dequantize(jnp.bfloat16), preferred_element_type=jnp.float32),
                    (x, w),
                )
                rows.append(("E xla-deq", t, qbytes))
            elif v == "Q8":
                # fused Q80 path (Q8Tensor): int8 codes + f16 scales,
                # 1.0625 B/weight streamed — same dispatch split as q40
                from dllama_tpu.ops.pallas.q80_matmul import q80_matmul
                from dllama_tpu.ops.quant import Q8Tensor

                rng8 = np.random.default_rng(0)
                w8 = Q8Tensor.quantize(
                    (rng8.standard_normal((k, n)) * 0.02).astype(np.float32))
                q8bytes = k * n + (k // Q_BLOCK) * n * 2
                t = bench(lambda x, w8=w8: q80_matmul(x, w8, interpret=INTERPRET), (x,))
                rows.append(("Q8 q80-fused", t, q8bytes))
            else:
                raise SystemExit(f"unknown variant {v!r}; see module docstring")
        except SystemExit:
            raise
        except Exception as e:
            print(f"m={m} {label} {v}: FAILED {e!r}"[:250])
            sys.stdout.flush()
    out = f"m={m} {label}: "
    for name, t, nb in rows:
        out += f"{name}={t*1e6:.0f}us({nb/t/1e9:.0f}GB/s) "
    print(out)
    sys.stdout.flush()


SUITE = [
    # decode shapes: the production dispatch + each forced style + rooflines
    # (+ Q8: the fused Q80-weight path at the same shape)
    (8, "w1", ["A", "BD", "MD", "LD", "DQ", "D", "E", "Q8"]),
    (8, "wcls", ["A", "D", "E"]),  # the lm head is ~18% of 1B weight bytes
    # prefill shapes: in-kernel deq vs the XLA dequant-dot the MXU loves
    (256, "w1", ["DQ", "D", "E", "Q8"]),
    (512, "w1", ["DQ", "D", "E"]),
]

SWEEP_TK = (512, 1024, 2048)
SWEEP_TN = (128, 256, 512)


def enable_smoke():
    """Same code path, CPU-sized: every SUITE row and the tile sweep run in
    interpret mode on shapes small enough for CI (seconds, not windows)."""
    global INTERPRET, ITERS, SHAPES, SUITE, SWEEP_TK, SWEEP_TN
    INTERPRET = True
    ITERS = 2
    SHAPES = {
        "wq": (128, 128),
        "w1": (128, 256),
        "w2": (256, 128),
        "wcls": (128, 512),
    }
    SUITE = [
        (8, "w1", ["A", "BD", "MD", "LD", "DQ", "B", "D", "E", "Q8"]),
        (8, "wcls", ["A", "D", "E"]),
        (32, "w1", ["DQ", "D", "E", "Q8"]),
    ]
    SWEEP_TK = (32, 64)
    SWEEP_TN = (128,)


def sweep_blockdot_tiles(m=8, label="w1"):
    """Autotune the decode kernel's (tk, tn) on hardware. Each combo prints
    (flushed) as soon as it's measured — a session timeout mid-sweep keeps
    everything already benchmarked — and a sorted summary lands at the end."""
    k, n = SHAPES[label]
    w, x, qbytes = make_inputs(m, label)
    rows = []
    for tk in SWEEP_TK:
        for tn in SWEEP_TN:
            if k % tk or n % tn:
                continue
            try:
                t = bench(dispatch_closure(w, "blockdot", tk, tn), (x,))
                rows.append((tk, tn, t))
                print(f"  tile tk={tk} tn={tn}: {t*1e6:.0f}us ({qbytes/t/1e9:.0f}GB/s)")
            except Exception as e:
                print(f"  tile tk={tk} tn={tn}: FAILED {e!r}"[:200])
            sys.stdout.flush()
    rows.sort(key=lambda r: r[2])
    out = f"tile sweep m={m} {label} best-first: "
    for tk, tn, t in rows:
        out += f"tk{tk}/tn{tn}={t*1e6:.0f}us({qbytes/t/1e9:.0f}GB/s) "
    print(out)
    sys.stdout.flush()


def bench_flash_decode():
    """Flash decode-shape A/Bs (VERDICT r3 weak #3/#4):

    1. pad-row cost: t=1 decode at group=4 (4 live rows padded to the tq=8
       sublane tile) vs group=8 with the SAME hkv (8 live rows, zero pad) —
       identical KV bytes streamed, identical grid, only live-row count
       differs. time(group=4) ~= time(group=8) proves the kernel is
       KV-DMA-bound: pad rows are free, doubling live rows is free, and a
       fold-2-kv-heads layout rework would buy nothing (it cannot reduce KV
       bytes). time(group=4) << time(group=8) means rows cost compute and a
       fold layout halving program count is worth building.
    2. pruning vs static grid: decode ms at S=8192 for pos 64 -> 7936. Time
       must scale ~linearly with the LIVE cache (pruned DMA+compute); a flat
       curve means the ~S/ts no-op grid steps dominate and the grid needs a
       dynamic bound.
    """
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    rng = np.random.default_rng(0)
    hd = 64 if INTERPRET else 128
    s_ab = 512 if INTERPRET else 1024
    for hq, hkv, kvdt, label in (
        (32, 8, jnp.bfloat16, "group=4 (4 live rows, 4 pad)"),
        (64, 8, jnp.bfloat16, "group=8 (8 live rows, 0 pad)"),
        # 3. f8 KV cache (--cache-dtype f8): same shapes as row 1 at HALF the
        #    cache bytes — if decode is cache-DMA-bound this should approach
        #    2x row 1's time-per-byte advantage
        (32, 8, jnp.float8_e4m3fn, "group=4 f8 KV cache"),
    ):
        q = jnp.asarray(rng.standard_normal((1, 1, hq, hd)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((1, hkv, s_ab, hd)), kvdt)
        v = jnp.asarray(rng.standard_normal((1, hkv, s_ab, hd)), kvdt)
        fn = lambda q, k, v: flash_gqa_attention(q, k, v, jnp.int32(s_ab - 2),
                                                 interpret=INTERPRET)
        try:
            t = bench(fn, (q, k, v))
            kv_bytes = 2 * hkv * s_ab * hd * jnp.dtype(kvdt).itemsize
            print(f"flash decode {label}: {t*1e6:.0f}us ({kv_bytes/t/1e9:.0f}GB/s cache)")
        except Exception as e:
            print(f"flash decode {label}: FAILED {e!r}"[:250])
        sys.stdout.flush()

    s_long = 1024 if INTERPRET else 8192
    k = jnp.asarray(rng.standard_normal((1, 8, s_long, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 8, s_long, hd)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((1, 1, 32, hd)), jnp.bfloat16)
    fn = lambda q, k, v, p: flash_gqa_attention(q, k, v, p, interpret=INTERPRET)
    rows = []
    for frac in (1 / 128, 1 / 8, 1 / 2, 63 / 64):
        pos = max(1, int(s_long * frac))
        try:
            t = bench(fn, (q, k, v, jnp.int32(pos)))
            rows.append((pos, t))
            print(f"flash decode S={s_long} pos={pos}: {t*1e6:.0f}us")
        except Exception as e:
            print(f"flash decode S={s_long} pos={pos}: FAILED {e!r}"[:250])
        sys.stdout.flush()
    if len(rows) >= 2:
        # live-cache scaling ratio vs grid-overhead floor
        (p0, t0), (p1, t1) = rows[0], rows[-1]
        print(f"pruning scaling: pos x{p1/p0:.0f} -> time x{t1/t0:.1f} "
              f"(~linear = pruning works; ~flat = static-grid overhead dominates)")
    sys.stdout.flush()

    # same depth sweep on the bucketed grid (DLLAMA_FLASH_BUCKETS): the
    # lax.switch dispatches to a pow-2 cache view, so shallow positions walk
    # a short grid instead of S/ts no-op steps. bucketed << static at small
    # pos (and ~equal at pos ~= S) => flip the engine default
    fnb = lambda q, k, v, p: flash_gqa_attention(q, k, v, p, interpret=INTERPRET,
                                                 s_buckets=True)
    for frac in (1 / 128, 1 / 8, 1 / 2, 63 / 64):
        pos = max(1, int(s_long * frac))
        try:
            t = bench(fnb, (q, k, v, jnp.int32(pos)))
            print(f"flash decode BUCKETED S={s_long} pos={pos}: {t*1e6:.0f}us")
        except Exception as e:
            print(f"flash decode BUCKETED S={s_long} pos={pos}: FAILED {e!r}"[:250])
        sys.stdout.flush()

    # prefill-chunk-at-shallow-depth A/B: an early chunk of a long chunked
    # prefill (pos=256, t=256) sees <= 512 live slots but statically walks
    # all of S — bucketing rides the 512 view instead
    tq_pf = 64 if INTERPRET else 256
    qp = jnp.asarray(rng.standard_normal((1, tq_pf, 32, hd)), jnp.bfloat16)
    for name, f in (("static", fn), ("BUCKETED", fnb)):
        try:
            t = bench(f, (qp, k, v, jnp.int32(tq_pf)))
            print(f"flash prefill t={tq_pf} {name} S={s_long} pos={tq_pf}: {t*1e6:.0f}us")
        except Exception as e:
            print(f"flash prefill {name}: FAILED {e!r}"[:250])
        sys.stdout.flush()


def main():
    # argv: 'suite [--smoke] [--no-flash]' | 'flash [--smoke]' |
    # M SHAPE [variant ...] — suite runs the whole decode + prefill matrix in
    # ONE process (one ~2 min device init, not six). --no-flash: the session
    # script passes this when the flash canary hung (a flash compile wedged
    # the 2026-07-31 window server-side, TPU_VALIDATE_r04.md) so the q40
    # numbers still land.
    no_flash = "--no-flash" in sys.argv
    if no_flash:
        sys.argv.remove("--no-flash")
    if "--smoke" in sys.argv:
        sys.argv.remove("--smoke")
        enable_smoke()
    if sys.argv[1:2] == ["flash"]:
        bench_flash_decode()
        print("KBENCH DONE")
        return
    if sys.argv[1:2] == ["suite"]:
        for m, label, variants in SUITE:
            try:
                run_one(m, label, variants)
            except Exception as e:
                print(f"m={m} {label}: FAILED {e!r}"[:300])
                sys.stdout.flush()
        try:
            sweep_blockdot_tiles()
        except Exception as e:
            print(f"tile sweep: FAILED {e!r}"[:300])
            sys.stdout.flush()
        if no_flash:
            print("flash bench SKIPPED (--no-flash)")
        else:
            try:
                bench_flash_decode()
            except Exception as e:
                print(f"flash bench: FAILED {e!r}"[:300])
                sys.stdout.flush()
        print("KBENCH DONE")
        sys.stdout.flush()
        return
    run_one(int(sys.argv[1]), sys.argv[2], sys.argv[3:] or ["A", "B", "D", "E"])


if __name__ == "__main__":
    main()
