"""Control canary: compile + run ONE tiny NON-flash Pallas kernel (the q40
blockdot decode matmul, the shape class the 2026-07-31 window PASSed before
wedging at the first flash compile — TPU_VALIDATE_r04.md).

Runs immediately before canary_flash.py in the session script. Its verdict is
what turns a later flash-canary hang into a yes/no wedge diagnosis instead of
an ambiguity (VERDICT r4 next #2 / #9):

  control OK + flash hang + post-hang probe dead  -> flash compile wedges the
                                                     server (reproduced)
  control OK + flash hang + post-hang probe alive -> flash-specific client
                                                     hang; server fine
  control hang                                    -> wedge is NOT flash-
                                                     specific (general Mosaic
                                                     compile / tunnel wedge)
"""
import numpy as np
import jax
import jax.numpy as jnp

from dllama_tpu.ops.pallas import q40_matmul as qmod
from dllama_tpu.ops.quant import QTensor

interp = jax.devices()[0].platform != "tpu"
rng = np.random.default_rng(0)
w = QTensor.quantize((rng.standard_normal((512, 512)) * 0.05).astype(np.float32))
x = jnp.asarray(rng.standard_normal((8, 512)), jnp.bfloat16)
qmod.STYLE = "blockdot"
try:
    out = qmod.q40_matmul(x, w, interpret=interp)
    jax.block_until_ready(out)
finally:
    qmod.STYLE = "auto"
assert np.isfinite(np.asarray(out, np.float32)).all()
print("CONTROL CANARY OK", flush=True)
