"""AOT Mosaic-acceptance check — NO TPU hardware needed.

libtpu is installed locally, and PJRT topology descriptions let XLA:TPU
compile a lowered module for a real chip target offline
(`jax.experimental.topologies.get_topology_desc`). That turns VERDICT r3's
biggest unknown — "does Mosaic accept the blockdot kernel's batched
dot_general?" (missing #2 / next-round #8) — into a question answerable
without the axon tunnel: compile every Pallas kernel, every decode style,
and the blockdot tile-sweep candidates for v5e/v6e (+ v4/v5p with --full)
and record ACCEPT or REJECT per (target, kernel).

Acceptance here means the Mosaic compiler inside XLA:TPU compiled the
kernel to machine code for that chip; runtime speed still needs the window
(kbench). Rejection surfaces the exact Mosaic error now, while there is
still time to fix the kernel before a window fires.

Usage: python experiments/aot_check.py [--full] [--md MOSAIC_AOT.md]
Exit 0 when every production-default kernel accepts on every target
(fallback styles may reject — they are insurance, flagged but not fatal).
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# topology-AOT needs no TPU attached, and off GCP the instance-metadata
# probe stalls through 30 failing fetches before libtpu gives up — skip it
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dllama_tpu.ops.pallas import q40_matmul as qmod
from dllama_tpu.ops.quant import Q_BLOCK

S = jax.ShapeDtypeStruct


def targets(full: bool):
    """Resolve every requested chip target; an unresolvable target is FATAL —
    a gate that silently compiled for fewer targets than requested would pass
    green while validating nothing (the whole point is that a Mosaic
    rejection must not survive to a live window)."""
    names = ["v5e:2x2", "v6e:2x2"] + (["v4:2x2x1", "v5p:2x2x1"] if full else [])
    from jax.experimental import topologies

    out = []
    for n in names:
        try:
            out.append((n, topologies.get_topology_desc(n, platform="tpu")))
        except Exception as e:
            raise SystemExit(
                f"FATAL: topology {n} unavailable ({repr(e)[:160]}) — the "
                "acceptance gate cannot run; do not treat this as a pass"
            )
    return out


def cases(full: bool):
    """(name, fn, abstract args, production) tuples. Shapes are the 1b preset's
    hot ops (kbench's SHAPES); `production` marks kernels whose rejection
    fails the check (the shipped defaults), vs fallback insurance."""
    L = 2
    sh_w = lambda k, n: (
        S((L, k // 2, n), jnp.uint8),
        S((L, k // Q_BLOCK, n), jnp.uint16),
    )
    layer = S((1,), jnp.int32)
    out = []

    def style_case(name, style, m, k, n, production, tk=None, tn=None):
        packed, scales = sh_w(k, n)

        def fn(l, x, p, s, style=style, tk=tk, tn=tn):
            qmod.STYLE, qmod.BLOCKDOT_TK, qmod.BLOCKDOT_TN = style, tk, tn
            try:
                return qmod.q40_matmul(x, qmod.QTensor(p, s), l)
            finally:
                qmod.STYLE = "auto"
                qmod.BLOCKDOT_TK = qmod.BLOCKDOT_TN = None

        out.append((name, fn, (layer, S((m, k), jnp.bfloat16), packed, scales), production))

    style_case("blockdot m=8 w1(2048x8192)", "blockdot", 8, 2048, 8192, True)
    style_case("blockdot m=8 wcls(2048x128256)", "blockdot", 8, 2048, 128256, True)
    style_case("deq m=256 w1(2048x8192)", "deq", 256, 2048, 8192, True)

    # the 8B preset's wcls (dim 4096) — the widest shape the flagship hits
    # (VERDICT r4 weak #6: on-chip validation covered one w1-sized point).
    # UNSTACKED 2-D weights with f16 scales and no layer index: byte-for-byte
    # the operands production wcls and the window's wcls validate group run,
    # so the gate pre-proves (and the compile cache pre-warms) those exact
    # executables.
    def flat_case(name, style, m, k, n):
        def fn(x, p, s, style=style):
            qmod.STYLE = style
            try:
                return qmod.q40_matmul(x, QTensor(p, s))
            finally:
                qmod.STYLE = "auto"

        out.append((name, fn,
                    (S((m, k), jnp.bfloat16), S((k // 2, n), jnp.uint8),
                     S((k // Q_BLOCK, n), jnp.float16)), True))

    from dllama_tpu.ops.quant import QTensor

    flat_case("blockdot m=8 wcls8b(4096x128256) flat", "blockdot", 8, 4096, 128256)
    flat_case("deq m=256 wcls8b(4096x128256) flat", "deq", 256, 4096, 128256)
    style_case("maskdot m=8 w1", "maskdot", 8, 2048, 8192, False)
    style_case("loopdot m=8 w1", "loopdot", 8, 2048, 8192, False)
    if full:
        for tk in (512, 1024, 2048):
            for tn in (128, 256, 512):
                style_case(f"blockdot tiles tk={tk} tn={tn}", "blockdot",
                           8, 2048, 8192, False, tk=tk, tn=tn)

    # q80 fused matmuls (packed int8 weights, the Q80-file fast path): the
    # same decode/prefill split as q40, production on unsharded engines
    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul
    from dllama_tpu.ops.quant import Q8Tensor

    q8w = Q8Tensor(S((L, 2048, 8192), jnp.int8), S((L, 2048 // Q_BLOCK, 8192), jnp.uint16))
    for q8m in (8, 256):
        out.append((f"q80 {'blockdot' if q8m <= 16 else 'deq'} m={q8m} w1(2048x8192)",
                    lambda x, l, c, s: q80_matmul(x, Q8Tensor(c, s), l),
                    (S((q8m, 2048), jnp.bfloat16), S((), jnp.int32),
                     q8w.codes, q8w.scales), True))
    # unstacked + f16 scales + no layer: identical operands to the wcls
    # validate group / a production Q80 head (see flat_case rationale)
    out.append(("q80 blockdot m=8 wcls8b(4096x128256) flat",
                lambda x, c, s: q80_matmul(x, Q8Tensor(c, s)),
                (S((8, 4096), jnp.bfloat16), S((4096, 128256), jnp.int8),
                 S((4096 // Q_BLOCK, 128256), jnp.float16)), True))

    # flash attention: decode (t=1, group=4 folded+pad) and prefill shapes
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    def flash(q_shape, s_len, kv_dtype=jnp.bfloat16):
        q = S(q_shape, jnp.bfloat16)
        kv = S((1, 8, s_len, 128), kv_dtype)
        return (lambda q, k, v: flash_gqa_attention(q, k, v, jnp.int32(7)),
                (q, kv, kv))

    fn, args = flash((1, 1, 32, 128), 1024)
    out.append(("flash decode t=1 S=1024", fn, args, True))
    fn, args = flash((1, 256, 32, 128), 1024)
    out.append(("flash prefill t=256 S=1024", fn, args, True))
    fn, args = flash((1, 1, 32, 128), 8192)
    out.append(("flash decode t=1 S=8192", fn, args, True))
    # f8 (e4m3) KV cache variant (--cache-dtype f8): half the cache DMA
    fn, args = flash((1, 1, 32, 128), 1024, jnp.float8_e4m3fn)
    out.append(("flash decode f8 KV cache", fn, args, True))
    # bucketed grid (DLLAMA_FLASH_BUCKETS): lax.switch over pow-2 cache
    # views — every branch is its own pallas_call instance, so Mosaic must
    # accept all of them plus the switch wrapping
    q8k = S((1, 1, 32, 128), jnp.bfloat16)
    kv8k = S((1, 8, 8192, 128), jnp.bfloat16)
    out.append(("flash decode bucketed S=8192",
                lambda q, k, v: flash_gqa_attention(q, k, v, jnp.int32(7),
                                                    s_buckets=True),
                (q8k, kv8k, kv8k), True))

    # general paged flash-decode kernel (ops/pallas/paged_attention): the
    # paged-by-default serving route — scalar-prefetched block tables,
    # double-buffered page DMA, fused KV-row scatter (whole-page RMW).
    # Production at the shipped default page size AND at the small/odd
    # sizes the old %64 gate rejected (the capability check admits them,
    # so Mosaic must keep accepting them); the t=9 case is the batched
    # spec-verify shape, the t=256 case exercises the XLA pre-scatter
    # prefill path of the same wrapper.
    from dllama_tpu.ops.pallas.paged_attention import paged_decode_attention

    def paged(page, nb, t=1, b=4, read_only=False):
        hq, hkv, hd = 32, 8, 128
        npool = b * nb + 1
        pools = S((npool, hkv, page, hd), jnp.bfloat16)
        args = [S((b, t, hq, hd), jnp.bfloat16), pools, pools,
                S((b, nb), jnp.int32), S((b,), jnp.int32)]
        if read_only:
            return (lambda q, kp, vp, tb, pos: paged_decode_attention(
                q, kp, vp, tb, pos, interpret=False), tuple(args))
        args += [S((b, hkv, t, hd), jnp.bfloat16),
                 S((b, hkv, t, hd), jnp.bfloat16), S((b,), jnp.bool_)]
        return (lambda q, kp, vp, tb, pos, nk, nv, act: paged_decode_attention(
            q, kp, vp, tb, pos, nk, nv, act, interpret=False), tuple(args))

    fn, args = paged(128, 8)
    out.append(("paged decode p=128 fused scatter", fn, args, True))
    fn, args = paged(8, 64)
    out.append(("paged decode p=8 fused scatter", fn, args, True))
    fn, args = paged(24, 16)
    out.append(("paged decode p=24 (odd page) fused scatter", fn, args, True))
    fn, args = paged(128, 8, t=9)
    out.append(("paged spec verify t=9 p=128 fused scatter", fn, args, True))
    fn, args = paged(128, 8, t=256, b=1)
    out.append(("paged prefill t=256 p=128 (XLA pre-scatter)", fn, args, True))
    fn, args = paged(128, 8, read_only=True)
    out.append(("paged decode p=128 read-only sweep", fn, args, True))

    from dllama_tpu.ops.pallas.rms_norm import rms_norm as prms

    out.append(("rms_norm (reserve)", lambda x, w: prms(x, w, 1e-5),
                (S((8, 2048), jnp.bfloat16), S((2048,), jnp.bfloat16)), False))

    # MoE compute schemes: no Pallas inside, but `sort` leans on
    # lax.ragged_dot and `dispatch` on .at[].add scatters — both exotic
    # enough on XLA:TPU that the gate must cover them before any default
    # flip (VERDICT r3 weak #6)
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.ops.layers import moe_ffn

    mcfg = LlamaConfig(dim=1024, hidden_dim=2048, n_layers=2, n_heads=8,
                       n_kv_heads=4, vocab_size=512, seq_len=64,
                       n_experts=8, n_active_experts=2)
    moe_args = (S((1, 64, 1024), jnp.bfloat16), S((1024, 8), jnp.float32),
                S((8, 1024, 2048), jnp.bfloat16),
                S((8, 2048, 1024), jnp.bfloat16),
                S((8, 1024, 2048), jnp.bfloat16))
    # production flags follow the auto resolution: sort (n >= E) and dense
    # (n < E, e.g. B=1 decode) are the shipped paths; dispatch is window-A/B
    # insurance only
    for impl in ("sort", "dispatch", "dense"):
        out.append((f"moe {impl} (8 experts, 64 tokens)",
                    lambda h, g, w1, w2, w3, impl=impl: moe_ffn(
                        mcfg, h, g, w1, w2, w3, impl=impl),
                    moe_args, impl != "dispatch"))
    return out


def full_step_case(topo):
    """The ENTIRE 1b decode step — embedding gather, 16-layer scan with
    blockdot matmuls + flash attention + KV cache update, final norm, wcls —
    AOT-compiled for one chip of the target. Kernel-level acceptance can miss
    interactions (Mosaic custom calls inside lax.scan, donated buffers);
    this is the whole production graph."""
    from functools import partial

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import forward
    from dllama_tpu.models.llama import KVCache
    from dllama_tpu.ops import matmul as mmod
    from dllama_tpu.ops.matmul import matmul
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention
    from dllama_tpu.ops.quant import QTensor

    cfg = LlamaConfig(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32,
                      n_kv_heads=8, vocab_size=128256, seq_len=1024)
    mesh = Mesh(topo.devices[:1], ("x",))
    repl = NamedSharding(mesh, P())
    A = lambda shape, dt: S(shape, dt, sharding=repl)

    def qw(lead, k, n):
        return QTensor(A((*lead, k // 2, n), jnp.uint8),
                       A((*lead, k // Q_BLOCK, n), jnp.uint16))

    L = cfg.n_layers
    params = {
        "embedding": A((cfg.vocab_size, cfg.dim), jnp.bfloat16),
        "final_norm": A((cfg.dim,), jnp.float32),
        "wcls": qw((), cfg.dim, cfg.vocab_size),
        "layers": {
            "wq": qw((L,), cfg.dim, cfg.dim),
            "wk": qw((L,), cfg.dim, cfg.kv_dim),
            "wv": qw((L,), cfg.dim, cfg.kv_dim),
            "wo": qw((L,), cfg.dim, cfg.dim),
            "w1": qw((L,), cfg.dim, cfg.hidden_dim),
            "w2": qw((L,), cfg.hidden_dim, cfg.dim),
            "w3": qw((L,), cfg.dim, cfg.hidden_dim),
            "rms_att": A((L, cfg.dim), jnp.float32),
            "rms_ffn": A((L, cfg.dim), jnp.float32),
        },
    }
    cshape = (L, 1, cfg.n_kv_heads, cfg.seq_len, cfg.head_size)
    cache = KVCache(A(cshape, jnp.bfloat16), A(cshape, jnp.bfloat16))
    rope = A((cfg.seq_len, cfg.head_size // 2, 2), jnp.float32)
    tokens = A((1, 1), jnp.int32)
    pos = A((), jnp.int32)

    def step(params, cache, tokens, pos, rope):
        mmod.INTERPRET = False
        try:
            logits, cache = forward(
                cfg, params, tokens, pos, cache, rope,
                partial(flash_gqa_attention, interpret=False),
                mm=partial(matmul, backend="pallas"), last_only=True,
            )
            return logits[:, -1], cache
        finally:
            mmod.INTERPRET = None

    # the speculative decoder: while_loop(propose + (k+1)-wide verify) over
    # the same kernels — m=9 blockdot, 9-row flash fold, scan-in-while_loop
    from dllama_tpu.engine.speculative import make_spec_decode

    def spec_fwd(params, cache, tokens, pos, rope, last_only=False):
        mmod.INTERPRET = False
        try:
            return forward(cfg, params, tokens, pos, cache, rope,
                           partial(flash_gqa_attention, interpret=False),
                           mm=partial(matmul, backend="pallas"),
                           last_only=last_only)
        finally:
            mmod.INTERPRET = None

    spec = make_spec_decode(spec_fwd, cfg.seq_len, k=8, donate=False)
    h = A((cfg.seq_len + 1,), jnp.int32)
    cur = A((), jnp.int32)

    def spec_step(params, cache, h, cur, pos, rope):
        return spec(params, cache, h, cur, pos, rope, 32)

    return [
        ("FULL 1b decode step (scan+flash+blockdot)", step,
         (params, cache, tokens, pos, rope), True),
        ("FULL 1b speculative decode (k=8 while_loop)", spec_step,
         (params, cache, h, cur, pos, rope), True),
    ]


def sharded_cases(topo):
    """The PRODUCTION shard_map'd Pallas paths (parallel/sharding.py), AOT-
    compiled on a 4-chip tp mesh of the target topology: out-dim-sharded mm,
    in-dim-sharded mm (+psum over 'tp'), head-sharded flash. This is the real
    multi-chip TP path compiling for a real chip — one step past the CPU
    dryrun (which can only prove partitioning, in interpret mode)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.ops import matmul as mmod
    from dllama_tpu.ops.quant import QTensor
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    cfg = LlamaConfig(dim=256, hidden_dim=512, n_layers=2, n_heads=8,
                      n_kv_heads=4, vocab_size=512, seq_len=256)
    mesh = make_mesh(MeshConfig(tp=4), devices=topo.devices[:4])
    sh = LlamaShardings(mesh, cfg)
    mm, mm_in = sh.pallas_mms(1)
    attn = sh.pallas_attn(1, interpret=False)
    ns = lambda spec: NamedSharding(mesh, spec)
    L = cfg.n_layers

    def qw(k, n, spec):
        return (S((L, k // 2, n), jnp.uint8, sharding=ns(spec)),
                S((L, k // Q_BLOCK, n), jnp.uint16, sharding=ns(spec)))

    def forced(fn):
        def wrapped(*args):
            mmod.INTERPRET = False
            try:
                return fn(*args)
            finally:
                mmod.INTERPRET = None
        return wrapped

    x = S((1, 1, cfg.dim), jnp.bfloat16, sharding=ns(P()))
    li = S((), jnp.int32, sharding=ns(P()))
    out = []
    p1, s1 = qw(cfg.dim, cfg.hidden_dim, P(None, None, "tp"))
    out.append(("shard_map mm out-shard (w1)",
                forced(lambda x, p, s, l: mm(x, QTensor(p, s), l)),
                (x, p1, s1, li), True))
    p2, s2 = qw(cfg.hidden_dim, cfg.dim, P(None, "tp", None))
    xh = S((1, 1, cfg.hidden_dim), jnp.bfloat16, sharding=ns(P(None, None, "tp")))
    out.append(("shard_map mm in-shard+psum (w2)",
                forced(lambda x, p, s, l: mm_in(x, QTensor(p, s), l)),
                (xh, p2, s2, li), True))
    q = S((1, 1, cfg.n_heads, 64), jnp.bfloat16, sharding=ns(P(None, None, "tp", None)))
    kc = S((1, cfg.n_kv_heads, cfg.seq_len, 64), jnp.bfloat16,
           sharding=ns(P(None, "tp", None, None)))
    pos = S((), jnp.int32, sharding=ns(P()))
    out.append(("shard_map head-sharded flash",
                lambda q, k, v, p: attn(q, k, v, p), (q, kc, kc, pos), True))
    return out


def main():
    full = "--full" in sys.argv
    md_path = "MOSAIC_AOT.md"
    if "--md" in sys.argv:
        i = sys.argv.index("--md") + 1
        if i >= len(sys.argv):
            raise SystemExit("usage: aot_check.py [--full] [--md OUTPUT.md]")
        md_path = sys.argv[i]
    rows, prod_reject = [], []
    for tname, topo in targets(full):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(topo.devices[:1], ("x",))
        repl = NamedSharding(mesh, P())
        single = [
            # pin abstract args to one device of the target topology so
            # XLA:TPU (not Host) compiles the module — Mosaic runs inside
            (cname, fn, tuple(S(a.shape, a.dtype, sharding=repl) for a in args), prod)
            for cname, fn, args, prod in cases(full)
        ]
        for cname, fn, args_sh, production in (
            single + sharded_cases(topo) + full_step_case(topo)
        ):
            t0 = time.time()
            try:
                jax.jit(fn).trace(*args_sh).lower().compile()
                verdict = "ACCEPT"
            except Exception as e:
                verdict = f"REJECT {repr(e)[:220]}"
                if production:
                    prod_reject.append((tname, cname))
            rows.append((tname, cname, production, verdict, time.time() - t0))
            print(f"{tname} | {cname}: {verdict} ({rows[-1][4]:.0f}s)", flush=True)

    with open(md_path, "w") as f:
        f.write(
            "# Mosaic AOT acceptance (offline XLA:TPU compile, no hardware)\n\n"
            "Per-target compile verdicts for every Pallas kernel, produced by\n"
            "`experiments/aot_check.py` via libtpu topology AOT compilation —\n"
            "the committed yes/no VERDICT r3 asked for on blockdot lowering\n"
            "(missing #2 / next-round #8). ACCEPT = Mosaic compiled the kernel\n"
            "to machine code for that chip; runtime perf still comes from\n"
            "kbench in a live window. 'prod' kernels are shipped defaults;\n"
            "others are fallback insurance.\n\n"
            "| target | kernel | prod | verdict |\n|---|---|---|---|\n"
        )
        for tname, cname, production, verdict, dt in rows:
            f.write(f"| {tname} | {cname} | {'yes' if production else ''} | "
                    f"{verdict.split(chr(10))[0][:120]} |\n")
    print(f"wrote {md_path}")
    print("AOT CHECK " + ("FAIL: production kernels rejected: " + str(prod_reject)
                          if prod_reject else "ALL PRODUCTION KERNELS ACCEPT"))
    return 1 if prod_reject else 0


if __name__ == "__main__":
    sys.exit(main())
