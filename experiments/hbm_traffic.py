"""Per-decode-token HBM traffic accounting — offline, no hardware needed.

VERDICT r3 (missing #1, weak #7) calls the decode tier's HBM-traffic claims
unmeasured: the fused Q40 kernels exist to stream ~4x fewer weight bytes
than a dequantize-then-dot path, but no artifact records what each path
actually moves. Two accounting methods, each used where it is valid:

* **XLA path** (dequant-dot): the whole graph is plain HLO, so XLA's
  post-fusion `bytes accessed` cost analysis — taken from the module
  AOT-compiled for the real v5e target via the local libtpu (same
  mechanism as MOSAIC_AOT.md) — is the compiler's own accounting of HBM
  reads/writes.
* **Pallas paths** (blockdot/deq): XLA treats Mosaic kernels as opaque
  custom-calls and its cost model UNDER-counts them — it reports fewer
  bytes than the physical Q40 weight floor a decode step must stream,
  which is impossible (run with --show-xla-undercount to see it). For
  these paths the kernel stream is accounted from the BlockSpec DMA
  contract instead, which is exact by construction: packed nibbles +
  f16-as-u16 scales + activations in, f32 out per matmul; q rows + live
  KV tiles + out per flash call; one cache row write per layer. The
  AOT compile still runs first, so every number here describes a graph
  Mosaic ACCEPTED for v5e.

Derived `roofline ms/token` = bytes / 819 GB/s (v5e HBM): the
decode-latency floor the live-window bench is judged against — not a
wall-clock measurement.

Reference analog: the report's bandwidth discussion and the per-token
console contract (/root/reference/src/dllama.cpp:54-104); the Q40 weight
stream math in nn-quants.hpp / converter/writer.py.

Usage: python experiments/hbm_traffic.py [--smoke] [--md HBM_TRAFFIC.md]
--smoke compiles one tiny case only (CI plumbing proof, CPU-safe).
"""

from __future__ import annotations

import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward
from dllama_tpu.ops import matmul as mmod
from dllama_tpu.ops.matmul import matmul
from dllama_tpu.ops.pallas import q40_matmul as qmod
from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention
from dllama_tpu.ops.quant import Q_BLOCK, QTensor

V5E_HBM_GBS = 819.0  # v5e HBM bandwidth (public spec) for the roofline line

PRESETS = {
    # bench.py's synthetic presets (llama-3.2-1b / llama-3.1-8b shapes)
    "1b": LlamaConfig(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32,
                      n_kv_heads=8, vocab_size=128256, seq_len=1024),
    "8b": LlamaConfig(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32,
                      n_kv_heads=8, vocab_size=128256, seq_len=1024),
    "tiny": LlamaConfig(dim=256, hidden_dim=512, n_layers=2, n_heads=8,
                        n_kv_heads=4, vocab_size=512, seq_len=256),
}


def q40_weight_bytes(cfg: LlamaConfig) -> int:
    """The theoretical per-token floor: every decode step must stream every
    Q40 weight byte once (16 packed + 2 scale bytes per 32 weights). Summed
    over the .m file's own tensor plan so it can never diverge from what the
    model actually loads."""
    from dllama_tpu.models import formats
    from dllama_tpu.ops.quant import FloatType

    total = 0
    for _name, shape, ft in formats.tensor_plan(cfg):
        if ft == FloatType.Q40:
            n = 1
            for d in shape:
                n *= d
            total += ft.nbytes(n)
    return total


def kernel_stream_bytes(cfg: LlamaConfig, live_frac: float = 1.0,
                        weight_bytes_per: float = 18 / 32) -> int:
    """Per-decode-token HBM bytes of the fused-Pallas step, from the
    BlockSpec DMA contract (ops/pallas/q40_matmul.py, flash_attention.py):

    * each Q40 matmul streams its packed [k/2, n] u8 + [k/32, n] u16 scales
      once, plus the [m, k] bf16 activation rows and [m, n] f32 out
      (negligible next to the weight stream at m = 8 padded decode rows);
    * flash reads the folded q rows + `live_frac` of the [Hkv, S, hd] KV
      cache (bf16 k and v) — the pruning horizon at pos = live_frac*S —
      and writes one [rows, hd] f32 block per kv head;
    * the KV cache update writes one [Hkv, hd] row pair per layer;
    * embedding gather reads one [dim] bf16 row.
    """
    m = 8  # decode rows after sublane padding (t=1, group<=8)
    L, d, h, kv, hd = (cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.kv_dim,
                       cfg.head_size)
    total = 0

    def mm(k, n):
        # weight_bytes_per covers packed codes + scales: 18/32 for Q40
        # (nibbles + f16 scales), 34/32 for Q80 (int8 + f16 scales)
        return int(k * n * weight_bytes_per) + m * k * 2 + m * n * 4

    per_layer = (mm(d, d) * 2 + mm(d, kv) * 2  # wq, wo, wk, wv
                 + mm(d, h) * 2 + mm(h, d)  # w1, w3 (d->h); w2 (h->d)
                 + int(2 * cfg.n_kv_heads * cfg.seq_len * hd * 2 * live_frac)
                 + m * hd * (2 + 4) * cfg.n_kv_heads  # flash q in + out blocks
                 + 2 * kv * 2)  # cache row write (k and v)
    total += per_layer * L
    total += mm(d, cfg.vocab_size)  # lm head
    total += d * 2  # embedding row
    return total


def batched_step_bytes(cfg: LlamaConfig, slots: int, live_frac: float = 1.0,
                       cache_bytes_per_el: int = 2, paged: bool = False,
                       page_size: int = 128,
                       paged_impl: str = "kernel") -> int:
    """Per-STEP HBM bytes of a `slots`-wide batched decode (BatchEngine):
    the weight stream is read once and serves every slot (the entire point
    of the serving tier), while the KV stream scales with slots — each
    slot's cache rows are its own. Activation rows scale with slots but
    stay negligible. cache_bytes_per_el=1 models the f8 KV cache.

    paged=True accounts the paged layout's overhead against the SAME
    DMA-contract discipline as the dense rows: (1) the live KV stream
    rounds up to whole pages per slot (the page is the DMA quantum of the
    flash-decode kernel), and (2) the i32 block tables ride as the
    scalar-prefetch operand — once per fused launch per layer on the
    ``paged_impl='kernel'`` route (ops/pallas/paged_attention, the shipped
    default), or per gather (k + v) PLUS a full re-materialized
    ``seq_len``-row view write+read on the ``'gather'`` jnp fallback. Both
    are per-step HBM reads the dense layout does not pay — the honest cost
    of making the 96-slot pool allocatable at all.

    The byte formula itself lives in ``dllama_tpu/obs/perf.decode_step_bytes``
    (ISSUE 7): the live bandwidth-attainment gauge prices every consumed
    decode chunk with the SAME function, so the offline tables here and the
    serving-time roofline cannot drift. This wrapper only supplies the
    Q40-weight-stream pricing and cfg unpacking the offline tables want."""
    from dllama_tpu.obs.perf import decode_step_bytes

    return decode_step_bytes(
        n_layers=cfg.n_layers, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
        kv_dim=cfg.kv_dim, head_size=cfg.head_size,
        n_kv_heads=cfg.n_kv_heads, vocab_size=cfg.vocab_size,
        seq_len=cfg.seq_len, weight_bytes=q40_weight_bytes(cfg),
        slots=slots, live_rows=live_frac * cfg.seq_len,
        cache_bytes_per_el=cache_bytes_per_el,
        paged=paged, page_size=page_size, paged_impl=paged_impl)


def abstract_model(cfg: LlamaConfig, sharding):
    A = lambda shape, dt: jax.ShapeDtypeStruct(shape, dt, sharding=sharding)

    def qw(lead, k, n):
        return QTensor(A((*lead, k // 2, n), jnp.uint8),
                       A((*lead, k // Q_BLOCK, n), jnp.uint16))

    L = cfg.n_layers
    params = {
        "embedding": A((cfg.vocab_size, cfg.dim), jnp.bfloat16),
        "final_norm": A((cfg.dim,), jnp.float32),
        "wcls": qw((), cfg.dim, cfg.vocab_size),
        "layers": {
            "wq": qw((L,), cfg.dim, cfg.dim),
            "wk": qw((L,), cfg.dim, cfg.kv_dim),
            "wv": qw((L,), cfg.dim, cfg.kv_dim),
            "wo": qw((L,), cfg.dim, cfg.dim),
            "w1": qw((L,), cfg.dim, cfg.hidden_dim),
            "w2": qw((L,), cfg.hidden_dim, cfg.dim),
            "w3": qw((L,), cfg.dim, cfg.hidden_dim),
            "rms_att": A((L, cfg.dim), jnp.float32),
            "rms_ffn": A((L, cfg.dim), jnp.float32),
        },
    }
    cshape = (L, 1, cfg.n_kv_heads, cfg.seq_len, cfg.head_size)
    cache = KVCache(A(cshape, jnp.bfloat16), A(cshape, jnp.bfloat16))
    rope = A((cfg.seq_len, cfg.head_size // 2, 2), jnp.float32)
    tokens = A((1, 1), jnp.int32)
    pos = A((), jnp.int32)
    return params, cache, tokens, pos, rope


def inventory_cross_check(compiled) -> dict:
    """Compiler-verified op inventory (VERDICT r4 next #9: the analytic
    roofline 'is accounting, not a stopwatch' — so at least the *inventory*
    it accounts must be the compiler's). Parses the v5e-AOT-compiled fused
    decode step's optimized HLO for Mosaic custom calls: the per-layer scan
    body must contain exactly 7 q40 matmuls (wq wk wv wo w1 w2 w3) + 1
    flash attention, and exactly 1 call (the wcls matmul) must sit outside
    the loop — the same inventory kernel_stream_bytes() sums. A mismatch
    means the formula forgot or double-counted an op and every roofline in
    HBM_TRAFFIC.md inherits the error."""
    import re

    text = compiled.as_text()
    # count tpu_custom_call occurrences per HLO computation: computations
    # open with '<name> (<params>) -> <type> {' and close with a bare '}'
    counts: dict[str, int] = {}
    cur = None
    for line in text.splitlines():
        if re.match(r"^(ENTRY\s+)?%?[\w\.\-]+ \(.*\) -> .* \{", line):
            cur = line.split(" ", 1)[0].lstrip("%")
            counts.setdefault(cur, 0)
        elif line.startswith("}"):
            cur = None
        elif cur is not None and "tpu_custom_call" in line:
            counts[cur] += 1
    total = sum(counts.values())
    body = max(counts.values(), default=0)  # the scan body computation
    outside = total - body
    expected_body, expected_outside = 7 + 1, 1
    ok = body == expected_body and outside == expected_outside
    return {"per_layer": body, "outside_loop": outside,
            "expected_per_layer": expected_body,
            "expected_outside": expected_outside, "ok": ok}


def cost_of(compiled) -> dict:
    """Unwrap compiled.cost_analysis() across jax versions (list vs dict)."""
    ca = compiled.cost_analysis()
    return ca[0] if isinstance(ca, (list, tuple)) else ca


def compile_step(cfg, topo, *, backend: str, style: str | None, on_cpu=False):
    """AOT-compile one decode step for the target; returns the compiled
    executable (cost_of() extracts the compiler accounting)."""
    if on_cpu:
        mesh = Mesh(jax.devices("cpu")[:1], ("x",))
    else:
        mesh = Mesh(topo.devices[:1], ("x",))
    repl = NamedSharding(mesh, P())
    args = abstract_model(cfg, repl)

    attn = partial(flash_gqa_attention, interpret=on_cpu)

    def step(params, cache, tokens, pos, rope):
        mmod.INTERPRET = on_cpu
        old_style = qmod.STYLE
        if style is not None:
            qmod.STYLE = style
        try:
            logits, cache = forward(cfg, params, tokens, pos, cache, rope,
                                    attn if backend == "pallas" else None,
                                    mm=partial(matmul, backend=backend),
                                    last_only=True)
            return logits[:, -1], cache
        finally:
            mmod.INTERPRET = None
            qmod.STYLE = old_style

    return jax.jit(step).trace(*args).lower().compile()


def main():
    smoke = "--smoke" in sys.argv
    show_undercount = "--show-xla-undercount" in sys.argv
    md_path = None
    if "--md" in sys.argv:
        i = sys.argv.index("--md") + 1
        if i >= len(sys.argv):
            raise SystemExit("usage: hbm_traffic.py [--smoke] [--md OUTPUT.md]")
        md_path = sys.argv[i]

    presets = ["tiny"] if smoke else ["1b", "8b"]
    topo = None
    on_cpu = smoke
    if not smoke:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")

    rows = []
    inventories = {}
    for preset in presets:
        cfg = PRESETS[preset]
        floor = q40_weight_bytes(cfg)

        # fused-Pallas decode step: AOT-compile first (Mosaic acceptance for
        # v5e), then account the kernel stream from the BlockSpec contract —
        # XLA's cost model under-counts opaque Mosaic calls (below)
        try:
            compiled = compile_step(cfg, topo, backend="pallas", style="blockdot",
                                    on_cpu=on_cpu)
            if show_undercount:
                ca = cost_of(compiled)
                print(f"  [xla cost model claims {ca.get('bytes accessed', 0)/1e9:.3f}GB "
                      f"for the pallas step — BELOW the {floor/1e9:.3f}GB "
                      f"physical weight floor, hence unusable here]")
            if not on_cpu:
                # compiler-verified inventory: the same compiled module the
                # rows below account must contain exactly the ops they sum
                inv = inventory_cross_check(compiled)
                inventories[preset] = inv
                print(f"{preset} inventory: {inv['per_layer']}/layer "
                      f"(expect {inv['expected_per_layer']}), "
                      f"{inv['outside_loop']} outside loop "
                      f"(expect {inv['expected_outside']}) -> "
                      f"{'OK' if inv['ok'] else 'FAILED (inventory mismatch)'}")
            for lf, tag in ((0.5, "cache half full"), (1.0, "cache full")):
                by = kernel_stream_bytes(cfg, live_frac=lf)
                rows.append((f"{preset} fused pallas ({tag})", by, floor,
                             by / V5E_HBM_GBS / 1e6, "DMA contract"))
        except Exception as e:
            rows.append((f"{preset} fused pallas", None, floor, None, ""))
            print(f"{preset} pallas: FAILED {e!r}"[:300])

        # Q80-weight variant of the same model (34/32 B/weight fused vs the
        # 2 B/weight dense-bf16 fallback meshes still use) — DMA-contract
        # accounting like the Q40 rows; Mosaic acceptance of the q80 kernels
        # is covered by MOSAIC_AOT.md
        if preset == "8b":
            q80_floor = int(floor / (18 / 32) * (34 / 32))
            for wb, tag in ((34 / 32, "q80 fused"), (2.0, "q80 dense-bf16 fallback")):
                by = kernel_stream_bytes(cfg, live_frac=0.5, weight_bytes_per=wb)
                rows.append((f"{preset} {tag} (cache half full)", by, q80_floor,
                             by / V5E_HBM_GBS / 1e6, "DMA contract"))

        # XLA dequant-dot step: plain HLO, compiler accounting is valid
        try:
            ca = cost_of(compile_step(cfg, topo, backend="xla", style=None,
                                      on_cpu=on_cpu))
            by = ca.get("bytes accessed", 0.0)
            if not by:
                # a cost-analysis schema change must not be committed as a
                # "the dequant path moves zero bytes" measurement
                raise RuntimeError(
                    f"cost_analysis returned no 'bytes accessed' ({sorted(ca)[:8]})")
            rows.append((f"{preset} xla dequant-dot", by, floor,
                         by / V5E_HBM_GBS / 1e6, "compiler (post-fusion HLO)"))
        except Exception as e:
            rows.append((f"{preset} xla dequant-dot", None, floor, None, ""))
            print(f"{preset} xla: FAILED {e!r}"[:300])

        for label, by, floor_, ms, how in [r for r in rows if r[0].startswith(preset)]:
            if by is not None:
                print(f"{label}: bytes/token={by/1e9:.3f}GB floor={floor_/1e9:.3f}GB "
                      f"({by/floor_:.2f}x) roofline={ms:.2f}ms [{how}]")
        sys.stdout.flush()

    # batched serving tier (the vs_baseline number): the weight stream is
    # read once per STEP and serves every slot, so aggregate tok/s scales
    # until the per-slot KV stream takes over — this is the committed
    # roofline the 8b slot sweep (BENCH batch records) is judged against
    batched = []
    if not smoke:
        cfg = PRESETS["8b"]
        for slots, cache_el, paged, tag in (
            (8, 2, False, "bf16 KV"), (32, 2, False, "bf16 KV"),
            (48, 2, False, "bf16 KV"), (48, 1, False, "f8 KV"),
            (96, 1, False, "f8 KV"),
            # paged rows: same DMA-contract accounting + block-table reads
            # and page-granular pruning — paging's honest per-step overhead.
            # The dense 96-slot rows above are ROOFLINE-ONLY (the dense
            # cache cannot be allocated at 96 slots in 16 GB); the paged
            # rows describe a configuration the engine can actually run.
            (48, 2, True, "bf16 KV, paged"), (48, 1, True, "f8 KV, paged"),
            (96, 1, True, "f8 KV, paged"),
        ):
            by = batched_step_bytes(cfg, slots, live_frac=0.5,
                                    cache_bytes_per_el=cache_el, paged=paged)
            step_ms = by / V5E_HBM_GBS / 1e6
            agg = slots / step_ms * 1000
            batched.append((f"8b {slots} slots ({tag})", by, step_ms, agg))
            print(f"8b batched {slots} slots {tag}: {by/1e9:.2f}GB/step "
                  f"{step_ms:.2f}ms -> {agg:.0f} tok/s aggregate roofline")
        sys.stdout.flush()

    if md_path and not smoke:
        with open(md_path, "w") as f:
            f.write(
                "# HBM traffic per decode token (v5e target, offline)\n\n"
                "Produced by `experiments/hbm_traffic.py`. The Q40 fused and\n"
                "xla rows' graphs were AOT-compiled for v5e via the local\n"
                "libtpu (Mosaic acceptance, same mechanism as MOSAIC_AOT.md);\n"
                "the q80 rows are DMA-contract accounting only — the q80\n"
                "kernels' acceptance is recorded separately in MOSAIC_AOT.md.\n"
                "Accounting:\n"
                "the fused-Pallas rows use the kernels' BlockSpec DMA\n"
                "contract (exact by construction; XLA's cost model treats\n"
                "Mosaic custom-calls as opaque and reports less than the\n"
                "physical weight floor, so it cannot be used there); the\n"
                "XLA-path rows use the compiler's own post-fusion\n"
                "`bytes accessed`. `floor` = the Q40 weight stream every\n"
                "decode step must read at least once (18 bytes/32 weights).\n"
                f"`roofline ms/token` = bytes / {V5E_HBM_GBS:.0f} GB/s (v5e\n"
                "HBM): the latency floor the live-window bench is judged\n"
                "against — static accounting, not a wall-clock measurement.\n\n"
                "| case | bytes/token | weight floor | ratio | roofline ms/token | accounting |\n"
                "|---|---|---|---|---|---|\n")
            for label, by, floor_, ms, how in rows:
                if by is None:
                    f.write(f"| {label} | FAILED | | | | |\n")
                else:
                    f.write(f"| {label} | {by/1e9:.3f} GB | {floor_/1e9:.3f} GB "
                            f"| {by/floor_:.2f}x | {ms:.2f} ms | {how} |\n")
            f.write(
                "\n## Batched serving roofline (8b, cache half full)\n\n"
                "One fused step reads the weight stream once for ALL slots;\n"
                "only the KV stream scales with slots. Aggregate tok/s =\n"
                "slots / step-time. The north star (BASELINE.json,\n"
                "1000 tok/s/chip serving) is judged on this tier.\n"
                "'paged' rows add the paged KV layout's per-step overhead\n"
                "under the same DMA-contract accounting: i32 block-table\n"
                "reads (k+v, per layer) plus page-granular (128-row)\n"
                "rounding of the live-KV pruning horizon. The dense\n"
                "96-slot row is roofline-only — 96 dense slots cannot be\n"
                "ALLOCATED in 16 GB (96 x 8 Ki-row reservations); the paged\n"
                "rows describe pools the engine actually allocates\n"
                "(--kv-layout paged), which is what makes the 96-slot\n"
                "number reachable.\n\n"
                "| case | bytes/step | step roofline | aggregate tok/s roofline |\n"
                "|---|---|---|---|\n")
            for label, by, step_ms, agg in batched:
                f.write(f"| {label} | {by/1e9:.2f} GB | {step_ms:.2f} ms "
                        f"| {agg:.0f} |\n")
            if inventories:
                f.write(
                    "\n## Op-inventory cross-check (compiler-verified)\n\n"
                    "The DMA-contract rows above are only as honest as the op\n"
                    "inventory they sum. This section parses the SAME v5e-AOT-\n"
                    "compiled module for Mosaic custom calls: the per-layer\n"
                    "scan body must hold exactly 7 q40 matmuls + 1 flash\n"
                    "attention, with exactly 1 call (the wcls matmul) outside\n"
                    "the loop — anything else means the formula forgot or\n"
                    "double-counted an op (VERDICT r4 next #9 offline leg).\n\n"
                    "| preset | calls/layer (expect 8) | outside loop (expect 1) | verdict |\n"
                    "|---|---|---|---|\n")
                for p, inv in inventories.items():
                    f.write(f"| {p} | {inv['per_layer']} | {inv['outside_loop']} | "
                            f"{'OK' if inv['ok'] else 'MISMATCH'} |\n")
            f.write(
                "\nReading the table: the fused decode tier sits within a\n"
                "few percent of the physical Q40 floor plus the live KV\n"
                "stream, while the dequantize-then-dot path moves 2-5x the\n"
                "floor — the offline confirmation of the packed-weights\n"
                "bandwidth win the decode kernels exist for (VERDICT r3\n"
                "weak #7 / missing #1's traffic claim). The live-window\n"
                "bench's decode ms/token should land within ~1.5x of the\n"
                "fused rows' roofline; further off means scheduling, not\n"
                "bandwidth, is the problem.\n")
        print(f"wrote {md_path}")
    if any(not inv["ok"] for inv in inventories.values()):
        # an inventory mismatch invalidates every DMA-contract roofline row:
        # fail loudly instead of regenerating a wrong artifact as a success
        raise SystemExit("HBM TRAFFIC FAILED: op-inventory mismatch — "
                         "kernel_stream_bytes() no longer matches the "
                         "compiled module; fix the formula before trusting "
                         "the rooflines")
    print("HBM TRAFFIC DONE")


if __name__ == "__main__":
    main()
