"""Mechanical BENCH_rN vs BENCH_r(N-1) regression gate (ISSUE 7).

Every round so far has compared bench records by eyeball — which is how a
10% decode regression hides behind a reshuffled JSON and a "looks fine".
This tool makes the comparison mechanical: it flattens the ``parsed``
record of two BENCH_r*.json files into dotted numeric leaves, matches each
leaf against a per-metric rule table (direction + relative tolerance), and
emits a machine-readable verdict. A metric only FAILS when it moved in its
*worse* direction by more than its tolerance; improvements and un-gated
informational fields never fail. Metrics missing from either side are
reported but do not fail the gate (bench legs are budget- and
env-gated — BENCH_PAGED=0 etc. — so absence is routine, not regression).

Exit status: 0 = no gated regressions (self-diff is a pass by
construction), 1 = at least one gated regression, 2 = usage/parse errors.

Usage:
    python experiments/perfdiff.py OLD.json NEW.json [--json] [--scale F]

``--scale`` multiplies every tolerance (CPU fallback runs are noisier than
TPU runs; scripts/perf_gate.sh forwards $PERFDIFF_SCALE). The wrapper
scripts/perf_gate.sh is the CI entry point.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys

#: (path glob, direction, relative tolerance) — first match wins.
#: direction 'higher' = bigger is better (fails when new < old * (1-tol)),
#: 'lower' = smaller is better (fails when new > old * (1+tol)),
#: 'info' = report only, never gate. Tolerances are deliberately loose:
#: the gate exists to catch step-function regressions (a broken kernel
#: route, a serialized pipeline), not to litigate run-to-run noise.
RULES: list[tuple[str, str, float]] = [
    # headline + per-preset engine numbers
    ("value", "higher", 0.15),
    ("presets.*.decode_tok_s", "higher", 0.15),
    ("presets.*.prefill_tok_s", "higher", 0.25),
    ("presets.*.decode_ms_per_token", "lower", 0.20),
    ("presets.*.spec.tok_s", "higher", 0.25),
    ("presets.*.compile_s", "lower", 1.00),
    # serving-tier A/B ratios (already normalized — tight tolerances)
    ("overlap.tok_s_ratio_on_off", "higher", 0.10),
    ("overlap.host_gap_reduction_x", "higher", 0.50),
    ("trace.tok_s_ratio_on_off", "higher", 0.05),
    ("paged.tok_s_ratio_paged_dense", "higher", 0.10),
    # ISSUE 8: the fused flash-decode kernel must keep beating the jnp
    # gather on the paged layout (ratio is normalized; loose tolerance
    # because the CPU-fallback legs time Pallas interpret mode)
    ("paged_kernel.pages.*.tok_s_ratio_kernel_gather", "higher", 0.50),
    ("batch.*.agg_tok_s", "higher", 0.20),
    ("admission.stall_reduction_x", "higher", 0.50),
    # ISSUE 12 hybrid fused step: the stall a joining prompt inflicts on
    # running streams must stay collapsed (ratio vs the sync baseline,
    # normalized) and the joiner's TTFT overhead must stay bounded; the
    # during-admission ITL tail gates down like the slo record's
    ("hybrid.stall_reduction_x", "higher", 0.50),
    ("hybrid.ttft_overhead_x", "lower", 0.35),
    ("hybrid.hybrid_itl_p95_ms", "lower", 0.50),
    # ISSUE 11 speculative continuous batching: the serving tier must keep
    # its spec-over-plain win on the draftable leg, and a spec neighbor
    # must never collapse the non-spec slots' throughput on the mixed leg
    # (ratios are normalized; loose tolerances — CPU-fallback hosts time
    # tiny models where per-cycle host overhead dominates)
    ("spec_batch.repetitive.tok_s_ratio_spec_plain", "higher", 0.50),
    ("spec_batch.mixed.nonspec_tok_s_ratio", "higher", 0.50),
    ("spec_batch.repetitive.tokens_per_cycle", "higher", 0.50),
    # ISSUE 15 router record: prefix-affinity must keep its warm-TTFT win
    # over round-robin (ratio on/off < 1, normalized) and two replicas
    # must keep out-scaling one (loose — CPU-fallback hosts share cores
    # between the in-process replicas, so scaling is well under 2x)
    ("router.affinity.warm_ttft_ratio_on_off", "lower", 0.50),
    ("router.scale.agg_tok_s_ratio_2_1", "higher", 0.50),
    # ISSUE 17 mesh observability: the full plane (trace minting + hop
    # headers + router spans + postmortem journal) must stay ~free on the
    # proxied path (ratio on/off is normalized; loose — CPU-fallback
    # hosts time tiny bursts where scheduler jitter dominates), every
    # scraped replica must land clock-ALIGNED in the merged trace
    # (absolute invariant, unscaled ceiling like the ledger residual),
    # and the federation scrape must stay cheap enough to poll
    ("fleet_obs.tok_s_ratio_on_off", "higher", 0.50),
    ("fleet_obs.trace.unaligned_replicas", "ceiling", 0.0),
    ("fleet_obs.scrape.federated_ms_mean", "lower", 1.00),
    # ISSUE 19 acceptance pin: federation + tracing + client SLO windows
    # may cost the proxy hot path at most 3% (off/on best-of-3 alternating
    # bursts — an UNSCALED ceiling, not a normalized ratio: 1.03 means
    # 1.03x, on every host)
    ("fleet_obs.proxy_overhead_x", "ceiling", 1.03),
    # ISSUE 9 radix record: warm TTFT must stay collapsed relative to cold
    # (ratio is normalized; loose tolerance — CPU hosts time compile-warm
    # suffix prefills against a chunked cold prefill)
    ("radix.warm_cold_ttft_ratio", "lower", 0.50),
    ("radix.shared_system.saved_prefill_tokens", "higher", 0.50),
    # ISSUE 7 slo record: tail latency gates DOWN, attainment gates UP
    ("slo.ttft_ms_p95", "lower", 0.35),
    ("slo.itl_ms_p95", "lower", 0.35),
    ("slo.agg_tok_s", "higher", 0.15),
    ("slo.goodput_tok_s", "higher", 0.25),
    ("slo.throughput_tok_s", "higher", 0.25),
    ("slo.bandwidth_attainment", "higher", 0.35),
    # the ledger partition invariant is an absolute property, not a trend:
    # gate it against a fixed ceiling via the pseudo-rule below
    ("slo.ledger_residual_frac", "ceiling", 0.02),
    # ISSUE 13 compile & device-traffic record: the steady-state decode
    # window must stay at ZERO recompiles (unexpected or otherwise) and
    # ZERO host->device upload bytes — absolute ceilings, unscaled, like
    # the ledger residual (invariants, not trends); and a warmed boot must
    # keep the first-request TTFT collapsed vs cold (ratio is normalized)
    ("compile.steady.compiles", "ceiling", 0.0),
    ("compile.steady.unexpected_compiles", "ceiling", 0.0),
    ("compile.steady.upload_bytes", "ceiling", 0.0),
    ("compile.warm_first_request_compiles", "ceiling", 0.0),
    ("compile.warmup_ttft_ratio", "lower", 0.5),
    ("*", "info", 0.0),
]


def flatten(obj, prefix: str = "") -> dict[str, float]:
    """Dotted-path -> numeric leaf map (list items become ``name.i`` —
    dotted, so fnmatch ``*`` rules cover sweeps and indices alike; bools and
    error strings are skipped — a leg that died carries no metrics)."""
    out: dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(flatten(v, f"{prefix}.{i}" if prefix else str(i)))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def rule_for(path: str) -> tuple[str, str, float]:
    # list indices are wildcarded so one rule covers the whole sweep
    for pat, direction, tol in RULES:
        if fnmatch.fnmatchcase(path, pat):
            return pat, direction, tol
    return "*", "info", 0.0


def judge(path: str, old: float, new: float, scale: float) -> dict:
    """One metric's verdict: status in {ok, regression, improved, info}."""
    pat, direction, tol = rule_for(path)
    tol *= scale
    rec = {"metric": path, "rule": pat, "direction": direction,
           "tol": round(tol, 4), "old": old, "new": new}
    if direction == "info":
        rec["status"] = "info"
        return rec
    if direction == "ceiling":
        # absolute bound on the NEW value only (invariants, not trends);
        # scale does not loosen invariants
        rec["status"] = "ok" if new <= tol / scale else "regression"
        rec["bound"] = tol / scale
        return rec
    span = abs(old)
    if span == 0.0:
        # a zero baseline gives relative tolerance nothing to scale by
        # (0.0 -> anything is an infinite relative move): report, never
        # gate — a self-diff or a first populated value must not fail
        rec["status"] = "ok" if new == old else "zero_baseline"
        return rec
    if direction == "higher":
        worse = old - new
    else:
        worse = new - old
    rec["delta_frac"] = round((new - old) / span, 4)
    if worse > tol * span:
        rec["status"] = "regression"
    elif worse < 0:
        rec["status"] = "improved"
    else:
        rec["status"] = "ok"
    return rec


def diff(old: dict, new: dict, scale: float = 1.0) -> dict:
    """Compare two parsed bench records -> the machine-readable verdict."""
    fo, fn = flatten(old), flatten(new)
    results = [judge(p, fo[p], fn[p], scale)
               for p in sorted(fo.keys() & fn.keys())]
    regressions = [r for r in results if r["status"] == "regression"]
    return {
        "ok": not regressions,
        "checked": sum(1 for r in results if r["direction"] != "info"),
        "compared": len(results),
        "only_old": sorted(fo.keys() - fn.keys()),
        "only_new": sorted(fn.keys() - fo.keys()),
        "regressions": regressions,
        "improvements": [r for r in results if r["status"] == "improved"],
        "scale": scale,
    }


def _parsed(path: str) -> dict:
    """The comparable record of a BENCH_r*.json: its ``parsed`` object (the
    wrapper's n/cmd/tail are run provenance, not metrics); a bare bench
    record (no wrapper) is accepted as-is."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    if isinstance(doc, dict):
        return doc
    raise ValueError(f"{path}: not a bench record (top level is "
                     f"{type(doc).__name__}, expected object)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH_r*.json (e.g. the previous round)")
    ap.add_argument("new", help="candidate BENCH_r*.json")
    ap.add_argument("--json", action="store_true",
                    help="emit the full verdict object (machine-readable); "
                         "default prints a human summary table")
    ap.add_argument("--scale", type=float,
                    default=1.0, help="tolerance multiplier (noisy hosts; "
                                      "invariant ceilings are NOT scaled)")
    args = ap.parse_args(argv)
    try:
        old, new = _parsed(args.old), _parsed(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perfdiff: {e}", file=sys.stderr)
        return 2
    verdict = diff(old, new, scale=args.scale)
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        print(f"perfdiff {args.old} -> {args.new}: "
              f"{verdict['compared']} shared metrics, "
              f"{verdict['checked']} gated, "
              f"{len(verdict['regressions'])} regression(s), "
              f"{len(verdict['improvements'])} improvement(s)")
        for r in verdict["regressions"]:
            bound = (f" bound={r['bound']}" if "bound" in r
                     else f" tol={r['tol']}")
            print(f"  REGRESSION {r['metric']} ({r['direction']}{bound}): "
                  f"{r['old']} -> {r['new']}")
        for r in verdict["improvements"]:
            print(f"  improved   {r['metric']}: {r['old']} -> {r['new']}")
        if verdict["only_old"]:
            print(f"  (not in new run: {', '.join(verdict['only_old'][:8])}"
                  + (" ..." if len(verdict["only_old"]) > 8 else "") + ")")
        print("VERDICT:", "PASS" if verdict["ok"] else "FAIL")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
