"""Flash-attention canary: compile + run ONE tiny flash kernel on the live
backend and exit 0.

The 2026-07-31 window wedged server-side exactly at the first flash compile
(TPU_VALIDATE_r04.md); whether flash *caused* the wedge is unknown. The
session script runs this under `timeout` before any flash-dependent stage:
on timeout/failure it exports BENCH_ATTN=jnp / EBENCH_ATTN=jnp / kbench
--no-flash so the window still produces engine numbers on the XLA attention
path instead of hanging every later stage.
"""
import numpy as np
import jax
import jax.numpy as jnp

from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

interp = jax.devices()[0].platform != "tpu"
rng = np.random.default_rng(0)
q = jnp.asarray(rng.standard_normal((1, 1, 8, 64)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.bfloat16)
out = flash_gqa_attention(q, k, v, jnp.int32(100), interpret=interp)
jax.block_until_ready(out)
assert np.isfinite(np.asarray(out, np.float32)).all()
print("FLASH CANARY OK", flush=True)
