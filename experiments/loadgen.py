"""Seeded multi-tenant open-loop load generator with SLO-attainment curves
(ISSUE 12).

Closed-loop drivers (submit, wait, submit again) hide overload: the arrival
rate collapses to whatever the server sustains, so tail latency looks flat
right up to the cliff. This generator is OPEN-LOOP — each tenant's arrivals
are a seeded Poisson process whose rate does NOT react to completions — so
saturation shows up where it belongs: in the latency distribution and the
SLO-attainment curve, not in a silently reduced offered load.

Per tenant: arrival rate (req/s), a prompt/output length mix (bounded
uniform), a priority class, and an optional deadline fraction. The report
is per-tenant AND aggregate:

* offered vs completed request counts, finish-reason histogram;
* TTFT / e2e percentiles (p50/p95/p99);
* **SLO-attainment curves**: for a sweep of TTFT and ITL targets, the
  fraction of finished requests that met each target — the whole latency
  CDF as operators consume it, not one aggregate tok/s number that hides
  the tail;
* preempt/resume and prefill-budget counters from the scheduler, so a
  priority mix shows WHAT the scheduler did, not just how it felt.

CLI (tiny synthetic model, CPU-friendly)::

    JAX_PLATFORMS=cpu python experiments/loadgen.py \
        --duration 20 --seed 0 --prefill-budget auto --out /tmp/loadgen.json

Library use: ``run_loadgen(sched, tenants, duration_s, seed)`` against any
Scheduler — tests/test_hybrid.py and bench.py reuse pieces of it.

**Router / HTTP target mode (ISSUE 15)**: ``--target http://host:port``
drives the same open-loop schedule over the serving HTTP surface instead
of an in-process Scheduler — point it at a single replica or at a
`dllama-tpu router` front. Each tenant's requests share a per-tenant
system prompt (so prefix-affinity routing has the fingerprint real
traffic would give it), stream their completion (TTFT = first content
event on the wire), and record the `X-Replica-Id` attribution header; the
report adds a per-replica request/token breakdown. Scheduler-internal
counters (preemptions, prefill budget) are absent in this mode — they
live on the replicas' own /metrics::

    python experiments/loadgen.py --target http://127.0.0.1:9980 \
        --duration 20 --seed 0 --out /tmp/loadgen_router.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from dataclasses import dataclass, field

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: default TTFT / ITL target sweeps (ms) for the attainment curves —
#: log-spaced to cover interactive (10 ms) through batch (10 s) regimes
TTFT_TARGETS_MS = (10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000)
ITL_TARGETS_MS = (5, 10, 25, 50, 100, 250, 500, 1000)


@dataclass
class TenantSpec:
    """One tenant's open-loop traffic: Poisson arrivals at `rate_rps`,
    prompts/outputs drawn uniformly from the given ranges."""

    name: str
    rate_rps: float
    prompt_len: tuple[int, int] = (8, 24)
    max_tokens: tuple[int, int] = (4, 12)
    priority: int = 1
    temperature: float = 0.0
    weight: float = 1.0


@dataclass
class _Flight:
    req: object
    tenant: str
    t_submit: float
    tokens: list = field(default_factory=list)
    shed: str | None = None


def _percentiles(xs: list[float]) -> dict:
    if not xs:
        return {"count": 0, "p50": None, "p95": None, "p99": None}
    s = sorted(xs)

    def q(p):
        r = p * (len(s) - 1)
        lo = int(r)
        hi = min(lo + 1, len(s) - 1)
        return round(s[lo] + (s[hi] - s[lo]) * (r - lo), 3)

    return {"count": len(s), "p50": q(0.5), "p95": q(0.95), "p99": q(0.99)}


def _attainment(samples_ms: list[float], targets_ms) -> list[dict]:
    """The SLO-attainment curve: fraction of samples at or under each
    target. Empty sample sets answer None (unknowable, not 100%)."""
    out = []
    for t in targets_ms:
        if not samples_ms:
            out.append({"target_ms": t, "attainment": None})
        else:
            ok = sum(1 for v in samples_ms if v <= t)
            out.append({"target_ms": t,
                        "attainment": round(ok / len(samples_ms), 4)})
    return out


def run_loadgen(sched, tenants: list[TenantSpec], duration_s: float,
                seed: int = 0, vocab: int = 90) -> dict:
    """Drive `sched` with open-loop multi-tenant traffic for `duration_s`,
    then wait for the tail and report. Deterministic arrival/shape schedule
    per (seed, tenants); completions are of course machine-dependent."""
    rng = random.Random(seed)
    flights: list[_Flight] = []
    lock = threading.Lock()
    stop = threading.Event()

    def consume(fl: _Flight):
        try:
            for t in fl.req.tokens():
                fl.tokens.append(t)
        except Exception as e:  # shed/error/shutdown — recorded, not raised
            fl.shed = type(e).__name__

    def tenant_driver(spec: TenantSpec, sub_seed: int):
        r = random.Random(sub_seed)
        t_end = time.monotonic() + duration_s
        while not stop.is_set() and time.monotonic() < t_end:
            # open loop: the NEXT arrival is scheduled regardless of how
            # the previous request is doing
            time.sleep(min(r.expovariate(max(spec.rate_rps, 1e-6)), 5.0))
            if stop.is_set() or time.monotonic() >= t_end:
                return
            plen = r.randint(*spec.prompt_len)
            prompt = [(r.randrange(vocab)) + 1 for _ in range(plen)]
            fl = _Flight(req=None, tenant=spec.name,
                         t_submit=time.monotonic())
            try:
                fl.req = sched.submit(
                    prompt, spec.temperature, 0.9,
                    r.randint(*spec.max_tokens), frozenset(),
                    seed=r.randrange(1 << 30), priority=spec.priority,
                    tenant=spec.name)
            except Exception as e:  # admission shed (429/503 analog)
                fl.shed = type(e).__name__
                with lock:
                    flights.append(fl)
                continue
            with lock:
                flights.append(fl)
            threading.Thread(target=consume, args=(fl,), daemon=True).start()

    drivers = [threading.Thread(target=tenant_driver, args=(s, seed * 977 + i),
                                daemon=True)
               for i, s in enumerate(tenants)]
    t0 = time.monotonic()
    for d in drivers:
        d.start()
    for d in drivers:
        d.join(timeout=duration_s + 30)
    # let the in-flight tail finish (bounded)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with lock:
            live = [f for f in flights
                    if f.shed is None and f.req.finish_reason is None]
        if not live:
            break
        time.sleep(0.05)
    stop.set()
    wall = time.monotonic() - t0

    def report_for(sel: list[_Flight]) -> dict:
        done = [f for f in sel if f.shed is None
                and f.req.finish_reason is not None]
        ttft = [f.req.ttft_ms for f in done if f.req.ttft_ms is not None]
        itl = [f.req.itl_ms for f in done if f.req.itl_ms is not None]
        e2e = [(f.req.finished_at - f.req.submitted_at) * 1000.0
               for f in done if f.req.finished_at is not None]
        reasons: dict[str, int] = {}
        for f in sel:
            key = f.shed or f.req.finish_reason or "unfinished"
            reasons[key] = reasons.get(key, 0) + 1
        return {
            "offered": len(sel),
            "completed": len(done),
            "finish_reasons": reasons,
            "ttft_ms": _percentiles(ttft),
            "itl_ms": _percentiles(itl),
            "e2e_ms": _percentiles(e2e),
            "tokens": sum(len(f.tokens) for f in sel),
            "slo_attainment": {
                "ttft": _attainment(ttft, TTFT_TARGETS_MS),
                "itl": _attainment(itl, ITL_TARGETS_MS),
            },
        }

    with lock:
        all_f = list(flights)
    out = {
        "seed": seed,
        "duration_s": round(wall, 3),
        "tenants": {s.name: {"rate_rps": s.rate_rps,
                             "priority": s.priority,
                             **report_for([f for f in all_f
                                           if f.tenant == s.name])}
                    for s in tenants},
        "aggregate": report_for(all_f),
        "tok_s": round(sum(len(f.tokens) for f in all_f) / max(wall, 1e-9),
                       3),
    }
    summary = sched.latency_summary()
    out["hybrid"] = summary.get("hybrid")
    out["scheduler"] = {
        "preemptions": getattr(sched, "preempt_count", 0),
        "resumed": getattr(sched, "resume_count", 0),
        "prefill_budget": getattr(sched, "_budget_now", None),
    }
    return out


@dataclass
class _HttpFlight:
    tenant: str
    t_submit: float
    ttft_ms: float | None = None
    e2e_ms: float | None = None
    tokens: int = 0
    replica: str = ""
    finish: str | None = None
    shed: str | None = None


def _http_complete(host: str, port: int, fl: _HttpFlight, system: str,
                   user: str, max_tokens: int, temperature: float,
                   priority: int) -> None:
    """One streamed chat completion over the wire; fills `fl` in place.
    TTFT is clocked at the first content-bearing SSE event — the
    client-seat number, queueing + routing + prefill included."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=120)
    try:
        conn.request("POST", "/v1/chat/completions", json.dumps({
            "messages": [{"role": "system", "content": system},
                         {"role": "user", "content": user}],
            "max_tokens": max_tokens, "temperature": temperature,
            "priority": priority, "tenant": fl.tenant, "stream": True,
        }), {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            fl.shed = f"http_{resp.status}"
            resp.read()
            return
        fl.replica = resp.getheader("X-Replica-Id") or ""
        buf = b""
        while True:
            # read1, not read: read(n) on a chunked response blocks until
            # n bytes or EOF, which would clock ttft_ms at the 4 KB
            # boundary instead of the first token frame
            chunk = resp.read1(4096)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, _, buf = buf.partition(b"\n\n")
                if not frame.startswith(b"data: "):
                    continue  # keep-alive comment frames
                payload = frame[6:]
                if payload == b"[DONE]":
                    fl.e2e_ms = (time.monotonic() - fl.t_submit) * 1000.0
                    return
                try:
                    ev = json.loads(payload)
                except ValueError:
                    continue
                if "error" in ev:
                    fl.finish = fl.finish or "error"
                    continue
                choice = (ev.get("choices") or [{}])[0]
                if choice.get("delta", {}).get("content"):
                    if fl.ttft_ms is None:
                        fl.ttft_ms = (time.monotonic()
                                      - fl.t_submit) * 1000.0
                    fl.tokens += 1
                if choice.get("finish_reason"):
                    fl.finish = choice["finish_reason"]
        fl.e2e_ms = (time.monotonic() - fl.t_submit) * 1000.0
    except OSError as e:
        fl.shed = type(e).__name__
    finally:
        conn.close()


def run_loadgen_http(target: str, tenants: list[TenantSpec],
                     duration_s: float, seed: int = 0) -> dict:
    """The open-loop schedule of :func:`run_loadgen`, driven over HTTP
    against `target` (a replica or a router front). Per-tenant system
    prompts give affinity routing its fingerprint; the report adds the
    per-replica attribution breakdown."""
    from dllama_tpu.serve.router import _parse_replica

    try:
        rep = _parse_replica(target)
    except ValueError:
        raise ValueError(f"--target {target!r}: expected http://host:port")
    host, port = rep.host, rep.port
    rng = random.Random(seed)
    flights: list[_HttpFlight] = []
    threads: list[threading.Thread] = []
    lock = threading.Lock()
    stop = threading.Event()

    def tenant_driver(spec: TenantSpec, sub_seed: int):
        r = random.Random(sub_seed)
        system = (f"You are serving tenant {spec.name}: a steady shared "
                  f"preamble that every {spec.name} request reuses, so the "
                  "router's prefix fingerprint matches real template "
                  "traffic.")
        t_end = time.monotonic() + duration_s
        while not stop.is_set() and time.monotonic() < t_end:
            time.sleep(min(r.expovariate(max(spec.rate_rps, 1e-6)), 5.0))
            if stop.is_set() or time.monotonic() >= t_end:
                return
            fl = _HttpFlight(tenant=spec.name, t_submit=time.monotonic())
            with lock:
                flights.append(fl)
            th = threading.Thread(
                target=_http_complete,
                args=(host, port, fl, system,
                      f"request {r.randrange(1 << 20)}",
                      r.randint(*spec.max_tokens), spec.temperature,
                      spec.priority),
                daemon=True)
            with lock:
                threads.append(th)
            th.start()

    drivers = [threading.Thread(target=tenant_driver,
                                args=(s, seed * 977 + i), daemon=True)
               for i, s in enumerate(tenants)]
    t0 = time.monotonic()
    for d in drivers:
        d.start()
    for d in drivers:
        d.join(timeout=duration_s + 30)
    # stop BEFORE the tail join: a driver that overran its join timeout must
    # not keep launching requests (unjoined, skewing wall/tok_s) while the
    # in-flight tail drains
    stop.set()
    with lock:
        tail = list(threads)
    for th in tail:  # bounded wait for the in-flight tail
        th.join(timeout=60)
    wall = time.monotonic() - t0

    def report_for(sel: list[_HttpFlight]) -> dict:
        done = [f for f in sel if f.shed is None and f.e2e_ms is not None]
        reasons: dict[str, int] = {}
        for f in sel:
            key = f.shed or f.finish or "unfinished"
            reasons[key] = reasons.get(key, 0) + 1
        ttft = [f.ttft_ms for f in done if f.ttft_ms is not None]
        return {
            "offered": len(sel),
            "completed": len(done),
            "finish_reasons": reasons,
            "ttft_ms": _percentiles(ttft),
            "e2e_ms": _percentiles([f.e2e_ms for f in done]),
            "tokens": sum(f.tokens for f in sel),
            "slo_attainment": {
                "ttft": _attainment(ttft, TTFT_TARGETS_MS),
            },
        }

    with lock:
        all_f = list(flights)
    replicas = sorted({f.replica for f in all_f if f.replica})
    # when the target is a router front, attach its client-seat SLO view
    # (GET /router/fleet) so the generator's own measurements reconcile
    # against what the router scored over the same window; a plain
    # replica target has no such endpoint and the key stays None
    router_view = None
    try:
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/router/fleet")
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        if resp.status == 200:
            fl_view = json.loads(body).get("fleet") or {}
            router_view = {"client": fl_view.get("client"),
                           "failovers": fl_view.get("failovers"),
                           "client_errors": fl_view.get("client_errors")}
    except (OSError, ValueError):
        pass
    return {
        "seed": seed,
        "target": target,
        "duration_s": round(wall, 3),
        "tenants": {s.name: {"rate_rps": s.rate_rps,
                             "priority": s.priority,
                             **report_for([f for f in all_f
                                           if f.tenant == s.name])}
                    for s in tenants},
        "aggregate": report_for(all_f),
        "replicas": {rid: {"requests": sum(1 for f in all_f
                                           if f.replica == rid),
                           "tokens": sum(f.tokens for f in all_f
                                         if f.replica == rid)}
                     for rid in replicas},
        "router": router_view,
        "tok_s": round(sum(f.tokens for f in all_f) / max(wall, 1e-9), 3),
    }


DEFAULT_TENANTS = [
    # interactive: short prompts, high priority, modest rate
    TenantSpec("interactive", rate_rps=2.0, prompt_len=(4, 10),
               max_tokens=(3, 6), priority=2),
    # chat: the bulk of traffic
    TenantSpec("chat", rate_rps=3.0, prompt_len=(8, 24),
               max_tokens=(4, 10), priority=1, temperature=0.8),
    # batch: long prompts, low priority — the preemption donor
    TenantSpec("batch", rate_rps=1.0, prompt_len=(24, 40),
               max_tokens=(8, 16), priority=0),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=15.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=3)
    ap.add_argument("--prefill-budget", default="auto")
    ap.add_argument("--slo-itl-ms", type=float, default=None)
    ap.add_argument("--slo-ttft-ms", type=float, default=None)
    ap.add_argument("--out", default=None, help="write the JSON report here")
    ap.add_argument("--target", default=None, metavar="http://HOST:PORT",
                    help="drive a live serving endpoint (a replica or a "
                         "`dllama-tpu router` front) over HTTP instead of "
                         "an in-process scheduler; --slots/--chunk/"
                         "--prefill-budget/--slo-* are the server's "
                         "business in this mode")
    args = ap.parse_args(argv)

    if args.target:
        report = run_loadgen_http(args.target, DEFAULT_TENANTS,
                                  args.duration, seed=args.seed)
        text = json.dumps(report, indent=2)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
        print(text)
        return 0

    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=128)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
    eng = BatchEngine(cfg, params, n_slots=args.slots,
                      cache_dtype=jnp.float32, kv_layout="paged",
                      page_size=8, max_prefill_chunk=16)
    budget = args.prefill_budget
    if budget != "auto":
        budget = int(budget)
    sched = Scheduler(eng, chunk=args.chunk, prefill_budget=budget,
                      slo_itl_ms=args.slo_itl_ms,
                      slo_ttft_ms=args.slo_ttft_ms)
    try:
        warm = sched.submit([1, 2, 3], 0.0, 0.9, 2, frozenset(), seed=7)
        list(warm.tokens())
        sched.reset_latency_stats()
        report = run_loadgen(sched, DEFAULT_TENANTS, args.duration,
                             seed=args.seed)
    finally:
        sched.shutdown()
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
