"""Engine-knob A/B on the real TPU: ONE process, one 1B param set, a matrix
of (layer_unroll, attn_impl, q40 style) combos timed through the production
InferenceEngine. Each combo prints (flushed) as soon as it's measured, so a
tunnel drop keeps earlier rows.

Usage: python experiments/ebench.py [n_decode]
"""

import os
import sys
import time

import numpy as np

t0 = time.time()
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), f"({time.time()-t0:.0f}s)", flush=True)

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params_fast
from dllama_tpu.ops import layers as layers_mod
from dllama_tpu.ops.pallas import q40_matmul as qmod

N_DECODE = int(sys.argv[1]) if len(sys.argv) > 1 else 64

if os.environ.get("EBENCH_TINY") == "1":  # CPU smoke of the harness itself
    cfg = LlamaConfig(dim=256, hidden_dim=512, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=512, seq_len=128)
else:
    cfg = LlamaConfig(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32,
                      n_kv_heads=8, vocab_size=128256, seq_len=1024)
params = random_params_fast(cfg, seed=0, dtype=jnp.bfloat16)
print(f"params ready ({time.time()-t0:.0f}s)", flush=True)

# (label, unroll, attn_impl, style, fuse)
COMBOS = [
    ("base u1 flash bd", 1, "auto", "auto", False),
    ("fused-qkv-w13", 1, "auto", "auto", True),
    ("fused+u4", 4, "auto", "auto", True),
    ("u4", 4, "auto", "auto", False),
    ("ufull", True, "auto", "auto", False),
    ("jnp-attn", 1, "jnp", "auto", False),
    ("maskdot", 1, "auto", "maskdot", False),
    ("loopdot", 1, "auto", "loopdot", False),
    ("deq-decode", 1, "auto", "deq", False),
    # reserve Pallas rms_norm (VERDICT r3 weak #8): flip only on a win here
    ("pallas-norm", 1, "auto", "auto", False),
]

PROMPT_LEN = min(512, cfg.seq_len // 2)
prompt = (np.arange(1, PROMPT_LEN + 1, dtype=np.int32)[None]) % cfg.vocab_size
first = np.array([[1]], np.int32)

# EBENCH_ATTN=jnp: set by tpu_session.sh when the flash canary hung (a flash
# compile wedged the 2026-07-31 window, TPU_VALIDATE_r04.md) — every combo
# runs on the XLA attention path so the unroll/style A/Bs still measure.
attn_override = os.environ.get("EBENCH_ATTN")
if attn_override:
    # relabel too: a row named "...flash..." measured on the jnp path would
    # poison any summary derived from the log
    COMBOS = [(f"{label} (attn={attn_override})", unroll, attn_override, style, fuse)
              for label, unroll, attn, style, fuse in COMBOS
              if label != "jnp-attn" or attn_override != "jnp"]

fails = []
for label, unroll, attn, style, fuse in COMBOS:
    qmod.STYLE = style
    # startswith: the EBENCH_ATTN override appends an "(attn=...)" suffix
    layers_mod.RMS_NORM_IMPL = "pallas" if label.startswith("pallas-norm") else "jnp"
    try:
        eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16,
                              max_prefill_chunk=512, layer_unroll=unroll,
                              attn_impl=attn, fuse_weights=fuse)
        tc = time.perf_counter()
        eng.prefill(prompt)
        eng.decode_greedy_n(first, N_DECODE)
        compile_s = time.perf_counter() - tc
        eng.reset(0)
        tp = time.perf_counter()
        eng.prefill(prompt)
        jax.block_until_ready(eng.cache.k)
        t_pre = time.perf_counter() - tp
        td = time.perf_counter()
        eng.decode_greedy_n(first, N_DECODE)
        t_dec = time.perf_counter() - td
        print(f"{label}: decode={1000*t_dec/N_DECODE:.2f}ms/tok "
              f"({N_DECODE/t_dec:.0f}tok/s) prefill={PROMPT_LEN/t_pre:.0f}tok/s "
              f"compile={compile_s:.0f}s", flush=True)
        del eng
    except Exception as e:
        fails.append(label)
        print(f"{label}: FAILED {e!r}"[:300], flush=True)
    finally:
        qmod.STYLE = "auto"
        layers_mod.RMS_NORM_IMPL = "jnp"

# machine-checkable completion marker: the CI smoke asserts fails=0; in a live
# window partial failure still exits 0 so later session stages run (tee'd log
# keeps the rows that did measure)
print(f"EBENCH DONE fails={len(fails)}" + (" " + ",".join(fails) if fails else ""),
      flush=True)
