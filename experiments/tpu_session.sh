#!/bin/sh
# One TPU window, fully scripted: validate kernels, micro-bench decode styles,
# then the full benchmark. Run from the repo root when the axon tunnel is
# alive (probe first!). Each stage tolerates failure and moves on; everything
# is logged to experiments/logs/.
#
# TPU_SESSION_SMOKE=1 runs the SAME script end-to-end on CPU with each
# stage's tiny/smoke variant — proves the shell plumbing (stage sequence,
# tee paths, timeouts) without a chip; exercised by CI
# (tests/test_window_scripts.py).
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments/logs
TS=$(date +%H%M%S)
L=experiments/logs
SMOKE="${TPU_SESSION_SMOKE:-0}"
if [ "$SMOKE" = "1" ]; then
  export JAX_PLATFORMS=cpu
  export PYTHONPATH="$PWD"
  KB_ARGS="--smoke"; AB_ARGS="--smoke"
  # smoke proves PLUMBING: keep the bench stage's record minimal/fast
  export EBENCH_TINY=1 BENCH_FORCE_CPU=1 BENCH_ADMIT=0 BENCH_SPEC=0 \
         BENCH_SLOTS=2 BENCH_CPU_DECODE_TOKENS=8
  EB_N=4
else
  KB_ARGS=""; AB_ARGS=""; EB_N=64
fi
# persistent compile cache: the window's stages (validate/kbench/ebench/bench)
# re-compile many shared shapes; first-compile-over-tunnel is 20-40s each,
# cache hits across processes AND across windows are ~free
export JAX_COMPILATION_CACHE_DIR="$PWD/experiments/jax_cache"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

echo "== 1. probe"
if [ "$SMOKE" = "1" ]; then
  echo "PROBE skipped (smoke)"
else
  timeout 60 python -c "import jax; print('PROBE', jax.devices())" || { echo "tunnel down"; exit 1; }
fi

echo "== 2. kernel validation (compile + parity, ~3-5 min)"
timeout 600 env PYTHONPATH="$PWD:${PYTHONPATH:-}" python experiments/tpu_validate.py 2>&1 | tee "$L/validate_$TS.log"

echo "== 3. kernel micro-bench suite (decode m=8 + prefill m=256/512, one process)"
timeout 900 env PYTHONPATH="$PWD:${PYTHONPATH:-}" python experiments/kbench.py suite $KB_ARGS 2>&1 | tee "$L/kbench_$TS.log"

echo "== 4. engine-knob A/B (1B, one process)"
timeout 900 env PYTHONPATH="$PWD:${PYTHONPATH:-}" python experiments/ebench.py $EB_N 2>&1 | tee "$L/ebench_$TS.log"

echo "== 5. full benchmark (1b + 8b + long + batched sweep)"
timeout 900 python bench.py 2>&1 | tee "$L/bench_$TS.log" | tail -1

echo "== 6. admission-stall A/B (8b serving tier, sync vs interleaved)"
timeout 900 env PYTHONPATH="$PWD:${PYTHONPATH:-}" python experiments/abench.py $AB_ARGS 2>&1 | tee "$L/abench_$TS.log"

echo "== done; logs in $L/*_$TS.log"
