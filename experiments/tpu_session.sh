#!/bin/sh
# One TPU window, fully scripted and wedge-hardened. The 2026-07-31 window
# (TPU_VALIDATE_r04.md) proved the failure mode that matters is not a crash
# but a server-side WEDGE: one compile RPC blocks forever and every later
# device call from every process hangs with it. So:
#   * a COMPUTE probe (experiments/probe.py) gates every stage — a wedged
#     tunnel costs one probe timeout, then the session exits and the watcher
#     re-arms for the next window;
#   * a flash-attention CANARY runs before any flash-dependent stage (the
#     wedge struck at the first flash compile); if it hangs, later stages run
#     with BENCH_ATTN/EBENCH_ATTN=jnp and kbench --no-flash so the window
#     still yields engine + q40 numbers on the XLA attention path;
#   * the full benchmark (BENCH_r04's source of truth) runs FIRST among the
#     long stages — the headline record must not be starved by micro-benches;
#   * tpu_validate runs as per-group processes, each timeout-bounded.
#
# TPU_SESSION_SMOKE=1 runs the SAME script end-to-end on CPU with each
# stage's tiny/smoke variant — proves the shell plumbing (stage sequence,
# tee paths, timeouts) without a chip; exercised by CI
# (tests/test_window_scripts.py).
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments/logs
TS=$(date +%H%M%S)
L=experiments/logs
SMOKE="${TPU_SESSION_SMOKE:-0}"
if [ "$SMOKE" = "1" ]; then
  export JAX_PLATFORMS=cpu
  export PYTHONPATH="$PWD"
  KB_ARGS="--smoke"; AB_ARGS="--smoke"
  # smoke proves PLUMBING: keep the bench stage's record minimal/fast
  export EBENCH_TINY=1 BENCH_FORCE_CPU=1 BENCH_ADMIT=0 BENCH_SPEC=0 \
         BENCH_SLOTS=2 BENCH_CPU_DECODE_TOKENS=8
  EB_N=4
else
  KB_ARGS=""; AB_ARGS=""; EB_N=64
fi
# persistent compile cache: the window's stages re-compile many shared
# shapes; first-compile-over-tunnel is 20-40s each, cache hits across
# processes AND across windows are ~free
export JAX_COMPILATION_CACHE_DIR="$PWD/experiments/jax_cache"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
PP="$PWD:${PYTHONPATH:-}"  # quoted at every use: paths with spaces must not word-split

# compute probe between stages: a wedged tunnel fails here in <=240s instead
# of eating every later stage's full timeout. Smoke skips (no tunnel).
probe() {
  if [ "$SMOKE" = "1" ]; then return 0; fi
  timeout -k 30 240 env PYTHONPATH="$PP" python experiments/probe.py >>"$L/probe_$TS.log" 2>&1
}

echo "== 1. probe (compute round-trip)"
probe || { echo "tunnel down/wedged"; exit 1; }

echo "== 2a. control canary (non-flash pallas compile: the wedge-diag baseline)"
CONTROL_OK=1
if timeout -k 30 360 env PYTHONPATH="$PP" python experiments/canary_control.py >"$L/control_$TS.log" 2>&1; then
  cat "$L/control_$TS.log"
  echo "control canary ok"
else
  cat "$L/control_$TS.log"
  CONTROL_OK=0
  if probe; then
    echo "WEDGE_DIAG verdict=CONTROL_FAIL_SERVER_ALIVE detail=non-flash-pallas-compile-failed-but-tunnel-fine" | tee -a "$L/control_$TS.log"
  else
    echo "WEDGE_DIAG verdict=GENERAL_WEDGE detail=non-flash-pallas-compile-wedged-tunnel (NOT flash-specific)" | tee -a "$L/control_$TS.log"
    echo "tunnel wedged by control canary; logs kept, watcher will re-arm"; exit 1
  fi
fi

echo "== 2b. flash canary (the 2026-07-31 wedge struck at a flash compile)"
FLASH_OK=1
# no pipe: a pipeline's status is tee's, which would mask a hung canary and
# leave flash armed on the exact wedge this stage exists to catch
if timeout -k 30 360 env PYTHONPATH="$PP" python experiments/canary_flash.py >"$L/canary_$TS.log" 2>&1; then
  cat "$L/canary_$TS.log"
  echo "flash canary ok: flash stays on"
  # bench.py re-canaries when BENCH_ATTN is unset; 'auto' (its default)
  # records the same result without a second fresh-process compile
  export BENCH_ATTN=auto
else
  cat "$L/canary_$TS.log"
  FLASH_OK=0
  export BENCH_ATTN=jnp EBENCH_ATTN=jnp
  KB_ARGS="$KB_ARGS --no-flash"
  echo "CANARY FAILED/HUNG: flash disabled for this window (attn=jnp)"
  # the r4 open question, answered mechanically (VERDICT r4 next #2): with
  # the control canary as baseline, the post-hang probe separates "flash
  # wedges the server" from "flash-specific client failure" from "tunnel
  # died coincidentally"
  if probe; then
    echo "WEDGE_DIAG verdict=FLASH_FAIL_SERVER_ALIVE control_ok=$CONTROL_OK detail=flash-canary-failed-but-tunnel-fine (client/compile error, not a server wedge)" | tee -a "$L/canary_$TS.log"
  else
    if [ "$CONTROL_OK" = "1" ]; then
      echo "WEDGE_DIAG verdict=FLASH_WEDGES_SERVER control_ok=1 detail=non-flash-compile-passed-then-flash-compile-killed-the-tunnel (r4 wedge REPRODUCED)" | tee -a "$L/canary_$TS.log"
    else
      echo "WEDGE_DIAG verdict=GENERAL_WEDGE control_ok=0 detail=both-canaries-failed-and-tunnel-dead" | tee -a "$L/canary_$TS.log"
    fi
    echo "tunnel wedged by canary; logs kept, watcher will re-arm"; exit 1
  fi
fi

echo "== 2c. quick bench (1b, tight budget): a real TPU record inside ~5 min"
# the 2026-07-31 window lasted ~2 minutes of device time; the full bench
# needs minutes of 8b param transfer before its first record. This stage
# lands a complete 1b record (batch=1 + 8-slot serving) early, so even a
# short window leaves hardware evidence (bench saves it as
# last_tpu_record; vs_baseline_config stays null on 1b, so watch_done
# keeps the watcher armed for the full 8b record)
if [ "$SMOKE" != "1" ]; then
  env BENCH_PRESET=1b BENCH_DECODE_TOKENS=32 BENCH_SLOTS=8 BENCH_ADMIT=0 \
      BENCH_BATCH_SPEC=0 BENCH_SPEC=0 BENCH_BUDGET_S=380 \
      timeout -k 30 420 python bench.py 2>&1 | tee "$L/bench_quick_$TS.log" | tail -1
  probe || { echo "tunnel wedged after quick bench"; exit 1; }
else
  echo "quick bench skipped (smoke)"
fi

echo "== 3. full benchmark (8b + long + 1b + batched sweep) — the round record"
# bench self-limits via BENCH_BUDGET_S (default 840, tuned for the driver's
# `timeout 900`); hand it the full stage budget or the extra time is dead
if [ "$SMOKE" != "1" ]; then export BENCH_BUDGET_S=1140; fi
timeout -k 30 1200 python bench.py 2>&1 | tee "$L/bench_$TS.log" | tail -1
if [ "$SMOKE" != "1" ]; then unset BENCH_BUDGET_S; fi
probe || { echo "tunnel wedged after bench"; exit 1; }

echo "== 4. kernel micro-bench suite (decode m=8 + prefill m=256/512 + tiles)"
timeout -k 30 900 env PYTHONPATH="$PP" python experiments/kbench.py suite $KB_ARGS 2>&1 | tee "$L/kbench_$TS.log"
probe || { echo "tunnel wedged after kbench"; exit 1; }

echo "== 5. engine-knob A/B (1B, one process)"
timeout -k 30 900 env PYTHONPATH="$PP" python experiments/ebench.py $EB_N 2>&1 | tee "$L/ebench_$TS.log"
probe || { echo "tunnel wedged after ebench"; exit 1; }

echo "== 6. admission-stall A/B (8b serving tier, sync vs strict vs paced)"
timeout -k 30 1400 env PYTHONPATH="$PP" python experiments/abench.py $AB_ARGS 2>&1 | tee "$L/abench_$TS.log"
probe || { echo "tunnel wedged after abench"; exit 1; }

echo "== 7. kernel validation (per-group, each timeout-bounded)"
VGROUPS="q40 q80 wcls"
if [ "$FLASH_OK" = "1" ]; then VGROUPS="q40 q80 wcls flash engine spec"; fi
# CI smoke skips ONLY wcls (vocab-wide interpret-mode matmuls crawl on one
# CPU core; the group is for real-chip windows). Strip-don't-reset: a
# failing flash canary must degrade the smoke group list too, or CI loses
# its signal for a canary regression.
if [ "$SMOKE" = "1" ]; then VGROUPS=$(printf '%s' "$VGROUPS" | sed 's/ *wcls//'); fi
: >"$L/validate_$TS.log"
VFAIL=0
for g in $VGROUPS; do
  # capture python's own exit status (a `| tee` would report tee's): a
  # timeout-killed or crashed group must set VFAIL even with no FAIL marker.
  # wcls moves ~0.8 GB of synthetic weights through the tunnel: more rope
  GT=420; [ "$g" = "wcls" ] && GT=700
  timeout -k 30 "$GT" env PYTHONPATH="$PP" python experiments/tpu_validate.py "$g" >"$L/.vgroup_$TS.log" 2>&1 || VFAIL=1
  cat "$L/.vgroup_$TS.log" >>"$L/validate_$TS.log"
  cat "$L/.vgroup_$TS.log"
  probe || { echo "tunnel wedged during validate $g"; exit 1; }
done
rm -f "$L/.vgroup_$TS.log"
# the CI smoke asserts the ALL PASS marker for the whole stage
if [ "$VFAIL" = "0" ] && ! grep -q FAIL "$L/validate_$TS.log"; then
  echo "VALIDATE STAGE CLEAN (groups: $VGROUPS)"
fi

echo "== 8. decision summary (pure log parsing, no device)"
python experiments/decide.py "$L" 2>&1 | tee "$L/decide_$TS.log"

echo "== done; logs in $L/*_$TS.log"
