"""Tunnel health probe: backend init + one tiny computation round-trip.

Exit 0 = the chip executes work. Device enumeration alone is NOT proof —
the 2026-07-31 window wedged in a state where `jax.devices()` had already
succeeded but every execution RPC blocked forever (TPU_VALIDATE_r04.md), so
the watcher and every inter-stage gate in tpu_session.sh call this instead.
Run under `timeout`: a wedged tunnel hangs this process rather than
erroring.
"""
import time

t0 = time.time()
import jax
import jax.numpy as jnp

d = jax.devices()
# no silent-CPU success: the watcher keys a whole measurement session off
# this exit code (PROBE_ALLOW_CPU=1 for local/dev runs)
import os
if not os.environ.get("PROBE_ALLOW_CPU"):
    assert d[0].platform == "tpu", f"not a TPU backend: {d}"
t1 = time.time()
x = jnp.ones((128, 128))
s = float((x @ x).sum())
assert s == 128.0 * 128 * 128, s
print(f"PROBE OK {d[0].platform} init={t1-t0:.1f}s compute={time.time()-t1:.1f}s",
      flush=True)
