#!/bin/sh
# Probe the axon tunnel on a loop; the moment it's up, run the scripted
# measurement session (experiments/tpu_session.sh). Designed to run nohup'd
# in the background for hours: every probe and the session output land in
# experiments/logs/ so a later shell can read the results.
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments/logs
W=experiments/logs/watch.log
i=0
while [ "$i" -lt 400 ]; do
  i=$((i + 1))
  # probe timeout must cover a live-but-slow tunnel's backend init (~120 s
  # measured); the short sleep keeps the window-catch latency low — a probe
  # against a down tunnel just hangs until its timeout anyway. COMPUTE probe,
  # not device enumeration: the 2026-07-31 wedge passed jax.devices() while
  # every execution RPC hung (TPU_VALIDATE_r04.md).
  if timeout -k 30 240 env PYTHONPATH="$PWD:${PYTHONPATH:-}" python experiments/probe.py \
      >>"$W" 2>&1; then
    echo "TUNNEL UP probe=$i $(date -u +%H:%M:%S)" >>"$W"
    sh experiments/tpu_session.sh >>experiments/logs/session.log 2>&1
    echo "SESSION DONE rc=$? $(date -u +%H:%M:%S)" >>"$W"
    # a window that died mid-session leaves no real TPU bench record —
    # keep watching for another window instead of giving up for the round.
    # A PARTIAL record (wedge mid-bench snapshot) is kept but doesn't end
    # the watch either: the next window should produce the full sweep.
    if sh experiments/watch_done.sh experiments/logs; then
      echo "TPU BENCH RECORDED; watcher exiting $(date -u +%H:%M:%S)" >>"$W"
      exit 0
    fi
    echo "session yielded no TPU bench record; re-arming" >>"$W"
  fi
  echo "probe $i down $(date -u +%H:%M:%S)" >>"$W"
  sleep 60
done
echo "GAVE UP after $i probes" >>"$W"
