"""One-shot TPU validation of every Pallas kernel path — run when the axon
tunnel is alive; designed to finish inside a short window (tiny shapes, few
compiles, one process).

Checks, each vs the XLA reference:
  1. q40_matmul blockdot (m=8 decode) on a stacked weight + layer index
  2. q40_matmul deq (m=128 prefill) on the same stacked weight
  3. flash attention with KV-tile pruning at a small pos in a long cache
  4. a 2-layer tiny engine end-to-end greedy parity (pallas vs xla)

Prints PASS/FAIL per item; exits nonzero on any FAIL.

Usage: python experiments/tpu_validate.py [GROUP ...]
GROUPs: q40 q80 flash engine spec wcls (default: all except wcls). The
session script runs each group as its own `timeout`-bounded process so a
tunnel wedge (the 2026-07-31 window died at the first flash compile,
TPU_VALIDATE_r04.md) costs one group's timeout, not the whole stage.

`wcls` (VERDICT r4 weak #6: on-chip PASSes covered one w1-sized shape
point) validates the decode/prefill q40 ladder and the fused q80 kernel at
the 8B preset's REAL classifier-head shape (4096x128256) — random Q40/Q80
codes, so no multi-GB host quantization; parity is vs XLA dequant-dot on
the same bits. Opt-in (not in the default list) because interpret-mode CPU
runs would crawl at this width; the session script requests it explicitly.
"""
import sys
import time

import numpy as np

_KNOWN_GROUPS = ("q40", "q80", "flash", "engine", "spec", "wcls")
_DEFAULT_GROUPS = ("q40", "q80", "flash", "engine", "spec")
GROUPS = [a for a in sys.argv[1:] if not a.startswith("-")] or list(_DEFAULT_GROUPS)
_bad = set(GROUPS) - set(_KNOWN_GROUPS)
if _bad:
    # a typo'd group must not run zero checks and still print the green
    # ALL PASS marker the session stage keys off
    raise SystemExit(f"unknown group(s) {sorted(_bad)}; known: {_KNOWN_GROUPS}")

t_start = time.time()
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), flush=True)

from dllama_tpu.ops.pallas import q40_matmul as qmod
from dllama_tpu.ops.quant import QTensor

failures = []


def check(name, got, want, atol=3e-2, rtol=3e-2):
    try:
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=atol, rtol=rtol,
        )
        print(f"PASS {name} ({time.time() - t_start:.0f}s)", flush=True)
    except Exception as e:
        failures.append(name)
        print(f"FAIL {name}: {str(e)[:300]}", flush=True)


rng = np.random.default_rng(0)
L, K, N = 2, 512, 512
ws = [QTensor.quantize((rng.standard_normal((K, N)) * 0.05).astype(np.float32)) for _ in range(L)]
stacked = QTensor(jnp.stack([w.packed for w in ws]), jnp.stack([w.scales for w in ws]))
wd1 = ws[1].dequantize(jnp.float32)

_interp = jax.devices()[0].platform != "tpu"
if "q40" in GROUPS:
    for style, m in (("blockdot", 8), ("maskdot", 8), ("loopdot", 8), ("deq", 128)):
        x = jnp.asarray(rng.standard_normal((m, K)), jnp.bfloat16)
        qmod.STYLE = style
        try:
            got = qmod.q40_matmul(x, stacked, layer=jnp.int32(1), interpret=_interp)
            want = jnp.dot(x, wd1.astype(jnp.bfloat16), preferred_element_type=jnp.float32).astype(jnp.bfloat16)
            check(f"q40 {style} m={m}", got, want)
        except Exception as e:
            failures.append(style)
            print(f"FAIL q40 {style} m={m} (compile/run): {str(e)[:400]}", flush=True)
        finally:
            qmod.STYLE = "auto"

if "q80" in GROUPS:
    # fused Q80 path (Q8Tensor int8 kernels) — both dispatch tiers on-chip;
    # its own timeout-bounded group so a wedge here cannot take q40 down
    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul
    from dllama_tpu.ops.quant import Q8Tensor, quantize_q80_np

    w8f = (rng.standard_normal((N, K)) * 0.05).astype(np.float32)
    codes, scales = quantize_q80_np(w8f.reshape(-1))
    w8 = Q8Tensor.from_file_layout(codes, scales, N, K)
    w8d = w8.dequantize(jnp.float32)
    for m in (8, 128):
        x = jnp.asarray(rng.standard_normal((m, K)), jnp.bfloat16)
        try:
            got = q80_matmul(x, w8, interpret=_interp)
            want = jnp.dot(x, w8d.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32).astype(jnp.bfloat16)
            check(f"q80 {'blockdot' if m <= 16 else 'deq'} m={m}", got, want)
        except Exception as e:
            failures.append(f"q80-m{m}")
            print(f"FAIL q80 m={m} (compile/run): {str(e)[:400]}", flush=True)

if "wcls" in GROUPS:
    # vocab-wide (8B wcls: 4096x128256) parity for the q40 decode/prefill
    # ladder and the fused q80 kernel. Weights are RANDOM CODES in the
    # device layout (bit-exact parity vs XLA dequant of the same bits needs
    # no realistic values), so host setup is cheap; each matmul also gets a
    # crude wall-time print — a window datum at the real head shape.
    from dllama_tpu.ops.quant import Q_BLOCK, Q8Tensor

    # full-range random codes make outputs O(50), so the reference must be
    # f32 (a bf16-rounded reference's own error exceeds rtol at k=4096);
    # atol 0.5 ~ 1% of typical magnitude absorbs cancellation-killed entries
    K8, N8 = 4096, 128256

    def timed_check(name, kernel_fn, wd, m):
        """warm (compile) -> parity vs f32 dequant reference -> mean ms over
        3 timed calls; one protocol for every wcls row."""
        x = jnp.asarray(rng.standard_normal((m, K8)), jnp.bfloat16)
        try:
            want = jnp.dot(x.astype(jnp.float32), wd,
                           preferred_element_type=jnp.float32
                           ).astype(jnp.bfloat16).block_until_ready()
            got = kernel_fn(x).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(3):
                got = kernel_fn(x)
            got.block_until_ready()
            dt = (time.perf_counter() - t0) / 3
            check(f"{name} [{dt*1e3:.2f} ms/call]", got, want, atol=0.5)
        except Exception as e:
            failures.append(f"wcls-{name.split()[0]}-{name.split()[1]}")
            print(f"FAIL {name} (compile/run): {str(e)[:400]}", flush=True)

    packed_np = rng.integers(0, 256, (K8 // 2, N8), dtype=np.uint8)
    scales_np = rng.uniform(0.005, 0.05, (K8 // Q_BLOCK, N8)).astype(np.float16)
    wq = QTensor(jnp.asarray(packed_np), jnp.asarray(scales_np))
    wqd = wq.dequantize(jnp.float32)
    # m=256 deq matches both the real prefill chunk and the AOT gate's
    # wcls8b row — the window runs exactly the pre-gated shapes
    for style, m in (("blockdot", 8), ("deq", 256)):
        qmod.STYLE = style
        try:
            timed_check(f"q40 {style} m={m} wcls8b(4096x128256)",
                        lambda x: qmod.q40_matmul(x, wq, interpret=_interp),
                        wqd, m)
        finally:
            qmod.STYLE = "auto"
    del wqd, wq

    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul as _q80mm

    w8w = Q8Tensor(jnp.asarray(rng.integers(-127, 128, (K8, N8), dtype=np.int8)),
                   jnp.asarray(rng.uniform(0.005, 0.05,
                                           (K8 // Q_BLOCK, N8)).astype(np.float16)))
    w8wd = w8w.dequantize(jnp.float32)
    timed_check("q80 blockdot m=8 wcls8b(4096x128256)",
                lambda x: _q80mm(x, w8w, interpret=_interp), w8wd, 8)
    del w8wd, w8w

if "flash" in GROUPS:
    # flash attention with pruning
    from dllama_tpu.ops.layers import gqa_attention
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    q = jnp.asarray(rng.standard_normal((1, 1, 8, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 4, 1024, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 4, 1024, 64)), jnp.bfloat16)
    try:
        got = flash_gqa_attention(q, k, v, jnp.int32(3), interpret=_interp)
        check("flash pruned pos=3 S=1024", got, gqa_attention(q, k, v, jnp.int32(3)))
    except Exception as e:
        failures.append("flash")
        print(f"FAIL flash (compile/run): {str(e)[:400]}", flush=True)

    # f8 (e4m3) KV cache through the flash kernel (--cache-dtype f8)
    try:
        k8 = k.astype(jnp.float8_e4m3fn)
        v8 = v.astype(jnp.float8_e4m3fn)
        got = flash_gqa_attention(q, k8, v8, jnp.int32(900), interpret=_interp)
        check("flash f8 KV cache", got, gqa_attention(q, k8, v8, jnp.int32(900)))
    except Exception as e:
        failures.append("flash-f8")
        print(f"FAIL flash f8 (compile/run): {str(e)[:400]}", flush=True)

if "engine" in GROUPS or "spec" in GROUPS:
    # engine-tier setup only when an engine-tier group runs: the q40-only
    # invocation (sole survivor of a flash-wedged window) must not spend its
    # timeout on param generation + host->device transfer it never uses
    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=512, seq_len=128)
    params = random_params(cfg, seed=1, dtype=jnp.bfloat16, quantize=True)
    prompt = np.arange(1, 9, dtype=np.int32)[None]

if "engine" in GROUPS:
    # end-to-end tiny engine parity
    try:
        outs = {}
        for kern in ("pallas", "xla"):
            eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, kernels=kern)
            eng.prefill(prompt)
            outs[kern] = [int(t) for t in eng.decode_greedy_n(np.array([[1]]), 8)[:, 0]]
        print("pallas greedy:", outs["pallas"], flush=True)
        print("xla    greedy:", outs["xla"], flush=True)
        if outs["pallas"] == outs["xla"]:
            print(f"PASS engine greedy parity ({time.time() - t_start:.0f}s)", flush=True)
        else:
            failures.append("engine-parity")
            print("FAIL engine greedy parity (token mismatch)", flush=True)
    except Exception as e:
        failures.append("engine")
        print(f"FAIL engine (compile/run): {str(e)[:400]}", flush=True)

    # fused wqkv/w13 launches: greedy continuation must match the unfused engine
    try:
        eng_f = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, kernels="pallas",
                                fuse_weights=True)
        eng_f.prefill(prompt)
        fused_toks = [int(t) for t in eng_f.decode_greedy_n(np.array([[1]]), 8)[:, 0]]
        if fused_toks == outs["pallas"]:
            print(f"PASS fused-weights parity ({time.time() - t_start:.0f}s)", flush=True)
        else:
            failures.append("fused")
            print(f"FAIL fused-weights parity: {fused_toks} != {outs['pallas']}", flush=True)
    except Exception as e:
        failures.append("fused")
        print(f"FAIL fused engine (compile/run): {str(e)[:400]}", flush=True)

    # continuous-batching tier: slot-sliced admission + fused multi-slot decode
    try:
        from dllama_tpu.engine.batch import BatchEngine

        be = BatchEngine(cfg, params, n_slots=4, cache_dtype=jnp.bfloat16, kernels="pallas")
        for s_ in range(3):
            be.add(s_, [1 + s_, 2, 3, 4], temperature=0.0, seed=s_)
        toks = be.decode(4)
        print(f"PASS batch engine 3/4 slots decode {toks.shape} ({time.time() - t_start:.0f}s)",
              flush=True)
    except Exception as e:
        failures.append("batch")
        print(f"FAIL batch engine (compile/run): {str(e)[:400]}", flush=True)

if "spec" in GROUPS:
    # speculative decode: exact-greedy parity vs the plain fused scan on-chip
    try:
        eng_s = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, kernels="pallas")
        sp = np.asarray([[1, 2, 3, 4] * 4], np.int32)
        lg = eng_s.prefill(sp)
        first = int(np.argmax(np.asarray(lg)[0]))
        spec_toks = [int(t) for t in eng_s.decode_spec_greedy_n(list(sp[0]), first, 12, k=4)]
        eng_g = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, kernels="pallas")
        eng_g.prefill(sp)
        ref_toks = [int(t) for t in eng_g.decode_greedy_n(np.array([[first]]), 12)[:, 0]]
        st = eng_s._spec_stats
        if spec_toks == ref_toks:
            print(f"PASS speculative parity ({st['emitted']} tokens / {st['cycles']} "
                  f"forwards) ({time.time() - t_start:.0f}s)", flush=True)
        else:
            failures.append("spec")
            print(f"FAIL speculative parity: {spec_toks} != {ref_toks}", flush=True)
    except Exception as e:
        failures.append("spec")
        print(f"FAIL speculative (compile/run): {str(e)[:400]}", flush=True)

    # batched speculative decode: the serving tier's per-slot propose/verify
    # cycle must match fused multi-slot greedy decode on-chip
    try:
        from dllama_tpu.engine.batch import BatchEngine

        prompts = {0: [1, 2, 3, 1, 2, 3], 2: [7, 6, 5, 7, 6]}
        streams = {}
        for use_spec in (False, True):
            be = BatchEngine(cfg, params, n_slots=3, cache_dtype=jnp.bfloat16,
                             kernels="pallas", spec=4 if use_spec else 0)
            got = {s_: [be.add(s_, p_, temperature=0.0)] for s_, p_ in prompts.items()}
            if use_spec:
                cyc = 0
                while any(len(v) < 9 for v in got.values()) and cyc < 40:
                    emit, adv = be.spec_step()
                    cyc += 1
                    for s_ in prompts:
                        got[s_] += [int(t) for t in emit[s_, : adv[s_]]]
            else:
                toks = be.decode(8)
                for s_ in prompts:
                    got[s_] += [int(t) for t in toks[:, s_]]
            streams[use_spec] = {s_: v[:9] for s_, v in got.items()}
        if streams[True] == streams[False]:
            print(f"PASS batched speculative parity ({time.time() - t_start:.0f}s)",
                  flush=True)
        else:
            failures.append("spec-batch")
            print(f"FAIL batched spec parity: {streams[True]} != {streams[False]}",
                  flush=True)
    except Exception as e:
        failures.append("spec-batch")
        print(f"FAIL batched speculative (compile/run): {str(e)[:400]}", flush=True)

print("TOTAL", "FAIL " + ",".join(failures) if failures else "ALL PASS", flush=True)
sys.exit(1 if failures else 0)
