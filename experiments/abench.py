"""Admission-stall A/B bench: what do decoding batch-mates experience while a
long prompt joins the batch? (VERDICT r3 #4 / weak #5.)

Runs the serving tier three times — 'synchronous' (legacy: the whole chunked
prefill runs between two decode chunks), 'strict' (one prefill chunk per
decode chunk; the r4 default whose joiner TTFT was unbounded, r4 weak #3) and
'paced' (the shipped default: prefill chunks pumped per visit until the
scheduler's stall budget is spent) — and reports, for each mode:

* client_gap_ms_max — the largest inter-token gap a DECODING request's
  stream observed while the admission was in flight (chunk-granular, i.e.
  the stall a user actually sees), vs its pre-admission baseline gap.
* scheduler admission_stall_ms_max/mean — the decode-to-decode gaps the
  scheduler attributed to admission work.

It finishes with the overlap-pipeline A/B (bench.bench_overlap): aggregate
decode tok/s and the inter-chunk host gap with the scheduler's overlapped
dispatch on vs off — same prompts/seeds, identical token streams, so the
delta is pure pipeline efficiency.

The reference has no analog tier (its server is single-request blocking,
dllama-api.cpp:522-533); this bench exists to prove the non-blocking claim
with numbers. Window config (TPU): ABENCH_PRESET=8b ABENCH_SLOTS=32
ABENCH_PROMPT=2048. '--smoke' runs a seconds-scale CPU config in CI.
"""

import os
import sys
import time

import numpy as np

t0 = time.time()
import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    smoke = "--smoke" in sys.argv
    print("devices:", jax.devices(), f"({time.time()-t0:.0f}s)", flush=True)

    import jax.numpy as jnp

    from bench import PRESETS
    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params_fast
    from dllama_tpu.serve.scheduler import Scheduler

    if smoke:
        # ONE protocol with bench.bench_admission (bench.ADMISSION_PROTOCOL):
        # the bench `admission` record and this experiment must be the same
        # experiment, or their headline ratios drift apart again (the
        # BENCH_r05 1.1x vs ADMISSION_CPU.md PASS confusion — see the
        # "Reconciliation (r6)" section there)
        from bench import ADMISSION_PROTOCOL as _P

        preset = "tiny"
        n_slots, prompt_len, chunk, pf_chunk, bg_steps = (
            _P["n_slots"], _P["prompt_len"], _P["chunk"], _P["pf_chunk"],
            _P["bg_steps"])
    else:
        preset = os.environ.get("ABENCH_PRESET", "8b")
        n_slots = int(os.environ.get("ABENCH_SLOTS", "32"))
        prompt_len = int(os.environ.get("ABENCH_PROMPT", "2048"))
        chunk = int(os.environ.get("ABENCH_CHUNK", "4"))
        pf_chunk = 256
        bg_steps = 256
    cfg = LlamaConfig(**PRESETS[preset])
    if prompt_len >= cfg.seq_len - bg_steps:
        prompt_len = cfg.seq_len - bg_steps - 8
    params = random_params_fast(cfg, seed=0, dtype=jnp.bfloat16)
    print(f"params ready: {preset} slots={n_slots} prompt={prompt_len} "
          f"({time.time()-t0:.0f}s)", flush=True)

    from bench import admission_streams

    # distinct-prefix streams + full pow-2 width warmup shared with
    # bench.bench_admission (prefix-cache reuse would gut the A/B otherwise)
    warm_prompt, bg_maker, long_prompt = admission_streams(cfg, pf_chunk, prompt_len)

    def run(mode: str, **kw) -> dict:
        eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=jnp.bfloat16,
                          max_prefill_chunk=pf_chunk)
        sched = Scheduler(eng, chunk=chunk, **kw)
        try:
            w = sched.submit(warm_prompt, 0.0, 0.9, chunk, frozenset(), seed=7)
            list(w.tokens())
            sched.reset_latency_stats()  # compile gaps are not stalls
            bg = [
                sched.submit(bg_maker(s), 0.8, 0.9, bg_steps, frozenset(), seed=s)
                for s in range(max(1, n_slots // 2))
            ]
            # timestamp bg[0]'s stream at chunk granularity
            stamps: list[float] = []
            it = bg[0].tokens()
            warm_tokens = max(4, 4 * chunk)
            for _ in range(warm_tokens):
                next(it)
                stamps.append(time.perf_counter())
            t_admit = time.perf_counter()
            r_long = sched.submit(long_prompt, 0.0, 0.9, 2 * chunk, frozenset(), seed=99)
            for tok in it:
                stamps.append(time.perf_counter())
            long_toks = list(r_long.tokens())
            for r in bg[1:]:
                list(r.tokens())
            arr = np.asarray(stamps)
            gaps = np.diff(arr) * 1000.0
            before = gaps[arr[1:] <= t_admit]
            after = gaps[arr[1:] > t_admit]
            s = sched.latency_summary()
            return {
                "mode": mode,
                "client_gap_ms_base": round(float(np.max(before)), 1) if before.size else None,
                "client_gap_ms_max": round(float(np.max(after)), 1) if after.size else None,
                "sched_stall_ms_max": round(s["admission_stall_ms_max"], 1)
                if s["admission_stall_ms_max"] else None,
                "sched_stall_ms_mean": round(s["admission_stall_ms_mean"], 1)
                if s["admission_stall_ms_mean"] else None,
                "long_ttft_ms": round(r_long.ttft_ms, 1),
                "long_tokens": len(long_toks),
            }
        finally:
            sched.shutdown()

    from bench import ADMISSION_MODES

    # same policy table as bench.bench_admission; 'sync' reads better as
    # 'synchronous' in these human-facing rows
    modes = {("synchronous" if m == "sync" else m): kw
             for m, kw in ADMISSION_MODES.items()}
    rows = {}
    for mode, kw in modes.items():
        try:
            r = run(mode, **kw)
            rows[mode] = r
            print(r, flush=True)
        except Exception as e:
            print(f"{mode}: FAILED {e!r}"[:300], flush=True)

    # overlap-pipeline A/B (shared with bench.py's `overlap` record):
    # inter-chunk host gap + aggregate tok/s, overlapped dispatch on vs off
    from bench import bench_overlap

    try:
        ov = bench_overlap(cfg, params, n_slots=n_slots, chunk=chunk,
                           steps=(24 if smoke else 128), pf_chunk=pf_chunk)
        print({"overlap_ab": ov}, flush=True)
        on, off = ov.get("overlap_on", {}), ov.get("overlap_off", {})
        if "agg_tok_s" in on and "agg_tok_s" in off:
            print(f"overlap host-gap reduction: "
                  f"{ov.get('host_gap_reduction_x')}x "
                  f"(mean {off.get('host_gap_ms_mean')}ms -> "
                  f"{on.get('host_gap_ms_mean')}ms); "
                  f"agg tok/s on/off: {ov.get('tok_s_ratio_on_off')}", flush=True)
    except Exception as e:
        print(f"overlap A/B: FAILED {e!r}"[:300], flush=True)
    if len(rows) == 3 and all(r["client_gap_ms_max"] is not None
                              for r in rows.values()):
        # timer-noise floor: a 0.0 best-case yields a large finite ratio
        gap = {m: rows[m]["client_gap_ms_max"] for m in rows}
        ttft = {m: rows[m]["long_ttft_ms"] for m in rows}
        print(f"stall reduction (sync/paced): {gap['synchronous'] / max(gap['paced'], 0.05):.1f}x",
              flush=True)
        # the r4 weak-#3 acceptance bar: the default (paced) must keep BOTH
        # metrics within 2x of the best mode for that metric
        best_gap, best_ttft = min(gap.values()), min(ttft.values())
        ok = (gap["paced"] <= 2 * max(best_gap, 0.05)
              and ttft["paced"] <= 2 * max(best_ttft, 0.05))
        print(f"paced within 2x of best on stall ({gap['paced']:.1f} vs {best_gap:.1f}) "
              f"and ttft ({ttft['paced']:.1f} vs {best_ttft:.1f}): "
              f"{'PASS' if ok else 'FAIL'}", flush=True)
    print(f"ABENCH DONE fails={3 - len(rows)}", flush=True)


if __name__ == "__main__":
    main()
