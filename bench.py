"""Benchmark: single-chip decode throughput on a synthetic Q40 Llama.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline is measured against the driver north star of 1000 tok/s/chip
(BASELINE.json: Llama-3.1-8B-Q40 on v5e-8; we scale the target by model size
so a 1B run compares against 8000 tok/s-equivalent... no — we report raw
decode tok/s on the benchmarked config and vs_baseline = value / north_star
where north_star is size-adjusted: 1000 tok/s * (8.03B / params_B)).

Presets via BENCH_PRESET env: tiny (CI smoke), 1b (default), 8b.
Runs on whatever jax.devices() provides (the axon-tunneled TPU v5e chip in
this container; CPU elsewhere).
"""

import json
import os
import time

import numpy as np


def params_count(cfg) -> float:
    per_layer = (
        cfg.dim * cfg.dim * 2  # wq, wo
        + cfg.dim * cfg.kv_dim * 2  # wk, wv
        + cfg.dim * cfg.hidden_dim * 3  # w1, w2, w3
    )
    return cfg.vocab_size * cfg.dim * 2 + cfg.n_layers * per_layer


def main():
    import jax
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    preset = os.environ.get("BENCH_PRESET", "1b")
    presets = {
        # dims follow the HF configs of the reference's model zoo (launch.py)
        "tiny": dict(dim=512, hidden_dim=1536, n_layers=4, n_heads=8, n_kv_heads=4,
                     vocab_size=2048, seq_len=512),
        "1b": dict(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32, n_kv_heads=8,
                   vocab_size=128256, seq_len=1024),
        "8b": dict(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
                   vocab_size=128256, seq_len=1024),
    }
    if preset not in presets:
        raise SystemExit(f"BENCH_PRESET must be one of {sorted(presets)}, got {preset!r}")
    label = {"tiny": "tiny", "1b": "Llama-3.2-1B", "8b": "Llama-3.1-8B"}[preset]
    cfg = LlamaConfig(**presets[preset])

    dev = jax.devices()[0]
    t0 = time.perf_counter()
    params = random_params(cfg, seed=0, dtype=jnp.bfloat16, quantize=True)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, max_prefill_chunk=128)
    t_setup = time.perf_counter() - t0

    prompt = np.arange(1, 129, dtype=np.int32)[None] % cfg.vocab_size
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_prefill_compile = time.perf_counter() - t0

    first = np.asarray(jnp.argmax(logits, -1)).astype(np.int32)
    prefill_end = eng.pos

    # warmup/compile the fused decode loop with the SAME static n as the timed
    # run (n is a static arg of the scan — a different n would recompile inside
    # the timed region)
    n_decode = int(os.environ.get("BENCH_DECODE_TOKENS", "256"))
    n_decode = min(n_decode, eng.seq_len - eng.pos - 1)
    t0 = time.perf_counter()
    _ = eng.decode_greedy_n(first, n_decode)
    t_decode_compile = time.perf_counter() - t0

    # timed decode over the same range (cache slots past pos are masked out)
    eng.reset(prefill_end)
    t0 = time.perf_counter()
    toks = eng.decode_greedy_n(first, n_decode)  # np.asarray inside = device sync
    t_decode = time.perf_counter() - t0
    tok_s = n_decode / t_decode

    # timed prefill (cache already compiled; re-run from pos 0)
    eng.reset(0)
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_prefill = time.perf_counter() - t0
    prefill_tok_s = prompt.shape[1] / t_prefill

    n_params = params_count(cfg)
    north_star = 1000.0 * (8.03e9 / n_params)  # size-adjusted 8B@1000tok/s/chip
    result = {
        "metric": f"decode tok/s, {label}-Q40 synthetic, batch=1, 1 chip ({dev.platform})",
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / north_star, 4),
        "prefill_tok_s": round(prefill_tok_s, 1),
        "decode_ms_per_token": round(1000.0 / tok_s, 3),
        "params_b": round(n_params / 1e9, 3),
        "device": str(dev),
        "setup_s": round(t_setup, 1),
        "compile_s": round(t_prefill_compile + t_decode_compile, 1),
    }
    # bytes/token is part of the benchmark contract (SURVEY.md §5.1/§6): on
    # one chip it's 0; multi-chip runs report the analytic ICI payload.
    from dllama_tpu.utils.profiling import collective_bytes_per_token

    n_dev = jax.device_count()
    result["kb_per_token_per_chip"] = round(
        collective_bytes_per_token(cfg, tp=n_dev)["kb_per_token_per_chip"], 1
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
