"""Benchmark: single-chip throughput on synthetic Q40 Llamas (1B + 8B).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The headline value is the best tokens/sec/chip across configs. vs_baseline
has ONE pinned definition (VERDICT r4 weak #8): 8B serving aggregate
tok/s/chip / 1000 — BASELINE.json's north star (Llama-3.1-8B-Q40 at
1000 tok/s/chip) — emitted only when this run measured that config
(vs_baseline_config names the winning row; 0.0 + null means unmeasured this
run, e.g. a tiny-preset CPU fallback). Everything else — batch=1
decode/prefill latency per preset, the tiny/1b rows, f8/spec sweep rows —
rides along as named fields and never feeds vs_baseline.

Hardened against the axon-tunnel wedge (VERDICT r1 #1): the parent process
never initializes a JAX backend. It probes the tunnel in a subprocess with a
timeout, retries UNAVAILABLE/hangs with a bounded budget, runs the real
measurement in ONE worker subprocess with a generous timeout, and if the TPU
never comes up emits a CPU-fallback record — the bench never exits non-zero
and never prints nothing.

Env knobs:
  BENCH_PRESET         all (default) | tiny | 1b | 8b — 'all' = 1b + 8b + the
                       8b batched sweep, budget permitting
  BENCH_SLOTS          comma list for the batched sweep (default '8,32,48')
  BENCH_DECODE_TOKENS  timed fused-decode length (default 128)
  BENCH_KERNELS        auto (default) | pallas | xla — engine matmul backend
  BENCH_Q40_STYLE      auto (default) | deq | blockdot | maskdot | loopdot —
                       decode-kernel style (prefill always uses deq)
  BENCH_XLA_PREFILL_M  int: route Pallas matmuls with flattened m >= this
                       through the XLA dequant-dot GEMM (prefill tier A/B;
                       unset = always fused kernels)
  BENCH_UNROLL         lax.scan unroll over layers: int, or 'full' (default 1)
  BENCH_FUSE           '1': fused wqkv/w13 launches (unsharded engines)
  BENCH_BUDGET_S       total wall-clock budget for the parent (default 840 —
                       fits under the driver's `timeout 900 python bench.py`)
  BENCH_CACHE          bf16 (default) | f8 — KV cache element type; f8
                       halves cache bytes (the batched-sweep bottleneck)
  BENCH_FORCE_CPU      '1': skip the TPU entirely (CI smoke)
  BENCH_OVERLAP        '0': skip the serving-tier overlap-pipeline A/B
                       (inter-chunk host gap + agg tok/s, on vs off)
  BENCH_TRACE          '0': skip the request-flow-tracing overhead A/B
                       (agg tok/s, span tracer on vs --trace-buffer 0)
  BENCH_PAGED          '0': skip the paged-vs-dense KV layout A/B and the
                       high-slot paged leg (dense-infeasible slot count on a
                       dense-at-base-slots HBM budget — the 96-slot roofline
                       configuration)
  BENCH_PAGED_HI       int: slot count for the high-slot paged leg
                       (default 2x the A/B slot count / 2x max BENCH_SLOTS)
  BENCH_RADIX          '0': skip the radix prefix-cache chat-replay record
                       (shared-system-prompt + multi-turn legs, cold-vs-warm
                       TTFT and saved-prefill tokens)
  BENCH_ROUTER         '0': skip the multi-replica router record (two real
                       tiny replicas behind serve/router.py: prefix-affinity
                       warm-TTFT win vs round-robin + the 2-vs-1-replica
                       aggregate tok/s scaling ratio)
  BENCH_FLEET_OBS      '0': skip the mesh observability record (fleet_obs
                       on/off proxy-path A/B over two real tiny replicas +
                       /router/metrics federation-scrape latency + merged-
                       trace clock alignment)
  BENCH_HYBRID         '0': skip the hybrid chunked-prefill record (client-
                       observed admission stall + joiner TTFT, legacy sync
                       phase-split vs the fused hybrid step, bit-exactness
                       + preempt/resume flags)
  BENCH_PAGED_KERNEL   '0': skip the paged-attention route A/B (jnp gather
                       vs the fused flash-decode kernel at 2-3 page sizes;
                       off-TPU the kernel leg runs interpret mode on a tiny
                       synthetic model — the ratio is only meaningful on TPU)
  BENCH_PAGED_KERNEL_PAGES  comma list of page sizes for that A/B
                       (default '16,64,128' on TPU, '8,16' off)
  BENCH_SLO            '0': skip the SLO/saturation snapshot record (windowed
                       percentiles + scheduler time ledger + roofline
                       attainment — the fields scripts/perf_gate.sh diffs)
  BENCH_SPEC_BATCH     '0': skip the speculative continuous-batching A/B
                       (scheduler-level spec-on vs spec-off on repetitive
                       text + a mixed spec/non-spec leg with per-class
                       tok/s and bit-exactness checks)
  BENCH_COMPILE        '0': skip the compile & device-traffic record
                       (cold-boot compile seconds, warmup-on vs warmup-off
                       first-request TTFT, and the steady-state zero-
                       recompile / zero-upload gate over a 200-token decode)
"""

import json
import os
import subprocess
import sys
import time

_PROBE = (
    "import jax, jax.numpy as jnp; jnp.ones(8).sum().block_until_ready(); "
    "print('PROBE_OK', jax.devices()[0].platform)"
)


def _cpu_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""  # skip the axon sitecustomize entirely
    env["JAX_PLATFORMS"] = "cpu"
    # the fallback must stay cheap and honest: no Pallas-interpret on CPU
    env.pop("BENCH_KERNELS", None)
    env.pop("BENCH_Q40_STYLE", None)
    return env


def _run_child(argv, env, timeout_s: float):
    """Run a child with a timeout, never blocking past it: on expiry the child
    is killed and — if it sits in uninterruptible IO on the wedged tunnel —
    ABANDONED rather than waited on (a plain subprocess.run would hang in its
    post-kill communicate()). Returns (stdout, stderr, rc) or (None, "", -1)."""
    import tempfile

    with tempfile.TemporaryFile("w+") as out, tempfile.TemporaryFile("w+") as err:
        proc = subprocess.Popen(argv, stdout=out, stderr=err, env=env, text=True)
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # D-state child: abandon it, do not block the bench
            return None, "", -1
        out.seek(0)
        err.seek(0)
        return out.read(), err.read(), rc


def probe_tpu(timeout_s: float) -> bool:
    """Can a fresh process reach the chip? Runs in a subprocess so a wedged
    tunnel hangs the child, not us. Requires a NON-CPU platform — a fast init
    failure makes JAX fall back to its CPU backend, which must not count."""
    stdout, _, rc = _run_child([sys.executable, "-c", _PROBE], None, timeout_s)
    if rc != 0 or stdout is None:
        return False
    for line in stdout.splitlines():
        if line.startswith("PROBE_OK"):
            platform = line.split()[-1].lower()
            return platform != "cpu"
    return False


def run_worker(env, timeout_s: float):
    """One measurement subprocess; returns the parsed JSON line or None."""
    stdout, stderr, rc = _run_child(
        [sys.executable, __file__, "--worker"], env, timeout_s
    )
    if stdout is None:
        print(f"bench worker timed out after {timeout_s:.0f}s", file=sys.stderr)
        return None
    for line in reversed(stdout.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                pass
    sys.stderr.write(stderr[-2000:])
    return None


def main():
    deadline = time.monotonic() + float(os.environ.get("BENCH_BUDGET_S", "840"))
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    tpu_ok = False
    if not force_cpu:
        # bounded probe/retry: a wedged relay clears only server-side, so a
        # couple of spaced attempts, then give up and record the CPU fallback.
        # Probe timeout must cover a LIVE-but-slow tunnel's backend init
        # (~120 s observed; the watcher uses 240 s for the same reason) — a
        # 90 s probe would write off a usable window as down.
        for attempt in range(2):
            budget = deadline - time.monotonic()
            if budget < 300:  # not enough left for probe + worker + fallback
                break
            tpu_ok = probe_tpu(min(180, budget - 180))
            if tpu_ok:
                break
            print(f"TPU probe {attempt + 1} failed (tunnel wedged/unavailable)",
                  file=sys.stderr)
            if deadline - time.monotonic() > 480:
                time.sleep(45)
    if tpu_ok and not os.environ.get("BENCH_ATTN"):
        # flash canary: the 2026-07-31 window wedged server-side at its first
        # flash-attention compile (TPU_VALIDATE_r04.md). A wedged worker
        # blocks inside one RPC and loses the window, so spend ~1 min proving
        # flash compiles before betting every preset on it; on hang/failure
        # the whole run (engines + batched sweep) rides the XLA attention
        # path instead of hanging.
        repo = os.path.dirname(os.path.abspath(__file__))
        cenv = dict(os.environ)
        cenv["PYTHONPATH"] = repo + os.pathsep + cenv.get("PYTHONPATH", "")
        # share the worker's compile cache so the canary's flash compile
        # (~35-60s over the tunnel) is a cache hit for the worker
        cenv.setdefault("JAX_COMPILATION_CACHE_DIR",
                        os.path.join(repo, "experiments", "jax_cache"))
        c_out, _, c_rc = _run_child(
            [sys.executable, os.path.join(repo, "experiments", "canary_flash.py")],
            cenv, min(300.0, max(deadline - time.monotonic() - 240, 60)))
        if c_rc != 0 or c_out is None or "FLASH CANARY OK" not in c_out:
            print("flash canary failed/hung; benching with BENCH_ATTN=jnp",
                  file=sys.stderr)
            os.environ["BENCH_ATTN"] = "jnp"
    if tpu_ok:
        budget = deadline - time.monotonic() - 120  # keep room for CPU fallback
        env = dict(os.environ)
        env["BENCH_WORKER_BUDGET_S"] = str(max(budget - 30, 30))
        # the worker snapshots its record here after every preset/sweep row:
        # a tunnel WEDGE mid-measurement (2026-07-31 window, blocked forever
        # inside one RPC — deadline checks never run) then degrades to the
        # last snapshot instead of losing every TPU number to the timeout
        partial_path = os.path.abspath(
            os.environ.get("BENCH_PARTIAL_PATH") or os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "experiments", "logs", f"bench_partial_{os.getpid()}.json"))
        os.makedirs(os.path.dirname(partial_path), exist_ok=True)
        try:  # never read a STALE snapshot (pid reuse across windows)
            os.remove(partial_path)
        except OSError:
            pass
        env["BENCH_PARTIAL_PATH"] = partial_path
        result = run_worker(env, max(budget, 60))
        if result is not None:
            try:
                os.remove(partial_path)  # superseded by the full record
            except OSError:
                pass
        if result is None:
            try:
                with open(partial_path) as f:
                    partial = json.load(f)
                os.remove(partial_path)  # consumed; don't litter or go stale
                if partial.get("value", 0) > 0:
                    print("TPU worker died mid-run (wedge?); emitting its last "
                          "partial snapshot", file=sys.stderr)
                    result = partial
            except (OSError, ValueError):
                pass
        if result is not None and "tpu" not in str(result.get("device", "")).lower():
            # the probe saw a TPU but the worker initialized JAX's CPU
            # fallback (tunnel died in between): these are CPU numbers and
            # must not masquerade as the round's TPU record
            print("TPU worker ran on a non-TPU backend "
                  f"({result.get('device')}); treating as fallback", file=sys.stderr)
            result = None
        if result is not None:
            _save_last_tpu_record(result)
            print(json.dumps(result))
            return 0
        print("TPU worker failed; falling back to CPU record", file=sys.stderr)
    env = _cpu_env()
    env["BENCH_DECODE_TOKENS"] = os.environ.get("BENCH_CPU_DECODE_TOKENS", "16")
    env["BENCH_PRESET"] = os.environ.get("BENCH_CPU_PRESET", "tiny")
    # the honest CPU record still demonstrates the serving tier: a small
    # batched sweep (+f8 row) and the admission-stall A/B at toy size
    env.setdefault("BENCH_SWEEP_TINY", "1")
    env.setdefault("BENCH_SLOTS", "4")
    remaining = max(deadline - time.monotonic(), 120)
    # the worker must SELF-limit inside the parent's window — a worker killed
    # mid-measurement prints no JSON and the whole record degrades to empty
    env["BENCH_WORKER_BUDGET_S"] = str(max(remaining - 30, 60))
    result = run_worker(env, remaining)
    if result is None:  # last resort: an honest empty record, still rc=0
        result = {
            "metric": "decode tok/s (UNMEASURED: TPU tunnel down, CPU fallback failed)",
            "value": 0.0, "unit": "tok/s", "vs_baseline": 0.0,
        }
    # reaching here means the emitted numbers are CPU ones — mark the record
    # unconditionally (watch_done.sh keys "keep watching" off this marker; a
    # probe-ok-but-worker-wedged run must NOT read as a TPU record), keep the
    # probe result as separate detail
    result["tpu_unavailable"] = True
    result["tpu_probe_ok"] = tpu_ok
    # the tunnel being down at THIS run must not hide hardware evidence a
    # watcher window already captured: attach the most recent real-TPU record
    # (clearly labeled, headline value stays the honest CPU number)
    last = _load_last_tpu_record()
    if last is not None:
        result["last_tpu_record"] = last
        print("attached last_tpu_record from an earlier live window "
              f"({last.get('recorded_at_utc', '?')})", file=sys.stderr)
    print(json.dumps(result))
    return 0


def _last_tpu_path():
    return os.environ.get("BENCH_LAST_TPU_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "experiments", "last_tpu_bench.json")


def _save_last_tpu_record(result):
    """Persist any real-TPU record (full or partial) so a later run against a
    dead tunnel can still surface hardware evidence in its JSON."""
    try:
        rec = dict(result)
        # a worker that silently fell back to JAX's CPU backend (tunnel died
        # between probe and worker start) must not overwrite real hardware
        # evidence with CPU numbers labeled as a TPU record
        if "tpu" not in str(rec.get("device", "")).lower():
            return
        rec["recorded_at_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # rank evidence before overwriting: a record that measured the 8b
        # serving north star (non-null vs_baseline_config — even a partial
        # wedge snapshot with 8b rows) outranks one that didn't (e.g. the
        # session's quick 1b record); within a rank, full beats partial;
        # equal rank -> newest wins
        old = _load_last_tpu_record()
        if old is not None:
            rank = lambda r: (1 if r.get("vs_baseline_config") else 0,
                              0 if r.get("partial") else 1)
            if rank(old) > rank(rec):
                return
        path = _last_tpu_path()
        tmp = f"{path}.{os.getpid()}.tmp"  # watcher + manual runs can overlap
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        pass  # evidence persistence must never fail a finished run


def _load_last_tpu_record():
    try:
        with open(_last_tpu_path()) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


# --------------------------------------------------------------------- worker


def params_count(cfg) -> float:
    per_layer = (
        cfg.dim * cfg.dim * 2  # wq, wo
        + cfg.dim * cfg.kv_dim * 2  # wk, wv
        + cfg.dim * cfg.hidden_dim * 3  # w1, w2, w3
    )
    return cfg.vocab_size * cfg.dim * 2 + cfg.n_layers * per_layer


PRESETS = {
    # dims follow the HF configs of the reference's model zoo (launch.py)
    "tiny": dict(dim=512, hidden_dim=1536, n_layers=4, n_heads=8, n_kv_heads=4,
                 vocab_size=2048, seq_len=512),
    "1b": dict(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32, n_kv_heads=8,
               vocab_size=128256, seq_len=1024),
    "8b": dict(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
               vocab_size=128256, seq_len=1024),
    # the long --max-seq-len config class (BASELINE "DeepSeek R1 Distill 8B,
    # long"): 8 Ki context, 2 Ki prompt — exercises chunked prefill + the
    # flash kernel's pos-based KV-tile pruning at depth
    "8b_long": dict(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
                    vocab_size=128256, seq_len=8192),
}
PROMPT_LENS = {"8b_long": 2048}  # default 512 elsewhere
LABELS = {"tiny": "tiny", "1b": "Llama-3.2-1B", "8b": "Llama-3.1-8B",
          "8b_long": "Llama-8B-8k"}


def _cache_dtype():
    import jax.numpy as jnp

    val = os.environ.get("BENCH_CACHE", "bf16")
    if val not in ("bf16", "f8"):
        raise SystemExit(f"BENCH_CACHE must be bf16|f8, got {val!r}")
    return jnp.float8_e4m3fn if val == "f8" else jnp.bfloat16


def bench_engine(cfg, params, n_decode, unroll, prompt_len=512, kernels=None,
                 attn_impl="auto"):
    """Batch=1 prefill + fused-decode timings for one preset. Returns dict."""
    import jax
    import numpy as np

    from dllama_tpu.engine.engine import InferenceEngine

    import jax.numpy as jnp

    eng = InferenceEngine(cfg, params, cache_dtype=_cache_dtype(),
                          max_prefill_chunk=512, layer_unroll=unroll,
                          attn_impl=attn_impl,
                          fuse_weights=os.environ.get("BENCH_FUSE") == "1",
                          kernels=kernels or os.environ.get("BENCH_KERNELS", "auto"))
    prompt_len = min(prompt_len, cfg.seq_len // 2)
    prompt = (np.arange(1, prompt_len + 1, dtype=np.int32)[None]) % cfg.vocab_size
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_compile = time.perf_counter() - t0
    first = np.asarray(jnp.argmax(logits, -1)).astype(np.int32)
    prefill_end = eng.pos

    n_decode = min(n_decode, eng.seq_len - eng.pos - 1)
    t0 = time.perf_counter()
    _ = eng.decode_greedy_n(first, n_decode)  # compile+warmup, same static n
    t_compile += time.perf_counter() - t0

    eng.reset(prefill_end)
    t0 = time.perf_counter()
    _ = eng.decode_greedy_n(first, n_decode)  # np.asarray inside = device sync
    t_decode = time.perf_counter() - t0

    eng.reset(0)
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_prefill = time.perf_counter() - t0

    n_params = params_count(cfg)
    prefill_tok_s = prompt.shape[1] / t_prefill
    # ~2 flops/param/token; v5e bf16 peak ~197 TFLOP/s
    mfu = prefill_tok_s * 2.0 * n_params / 197e12
    out = {
        "decode_tok_s": round(n_decode / t_decode, 2),
        "decode_ms_per_token": round(1000.0 * t_decode / n_decode, 3),
        "prefill_tok_s": round(prefill_tok_s, 1),
        "prefill_mfu": round(mfu, 4),
        "compile_s": round(t_compile, 1),
        "params_b": round(n_params / 1e9, 3),
    }

    # prompt-lookup speculative decoding on a REPETITIVE prompt: exact greedy
    # output in fewer forwards. Honest framing: the accept rate (and so the
    # speedup) is data-dependent — a periodic prompt shows the ceiling, the
    # structureless arange prompt above would show ~1x. BENCH_SPEC=0 skips.
    spec_k = int(os.environ.get("BENCH_SPEC", "8"))
    if spec_k > 0 and cfg.seq_len < 4096:  # skip on the long preset: the
        # spec story is 1b/8b's; the long preset's budget goes to pruning
        # evidence (its whole reason to exist)
        try:
            motif = list(np.random.default_rng(3).integers(1, cfg.vocab_size, 16))
            rep = (motif * (prompt_len // 16 + 1))[:prompt_len]
            eng.reset(0)
            rep_logits = eng.prefill(np.asarray([rep], np.int32))
            base = eng.pos
            first = int(np.argmax(np.asarray(rep_logits)[0]))
            eng.decode_spec_greedy_n(rep, first, n_decode, k=spec_k)  # compile+warm
            eng.reset(base)
            t0 = time.perf_counter()
            toks = eng.decode_spec_greedy_n(rep, first, n_decode, k=spec_k)
            t_spec = time.perf_counter() - t0
            st = eng._spec_stats
            out["spec"] = {
                "k": spec_k,
                "tok_s": round(len(toks) / t_spec, 2),
                "tokens_per_forward": round(st["emitted"] / max(st["cycles"], 1), 2),
                "speedup_vs_decode": round(
                    (len(toks) / t_spec) / (n_decode / t_decode), 2
                ),
            }
        except Exception as e:
            out["spec"] = {"error": repr(e)[:160]}
    del eng
    return out


def bench_batched(cfg, params, slots, n_decode=64, kernels=None, cache_dtype=None):
    """Aggregate decode tok/s/chip from the continuous-batching tier with all
    `slots` sequences decoding together (BatchEngine, per-slot positions)."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine

    import jax.numpy as jnp

    eng = BatchEngine(cfg, params, n_slots=slots,
                      cache_dtype=cache_dtype or _cache_dtype(),
                      max_prefill_chunk=64,
                      fuse_weights=os.environ.get("BENCH_FUSE") == "1",
                      kernels=kernels or os.environ.get("BENCH_KERNELS", "auto"),
                      attn_impl=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for s in range(slots):
        eng.add(s, list(rng.integers(1, cfg.vocab_size, 64)), temperature=0.8, seed=s)
    t_prefill = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.decode(n_decode)  # compile + warmup (same static n)
    t_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.decode(n_decode)
    t = time.perf_counter() - t0
    del eng
    return {
        "slots": slots,
        "agg_tok_s": round(slots * n_decode / t, 1),
        "step_ms": round(1000.0 * t / n_decode, 2),
        "admit_prefill_s": round(t_prefill, 1),
        "compile_s": round(t_compile, 1),
    }


def bench_batched_spec(cfg, params, slots, k=8, kernels=None, cache_dtype=None):
    """Aggregate tok/s of the serving tier under batched speculation: all
    slots greedy on periodic prompts (the draft-friendly workload — the
    acceptance CEILING, like the single-engine spec bench). Reported
    tokens_per_cycle > 1 is the multiplier over one-token-per-forward
    batched decode at the same slot count."""
    import numpy as np

    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine

    eng = BatchEngine(cfg, params, n_slots=slots,
                      cache_dtype=cache_dtype or _cache_dtype(),
                      max_prefill_chunk=64, spec=k,
                      kernels=kernels or os.environ.get("BENCH_KERNELS", "auto"),
                      attn_impl=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    for s in range(slots):
        base = list(rng.integers(1, cfg.vocab_size, 4))
        eng.add(s, (base * 16)[:64], temperature=0.0, seed=s)
    t0 = time.perf_counter()
    eng.spec_step()  # compile + warmup
    t_compile = time.perf_counter() - t0
    room = eng.seq_len - int(eng.pos.max()) - k - 2
    cycles = max(4, min(24, room // (k + 1)))
    total = 0
    t0 = time.perf_counter()
    for _ in range(cycles):
        _, adv = eng.spec_step()
        total += int(adv.sum())
    t = time.perf_counter() - t0
    del eng
    return {
        "slots": slots,
        "spec_k": k,
        "agg_tok_s": round(total / t, 1),
        "tokens_per_cycle": round(total / cycles / slots, 2),
        "step_ms": round(1000.0 * t / cycles, 2),
        "compile_s": round(t_compile, 1),
    }


def bench_spec_batch(cfg, params, n_slots=4, chunk=4, steps=144, k=8,
                     pf_chunk=64):
    """Speculative continuous batching A/B through the REAL scheduler
    (ISSUE 11) — unlike bench_batched_spec (the raw-engine acceptance
    ceiling), this record drives Scheduler end to end, so admission,
    overlap composition, and per-request spec_k are all on the measured
    path. Two legs:

    1. repetitive: all slots greedy on periodic (draft-friendly) prompts,
       spec-on (per-request spec_k=k) vs spec-off (a spec=0 engine) —
       `tok_s_ratio_spec_plain` is the serving-tier speculation win the
       perfdiff gate tracks (acceptance: >= 2x on this leg);
    2. mixed: half the slots speculate, half are SAMPLED spec_k=0 traffic —
       the non-spec slots' per-class tok/s vs the same workload on the
       spec-off engine (`nonspec_tok_s_ratio`, gate: no collapse) plus a
       bit-exactness check that a spec neighbor never perturbs a sampled
       stream (`nonspec_exact`).
    """
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.serve.scheduler import Scheduler

    rng = np.random.default_rng(0)
    # "repetitive text" = text the model itself predicts: probe each slot's
    # own greedy continuation once and use seed+continuation as the prompt,
    # so the sequence's n-gram statistics really do predict what greedy
    # decoding emits next — the core speculative-decoding workload
    # (boilerplate, code, templated text), not an artificial token loop
    probe = BatchEngine(cfg, params, n_slots=n_slots,
                        cache_dtype=_cache_dtype(), max_prefill_chunk=pf_chunk,
                        attn_impl=os.environ.get("BENCH_ATTN", "auto"))
    seeds = [[int(x) for x in rng.integers(1, cfg.vocab_size, 4)]
             for _ in range(n_slots)]
    conts = {s: [probe.add(s, seeds[s], temperature=0.0, seed=s)]
             for s in range(n_slots)}
    for _ in range(12):
        toks = probe.decode(4)
        for s in range(n_slots):
            conts[s] += [int(t) for t in toks[:, s]]
    del probe
    rep_prompts = [seeds[s] + conts[s][:48] for s in range(n_slots)]
    mix_prompts = [[int(x) for x in rng.integers(1, cfg.vocab_size, 8)]
                   for _ in range(n_slots)]
    out = {"slots": n_slots, "chunk": chunk, "steps": steps, "spec_k": k,
           # honesty note for off-TPU readers: a verify forward is K+1 q
           # rows wide, so on a compute-bound host (CPU fallback) non-spec
           # batch-mates pay a real FLOP tax per cycle; on the HBM-bound
           # TPU decode path the wide forward streams the same bytes as a
           # 1-wide one and that tax ~vanishes
           "timing": "decode-phase (clock starts after every stream's "
                     "first token)"}

    def drive(spec_engine, leg):
        """-> (per-request token lists by class, decode_s, spec stats).
        The clock starts once EVERY stream has its first token (prompts and
        compile are identical across legs — including prefill would dilute
        the decode-path ratio this record exists to gate) and stops when
        the last stream drains."""
        eng = BatchEngine(cfg, params, n_slots=n_slots,
                          cache_dtype=_cache_dtype(),
                          max_prefill_chunk=pf_chunk,
                          spec=k if spec_engine else 0,
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"))
        sched = Scheduler(eng, chunk=chunk)
        try:
            # warm EVERY compiled path out of the measured window: a greedy
            # spec request long enough to hit both fused-scan shapes (the
            # chunk-sized launch and the tail-clamped single cycle), then a
            # sampled spec_k=0 one so the plain decode scan compiles too
            # (the mixed leg switches modes mid-run)
            warm = sched.submit(rep_prompts[0], 0.0, 0.9, 2 * (k + 1),
                                frozenset(), seed=99,
                                spec_k=k if spec_engine else 0)
            list(warm.tokens())
            warm2 = sched.submit(mix_prompts[0], 0.9, 0.9, 2 * chunk,
                                 frozenset(), seed=98, spec_k=0)
            list(warm2.tokens())
            sched.reset_latency_stats()
            # engine spec totals are LIFETIME counters: snapshot after the
            # warm requests so the recorded acceptance stats describe the
            # measured leg only, not the warmup's high-acceptance tokens
            spec_base = dict(getattr(eng, "_spec_totals", {}))
            if leg == "repetitive":
                reqs = [(sched.submit(rep_prompts[s], 0.0, 0.9, steps,
                                      frozenset(), seed=s,
                                      spec_k=k if spec_engine else 0),
                         "spec")
                        for s in range(n_slots)]
            else:  # mixed: even slots greedy+spec, odd slots sampled spec_k=0
                reqs = []
                for s in range(n_slots):
                    if s % 2 == 0:
                        reqs.append((sched.submit(
                            rep_prompts[s], 0.0, 0.9, steps, frozenset(),
                            seed=s, spec_k=k if spec_engine else 0), "spec"))
                    else:
                        reqs.append((sched.submit(
                            mix_prompts[s], 0.9, 0.9, steps, frozenset(),
                            seed=1000 + s, spec_k=0), "nonspec"))
            its = [(r.tokens(), cls, r) for r, cls in reqs]
            heads = [(next(it), cls) for it, cls, _ in its]
            t0 = time.perf_counter()
            toks = {"spec": [], "nonspec": []}
            for (it, cls, _r), (head, _) in zip(its, heads):
                toks[cls].append([head] + list(it))
            dt = time.perf_counter() - t0
            stats = sched.latency_summary().get("spec")
            if stats:
                # warmup-corrected leg stats (see spec_base above)
                for key in ("cycles", "drafted", "accepted", "emitted"):
                    stats[key] -= spec_base.get(key, 0)
                stats["tokens_per_cycle"] = (
                    round(stats["emitted"] / stats["cycles"], 3)
                    if stats["cycles"] else None)
                stats["accept_mean"] = (
                    round(stats["accepted"] / stats["drafted"], 3)
                    if stats["drafted"] else None)
            return toks, dt, stats
        finally:
            sched.shutdown()

    for leg in ("repetitive", "mixed"):
        try:
            on_toks, on_dt, on_stats = drive(True, leg)
            off_toks, off_dt, _ = drive(False, leg)
            total_on = sum(len(t) for ts in on_toks.values() for t in ts)
            total_off = sum(len(t) for ts in off_toks.values() for t in ts)
            rec = {
                "spec_tok_s": round(total_on / on_dt, 1),
                "plain_tok_s": round(total_off / off_dt, 1),
                "tok_s_ratio_spec_plain": round(
                    (total_on / on_dt) / (total_off / off_dt), 3),
                "exact": on_toks == off_toks,  # bit-exactness, both classes
                "tokens_per_cycle": (on_stats or {}).get("tokens_per_cycle"),
                "accept_mean": (on_stats or {}).get("accept_mean"),
            }
            if leg == "mixed":
                ns_on = sum(len(t) for t in on_toks["nonspec"])
                ns_off = sum(len(t) for t in off_toks["nonspec"])
                # per-class rate: the sampled slots' share of the leg's
                # wall time is the whole leg (they run start to finish)
                rec["nonspec_tok_s"] = round(ns_on / on_dt, 1)
                rec["nonspec_plain_tok_s"] = round(ns_off / off_dt, 1)
                rec["nonspec_tok_s_ratio"] = round(
                    (ns_on / on_dt) / (ns_off / off_dt), 3)
                rec["nonspec_exact"] = on_toks["nonspec"] == off_toks["nonspec"]
            out[leg] = rec
        except Exception as e:
            out[leg] = {"error": repr(e)[:160]}
    return out


def _widen_scales(params):
    """QTensor leaves with f16 scales -> f32 copies (the Mosaic-u16 escape
    hatch: Pallas keeps running, at f32-scale HBM traffic)."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.ops.quant import QTensor

    def widen(leaf):
        if isinstance(leaf, QTensor) and leaf.scales.dtype == jnp.float16:
            return QTensor(leaf.packed, leaf.scales.astype(jnp.float32))
        return leaf

    return jax.tree.map(widen, params, is_leaf=lambda l: isinstance(l, QTensor))


def bench_moe(n_tokens=256, iters=20):
    """Micro-bench of the sparse-MoE FFN op: GShard-style dispatch and the
    sort-based grouped GEMM (O(k/E) FLOPs each) vs the dense all-experts
    reference, Mixtral-shaped experts (E=8, k=2) at 2048 width. One line in
    the result JSON; 'auto' should follow whichever sparse scheme wins here
    (VERDICT r3 #6)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.ops.layers import moe_ffn

    cfg = LlamaConfig(dim=2048, hidden_dim=4096, n_layers=1, n_heads=16,
                      n_kv_heads=8, vocab_size=256, seq_len=8,
                      n_experts=8, n_active_experts=2)
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((1, n_tokens, cfg.dim)) * 0.1, jnp.bfloat16)
    gate = jnp.asarray(rng.standard_normal((cfg.dim, 8)) * 0.1, jnp.float32)
    ws = [jnp.asarray(rng.standard_normal(s) * 0.02, jnp.bfloat16)
          for s in ((8, cfg.dim, cfg.hidden_dim), (8, cfg.hidden_dim, cfg.dim),
                    (8, cfg.dim, cfg.hidden_dim))]
    out = {}
    for impl in ("dispatch", "sort", "dense"):
        try:
            fn = jax.jit(lambda h, impl=impl: moe_ffn(cfg, h, gate, *ws, impl=impl))
            jax.block_until_ready(fn(h))  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(h)
            jax.block_until_ready(r)
            out[f"{impl}_ms"] = round(1000 * (time.perf_counter() - t0) / iters, 3)
        except Exception as e:  # one scheme failing to lower must not kill the row
            out[f"{impl}_error"] = repr(e)[:160]
    best_sparse = min(
        (v for k2, v in out.items() if k2 in ("dispatch_ms", "sort_ms")), default=None
    )
    if best_sparse and out.get("dense_ms"):
        out["speedup"] = round(out["dense_ms"] / best_sparse, 2)
    out["tokens"] = n_tokens
    return out


def admission_streams(cfg, pf_chunk: int, prompt_len: int):
    """Token streams for the admission-stall scenario, shared with
    experiments/abench.py. DISTINCT leading tokens per stream: the
    scheduler's prefix cache would otherwise match a measured admission
    against a warmup slot's history and prefill 1 token instead of
    prompt_len (silently gutting the measurement). The warmup prompt's
    (2*pf_chunk - 1) length decomposes into every pow-2 prefill width."""
    import numpy as np

    mk = lambda base, n: list(((np.arange(n) * 7 + base) % (cfg.vocab_size - 2) + 1).astype(int))
    warm = mk(501, 2 * pf_chunk - 1)
    bg_maker = lambda s: mk(1001 + 97 * s, 3)
    return warm, bg_maker, mk(3001, prompt_len)


# the admission-policy A/B, shared with experiments/abench.py so both
# harnesses always measure the same three policies: legacy synchronous,
# strict one-chunk-per-decode interleaving (budget 0), and the scheduler's
# default paced budget (VERDICT r4 weak #3)
ADMISSION_MODES = {
    # prefill_budget=0 pins every mode to the LEGACY phase-split admission
    # this record A/Bs (sync vs strict vs paced pacing); the fused hybrid
    # step — the shipped default since ISSUE 12 — has its own `hybrid`
    # record (bench_hybrid) measured against this same protocol
    "sync": dict(admit_interleave=False, prefill_budget=0),
    "strict": dict(admit_interleave=True, admit_stall_budget_ms=0.0,
                   prefill_budget=0),
    "paced": dict(admit_interleave=True, prefill_budget=0),  # default budget
}

# ONE protocol for bench_admission AND experiments/abench.py --smoke
# (VERDICT r5 flagged that BENCH_r05's admission record — stall_reduction_x
# 1.1 — "contradicted" ADMISSION_CPU.md's passing A/B: the two harnesses ran
# DIFFERENT knobs (8 slots / 256-token prompt / chunk 4 / pf 64 vs 4 / 96 /
# 2 / 16) and judged different metrics. With prompt≈budget a paced admission
# legitimately approaches the sync stall — the budget caps the stall, and a
# prefill that fits in one budget window IS the sync prefill — so the ratio
# is protocol-dependent; sharing the dict makes the two records the same
# experiment. See experiments/ADMISSION_CPU.md "Reconciliation (r6)".)
ADMISSION_PROTOCOL = dict(n_slots=4, prompt_len=96, chunk=2, pf_chunk=16,
                          bg_steps=48)


def bench_admission(cfg, params, n_slots=None, prompt_len=None, chunk=None,
                    pf_chunk=None, bg_steps=None):
    """Admission-stall record for the serving tier (VERDICT r3 #4, r4 weak
    #3): the max decode-to-decode gap batch-mates see while a long prompt
    joins, and the joiner's TTFT, across three admission policies —
    'sync' (legacy whole-prefill-at-once), 'strict' (one prefill chunk per
    decode chunk, the r4 default whose TTFT cost was unbounded), and 'paced'
    (the shipped default: chunks pumped per visit until the stall budget is
    spent). Defaults come from ADMISSION_PROTOCOL — the same knobs
    experiments/abench.py --smoke runs, so the bench record and
    ADMISSION_CPU.md measure the same experiment. Emits the same
    within-2x-of-best acceptance fields the experiment's PASS bar uses."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.serve.scheduler import Scheduler

    proto = ADMISSION_PROTOCOL
    n_slots = n_slots or proto["n_slots"]
    prompt_len = min(prompt_len or proto["prompt_len"], cfg.seq_len // 2)
    chunk = chunk or proto["chunk"]
    pf_chunk = pf_chunk or proto["pf_chunk"]
    bg_steps = bg_steps or proto["bg_steps"]
    out = {"slots": n_slots, "prompt": prompt_len, "chunk": chunk,
           "pf_chunk": pf_chunk, "protocol": "ADMISSION_PROTOCOL"}
    warm, bg_maker, prompt = admission_streams(cfg, pf_chunk, prompt_len)
    for key, kw in ADMISSION_MODES.items():
        sched = None
        try:
            eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=jnp.bfloat16,
                              max_prefill_chunk=pf_chunk,
                              attn_impl=os.environ.get("BENCH_ATTN", "auto"))
            sched = Scheduler(eng, chunk=chunk, **kw)
            w = sched.submit(warm, 0.0, 0.9, chunk, frozenset(), seed=7)
            list(w.tokens())
            sched.reset_latency_stats()  # compile gaps are not stalls
            bg = [sched.submit(bg_maker(s), 0.8, 0.9, bg_steps, frozenset(), seed=s)
                  for s in range(max(1, n_slots // 2))]
            it = bg[0].tokens()
            for _ in range(2 * chunk):
                next(it)
            r_long = sched.submit(prompt, 0.0, 0.9, chunk, frozenset(), seed=99)
            for _ in it:
                pass
            list(r_long.tokens())
            for r in bg[1:]:
                list(r.tokens())
            s = sched.latency_summary()
            if s["admission_stall_ms_max"] is not None:
                out[key + "_stall_ms_max"] = round(s["admission_stall_ms_max"], 1)
            out[key + "_long_ttft_ms"] = round(r_long.ttft_ms or 0.0, 1)
        except Exception as e:
            out[key + "_error"] = repr(e)[:160]
        finally:
            if sched is not None:
                sched.shutdown()
    sync_s, paced_s = out.get("sync_stall_ms_max"), out.get("paced_stall_ms_max")
    if sync_s is not None and paced_s is not None:
        # floor the denominator at timer noise so a 0.0 best-case still yields
        # a (large, finite) ratio instead of vanishing from the JSON
        out["stall_reduction_x"] = round(sync_s / max(paced_s, 0.05), 1)
    sync_t, paced_t = out.get("sync_long_ttft_ms"), out.get("paced_long_ttft_ms")
    if sync_t is not None and paced_t is not None:
        out["ttft_overhead_x"] = round(paced_t / max(sync_t, 0.05), 2)
    # the experiment's acceptance bar (VERDICT r4 next #5), on the series
    # this harness records: paced must keep BOTH metrics within 2x of the
    # best mode for that metric (abench applies the same bar to its
    # client-observed gaps; the stall series here is the scheduler's own
    # attribution — same knobs, adjacent vantage points)
    stalls = {m: out.get(m + "_stall_ms_max") for m in ADMISSION_MODES}
    ttfts = {m: out.get(m + "_long_ttft_ms") for m in ADMISSION_MODES}
    if all(v is not None for v in stalls.values()) and all(
            v is not None for v in ttfts.values()):
        best_s, best_t = min(stalls.values()), min(ttfts.values())
        out["paced_within_2x_stall"] = stalls["paced"] <= 2 * max(best_s, 0.05)
        out["paced_within_2x_ttft"] = ttfts["paced"] <= 2 * max(best_t, 0.05)
    return out


# the hybrid fused-step record's protocol (ISSUE 12): one background probe
# stream + one long joiner, chunk=1 — the regime the feature targets is
# prefill-heavy joins, so the prompt is several budget slices long. On CPU
# hosts the record shrinks to a FIXTURE-sized model (same precedent as
# bench_paged_kernel off-TPU): the tiny preset's ~60 ms per-dispatch CPU
# decode floor is host overhead that drowns the scheduling mechanism the
# record measures — the fixture keeps prefill compute dominant over the
# dispatch floor, which is the shape of the problem on real accelerators.
HYBRID_PROTOCOL = dict(n_slots=2, prompt_len=384, chunk=1, pf_chunk=128,
                       bg_steps=192, budget=128)

#: CPU-fixture model for bench_hybrid (tagged "fixture": true in the
#: record): small enough that a decode step costs ~2 ms host-side while a
#: 128-token prefill slice costs ~2-3x that — scheduling, not XLA dispatch,
#: is what the ratios then measure
HYBRID_FIXTURE = dict(dim=64, hidden_dim=128, n_layers=4, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=512)


def bench_hybrid(cfg, params, n_slots=None, prompt_len=None, chunk=None,
                 pf_chunk=None, bg_steps=None, budget=None):
    """Hybrid chunked-prefill record (ISSUE 12): what a long joining prompt
    costs a RUNNING stream and the joiner itself, legacy sync phase-split
    vs the fused hybrid step (--prefill-budget N — each decode chunk
    co-processes a budget-sized prompt slice in the same device launch).

    Two stall vantage points, both recorded:

    * ``*_stall_ms_max`` — the probe stream's CLIENT-observed max
      inter-token gap inside the joiner's admission window (what an SSE
      consumer experiences; the headline stall_reduction_x divides these);
    * ``*_sched_stall_ms_max`` — the scheduler's own decode-to-decode
      admission-gap attribution (the series BENCH_r05's admission record
      reports; ~0 under hybrid because no admission work runs BETWEEN
      chunks — the per-chunk cost shows up in the ITL series instead).

    Plus ``*_itl_p95_ms`` during the admission window (the satellite's
    ITL-p95-during-admission series), the joiner's TTFT
    (ttft_overhead_x = hybrid/sync), and two exactness flags: hybrid-on
    streams bit-exact vs --prefill-budget 0, and a preempted+resumed
    request byte-identical to its uninterrupted run.

    Acceptance (ISSUE 12): stall_reduction_x >= 2 (BENCH_r05's paced mode
    managed 1.1) with ttft_overhead_x <= 1.2 (paced paid 1.63) — hybrid
    must dominate pacing on BOTH axes, not trade one for the other."""
    import threading

    import jax
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    proto = HYBRID_PROTOCOL
    n_slots = n_slots or proto["n_slots"]
    chunk = chunk or proto["chunk"]
    pf_chunk = pf_chunk or proto["pf_chunk"]
    bg_steps = bg_steps or proto["bg_steps"]
    budget = budget or proto["budget"]
    fixture = jax.default_backend() == "cpu"
    if fixture:
        cfg = LlamaConfig(**HYBRID_FIXTURE)
        params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
        cache_dtype = jnp.float32
    else:
        cache_dtype = jnp.bfloat16
    prompt_len = min(prompt_len or proto["prompt_len"], cfg.seq_len - 96)
    out = {"slots": n_slots, "prompt": prompt_len, "chunk": chunk,
           "pf_chunk": pf_chunk, "budget": budget, "fixture": fixture,
           "protocol": "HYBRID_PROTOCOL"}
    mk = lambda base, n: [int(x) for x in
                          ((__import__("numpy").arange(n) * 7 + base)
                           % (cfg.vocab_size - 2) + 1)]
    warm_join = mk(4001, prompt_len)  # distinct from the measured prompt:
    # prefix reuse must not gut the measured admission
    prompt = mk(3001, prompt_len)
    modes = {
        "sync": dict(admit_interleave=False, prefill_budget=0),
        "hybrid": dict(prefill_budget=budget),
    }
    streams: dict[str, list] = {}
    for key, kw in modes.items():
        sched = None
        try:
            eng = BatchEngine(cfg, params, n_slots=n_slots,
                              cache_dtype=cache_dtype,
                              max_prefill_chunk=pf_chunk,
                              attn_impl=os.environ.get("BENCH_ATTN", "auto"))
            sched = Scheduler(eng, chunk=chunk, **kw)
            # ---- warm-up: compile decode AND the mode's admission shapes
            # (hybrid slices / phase-split prefill chunks) via a throwaway
            # join while a warm stream decodes — the measured leg must time
            # serving, not XLA
            wbg = sched.submit(mk(501, 3), 0.8, 0.9, 8 * chunk, frozenset(),
                               seed=7)
            wit = wbg.tokens()
            next(wit)
            wj = sched.submit(warm_join, 0.0, 0.9, chunk, frozenset(),
                              seed=8)
            list(wj.tokens())
            for _ in wit:
                pass
            sched.reset_latency_stats()
            # ---- measured leg: one probe stream, then the long joiner
            bg = sched.submit(mk(1001, 3), 0.8, 0.9, bg_steps, frozenset(),
                              seed=1)
            stamps: list[tuple[int, float]] = []
            rolled = threading.Event()

            def consume():
                for t in bg.tokens():
                    stamps.append((int(t), time.perf_counter()))
                    if len(stamps) >= 4 * chunk:
                        rolled.set()

            th = threading.Thread(target=consume, daemon=True)
            th.start()
            rolled.wait(timeout=120)
            t_sub = time.perf_counter()
            r_long = sched.submit(prompt, 0.0, 0.9, 2, frozenset(), seed=99)
            long_it = r_long.tokens()
            first_long = next(long_it)
            t_first = time.perf_counter()
            long_toks = [int(first_long)] + [int(t) for t in long_it]
            th.join(timeout=120)
            # the admission window on the probe stream's own clock
            gaps, prev = [], None
            for _tok, ts in stamps:
                if prev is not None and ts >= t_sub and prev <= t_first:
                    gaps.append((ts - prev) * 1000.0)
                prev = ts
            if gaps:
                srt = sorted(gaps)
                out[key + "_stall_ms_max"] = round(srt[-1], 2)
                out[key + "_itl_p95_ms"] = round(
                    srt[min(len(srt) - 1, int(0.95 * (len(srt) - 1)))], 2)
            out[key + "_long_ttft_ms"] = round(r_long.ttft_ms or 0.0, 1)
            s = sched.latency_summary()
            if s["admission_stall_ms_max"] is not None:
                out[key + "_sched_stall_ms_max"] = round(
                    s["admission_stall_ms_max"], 2)
            streams[key] = [[t for t, _ in stamps], long_toks]
            if key == "hybrid":
                out["hybrid_ledger_s"] = round(
                    sched.ledger.totals.get("hybrid", 0.0), 3)
        except Exception as e:
            out[key + "_error"] = repr(e)[:160]
        finally:
            if sched is not None:
                sched.shutdown()
    if "sync" in streams and "hybrid" in streams:
        # the tentpole's exactness contract, measured where the ratios are
        out["streams_exact"] = streams["sync"] == streams["hybrid"]
    sync_s, hyb_s = out.get("sync_stall_ms_max"), out.get("hybrid_stall_ms_max")
    if sync_s is not None and hyb_s is not None:
        out["stall_reduction_x"] = round(sync_s / max(hyb_s, 0.05), 1)
    sync_t, hyb_t = out.get("sync_long_ttft_ms"), out.get("hybrid_long_ttft_ms")
    if sync_t is not None and hyb_t is not None:
        out["ttft_overhead_x"] = round(hyb_t / max(sync_t, 0.05), 2)
    # preempt-to-pages exactness leg: a low-priority sampled stream
    # suspended by a high-priority arrival, resumed, compared byte-for-byte
    # with its uninterrupted twin (1 slot forces the preemption)
    try:
        from dllama_tpu.utils import faults as _faults

        def one(preempt: bool):
            eng = BatchEngine(cfg, params, n_slots=1,
                              cache_dtype=cache_dtype, max_prefill_chunk=16)
            s2 = Scheduler(eng, chunk=max(chunk, 2))
            try:
                lo = s2.submit([3, 1, 4], 0.8, 0.9, 12, frozenset(), seed=5,
                               priority=0)
                it = lo.tokens()
                head = [next(it)]
                if preempt:
                    _faults.install("engine.decode", "delay", ms=10, times=40)
                    hi = s2.submit([9, 2, 6], 0.0, 0.9, 2, frozenset(),
                                   seed=6, priority=2)
                    list(hi.tokens())
                toks = head + list(it)
                return toks, s2.preempt_count if preempt else 0
            finally:
                _faults.clear()
                s2.shutdown()

        interrupted, n_pre = one(True)
        uninterrupted, _ = one(False)
        out["preemptions"] = n_pre
        out["preempt_resume_exact"] = interrupted == uninterrupted
    except Exception as e:
        out["preempt_error"] = repr(e)[:160]
    return out


def bench_compile(cfg, params, n_slots=2, chunk=4, steps=200, pf_chunk=64):
    """Compile & device-traffic record (ISSUE 13), three legs:

    * **cold** — scheduler boots with ``--warmup off``; the first request's
      TTFT carries every XLA compile (``cold_ttft_ms``), and the compile
      ledger's seconds delta is the cold-boot compile bill
      (``cold_compile_s``).
    * **warm** — a fresh engine boots with ``--warmup auto`` (its compile
      bill moves to boot: ``warmup_s``, ``warmup_buckets``,
      ``warmup_full_coverage``); the first request must then compile
      NOTHING (``warm_first_request_compiles``) and
      ``warmup_ttft_ratio = warm/cold`` is the headline TTFT win the
      perfdiff gate pins.
    * **steady** — a 200-token decode driven at the ENGINE level (the
      worker is shut down first, so snapshots can't race it): after one
      warm chunk and a page pre-grow, the measured window must record
      ZERO compiles (unexpected or otherwise) and ZERO host->device upload
      bytes — the PR 3 device-resident-state invariant plus the bounded
      compiled-shape universe, both as absolute perfdiff ceilings. The
      window runs under ``transfer_guard='strict'``, so an implicit upload
      would fail the leg loudly, not just move a counter.

    CPU hosts shrink to the HYBRID_FIXTURE model (same precedent as
    bench_hybrid: the record measures scheduling/compile behavior, not
    model FLOPs). BENCH_COMPILE=0 skips."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.obs import compile as cobs
    from dllama_tpu.serve.scheduler import Scheduler

    fixture = jax.default_backend() == "cpu"
    if fixture:
        cfg = LlamaConfig(**HYBRID_FIXTURE)
        params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
        cache_dtype = jnp.float32
    else:
        cache_dtype = jnp.bfloat16
    steps = min(steps, cfg.seq_len - 32)
    out = {"slots": n_slots, "chunk": chunk, "steps": steps,
           "fixture": fixture, "layout": "paged/64"}
    prompt = [int(x) % (cfg.vocab_size - 2) + 1 for x in range(7, 15)]

    def boot_and_first(warmup: str):
        eng = BatchEngine(cfg, params, n_slots=n_slots,
                          cache_dtype=cache_dtype, max_prefill_chunk=pf_chunk,
                          kv_layout="paged", page_size=64,  # serving default
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"))
        s0 = cobs.LEDGER.total_seconds()
        sched = Scheduler(eng, chunk=chunk, warmup=warmup)
        boot_compile_s = cobs.LEDGER.total_seconds() - s0
        c0 = cobs.LEDGER.total_compiles()
        r = sched.submit(prompt, 0.0, 0.9, 2 * chunk, frozenset(), seed=1)
        toks = list(r.tokens())
        assert len(toks) == 2 * chunk
        first_compiles = cobs.LEDGER.total_compiles() - c0
        first_compile_s = cobs.LEDGER.total_seconds() - s0 - boot_compile_s
        return sched, (r.ttft_ms or 0.0), boot_compile_s, first_compiles, \
            first_compile_s

    # ---- cold leg: first request pays the compile bill
    sched, ttft, boot_s, n_first, s_first = boot_and_first("off")
    sched.shutdown()
    out["cold_ttft_ms"] = round(ttft, 1)
    out["cold_compile_s"] = round(boot_s + s_first, 3)
    out["cold_first_request_compiles"] = n_first
    # ---- warm leg: the bill moves to boot; first request compiles nothing
    sched, ttft, boot_s, n_first, _ = boot_and_first("auto")
    rep = sched.warmup_report or {}
    out["warmup_s"] = rep.get("seconds")
    out["warmup_buckets"] = rep.get("buckets")
    out["warmup_full_coverage"] = bool(rep.get("full_coverage"))
    out["warm_ttft_ms"] = round(ttft, 1)
    out["warm_first_request_compiles"] = n_first
    if out["cold_ttft_ms"]:
        out["warmup_ttft_ratio"] = round(
            out["warm_ttft_ms"] / max(out["cold_ttft_ms"], 0.05), 3)
    # ---- steady leg: engine-level (no worker to race), strict guard
    sched.shutdown()
    eng = sched.engine
    eng.add(0, prompt, temperature=0.0, seed=2)
    eng.decode(chunk)  # one warm chunk past the admission boundary
    eng._alloc_decode_rows(steps + 2 * chunk)  # pre-grow: page allocation
    # is an amortized boundary event, not per-chunk traffic — provision the
    # window so the gate measures the steady path alone
    warm = eng.decode(chunk)  # consume the pre-grow's vector refresh
    assert warm.shape[0] == chunk
    eng.transfer_guard = "strict"
    cobs.reset_transfers()
    c0, u0 = cobs.LEDGER.total_compiles(), cobs.LEDGER.total_unexpected()
    n_chunks = max(1, steps // chunk)
    pending = eng.decode_dispatch(chunk)
    for _ in range(n_chunks - 1):  # overlapped: successor off the carry
        nxt = eng.decode_dispatch(chunk)
        eng.decode_consume(pending)
        pending = nxt
    eng.decode_consume(pending)
    tr = cobs.transfer_snapshot()
    out["steady"] = {
        "chunks": n_chunks,
        "decode_tokens": n_chunks * chunk,
        "compiles": cobs.LEDGER.total_compiles() - c0,
        "unexpected_compiles": cobs.LEDGER.total_unexpected() - u0,
        "upload_bytes": tr["h2d"]["bytes"],
        "upload_transfers": tr["h2d"]["count"],
        "download_bytes": tr["d2h"]["bytes"],
        "transfer_guard": "strict",
    }
    return out


def bench_overlap(cfg, params, n_slots=8, chunk=4, steps=48, pf_chunk=64):
    """Overlap A/B for the serving tier: aggregate decode tok/s and the
    inter-chunk host gap with the scheduler's overlapped dispatch on vs off
    (same engine config, prompts, and seeds — token streams are identical by
    construction, so the delta is pure pipeline efficiency). The host gap is
    the device-idle window the scheduler's Python work (emit loops, EOS
    checks, metrics) inserts between fused chunks; overlap hides it behind
    the in-flight chunk's device compute."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.serve.scheduler import Scheduler

    mk = lambda base: [int(x) for x in
                       ((np.arange(3) * 11 + base) % (cfg.vocab_size - 2) + 1)]
    out = {"slots": n_slots, "chunk": chunk, "steps": steps}
    for key, ov in (("overlap_on", True), ("overlap_off", False)):
        sched = None
        try:
            eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=_cache_dtype(),
                              max_prefill_chunk=pf_chunk,
                              attn_impl=os.environ.get("BENCH_ATTN", "auto"))
            sched = Scheduler(eng, chunk=chunk, overlap=ov)
            warm = sched.submit(mk(701), 0.0, 0.9, 2 * chunk, frozenset(), seed=7)
            list(warm.tokens())
            sched.reset_latency_stats()  # compile gaps are not host gaps
            t0 = time.perf_counter()
            reqs = [sched.submit(mk(1201 + 97 * s), 0.8, 0.9, steps, frozenset(),
                                 seed=s) for s in range(n_slots)]
            total = sum(len(list(r.tokens())) for r in reqs)
            dt = time.perf_counter() - t0
            s = sched.latency_summary()
            out[key] = {
                "agg_tok_s": round(total / dt, 1),
                "host_gap_ms_mean": round(s["decode_host_gap_ms_mean"], 3)
                if s["decode_host_gap_ms_mean"] is not None else None,
                "host_gap_ms_max": round(s["decode_host_gap_ms_max"], 3)
                if s["decode_host_gap_ms_max"] is not None else None,
            }
        except Exception as e:
            out[key] = {"error": repr(e)[:160]}
        finally:
            if sched is not None:
                sched.shutdown()
    on, off = out.get("overlap_on", {}), out.get("overlap_off", {})
    if on.get("host_gap_ms_mean") is not None and off.get("host_gap_ms_mean"):
        # floor at timer noise: a ~0 overlapped gap should read as a large
        # finite reduction, not divide-by-zero
        out["host_gap_reduction_x"] = round(
            off["host_gap_ms_mean"] / max(on["host_gap_ms_mean"], 0.001), 1)
    if on.get("agg_tok_s") and off.get("agg_tok_s"):
        out["tok_s_ratio_on_off"] = round(on["agg_tok_s"] / off["agg_tok_s"], 3)
    return out


def bench_paged(cfg, params, slots, n_decode=64, page_size=128,
                hi_slots=None, hbm_budget_gb=16.0):
    """Paged-vs-dense KV layout A/B for the serving tier (ISSUE 5):

    1. same-slot-count record: aggregate decode tok/s with `kv_layout`
       'dense' vs 'paged' at full pool coverage (bit-identical token
       streams — the delta is pure block-table gather/scatter overhead);
    2. high-slot-count paged leg: a slot count whose DENSE cache would not
       fit the chip (cache bytes vs the HBM budget minus weights), run with
       a pool sized to the dense footprint of `slots` — the configuration
       the 96-slot roofline needs, producible only by paging. The record
       carries the dense-infeasibility arithmetic so the first live TPU
       window emits the 96-slot number mechanically (BENCH_PAGED=0 skips).
    """
    import numpy as np

    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine

    cache_el = 1 if os.environ.get("BENCH_CACHE") == "f8" else 2
    page_size = min(page_size, cfg.seq_len)
    while cfg.seq_len % page_size:
        page_size //= 2  # tiny presets: largest pow-2 divisor of seq_len
    out = {"slots": slots, "page_size": page_size}
    rng = np.random.default_rng(0)

    def run(layout, n_slots, kv_pages=0, prompt_rows=64, decode=n_decode):
        eng = BatchEngine(cfg, params, n_slots=n_slots,
                          cache_dtype=_cache_dtype(), max_prefill_chunk=64,
                          kernels=os.environ.get("BENCH_KERNELS", "auto"),
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"),
                          kv_layout=layout, page_size=page_size,
                          kv_pages=kv_pages)
        try:
            for s in range(n_slots):
                eng.add(s, list(rng.integers(1, cfg.vocab_size, prompt_rows)),
                        temperature=0.8, seed=s)
            eng.decode(decode)  # compile + warmup (same static n)
            pos0 = eng.pos.copy()
            t0 = time.perf_counter()
            eng.decode(decode)
            t = time.perf_counter() - t0
            # rows actually advanced (a starved/frozen slot must not be
            # billed as produced tokens), equal to slots*decode when the
            # pool covers the window
            rows = int((eng.pos - pos0).sum())
            rec = {"kv_layout": layout,
                   "agg_tok_s": round(rows / t, 1),
                   "step_ms": round(1000.0 * t / decode, 2),
                   "rows_advanced": rows, "rows_asked": n_slots * decode}
            if eng.kv_page_stats() is not None:
                rec["kv_pages"] = eng.kv_page_stats()
            return rec
        finally:
            del eng

    for layout in ("dense", "paged"):
        try:
            out[layout] = run(layout, slots)
        except Exception as e:
            out[layout] = {"kv_layout": layout, "error": repr(e)[:200]}
    d, p = out.get("dense", {}), out.get("paged", {})
    if d.get("agg_tok_s") and p.get("agg_tok_s"):
        out["paged_overhead_x"] = round(d["agg_tok_s"] / p["agg_tok_s"], 3)

    # high-slot leg: dense at hi_slots would reserve hi*seq_len rows of
    # cache up front — infeasible in HBM long before 96 slots at real
    # contexts; paged backs the same slot count with 2 pages per slot
    # (prompt + decode growth), a pool ~seq_len/(2*page) times smaller than
    # the dense reservation. The record carries both footprints so the
    # infeasibility arithmetic rides with the throughput number.
    hi = hi_slots or int(os.environ.get("BENCH_PAGED_HI", "0")) or 2 * slots
    row_bytes = (2 * cfg.n_layers * cfg.kv_dim * cache_el)
    dense_hi_gb = hi * cfg.seq_len * row_bytes / 1e9
    weights_gb = params_count(cfg) * (18 / 32) / 1e9
    pool_pages = 2 * hi  # two pages per slot: prompt page + decode growth
    leg = {"slots": hi, "kv_layout": "paged", "pool_pages": pool_pages,
           "pool_gb": round(pool_pages * page_size * row_bytes / 1e9, 2),
           "dense_cache_gb": round(dense_hi_gb, 2),
           "dense_fits_hbm": dense_hi_gb + weights_gb < hbm_budget_gb,
           "overcommit_x": round(hi * cfg.seq_len
                                 / (pool_pages * page_size), 1)}
    try:
        # short prompts + a decode window two pages per slot always cover
        decode = max(8, min(n_decode, 2 * page_size - 8 - 4))
        leg.update(run("paged", hi, kv_pages=pool_pages, prompt_rows=4,
                       decode=decode))
        leg["slots"] = hi
    except Exception as e:
        leg["error"] = repr(e)[:200]
    out["high_slot_leg"] = leg
    return out


def bench_paged_kernel(cfg=None, params=None, slots=4, n_decode=None,
                       page_sizes=None):
    """Paged-attention ROUTE A/B (ISSUE 8): the same paged engine decoding
    through the jnp block-table gather (`attn_impl='jnp'` ->
    'paged_gather') vs the fused flash-decode kernel (`attn_impl='flash'`
    -> 'paged_kernel') at 2-3 page sizes — including ones the old %64 gate
    could not route. Token streams are bit-identical (tested); the ratio is
    the traffic/dispatch win of streaming live pages + fusing the KV
    scatter instead of re-materializing the whole view through XLA.

    Off-TPU the kernel leg runs in Pallas INTERPRET mode (an emulator, not
    a perf path), so the record shrinks to a tiny synthetic model and tags
    itself ``interpret: true`` — the ratio only carries meaning from a TPU
    window. BENCH_PAGED_KERNEL=0 skips."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    on_tpu = jax.devices()[0].platform == "tpu"
    tiny = cfg is None or params is None or not on_tpu
    if n_decode is None:
        # the tiny fixture's 64-row context must bound the decode window
        # even on TPU (prompt 8 + warmup + timed passes must stay inside
        # the per-row limit, or the timed pass measures frozen no-op steps)
        n_decode = 8 if tiny else 64
    if page_sizes is None:
        env = os.environ.get("BENCH_PAGED_KERNEL_PAGES")
        if env:
            page_sizes = tuple(int(x) for x in env.split(","))
        else:
            page_sizes = (8, 16) if tiny else (16, 64, 128)
    if tiny:
        cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=96, seq_len=64)
        params = random_params(cfg, seed=0, dtype=jnp.float32, quantize=False)
        cache_dtype = jnp.float32
    else:
        cache_dtype = _cache_dtype()
    rng = np.random.default_rng(0)
    out = {"interpret": not on_tpu, "n_decode": n_decode, "slots": slots,
           "pages": {}}

    def run(attn_impl, page):
        eng = BatchEngine(cfg, params, n_slots=slots, cache_dtype=cache_dtype,
                          max_prefill_chunk=64, kv_layout="paged",
                          page_size=page, attn_impl=attn_impl)
        try:
            route = eng.attn_route
            for s in range(slots):
                eng.add(s, list(rng.integers(1, cfg.vocab_size, 8)),
                        temperature=0.0, seed=s)
            eng.decode(n_decode)  # compile + warmup
            t0 = time.perf_counter()
            eng.decode(n_decode)
            t = time.perf_counter() - t0
            return {"attn_route": route,
                    "agg_tok_s": round(slots * n_decode / t, 1),
                    "step_ms": round(1000.0 * t / n_decode, 2)}
        finally:
            del eng
    for page in page_sizes:
        # shrink to the largest 8-row-aligned divisor of the context so tiny
        # presets keep every requested leg
        p = min(page, cfg.seq_len) // 8 * 8  # align down to the sublane
        while p >= 8 and cfg.seq_len % p:
            p -= 8
        if p < 8 or str(p) in out["pages"]:
            continue
        rec = {}
        for impl, attn in (("gather", "jnp"), ("kernel", "flash")):
            try:
                rec[impl] = run(attn, p)
            except Exception as e:
                rec[impl] = {"error": repr(e)[:200]}
        g, k = rec.get("gather", {}), rec.get("kernel", {})
        if g.get("agg_tok_s") and k.get("agg_tok_s"):
            rec["tok_s_ratio_kernel_gather"] = round(
                k["agg_tok_s"] / g["agg_tok_s"], 3)
        out["pages"][str(p)] = rec
    return out


def bench_radix(cfg, params, n_slots=4, chunk=4, steps=24, pf_chunk=64,
                page_size=64, sys_pages=4, followers=4, turns=3):
    """Radix prefix-cache chat-replay record (ISSUE 9): the two dominant
    reuse shapes, measured cold vs warm through a real Scheduler with the
    cache ON (the paged default):

    * **shared-system-prompt leg**: one cold request pays the full prefill
      of a `sys_pages`-page system prompt; `followers` requests sharing it
      map the pages from the tree and prefill only their few-token suffix —
      warm TTFT collapses toward the suffix cost
      (`warm_cold_ttft_ratio`, the perfdiff-gated field);
    * **multi-turn leg**: a conversation re-sending its whole history each
      turn — per-turn prefilled-vs-saved token counts show prefill cost
      proportional to NEW tokens only.

    BENCH_RADIX=0 skips. CPU-feasible; the ratio is meaningful on any
    host since both legs share one engine/compile."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.serve.scheduler import Scheduler

    page_size = min(page_size, cfg.seq_len)
    while cfg.seq_len % page_size:
        page_size //= 2
    sys_len = min(sys_pages * page_size, max(8, cfg.seq_len // 2))
    rng = np.random.default_rng(0)
    system = [int(x) for x in rng.integers(1, cfg.vocab_size - 1, sys_len)]
    sched = None
    try:
        eng = BatchEngine(cfg, params, n_slots=n_slots, cache_dtype=_cache_dtype(),
                          max_prefill_chunk=pf_chunk, kv_layout="paged",
                          page_size=page_size, radix_cache="on",
                          kernels=os.environ.get("BENCH_KERNELS", "auto"),
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"))
        sched = Scheduler(eng, chunk=chunk)
        warm = sched.submit([3, 1, 4], 0.0, 0.9, 2 * chunk, frozenset(), seed=5)
        list(warm.tokens())  # compile warm-up (prefill + decode paths)
        eng.radix_evict(1 << 30)  # start the legs from an empty tree
        sched.reset_latency_stats()

        def run_one(prompt, seed):
            r = sched.submit(list(prompt), 0.0, 0.9, steps, frozenset(),
                             seed=seed)
            toks = list(r.tokens())
            return r.ttft_ms, len(toks)

        base = eng.radix_stats()["hit_tokens"]
        cold_ttft, _ = run_one(system + [7, 8], seed=0)
        warm_ttfts = []
        for i in range(followers):
            t, _ = run_one(system + [20 + i, 21 + i], seed=i + 1)
            warm_ttfts.append(t)
        st = eng.radix_stats()
        shared_leg = {
            "system_tokens": sys_len,
            "followers": followers,
            "cold_ttft_ms": round(cold_ttft, 3),
            "warm_ttft_ms_mean": round(sum(warm_ttfts) / len(warm_ttfts), 3),
            "saved_prefill_tokens": st["hit_tokens"] - base,
        }

        # multi-turn leg: the agent-loop shape — each turn re-sends history
        history = list(system[: 2 * page_size])
        turn_rows = []
        for t in range(turns):
            base = eng.radix_stats()["hit_tokens"]
            history = history + [int(x) for x in
                                 rng.integers(1, cfg.vocab_size - 1, 5)]
            ttft, n = run_one(history, seed=100 + t)
            saved = eng.radix_stats()["hit_tokens"] - base
            turn_rows.append({"turn": t, "prompt_tokens": len(history),
                              "saved_tokens": saved,
                              "prefilled_tokens": len(history) - saved,
                              "ttft_ms": round(ttft, 3)})
            history += [7] * n  # fold the reply in, like a chat client
        out = {
            "page_size": page_size, "slots": n_slots, "chunk": chunk,
            "shared_system": shared_leg,
            "multi_turn": turn_rows,
            "radix": eng.radix_stats(),
        }
        if cold_ttft and warm_ttfts:
            out["warm_cold_ttft_ratio"] = round(
                shared_leg["warm_ttft_ms_mean"] / cold_ttft, 4)
        return out
    finally:
        if sched is not None:
            sched.shutdown()


def bench_router(n_slots=2, steps=10, followers=5, clients=4,
                 scale_rounds=6):
    """Multi-replica router record (ISSUE 15): two REAL engine replicas —
    the full serve HTTP surface on the aio front-end — behind
    serve/router.py, measuring the two claims the subsystem makes:

    * **affinity leg**: `followers` completions sharing one long system
      prompt, routed with prefix-affinity ON vs OFF (OFF = least-loaded
      with LRU tie-break, which alternates replicas for sequential
      traffic — round-robin in effect). ON pins the shared prefix to ONE
      radix-warm replica, so the mean follower TTFT collapses
      (`affinity.warm_ttft_ratio_on_off`, perfdiff-gated < 1);
    * **scale leg**: the same concurrent distinct-prefix closed-loop
      burst through the router over ONE replica vs over BOTH
      (`scale.agg_tok_s_ratio_2_1`, perfdiff-gated > 1; both in-process
      replicas share this host's cores, so the CPU ratio sits well under
      the ~2x a two-chip deployment shows).

    Builds its OWN tiny fixture model rather than using the preset: the
    signal here is routing policy, not model compute, and two
    preset-sized replicas in one process would double HBM.
    BENCH_ROUTER=0 skips. CPU-feasible (~1 min)."""
    import http.client as _hc
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.formats import save_model, tensor_plan
    from dllama_tpu.serve.api import make_server
    from dllama_tpu.serve.router import make_router
    from dllama_tpu.tokenizer.tokenizer import Tokenizer

    # ---- tiny fixture (tests/test_serve.make_tiny_files's shape, inline
    # so the bench stays importable without the test tree)
    tmp = tempfile.mkdtemp(prefix="dllama_bench_router_")
    vocab = [bytes([i]) for i in range(256)]
    scores = [0.0] * 256
    for piece, score in {b"he": 1.0, b"ll": 2.0, b"hello": 4.0}.items():
        vocab.append(piece)
        scores.append(score)
    bos_id = len(vocab)
    vocab += [b"<s>", b"</s>"]
    scores += [0.0, 0.0]
    tok = Tokenizer(vocab, scores, bos_id, [bos_id + 1],
                    chat_template="...<|start_header_id|>...")
    tpath = os.path.join(tmp, "tok.t")
    tok.save(tpath)
    tiny = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=len(vocab), seq_len=512)
    rng = np.random.default_rng(0)
    tensors = {}
    for name, shape, _ft in tensor_plan(tiny):
        if name.endswith(("rms_att", "rms_ffn")) or name == "final_norm":
            tensors[name] = np.ones(shape, np.float32)
        else:
            tensors[name] = (rng.standard_normal(shape) * 0.05).astype(
                np.float32)
    mpath = os.path.join(tmp, "model.m")
    save_model(mpath, tiny, tensors)

    def post(port, body, timeout=120):
        conn = _hc.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request("POST", "/v1/chat/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        hdrs = dict(resp.getheaders())
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"completion -> {resp.status}: {data}")
        return data, hdrs

    def complete(port, system, user, max_tokens=steps):
        body, hdrs = post(port, {
            "messages": [{"role": "system", "content": system},
                         {"role": "user", "content": user}],
            "max_tokens": max_tokens, "temperature": 0.0})
        return body, hdrs.get("X-Replica-Id", "")

    servers, routers = [], []
    try:
        for _ in range(2):
            loaded = load_model(mpath, tpath, mesh=None)
            httpd, api = make_server(loaded, host="127.0.0.1", port=0,
                                     n_slots=n_slots, kv_layout="paged",
                                     page_size=8)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            servers.append((httpd, api))
        addrs = [f"127.0.0.1:{h.server_address[1]}" for h, _ in servers]
        # compile warm-up straight at each replica (prefill + decode paths)
        for h, _ in servers:
            complete(h.server_address[1], "warm-up preamble", "hi",
                     max_tokens=4)

        def boot_router(replicas, affinity):
            server, router = make_router(replicas, poll_s=1.0,
                                         affinity=affinity)
            router.start()
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            routers.append((server, router))
            # a health poll can time out while the host's cores are pegged
            # by a neighbor's XLA compute; measuring a leg with a replica
            # transiently marked down would bias the routing under test
            deadline = time.monotonic() + 30
            while not all(r.ready and r.handshaken and r.config_ok
                          for r in router.replicas):
                if time.monotonic() > deadline:
                    raise RuntimeError("router never saw every replica "
                                       "ready")
                time.sleep(0.2)
                for rep in router.replicas:
                    router._poll_one(rep)
            return server.server_address[1]

        # a long shared system prompt: cold prefill dominates TTFT, which
        # is exactly the cost affinity routing avoids on the warm path
        # (byte-level fixture tokenizer: ~1 token/char — stay well under
        # the 512-token context while still dwarfing the few-token suffix)
        preamble = ("You are a careful, thorough assistant who always "
                    "answers in complete sentences, cites sources, and "
                    "keeps a steady, measured tone across every turn. " * 2)

        def affinity_leg(port, tag):
            cold, _ = complete(port, preamble + tag, "first question")
            ttfts, rids = [], set()
            for i in range(followers):
                body, rid = complete(port, preamble + tag, f"question {i}")
                ttfts.append(body["timings"]["ttft_ms"])
                rids.add(rid)
            return {
                "cold_ttft_ms": round(cold["timings"]["ttft_ms"], 3),
                "warm_ttft_ms_mean": round(sum(ttfts) / len(ttfts), 3),
                "replicas_used": len(rids),
            }

        port_on = boot_router(addrs, affinity=True)
        on = affinity_leg(port_on, "affinity-on leg.")
        port_off = boot_router(addrs, affinity=False)
        off = affinity_leg(port_off, "affinity-off leg.")
        affinity = {
            "on": on, "off": off,
            "warm_ttft_ratio_on_off": round(
                on["warm_ttft_ms_mean"] / max(off["warm_ttft_ms_mean"],
                                              1e-9), 4),
        }

        # ---- scale leg: closed-loop concurrent burst, distinct prefixes
        def burst(port, tag):
            tokens = [0] * clients
            errors: list[BaseException] = []

            def run(ci):
                try:
                    for r in range(scale_rounds):
                        body, _ = complete(
                            port, f"distinct {tag} prefix c{ci}",
                            f"round {r}")
                        tokens[ci] += body["usage"]["completion_tokens"]
                except BaseException as e:  # surfaced below, never swallowed
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(ci,))
                       for ci in range(clients)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            if errors:
                # a partially-failed burst must not publish a deflated
                # agg_tok_s into a perfdiff-gated record
                raise RuntimeError(
                    f"router scale leg ({tag}): {len(errors)} client "
                    f"thread(s) failed: {errors[0]!r}")
            return {"agg_tok_s": round(sum(tokens) / max(wall, 1e-9), 3),
                    "completions": clients * scale_rounds,
                    "wall_s": round(wall, 3)}

        port_one = boot_router(addrs[:1], affinity=False)
        one = burst(port_one, "solo")
        port_two = boot_router(addrs, affinity=False)
        two = burst(port_two, "duo")
        scale = {
            "replica_1": one, "replica_2": two,
            "agg_tok_s_ratio_2_1": round(
                two["agg_tok_s"] / max(one["agg_tok_s"], 1e-9), 4),
        }
        return {"slots": n_slots, "followers": followers,
                "clients": clients, "affinity": affinity, "scale": scale}
    finally:
        for server, router in routers:
            router.stop()
            server.shutdown()
            server.server_close()
        for httpd, api in servers:
            try:
                if api.scheduler is not None:
                    api.scheduler.shutdown()
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass


def bench_fleet_obs(n_slots=2, steps=8, clients=3, rounds=4, scrapes=5):
    """Mesh observability overhead record (ISSUE 17): the same two REAL
    in-process replicas behind serve/router.py as bench_router, A/B'ing
    the observability plane itself:

    * **overhead leg**: identical concurrent closed-loop bursts through
      a router with fleet_obs ON (trace minting + hop headers + router
      span recording + client SLO windows + postmortem journal on every
      proxied request) vs OFF (NULL tracer, no hop header, no journal),
      run ALTERNATING with best-of-3 per arm, reporting
      `tok_s_ratio_on_off` and `proxy_overhead_x` (off/on) — perfdiff
      pins the latter at <= 1.03x (ISSUE 19 acceptance);
    * **scrape leg**: timed GET /router/metrics federation scrapes
      (mean/max ms, parse sanity: relabeled replica series and
      dllama_fleet_ rollups present) plus one timed GET /router/trace
      merge, reporting `trace.unaligned_replicas` — perfdiff-gated == 0:
      every scraped replica must land clock-aligned in the merged file.

    Builds its OWN tiny fixture model (routing + observability cost, not
    model compute). BENCH_FLEET_OBS=0 skips. CPU-feasible (~1 min)."""
    import http.client as _hc
    import tempfile
    import threading

    import numpy as np

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.formats import save_model, tensor_plan
    from dllama_tpu.serve.api import make_server
    from dllama_tpu.serve.router import make_router
    from dllama_tpu.tokenizer.tokenizer import Tokenizer

    tmp = tempfile.mkdtemp(prefix="dllama_bench_fleetobs_")
    vocab = [bytes([i]) for i in range(256)]
    scores = [0.0] * 256
    bos_id = len(vocab)
    vocab += [b"<s>", b"</s>"]
    scores += [0.0, 0.0]
    tok = Tokenizer(vocab, scores, bos_id, [bos_id + 1],
                    chat_template="...<|start_header_id|>...")
    tpath = os.path.join(tmp, "tok.t")
    tok.save(tpath)
    tiny = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=len(vocab), seq_len=512)
    rng = np.random.default_rng(0)
    tensors = {}
    for name, shape, _ft in tensor_plan(tiny):
        if name.endswith(("rms_att", "rms_ffn")) or name == "final_norm":
            tensors[name] = np.ones(shape, np.float32)
        else:
            tensors[name] = (rng.standard_normal(shape) * 0.05).astype(
                np.float32)
    mpath = os.path.join(tmp, "model.m")
    save_model(mpath, tiny, tensors)

    def post(port, body, timeout=120):
        conn = _hc.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request("POST", "/v1/chat/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"completion -> {resp.status}: {data}")
        return data

    def get(port, path, timeout=30):
        conn = _hc.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read().decode("utf-8", "replace")
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path} -> {resp.status}")
        return data

    def complete(port, system, user, max_tokens=steps):
        return post(port, {
            "messages": [{"role": "system", "content": system},
                         {"role": "user", "content": user}],
            "max_tokens": max_tokens, "temperature": 0.0})

    servers, routers = [], []
    try:
        for _ in range(2):
            loaded = load_model(mpath, tpath, mesh=None)
            httpd, api = make_server(loaded, host="127.0.0.1", port=0,
                                     n_slots=n_slots, kv_layout="paged",
                                     page_size=8)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            servers.append((httpd, api))
        addrs = [f"127.0.0.1:{h.server_address[1]}" for h, _ in servers]

        def boot_router(fleet_obs):
            server, router = make_router(addrs, poll_s=1.0,
                                         fleet_obs=fleet_obs)
            router.start()
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            routers.append((server, router))
            deadline = time.monotonic() + 30
            while not all(r.ready and r.handshaken and r.config_ok
                          for r in router.replicas):
                if time.monotonic() > deadline:
                    raise RuntimeError("router never saw every replica "
                                       "ready")
                time.sleep(0.2)
                for rep in router.replicas:
                    router._poll_one(rep)
            return server.server_address[1]

        def burst(port, tag):
            tokens = [0] * clients
            errors: list[BaseException] = []

            def run(ci):
                try:
                    for r in range(rounds):
                        body = complete(port, f"distinct {tag} prefix c{ci}",
                                        f"round {r}")
                        tokens[ci] += body["usage"]["completion_tokens"]
                except BaseException as e:  # surfaced below, never swallowed
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(ci,))
                       for ci in range(clients)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            if errors:
                raise RuntimeError(
                    f"fleet_obs {tag} leg: {len(errors)} client thread(s) "
                    f"failed: {errors[0]!r}")
            return {"agg_tok_s": round(sum(tokens) / max(wall, 1e-9), 3),
                    "completions": clients * rounds,
                    "wall_s": round(wall, 3)}

        port_on = boot_router(fleet_obs=True)
        port_off = boot_router(fleet_obs=False)
        # TWO untimed warm bursts of the EXACT timed shapes (all leg tags
        # are byte-length-equal): the legs run in a fixed order, and a
        # shape compiled on the first leg's clock would masquerade as
        # observability cost. Two passes, not one — the first burst's
        # cold prefills and the second's radix-partial-hit prefills
        # compile DIFFERENT chunk buckets; only the third distinct-tag
        # burst onward is compile-free. The OFF router gets one warm pass
        # of its own (router-side connection/affinity warmth; the replica
        # compile caches are shared, the ON warms already paid those)
        burst(port_on, "obs-wm1")
        burst(port_on, "obs-wm2")
        burst(port_off, "obs-wm3")
        # ALTERNATING measured bursts, best-of per arm: the perfdiff
        # ceiling on proxy_overhead_x is tight (1.03x), and a single
        # burst per arm is hostage to scheduler noise on a shared CPU —
        # interleaving means a load spike hits both arms, and best-of
        # compares each arm's least-disturbed run
        on_runs, off_runs = [], []
        for i in range(3):
            on_runs.append(burst(port_on, f"obs-on{i}"))
            off_runs.append(burst(port_off, f"obs-of{i}"))
        on = max(on_runs, key=lambda b: b["agg_tok_s"])
        off = max(off_runs, key=lambda b: b["agg_tok_s"])

        # scrape leg, against the ON router while its journal is warm
        lat_ms = []
        for _ in range(scrapes):
            t0 = time.monotonic()
            text = get(port_on, "/router/metrics")
            lat_ms.append((time.monotonic() - t0) * 1e3)
        assert 'replica="' in text and "dllama_fleet_" in text, (
            "federated exposition missing relabeled/fleet series")
        t0 = time.monotonic()
        merged = json.loads(get(port_on, "/router/trace"))
        trace_ms = (time.monotonic() - t0) * 1e3
        other = merged["otherData"]
        unaligned = (2 - other["replicas_merged"]) + sum(
            1 for c in other["clock"].values() if not c["aligned"])

        return {
            "slots": n_slots, "clients": clients, "rounds": rounds,
            "on": on, "off": off,
            "tok_s_ratio_on_off": round(
                on["agg_tok_s"] / max(off["agg_tok_s"], 1e-9), 4),
            # the ISSUE 19 acceptance pin: federation + tracing may cost
            # the proxy hot path at most 3% (ceiling 1.03 in perfdiff)
            "proxy_overhead_x": round(
                off["agg_tok_s"] / max(on["agg_tok_s"], 1e-9), 4),
            "scrape": {
                "federated_ms_mean": round(sum(lat_ms) / len(lat_ms), 3),
                "federated_ms_max": round(max(lat_ms), 3),
                "scrapes": scrapes,
            },
            "trace": {
                "merge_ms": round(trace_ms, 3),
                "replicas_merged": other["replicas_merged"],
                "unaligned_replicas": unaligned,
                "events": len(merged["traceEvents"]),
            },
        }
    finally:
        for server, router in routers:
            router.stop()
            server.shutdown()
            server.server_close()
        for httpd, api in servers:
            try:
                if api.scheduler is not None:
                    api.scheduler.shutdown()
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass


def bench_slo(cfg, params, n_slots=8, chunk=4, steps=48, pf_chunk=64,
              slo_ttft_ms=5000.0, slo_itl_ms=500.0):
    """SLO & saturation record (ISSUE 7): serve a short mixed burst through
    a Scheduler with SLO targets armed and report the /debug/perf join —
    sliding-window TTFT/ITL percentiles, SLO attainment, the scheduler time
    ledger's per-state fractions plus its partition-invariant residual
    (|sum(states) - wall| / wall, ~0 by construction), and roofline/goodput
    attribution of the decode path. experiments/perfdiff.py gates
    BENCH_rN-vs-r(N-1) on these fields, so regressions in tail latency or
    bandwidth attainment fail mechanically instead of by eyeball. The
    default targets are deliberately loose (CPU-feasible): the record's job
    is a populated, comparable snapshot, not a pass/fail on this host."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.obs import instruments as ins
    from dllama_tpu.serve.scheduler import Scheduler

    mk = lambda base: [int(x) for x in
                       ((np.arange(3) * 13 + base) % (cfg.vocab_size - 2) + 1)]
    sched = None
    try:
        eng = BatchEngine(cfg, params, n_slots=n_slots,
                          cache_dtype=_cache_dtype(),
                          max_prefill_chunk=pf_chunk,
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"))
        sched = Scheduler(eng, chunk=chunk,
                          slo_ttft_ms=slo_ttft_ms, slo_itl_ms=slo_itl_ms)
        warm = sched.submit(mk(311), 0.0, 0.9, 2 * chunk, frozenset(), seed=3)
        list(warm.tokens())
        sched.reset_latency_stats()  # compile latencies out of the window
        # burn counters are process-global and monotonic: baseline them here
        # so the record reports THIS leg's violations, not the warmup's
        # compile-time burns
        base_v = {k: ins.SLO_VIOLATIONS.labels(kind=k).value()
                  for k in ("ttft", "itl")}
        t0 = time.perf_counter()
        reqs = [sched.submit(mk(811 + 89 * s), 0.8 if s % 2 else 0.0, 0.9,
                             steps, frozenset(), seed=s)
                for s in range(n_slots)]
        total = sum(len(list(r.tokens())) for r in reqs)
        dt = time.perf_counter() - t0
        win = sched.perf.window_snapshot()
        slo = sched.perf.slo_snapshot()
        roof = sched.perf.roofline_snapshot()
        led = sched.ledger.snapshot()
        resid = (abs(led["covered_s"] - led["wall_s"]) / led["wall_s"]
                 if led["wall_s"] > 0 else 0.0)
        return {
            "slots": n_slots, "chunk": chunk, "steps": steps,
            "tokens": total, "agg_tok_s": round(total / dt, 1),
            "targets_ms": {"ttft": slo_ttft_ms, "itl": slo_itl_ms},
            "ttft_ms_p50": win["ttft"]["p50"],
            "ttft_ms_p95": win["ttft"]["p95"],
            "itl_ms_p50": win["itl"]["p50"],
            "itl_ms_p95": win["itl"]["p95"],
            "attainment": slo["attainment"],
            "violations": {k: slo["violations_total"][k] - base_v[k]
                           for k in base_v},
            "ledger_fractions": led["fractions"],
            "ledger_residual_frac": round(resid, 6),
            "bandwidth_attainment": roof["bandwidth_attainment"],
            "achieved_gbs": roof["achieved_gbs"],
            "throughput_tok_s": roof["throughput_tok_s"],
            "goodput_tok_s": roof["goodput_tok_s"],
        }
    finally:
        if sched is not None:
            sched.shutdown()


def bench_trace(cfg, params, n_slots=8, chunk=4, steps=48, pf_chunk=64,
                rounds=4):
    """Tracing-overhead A/B for the serving tier: aggregate decode tok/s
    with the request-flow span tracer at the CLI default ring size vs fully
    disabled (`--trace-buffer 0`'s no-op fast path).

    ONE engine/scheduler serves both modes with the tracer toggled live
    (call sites read the global per use), alternating on/off each round —
    separate engines drift (fresh compiles, growing jit caches, thermal),
    and a two-leg layout attributes all of that drift to whichever mode
    runs second. The acceptance bar is <= ~2% regression with tracing on
    (direct microbench: the full per-chunk span work is ~20 us)."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.obs import trace as reqtrace
    from dllama_tpu.serve.scheduler import Scheduler

    mk = lambda base: [int(x) for x in
                       ((np.arange(3) * 11 + base) % (cfg.vocab_size - 2) + 1)]
    out = {"slots": n_slots, "chunk": chunk, "steps": steps, "rounds": rounds}
    prev = reqtrace.TRACER
    sched = None
    try:
        reqtrace.configure(0)
        eng = BatchEngine(cfg, params, n_slots=n_slots,
                          cache_dtype=_cache_dtype(),
                          max_prefill_chunk=pf_chunk,
                          attn_impl=os.environ.get("BENCH_ATTN", "auto"))
        sched = Scheduler(eng, chunk=chunk)
        warm = sched.submit(mk(701), 0.0, 0.9, 2 * chunk, frozenset(), seed=7)
        list(warm.tokens())
        sched.reset_latency_stats()
        agg = {"trace_on": [0.0, 0], "trace_off": [0.0, 0]}  # [seconds, tokens]
        spans = 0
        for r in range(rounds):
            for key, cap in (("trace_on", 2048), ("trace_off", 0)):
                reqtrace.configure(cap)
                t0 = time.perf_counter()
                reqs = [sched.submit(mk(1201 + 97 * s + 13 * r), 0.8, 0.9,
                                     steps, frozenset(), seed=1000 * r + s,
                                     req_id=f"req_bench_{key}_{r}_{s}")
                        for s in range(n_slots)]
                total = sum(len(list(q.tokens())) for q in reqs)
                agg[key][0] += time.perf_counter() - t0
                agg[key][1] += total
                if cap:
                    spans += reqtrace.TRACER.stats()["events"]
        for key, (dt, total) in agg.items():
            out[key] = {"agg_tok_s": round(total / dt, 1) if dt else None}
        out["trace_on"]["spans"] = spans
    except Exception as e:
        out["error"] = repr(e)[:200]
    finally:
        if sched is not None:
            sched.shutdown()
        reqtrace.TRACER = prev
    on, off = out.get("trace_on", {}), out.get("trace_off", {})
    if on.get("agg_tok_s") and off.get("agg_tok_s"):
        # >= 0.98 meets the acceptance bar (<= ~2% cost with tracing on)
        out["tok_s_ratio_on_off"] = round(on["agg_tok_s"] / off["agg_tok_s"], 3)
    return out


def worker():
    # persistent compile cache: repeated bench runs (and the tpu_session
    # stages) reuse executables instead of paying tunnel compiles again
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "experiments", "jax_cache"),
    )
    import jax
    import jax.numpy as jnp

    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params_fast

    deadline = time.monotonic() + float(os.environ.get("BENCH_WORKER_BUDGET_S", "1e9"))
    preset = os.environ.get("BENCH_PRESET", "all")
    unroll_env = os.environ.get("BENCH_UNROLL", "1")
    unroll = True if unroll_env == "full" else int(unroll_env)
    n_decode = int(os.environ.get("BENCH_DECODE_TOKENS", "128"))
    # 48 slots ≈ 6.4 GB KV at 1 Ki seq + 4.5 GB weights on the 8b preset —
    # fits 16 GB HBM; an OOM is caught by the fallback ladder (error recorded,
    # sweep continues), so reaching for the higher-throughput point is safe
    slot_list = [int(s) for s in os.environ.get("BENCH_SLOTS", "8,32,48").split(",")]
    # 8b FIRST: its serving sweep is the pinned vs_baseline source and must
    # not be starved by 1b extras in a tight window (the session's quick
    # stage covers 1b early); 8b_long second shares the just-transferred 8b
    # params; 1b last pays its own (cheap) param gen
    run_presets = ["8b", "8b_long", "1b"] if preset == "all" else [preset]
    # the batched serving sweep runs on the north-star config; never on a
    # long-seq preset (n_slots * 8Ki KV exceeds one chip's HBM)
    sweep_on = "8b" if "8b" in run_presets else (
        run_presets[-1]
        if run_presets[-1] != "tiny" and PRESETS[run_presets[-1]]["seq_len"] < 4096
        else None
    )
    if os.environ.get("BENCH_SWEEP_TINY") == "1" and "tiny" in run_presets:
        sweep_on = "tiny"  # CI-only: exercise the sweep path at toy size

    for name in run_presets:
        if name not in PRESETS:
            raise SystemExit(
                f"BENCH_PRESET must be 'all' or one of {sorted(PRESETS)}, got {name!r}"
            )

    q40_style = os.environ.get("BENCH_Q40_STYLE", "auto")
    if q40_style not in ("auto", "deq", "blockdot", "maskdot", "loopdot"):
        raise SystemExit(
            f"BENCH_Q40_STYLE must be auto|deq|blockdot|maskdot|loopdot, got {q40_style!r}"
        )
    if q40_style != "auto":
        from dllama_tpu.ops.pallas import q40_matmul as _qmod

        _qmod.STYLE = q40_style

    from dllama_tpu.ops import matmul as _mmod

    xla_prefill_m = os.environ.get("BENCH_XLA_PREFILL_M")
    if xla_prefill_m:
        _mmod.XLA_PREFILL_MIN_M = int(xla_prefill_m)
    prefill_tuned = False

    dev = jax.devices()[0]
    results = {}
    batch_results = []
    admit_params = None  # the sweep preset's live params (bench_admission
    # needs params that match its cfg after later presets regenerate)
    best = (0.0, "", 0.0)  # (tok_s/north_star, label, tok_s)
    # vs_baseline is PINNED (VERDICT r4 weak #8: its semantics drifted across
    # rounds): it is 8B serving aggregate tok/s/chip / 1000 — BASELINE.json's
    # north star — and is emitted ONLY when this run measured that exact
    # config. Every other preset rides along as named fields; a tiny-preset
    # CPU fallback reports 0.0 + vs_baseline_config=null instead of a
    # tiny-normalized number that isn't comparable round-over-round.
    pinned = (0.0, None)  # (agg_tok_s / 1000, config label) for the 8b sweep
    setup_s = 0.0
    params, last_pkey = None, None

    def dump_partial():
        """Snapshot the record-so-far for the parent. A tunnel wedge blocks
        this process forever inside one RPC (2026-07-31 window) — the parent
        then recovers the last snapshot instead of losing the whole run."""
        path = os.environ.get("BENCH_PARTIAL_PATH")
        if not path:
            return
        try:
            rec = {
                "metric": f"tokens/sec/chip, {best[1]} (PARTIAL: worker died "
                          f"mid-run), Q40 synthetic, 1 chip ({dev.platform})",
                "value": best[2], "unit": "tok/s",
                "vs_baseline": round(pinned[0], 4),
                "vs_baseline_def": "8B serving aggregate tok/s/chip / 1000 (BASELINE.json)",
                "vs_baseline_config": pinned[1],
                "presets": dict(results), "batch": list(batch_results),
                "device": str(dev), "partial": True,
            }
            with open(path + ".tmp", "w") as f:
                json.dump(rec, f)
            os.replace(path + ".tmp", path)
        except OSError:
            pass  # snapshotting must never break a live run

    for name in run_presets:
        if time.monotonic() > deadline - 180 and results:
            # out of budget: keep the measurements we already have rather than
            # letting the parent's timeout discard everything
            results[name] = {"skipped": "budget"}
            continue
        cfg = LlamaConfig(**PRESETS[name])
        t0 = time.perf_counter()
        # params depend on dims but not seq_len: 8b and 8b_long share one
        # generation + host->device transfer (the tunnel makes 4.5 GB pricey)
        pkey = (cfg.dim, cfg.hidden_dim, cfg.n_layers, cfg.n_kv_heads, cfg.vocab_size)
        if pkey != last_pkey:
            params = random_params_fast(cfg, seed=0, dtype=jnp.bfloat16)
            last_pkey = pkey
        setup_s += time.perf_counter() - t0
        north = 1000.0 * (8.03e9 / params_count(cfg))
        # graceful degradation: the fused auto path first, then the simpler
        # deq-style Pallas kernel, then Pallas with f32-widened scales (in
        # case Mosaic rejects the u16 scale tiles), then the XLA backend — a
        # kernel regression downgrades the number instead of erasing it
        from dllama_tpu.ops.pallas import q40_matmul as _qm

        # each attempt: (q40 style, kernels, widen scales, attn impl) — the
        # last rung turns flash attention off too (a flash compile failure
        # would otherwise sink every rung: kernels='xla' keeps flash on TPU)
        attempts = [(q40_style, None, False, "auto")] + [
            a for a in (("maskdot", None, False, "auto"),
                        ("deq", None, False, "auto"),
                        ("auto", None, True, "auto"),
                        ("auto", "xla", False, "auto"),
                        ("auto", "xla", False, "jnp"))
            if a != (q40_style, None, False, "auto")
        ]
        # BENCH_ATTN=jnp (set by tpu_session.sh when the flash canary hung —
        # a flash compile wedged the 2026-07-31 window server-side,
        # TPU_VALIDATE_r04.md): never compile flash on any rung. The ladder's
        # own jnp rung only helps when flash FAILS; a wedge hangs forever.
        # 'auto' (what tpu_session.sh exports on canary success, so this
        # parent skips a duplicate canary) must keep the ladder intact —
        # only a real override ('jnp') flattens it
        attn_env = os.environ.get("BENCH_ATTN")
        if attn_env and attn_env != "auto":
            attempts = list(dict.fromkeys(
                (style, kern, widen, attn_env)
                for style, kern, widen, _ in attempts))
        wide_params = None
        # batched sweep FIRST on the north-star preset (its agg_tok_s is what
        # vs_baseline is judged on — in a tight window it must not be starved
        # by the batch=1 extras); skip slots we no longer have budget for
        if name == sweep_on:
            admit_params = params
            ok = []  # (slots, kern, widen) of successful bf16 rows
            for slots in slot_list:
                if time.monotonic() > deadline - 120:
                    batch_results.append({"slots": slots, "skipped": "budget"})
                    continue
                br = None
                last_err = None
                # same degradation as batch=1: fused auto -> widened-scales
                # Pallas (Mosaic-u16 escape hatch) -> XLA backend
                for kern, widen in ((None, False), (None, True), ("xla", False)):
                    try:
                        if widen and wide_params is None:
                            wide_params = _widen_scales(params)
                        br = bench_batched(cfg, wide_params if widen else params,
                                           slots, kernels=kern)
                        br["path"] = f"kernels={kern or 'auto'}" + (
                            " scales=f32" if widen else "")
                        ok.append((slots, kern, widen))
                        break
                    except Exception as e:
                        print(f"batched slots={slots} ({kern},{widen}) failed: {e!r}"[:500],
                              file=sys.stderr)
                        last_err = e
                if br is None:  # one record per slots value, only if ALL failed
                    batch_results.append({"slots": slots, "error": repr(last_err)[:200]})
                    continue
                br["preset"] = name
                batch_results.append(br)
                if br["agg_tok_s"] / north > best[0]:
                    best = (br["agg_tok_s"] / north, f"{LABELS[name]} {slots}-slot serving", br["agg_tok_s"])
                if name == "8b" and br["agg_tok_s"] / 1000.0 > pinned[0]:
                    pinned = (br["agg_tok_s"] / 1000.0,
                              f"8b {slots}-slot serving ({br['path']})")
                dump_partial()
            # f8-cache variant at the largest slot count that produced a bf16
            # row (half the cache bytes — the sweep's bottleneck), with that
            # row's proven kernel path: one extra row, budget permitting, so
            # the driver's single run captures the f8 win AND its baseline
            if (ok and os.environ.get("BENCH_CACHE", "bf16") == "bf16"
                    and time.monotonic() < deadline - 150):
                try:
                    import jax.numpy as _jnp

                    slots_f8, kern, widen = max(ok)
                    br = bench_batched(cfg, wide_params if widen else params,
                                       slots_f8, kernels=kern,
                                       cache_dtype=_jnp.float8_e4m3fn)
                    br["preset"] = name
                    br["path"] = f"cache=f8 kernels={kern or 'auto'}" + (
                        " scales=f32" if widen else "")
                    batch_results.append(br)
                    if br["agg_tok_s"] / north > best[0]:
                        best = (br["agg_tok_s"] / north,
                                f"{LABELS[name]} {slots_f8}-slot serving (f8 KV)",
                                br["agg_tok_s"])
                    # deliberately NOT fed into pinned/vs_baseline: the pinned
                    # number compares bf16-cache serving round-over-round; the
                    # f8 row is a named capacity data point alongside
                    dump_partial()
                except Exception as e:
                    batch_results.append({"slots": "f8", "error": repr(e)[:200]})
            # batched-speculation row at the largest proven slot count:
            # greedy periodic workload, tokens_per_cycle is the multiplier
            # over one-token-per-forward serving (acceptance ceiling)
            if (ok and os.environ.get("BENCH_BATCH_SPEC", "1") == "1"
                    and time.monotonic() < deadline - 150):
                try:
                    slots_sp, kern, widen = max(ok)
                    br = bench_batched_spec(cfg, wide_params if widen else params,
                                            slots_sp, kernels=kern)
                    br["preset"] = name
                    br["path"] = f"spec={br['spec_k']} kernels={kern or 'auto'}" + (
                        " scales=f32" if widen else "")
                    # recorded but deliberately NOT fed into best/vs_baseline:
                    # the periodic-prompt workload is the acceptance CEILING,
                    # and the headline must stay a real-workload number (the
                    # single-engine spec row gets the same treatment)
                    batch_results.append(br)
                    dump_partial()
                except Exception as e:
                    batch_results.append({"slots": "spec", "error": repr(e)[:200]})
        for style, kern, widen, attn in attempts:
            _qm.STYLE = style
            try:
                if widen and wide_params is None:
                    wide_params = _widen_scales(params)
                r = bench_engine(cfg, wide_params if widen else params, n_decode,
                                 unroll, prompt_len=PROMPT_LENS.get(name, 512),
                                 kernels=kern, attn_impl=attn)
                r["path"] = f"style={style} kernels={kern or 'auto'}" + (
                    " scales=f32" if widen else "") + (
                    " attn=jnp" if attn == "jnp" else "")
                results[name] = r
                if r["decode_tok_s"] / north > best[0]:
                    best = (r["decode_tok_s"] / north, f"{LABELS[name]} batch=1 decode",
                            r["decode_tok_s"])
                break
            except Exception as e:  # keep other configs' numbers (a kernel
                # compile failure on one tier must not zero the whole record)
                print(f"preset {name} ({style}/{kern}) failed: {e!r}"[:500],
                      file=sys.stderr)
                results[name] = {"error": repr(e)[:200]}
            finally:
                _qm.STYLE = q40_style
        dump_partial()
        # prefill-route A/B (1b ONLY — the cheap preset, which now runs LAST
        # so this can never starve the 8b sweep in a tight window):
        # re-measure with large-m matmuls routed through the XLA dequant-dot
        # GEMM. >20% prefill win records the route; it no longer retunes
        # same-run routing of earlier presets (8b ran first) — the committed
        # record + decide.py's kbench rule carry the decision forward instead
        # (the driver's bench runs with default env, so the data must come
        # from the worker itself rather than BENCH_XLA_PREFILL_M).
        if (xla_prefill_m is None and not prefill_tuned
                and name in ("1b", "tiny")
                and name in results and "prefill_tok_s" in results[name]
                and "kernels=auto" in results[name].get("path", "")
                and time.monotonic() < deadline - 240):
            prefill_tuned = True
            try:
                _mmod.XLA_PREFILL_MIN_M = 64
                r2 = bench_engine(cfg, params, min(n_decode, 32), unroll,
                                  prompt_len=PROMPT_LENS.get(name, 512))
                r2["path"] = "style=auto kernels=auto xla_prefill_m=64"
                results[name + "_xla_prefill"] = r2
                if r2["prefill_tok_s"] > 1.2 * results[name]["prefill_tok_s"]:
                    results["prefill_route"] = "xla (kept: fused deq slower)"
                else:
                    _mmod.XLA_PREFILL_MIN_M = None
                    results["prefill_route"] = "fused deq"
            except Exception as e:
                _mmod.XLA_PREFILL_MIN_M = None
                results[name + "_xla_prefill"] = {"error": repr(e)[:200]}
        # long-context bucketed-grid A/B (VERDICT r3 weak #4): the deep
        # preset re-measures decode with the pow-2 cache-view dispatch so the
        # unattended window captures the engine-level flip decision, not
        # just kbench's kernel-level sweep. Guards: the baseline must be the
        # CLEAN fused rung (kernels=auto, no widened scales, no jnp attn —
        # the rerun uses the same defaults, so a degraded baseline would make
        # a confounded A/B), and the device must be a TPU (kernel_select only
        # arms s_buckets on the flash path; on CPU the flag is a no-op and
        # the "A/B" would measure the same config twice).
        if (name == "8b_long"
                and "decode_ms_per_token" in results.get(name, {})
                # exactly the clean default rung — a fallback-style baseline
                # (maskdot/deq/widened) would make a style-confounded A/B
                and results[name].get("path") == f"style={q40_style} kernels=auto"
                and dev.platform == "tpu"
                and not os.environ.get("DLLAMA_FLASH_BUCKETS")
                and time.monotonic() < deadline - 240):
            try:
                os.environ["DLLAMA_FLASH_BUCKETS"] = "1"
                # same n_decode as the baseline: decode ms/token IS the
                # compared metric, so the averaging window must match
                r3 = bench_engine(cfg, params, n_decode, unroll,
                                  prompt_len=PROMPT_LENS.get(name, 512))
                r3["path"] = (results[name]["path"] + " flash_buckets=1"
                              + (" xla_prefill_m=64"
                                 if _mmod.XLA_PREFILL_MIN_M else ""))
                results[name + "_bucketed"] = r3
            except Exception as e:
                results[name + "_bucketed"] = {"error": repr(e)[:200]}
            finally:
                del os.environ["DLLAMA_FLASH_BUCKETS"]
        del wide_params  # params persists: the next preset may share its shapes
        dump_partial()

    # bytes/token is part of the benchmark contract (SURVEY.md §5.1/§6): on
    # one chip it's 0; multi-chip runs report the MEASURED per-token HLO
    # collective bytes when experiments/collectives.json covers the mesh
    # (COLLECTIVES.md, the reference's Fig. 6 analog), else the analytic
    # ICI payload model.
    from dllama_tpu.utils.profiling import collective_bytes_per_token

    if not best[1]:
        # every config failed: no JSON — the parent falls back to the honest
        # CPU record instead of publishing a success-shaped 0.0
        raise SystemExit("all bench configs failed; see stderr")

    moe = None
    if preset != "tiny" and time.monotonic() < deadline - 90:
        try:
            moe = bench_moe()
        except Exception as e:
            moe = {"error": repr(e)[:200]}

    # serving-tier admission-stall record: must use the SWEEP preset's own
    # params (later presets regenerate `params` with different shapes)
    admit = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_ADMIT") != "0"
            and time.monotonic() < deadline - 240):
        try:
            admit = bench_admission(LlamaConfig(**PRESETS[sweep_on]), admit_params)
        except Exception as e:
            admit = {"error": repr(e)[:200]}

    # overlap-pipeline A/B on the same preset: inter-chunk host gap and
    # aggregate tok/s with overlapped dispatch on vs off (BENCH_OVERLAP=0
    # skips)
    overlap_ab = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_OVERLAP") != "0"
            and time.monotonic() < deadline - 180):
        try:
            overlap_ab = bench_overlap(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                n_slots=min(8, min(s for s in slot_list) if slot_list else 8))
        except Exception as e:
            overlap_ab = {"error": repr(e)[:200]}

    # request-flow tracing overhead A/B on the same preset: tok/s with the
    # span tracer at the CLI default ring vs --trace-buffer 0 (BENCH_TRACE=0
    # skips); the acceptance bar is tok_s_ratio_on_off >= ~0.98
    trace_ab = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_TRACE") != "0"
            and time.monotonic() < deadline - 150):
        try:
            trace_ab = bench_trace(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                n_slots=min(8, min(s for s in slot_list) if slot_list else 8))
        except Exception as e:
            trace_ab = {"error": repr(e)[:200]}

    # SLO & saturation snapshot on the same preset (ISSUE 7): windowed
    # percentiles, ledger fractions, roofline attainment — the record
    # experiments/perfdiff.py gates round-over-round (BENCH_SLO=0 skips)
    slo_rec = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_SLO") != "0"
            and time.monotonic() < deadline - 120):
        try:
            slo_rec = bench_slo(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                n_slots=min(8, min(s for s in slot_list) if slot_list else 8))
        except Exception as e:
            slo_rec = {"error": repr(e)[:200]}

    # paged-vs-dense KV layout A/B + the high-slot paged leg dense cannot
    # run (ISSUE 5); BENCH_PAGED=0 skips
    paged_ab = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_PAGED") != "0"
            and time.monotonic() < deadline - 150):
        try:
            paged_ab = bench_paged(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                slots=min(8, min(s for s in slot_list) if slot_list else 8),
                hi_slots=max(slot_list) * 2 if sweep_on == "8b" else None)
        except Exception as e:
            paged_ab = {"error": repr(e)[:200]}

    # radix prefix-cache chat replay (ISSUE 9): shared-system-prompt +
    # multi-turn legs, cold-vs-warm TTFT and saved-prefill tokens with the
    # cache on; BENCH_RADIX=0 skips
    radix_rec = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_RADIX") != "0"
            and time.monotonic() < deadline - 120):
        try:
            radix_rec = bench_radix(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                n_slots=min(4, min(s for s in slot_list) if slot_list else 4))
        except Exception as e:
            radix_rec = {"error": repr(e)[:200]}

    # speculative continuous batching A/B (ISSUE 11): scheduler-level
    # spec-on vs spec-off on repetitive text + the mixed spec/non-spec leg;
    # BENCH_SPEC_BATCH=0 skips
    spec_batch_rec = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_SPEC_BATCH") != "0"
            and time.monotonic() < deadline - 120):
        try:
            spec_batch_rec = bench_spec_batch(
                LlamaConfig(**PRESETS[sweep_on]), admit_params,
                n_slots=min(4, min(s for s in slot_list) if slot_list else 4))
        except Exception as e:
            spec_batch_rec = {"error": repr(e)[:200]}

    # hybrid chunked-prefill record (ISSUE 12): client-observed stall +
    # joiner TTFT, sync phase-split vs the fused hybrid step, with the
    # bit-exactness and preempt/resume flags; BENCH_HYBRID=0 skips
    hybrid_rec = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_HYBRID") != "0"
            and time.monotonic() < deadline - 120):
        try:
            hybrid_rec = bench_hybrid(LlamaConfig(**PRESETS[sweep_on]),
                                      admit_params)
        except Exception as e:
            hybrid_rec = {"error": repr(e)[:200]}

    # compile & device-traffic record (ISSUE 13): cold vs warmed-boot
    # first-request TTFT + the steady-state zero-recompile / zero-upload
    # gate; BENCH_COMPILE=0 skips
    compile_rec = None
    if (sweep_on and admit_params is not None
            and os.environ.get("BENCH_COMPILE") != "0"
            and time.monotonic() < deadline - 90):
        try:
            compile_rec = bench_compile(LlamaConfig(**PRESETS[sweep_on]),
                                        admit_params)
        except Exception as e:
            compile_rec = {"error": repr(e)[:200]}

    # multi-replica router record (ISSUE 15): affinity warm-TTFT win vs
    # round-robin + the 2-vs-1-replica scaling ratio over two real tiny
    # replicas behind serve/router.py; BENCH_ROUTER=0 skips
    router_rec = None
    if (os.environ.get("BENCH_ROUTER") != "0"
            and time.monotonic() < deadline - 90):
        try:
            router_rec = bench_router()
        except Exception as e:
            router_rec = {"error": repr(e)[:200]}

    # mesh observability record (ISSUE 17): fleet_obs on/off proxy-path
    # A/B + federation-scrape latency + merged-trace clock alignment over
    # two real tiny replicas; BENCH_FLEET_OBS=0 skips
    fleet_obs_rec = None
    if (os.environ.get("BENCH_FLEET_OBS") != "0"
            and time.monotonic() < deadline - 90):
        try:
            fleet_obs_rec = bench_fleet_obs()
        except Exception as e:
            fleet_obs_rec = {"error": repr(e)[:200]}

    # paged-attention route A/B: jnp gather vs the fused flash-decode
    # kernel at 2-3 page sizes (ISSUE 8); BENCH_PAGED_KERNEL=0 skips
    paged_kernel_ab = None
    if (os.environ.get("BENCH_PAGED_KERNEL") != "0"
            and time.monotonic() < deadline - 90):
        try:
            paged_kernel_ab = bench_paged_kernel(
                LlamaConfig(**PRESETS[sweep_on]) if sweep_on else None,
                admit_params)
        except Exception as e:
            paged_kernel_ab = {"error": repr(e)[:200]}

    # bytes/token describes the headline (sweep) config when one ran
    cfg8 = LlamaConfig(**PRESETS[sweep_on or run_presets[-1]])
    n_dev = jax.device_count()
    kb = collective_bytes_per_token(cfg8, tp=n_dev)["kb_per_token_per_chip"]
    kb_measured = None
    if n_dev > 1:
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "experiments", "collectives.json")) as f:
                tbl = json.load(f)
            rec = tbl.get(f"{sweep_on or run_presets[-1]}/tp{n_dev}/bf16")
            if isinstance(rec, dict) and isinstance(
                rec.get("measured_kb_per_token_per_chip"), (int, float)
            ):
                kb_measured = round(rec["measured_kb_per_token_per_chip"], 1)
        except (OSError, ValueError):
            pass  # malformed table must never cost a finished bench run
    result = {
        "metric": f"tokens/sec/chip, {best[1]}, Q40 synthetic, 1 chip ({dev.platform})",
        "value": best[2],
        "unit": "tok/s",
        # pinned definition — comparable by construction round-over-round;
        # 0.0 + config null = the north-star config wasn't measured this run
        "vs_baseline": round(pinned[0], 4),
        "vs_baseline_def": "8B serving aggregate tok/s/chip / 1000 (BASELINE.json)",
        "vs_baseline_config": pinned[1],
        "presets": results,
        "batch": batch_results,
        "device": str(dev),
        "setup_s": round(setup_s, 1),
        "unroll": unroll_env,
        "kernels": os.environ.get("BENCH_KERNELS", "auto"),
        "attn": os.environ.get("BENCH_ATTN", "auto"),
        "cache_dtype": os.environ.get("BENCH_CACHE", "bf16"),
        "q40_style": q40_style,
        "xla_prefill_m": int(xla_prefill_m) if xla_prefill_m else None,
        "moe": moe,
        "admission": admit,
        "hybrid": hybrid_rec,
        "compile": compile_rec,
        "overlap": overlap_ab,
        "trace": trace_ab,
        "paged": paged_ab,
        "paged_kernel": paged_kernel_ab,
        "radix": radix_rec,
        "router": router_rec,
        "fleet_obs": fleet_obs_rec,
        "slo": slo_rec,
        "spec_batch": spec_batch_rec,
        "kb_per_token_per_chip": kb_measured if kb_measured is not None else round(kb, 1),
        "kb_per_token_source": "measured_hlo" if kb_measured is not None else "analytic",
    }
    print(json.dumps(result))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        sys.exit(main())
