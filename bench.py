"""Benchmark: single-chip decode throughput on a synthetic Q40 Llama.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline = decode tok/s vs the size-adjusted driver north star
(BASELINE.json: Llama-3.1-8B-Q40 at 1000 tok/s/chip -> north_star =
1000 * 8.03e9 / params).

Hardened against the axon-tunnel wedge (VERDICT r1 #1): the parent process
never initializes a JAX backend. It probes the tunnel in a subprocess with a
timeout, retries UNAVAILABLE/hangs with a bounded budget, runs the real
measurement in ONE worker subprocess with a generous timeout, and if the TPU
never comes up emits a CPU-fallback record — the bench never exits non-zero
and never prints nothing.

Env knobs:
  BENCH_PRESET         tiny | 1b (default) | 8b
  BENCH_DECODE_TOKENS  timed fused-decode length (default 256)
  BENCH_UNROLL         lax.scan unroll over layers: int, or 'full' (default 1)
  BENCH_BUDGET_S       total wall-clock budget for the parent (default 840 —
                       fits under the driver's `timeout 900 python bench.py`)
  BENCH_FORCE_CPU      '1': skip the TPU entirely (CI smoke)
"""

import json
import os
import subprocess
import sys
import time

_PROBE = (
    "import jax, jax.numpy as jnp; jnp.ones(8).sum().block_until_ready(); "
    "print('PROBE_OK', jax.devices()[0].platform)"
)


def _cpu_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""  # skip the axon sitecustomize entirely
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_child(argv, env, timeout_s: float):
    """Run a child with a timeout, never blocking past it: on expiry the child
    is killed and — if it sits in uninterruptible IO on the wedged tunnel —
    ABANDONED rather than waited on (a plain subprocess.run would hang in its
    post-kill communicate()). Returns (stdout, stderr, rc) or (None, "", -1)."""
    import tempfile

    with tempfile.TemporaryFile("w+") as out, tempfile.TemporaryFile("w+") as err:
        proc = subprocess.Popen(argv, stdout=out, stderr=err, env=env, text=True)
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # D-state child: abandon it, do not block the bench
            return None, "", -1
        out.seek(0)
        err.seek(0)
        return out.read(), err.read(), rc


def probe_tpu(timeout_s: float) -> bool:
    """Can a fresh process reach the chip? Runs in a subprocess so a wedged
    tunnel hangs the child, not us. Requires a NON-CPU platform — a fast init
    failure makes JAX fall back to its CPU backend, which must not count."""
    stdout, _, rc = _run_child([sys.executable, "-c", _PROBE], None, timeout_s)
    if rc != 0 or stdout is None:
        return False
    for line in stdout.splitlines():
        if line.startswith("PROBE_OK"):
            platform = line.split()[-1].lower()
            return platform != "cpu"
    return False


def run_worker(env, timeout_s: float):
    """One measurement subprocess; returns the parsed JSON line or None."""
    stdout, stderr, rc = _run_child(
        [sys.executable, __file__, "--worker"], env, timeout_s
    )
    if stdout is None:
        print(f"bench worker timed out after {timeout_s:.0f}s", file=sys.stderr)
        return None
    for line in reversed(stdout.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                pass
    sys.stderr.write(stderr[-2000:])
    return None


def main():
    deadline = time.monotonic() + float(os.environ.get("BENCH_BUDGET_S", "840"))
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    tpu_ok = False
    if not force_cpu:
        # bounded probe/retry: a wedged relay clears only server-side, so a
        # couple of spaced attempts, then give up and record the CPU fallback.
        for attempt in range(3):
            budget = deadline - time.monotonic()
            if budget < 240:  # not enough left for probe + worker + fallback
                break
            tpu_ok = probe_tpu(min(90, budget - 180))
            if tpu_ok:
                break
            print(f"TPU probe {attempt + 1} failed (tunnel wedged/unavailable)",
                  file=sys.stderr)
            if deadline - time.monotonic() > 420:
                time.sleep(60)
    if tpu_ok:
        budget = deadline - time.monotonic() - 120  # keep room for CPU fallback
        result = run_worker(dict(os.environ), max(budget, 60))
        if result is not None:
            print(json.dumps(result))
            return 0
        print("TPU worker failed; falling back to CPU record", file=sys.stderr)
    env = _cpu_env()
    env["BENCH_DECODE_TOKENS"] = os.environ.get("BENCH_CPU_DECODE_TOKENS", "16")
    result = run_worker(env, max(deadline - time.monotonic(), 120))
    if result is None:  # last resort: an honest empty record, still rc=0
        result = {
            "metric": "decode tok/s (UNMEASURED: TPU tunnel down, CPU fallback failed)",
            "value": 0.0, "unit": "tok/s", "vs_baseline": 0.0,
        }
    result["tpu_unavailable"] = not tpu_ok
    print(json.dumps(result))
    return 0


# --------------------------------------------------------------------- worker


def params_count(cfg) -> float:
    per_layer = (
        cfg.dim * cfg.dim * 2  # wq, wo
        + cfg.dim * cfg.kv_dim * 2  # wk, wv
        + cfg.dim * cfg.hidden_dim * 3  # w1, w2, w3
    )
    return cfg.vocab_size * cfg.dim * 2 + cfg.n_layers * per_layer


def worker():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    preset = os.environ.get("BENCH_PRESET", "1b")
    presets = {
        # dims follow the HF configs of the reference's model zoo (launch.py)
        "tiny": dict(dim=512, hidden_dim=1536, n_layers=4, n_heads=8, n_kv_heads=4,
                     vocab_size=2048, seq_len=512),
        "1b": dict(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32, n_kv_heads=8,
                   vocab_size=128256, seq_len=1024),
        "8b": dict(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
                   vocab_size=128256, seq_len=1024),
    }
    if preset not in presets:
        raise SystemExit(f"BENCH_PRESET must be one of {sorted(presets)}, got {preset!r}")
    label = {"tiny": "tiny", "1b": "Llama-3.2-1B", "8b": "Llama-3.1-8B"}[preset]
    cfg = LlamaConfig(**presets[preset])
    unroll_env = os.environ.get("BENCH_UNROLL", "1")
    unroll = True if unroll_env == "full" else int(unroll_env)

    dev = jax.devices()[0]
    t0 = time.perf_counter()
    params = random_params(cfg, seed=0, dtype=jnp.bfloat16, quantize=True)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16, max_prefill_chunk=128,
                          layer_unroll=unroll)
    t_setup = time.perf_counter() - t0

    prompt = np.arange(1, 129, dtype=np.int32)[None] % cfg.vocab_size
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_prefill_compile = time.perf_counter() - t0

    first = np.asarray(jnp.argmax(logits, -1)).astype(np.int32)
    prefill_end = eng.pos

    # warmup/compile the fused decode loop with the SAME static n as the timed
    # run (n is a static arg of the scan — a different n would recompile inside
    # the timed region)
    n_decode = int(os.environ.get("BENCH_DECODE_TOKENS", "256"))
    n_decode = min(n_decode, eng.seq_len - eng.pos - 1)
    t0 = time.perf_counter()
    _ = eng.decode_greedy_n(first, n_decode)
    t_decode_compile = time.perf_counter() - t0

    # timed decode over the same range (cache slots past pos are masked out)
    eng.reset(prefill_end)
    t0 = time.perf_counter()
    toks = eng.decode_greedy_n(first, n_decode)  # np.asarray inside = device sync
    t_decode = time.perf_counter() - t0
    tok_s = n_decode / t_decode

    # timed prefill (cache already compiled; re-run from pos 0)
    eng.reset(0)
    t0 = time.perf_counter()
    logits = eng.prefill(prompt)
    jax.block_until_ready(logits)
    t_prefill = time.perf_counter() - t0
    prefill_tok_s = prompt.shape[1] / t_prefill

    n_params = params_count(cfg)
    north_star = 1000.0 * (8.03e9 / n_params)  # size-adjusted 8B@1000tok/s/chip
    result = {
        "metric": f"decode tok/s, {label}-Q40 synthetic, batch=1, 1 chip ({dev.platform})",
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / north_star, 4),
        "prefill_tok_s": round(prefill_tok_s, 1),
        "decode_ms_per_token": round(1000.0 / tok_s, 3),
        "params_b": round(n_params / 1e9, 3),
        "device": str(dev),
        "setup_s": round(t_setup, 1),
        "compile_s": round(t_prefill_compile + t_decode_compile, 1),
        "unroll": unroll_env,
    }
    # bytes/token is part of the benchmark contract (SURVEY.md §5.1/§6): on
    # one chip it's 0; multi-chip runs report the analytic ICI payload.
    from dllama_tpu.utils.profiling import collective_bytes_per_token

    n_dev = jax.device_count()
    result["kb_per_token_per_chip"] = round(
        collective_bytes_per_token(cfg, tp=n_dev)["kb_per_token_per_chip"], 1
    )
    print(json.dumps(result))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        sys.exit(main())
