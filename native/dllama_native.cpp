// dllama-tpu native runtime components.
//
// The TPU compute path is JAX/XLA/Pallas; these are the *host-side* hot loops,
// the counterparts of the reference's C++ core that stay CPU-bound in any
// design: byte-level BPE encode (tokenizer.cpp:265-330 role) and Q40/Q80
// block quantization for the converter/writer path (nn-quants.cpp:67-200
// role). Exposed through a plain C ABI consumed via ctypes
// (dllama_tpu/utils/native.py); every function has a pure-Python/numpy
// fallback with identical semantics, enforced by tests/test_native.py.
//
// Numeric contract: quantization matches the numpy implementations in
// dllama_tpu/ops/quant.py bit-for-bit — f32->f16 uses round-to-nearest-even
// (numpy astype semantics), Q40 uses the reference's floor(x/delta + 8.5)
// rule with the *unrounded* f32 delta, Q80 uses round-half-to-even.

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1Fu;
    uint32_t man = h & 0x3FFu;
    uint32_t bits;
    if (exp == 0) {
        if (man == 0) {
            bits = sign;  // +-0
        } else {  // subnormal: normalize
            int shift = 0;
            while (!(man & 0x400u)) { man <<= 1; shift++; }
            man &= 0x3FFu;
            bits = sign | ((127 - 15 - shift + 1) << 23) | (man << 13);
        }
    } else if (exp == 31) {
        bits = sign | 0x7F800000u | (man << 13);  // inf/nan
    } else {
        bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
    }
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
}

uint16_t f32_to_f16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    uint32_t sign = (x >> 16) & 0x8000u;
    uint32_t exp8 = (x >> 23) & 0xFFu;
    uint32_t mant = x & 0x7FFFFFu;
    if (exp8 == 0xFFu) return sign | 0x7C00u | (mant ? 0x200u : 0u);  // inf/nan
    int32_t exp = (int32_t)exp8 - 127 + 15;
    if (exp >= 0x1F) return sign | 0x7C00u;  // overflow -> inf
    if (exp <= 0) {                          // subnormal half
        if (exp < -10) return sign;          // underflow -> signed zero
        mant |= 0x800000u;
        uint32_t shift = (uint32_t)(14 - exp);
        uint32_t half = mant >> shift;
        uint32_t rem = mant & ((1u << shift) - 1u);
        uint32_t halfway = 1u << (shift - 1u);
        if (rem > halfway || (rem == halfway && (half & 1u))) half++;
        return (uint16_t)(sign | half);
    }
    uint32_t half = ((uint32_t)exp << 10) | (mant >> 13);
    uint32_t rem = mant & 0x1FFFu;
    if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half++;  // carry ok
    return (uint16_t)(sign | half);
}

struct Tok {
    std::vector<std::string> vocab;
    std::vector<float> scores;
    std::unordered_map<std::string, int32_t> regular;
    std::vector<int32_t> specials;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- quantize

// x[n] f32 -> packed[n/32 * 16] u8 (byte j of block b = codes 32b+j | 32b+j+16<<4),
// scales[n/32] f16-as-u16. n must be a multiple of 32.
void dllama_quantize_q40(const float* x, int64_t n, uint8_t* packed, uint16_t* scales) {
    int64_t nb = n / 32;
    for (int64_t b = 0; b < nb; b++) {
        const float* g = x + b * 32;
        float mx = g[0], mn = g[0];
        for (int j = 1; j < 32; j++) {
            if (g[j] > mx) mx = g[j];
            if (g[j] < mn) mn = g[j];
        }
        float delta = ((-mn > mx) ? mn : mx) / -8.0f;
        scales[b] = f32_to_f16(delta);
        float inv = (delta != 0.0f) ? 1.0f / delta : 0.0f;
        uint8_t q[32];
        for (int j = 0; j < 32; j++) {
            float v = g[j] * inv + 8.5f;
            if (v < 0.0f) v = 0.0f;
            if (v > 15.0f) v = 15.0f;
            q[j] = (uint8_t)v;  // truncation == numpy astype(uint8) after clip
        }
        for (int j = 0; j < 16; j++) packed[b * 16 + j] = (uint8_t)(q[j] | (q[j + 16] << 4));
    }
}

// .m Q40 record blob [n_out, nb_total, 18B] -> device-layout shard slices
// rows [n0,n1) x blocks [b0,b1): packed u8[(b1-b0)*16, n1-n0] (device row
// 16*b + j holds codes for input dims 32*b + j low / +16 high) and scales
// f32[b1-b0, n1-n0]. Either output may be null to skip its pass. This is the
// hot loop of checkpoint loading (a strided gather-transpose numpy does with
// several large temporaries); one C++ pass streams only the shard's bytes.
void dllama_q40_shard(const uint8_t* rec, int64_t nb_total,
                      int64_t n0, int64_t n1, int64_t b0, int64_t b1,
                      uint8_t* packed, float* scales) {
    const int64_t ns = n1 - n0;
    for (int64_t n = 0; n < ns; n++) {
        const uint8_t* row = rec + ((n0 + n) * nb_total + b0) * 18;
        for (int64_t b = 0; b < b1 - b0; b++) {
            const uint8_t* blk = row + b * 18;
            if (scales) {
                uint16_t s16 = (uint16_t)blk[0] | ((uint16_t)blk[1] << 8);
                scales[b * ns + n] = f16_to_f32(s16);
            }
            if (packed) {
                for (int j = 0; j < 16; j++)
                    packed[(b * 16 + j) * ns + n] = blk[2 + j];
            }
        }
    }
}

// x[n] f32 -> codes[n] i8, scales[n/32] f16-as-u16.
void dllama_quantize_q80(const float* x, int64_t n, int8_t* codes, uint16_t* scales) {
    int64_t nb = n / 32;
    for (int64_t b = 0; b < nb; b++) {
        const float* g = x + b * 32;
        float am = 0.0f;
        for (int j = 0; j < 32; j++) {
            float a = std::fabs(g[j]);
            if (a > am) am = a;
        }
        float delta = am / 127.0f;
        scales[b] = f32_to_f16(delta);
        float inv = (delta != 0.0f) ? 1.0f / delta : 0.0f;
        for (int j = 0; j < 32; j++)
            codes[b * 32 + j] = (int8_t)std::nearbyintf(g[j] * inv);  // half-to-even
    }
}

// ---------------------------------------------------------------- tokenizer

// vocab: concatenated piece bytes + offsets[n_vocab+1]; special_ids are
// matched greedily as literal prefixes (in the given order) and excluded from
// the merge index. Returns an opaque handle.
void* dllama_tok_create(const uint8_t* blob, const int64_t* offsets, const float* scores,
                        int32_t n_vocab, const int32_t* special_ids, int32_t n_special) {
    Tok* t = new Tok();
    t->vocab.reserve(n_vocab);
    t->scores.assign(scores, scores + n_vocab);
    std::vector<char> is_special((size_t)n_vocab, 0);
    t->specials.reserve(n_special);
    for (int32_t i = 0; i < n_special; i++) {
        t->specials.push_back(special_ids[i]);
        if (special_ids[i] >= 0 && special_ids[i] < n_vocab) is_special[special_ids[i]] = 1;
    }
    for (int32_t i = 0; i < n_vocab; i++) {
        t->vocab.emplace_back((const char*)blob + offsets[i], (size_t)(offsets[i + 1] - offsets[i]));
        if (!is_special[i]) t->regular[t->vocab[i]] = i;  // later duplicate wins
    }
    return t;
}

void dllama_tok_destroy(void* h) { delete (Tok*)h; }

// Byte-level BPE encode with the exact semantics of Tokenizer.encode
// (greedy special prefix scan, byte accumulation, best-score pair merges,
// first occurrence wins ties). Returns token count, -1 if a byte sequence
// cannot be tokenized, -2 if out buffer is too small.
int32_t dllama_tok_encode(void* h, const uint8_t* data, int32_t n, int32_t add_special,
                          int32_t* out, int32_t max_out) {
    Tok* t = (Tok*)h;
    std::vector<int32_t> toks;
    std::string buf;
    int32_t i = 0;
    while (i < n) {
        if (add_special && buf.empty()) {
            int32_t sid = -1;
            for (int32_t cand : t->specials) {
                const std::string& piece = t->vocab[cand];
                if (!piece.empty() && (size_t)(n - i) >= piece.size() &&
                    std::memcmp(data + i, piece.data(), piece.size()) == 0) {
                    sid = cand;
                    break;
                }
            }
            if (sid >= 0) {
                toks.push_back(sid);
                i += (int32_t)t->vocab[sid].size();
                continue;
            }
        }
        buf.push_back((char)data[i]);
        i++;
        auto it = t->regular.find(buf);
        if (it != t->regular.end()) {
            toks.push_back(it->second);
            buf.clear();
        }
    }
    if (!buf.empty()) return -1;

    // Best-score pair merging via doubly-linked list + max-heap: O(n log n)
    // against the O(n^2) rescan of the Python fallback, with identical
    // results — the heap tie-breaks equal scores by the left token's original
    // position, which matches "first occurrence wins" because merges preserve
    // relative order.
    struct Node {
        int32_t id;
        int32_t prev, next;  // indices into nodes; -1 = end
        bool alive;
    };
    struct Cand {
        float score;
        int32_t pos;        // left node's original position (tie-break)
        int32_t left;       // node indices
        int32_t merged_id;
        int32_t left_id, right_id;  // staleness check
        bool operator<(const Cand& o) const {
            if (score != o.score) return score < o.score;   // max-heap on score
            return pos > o.pos;                             // then min position
        }
    };
    std::vector<Node> nodes(toks.size());
    for (size_t j = 0; j < toks.size(); j++)
        nodes[j] = {toks[j], (int32_t)j - 1, j + 1 < toks.size() ? (int32_t)(j + 1) : -1, true};

    std::priority_queue<Cand> heap;
    std::string merged;
    auto push_cand = [&](int32_t li) {
        int32_t ri = nodes[li].next;
        if (ri < 0) return;
        merged.assign(t->vocab[nodes[li].id]);
        merged += t->vocab[nodes[ri].id];
        auto it = t->regular.find(merged);
        if (it != t->regular.end())
            heap.push({t->scores[it->second], li, li, it->second, nodes[li].id, nodes[ri].id});
    };
    for (size_t j = 0; j + 1 < toks.size(); j++) push_cand((int32_t)j);

    size_t count = toks.size();
    while (!heap.empty()) {
        Cand c = heap.top();
        heap.pop();
        int32_t li = c.left;
        if (!nodes[li].alive || nodes[li].id != c.left_id) continue;
        int32_t ri = nodes[li].next;
        if (ri < 0 || nodes[ri].id != c.right_id) continue;
        nodes[li].id = c.merged_id;
        nodes[li].next = nodes[ri].next;
        if (nodes[ri].next >= 0) nodes[nodes[ri].next].prev = li;
        nodes[ri].alive = false;
        count--;
        if (nodes[li].prev >= 0) push_cand(nodes[li].prev);
        push_cand(li);
    }
    if ((int32_t)count > max_out) return -2;
    int32_t w = 0;
    for (int32_t j = 0; j >= 0 && j < (int32_t)nodes.size(); j = nodes[j].next)
        if (nodes[j].alive) out[w++] = nodes[j].id;
    return w;
}

}  // extern "C"
