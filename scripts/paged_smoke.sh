#!/usr/bin/env bash
# paged_smoke.sh — end-to-end paged-KV-cache smoke target.
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with
# `--kv-layout paged`, waits for /health/ready, runs ONE chat completion,
# and asserts: the completion succeeds, /health carries the kv_pages
# occupancy object, and the dllama_kv_pages_{total,used} gauges on /metrics
# are live (total > 0, used > 0 after the completion's prefix rows were
# cached) — proving the pool allocator, the paged forward path, the
# scheduler's capacity accounting, and the observability plumbing agree
# through the real serving surface. Finishes with a SIGTERM drain.
#
# SMOKE TARGET, not a pytest test (lives outside tests/, exempt from the
# tier-1 run). CPU-only, no model download, ~1 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_paged_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--kv-layout", "paged", "--page-size", "8"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def gauge(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else None


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    st, health = get("/health")
    kv = json.loads(health).get("kv_pages")
    assert kv and kv["total"] > 0, f"/health kv_pages missing/empty: {kv}"

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 8, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}"
    assert body["usage"]["completion_tokens"] > 0

    st, metrics = get("/metrics")
    assert st == 200
    total = gauge(metrics, "dllama_kv_pages_total")
    used = gauge(metrics, "dllama_kv_pages_used")
    assert total and total > 0, f"dllama_kv_pages_total not live: {total}"
    # the released slot keeps its prefix rows as reusable cache -> pages
    # stay referenced after the completion
    assert used and used > 0, f"dllama_kv_pages_used not live: {used}"
    print(f"PASS: paged serve OK — kv pages total={total:.0f} "
          f"used={used:.0f} (health kv_pages={kv})")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
