#!/usr/bin/env bash
# trace_smoke.sh — end-to-end request-flow-tracing smoke target.
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with the default trace
# buffer, waits for /health/ready, runs ONE chat completion, and asserts:
#
#   * the response body carries the `timings` object;
#   * GET /debug/requests/{req_id} replays the request with a prefill
#     record and >= 1 decode chunk (the flight recorder end to end);
#   * GET /debug/trace parses as Chrome trace-event JSON, and some decode
#     `dispatch` span for chunk N+1 STARTS before chunk N's `consume` span
#     ends — the overlapped pipeline (PR 3) made visible as interleaved
#     spans, which is the whole point of the tracer.
#
# Finishes with a SIGTERM drain. This is a SMOKE TARGET, not a pytest test:
# it is exempt from the tier-1 `-m 'not slow'` pytest run (it lives outside
# tests/) and is meant for CI smoke stages or manual runs:
#
#     scripts/trace_smoke.sh
#
# CPU-only, no model download, ~1 min (XLA compile dominates). Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_tsmoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--overlap", "on",
     "--port", str(port), "--log-format", "json"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 16, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}"
    rid = body["request_id"]
    timings = body.get("timings")
    assert timings and timings["decode_tokens"] > 0, (
        f"timings object missing/empty: {timings!r}")
    assert timings["e2e_ms"] >= timings["ttft_ms"] > 0

    # ---- flight recorder: the request is replayable post-hoc
    st, raw = get(f"/debug/requests/{rid}")
    assert st == 200, f"/debug/requests/{rid} -> {st}"
    rec = json.loads(raw)
    assert rec["state"] == "finished", rec["state"]
    assert rec["prefill"] and rec["prefill"]["tokens"] > 0, (
        f"no prefill record: {rec.get('prefill')!r}")
    assert len(rec["chunks"]) >= 1, "no decode chunks recorded"
    st, raw = get("/debug/requests")
    assert st == 200 and rid in [r["req_id"] for r in json.loads(raw)["requests"]]

    # ---- Chrome export parses, and the overlap is VISIBLE: a dispatch
    # span for chunk N+1 starts before chunk N's consume span ends
    st, raw = get("/debug/trace")
    assert st == 200, f"/debug/trace -> {st}"
    doc = json.loads(raw)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evs, "trace export has no spans"
    disp = {e["args"]["chunk"]: e for e in evs if e["name"] == "decode.dispatch"}
    cons = {e["args"]["chunk"]: e for e in evs if e["name"] == "decode.consume"}
    assert disp and cons, f"decode spans missing (have {sorted({e['name'] for e in evs})})"
    overlapped = [
        k for k, c in cons.items()
        if k + 1 in disp and disp[k + 1]["ts"] < c["ts"] + c["dur"]
    ]
    assert overlapped, (
        "no chunk N+1 dispatch started before chunk N's consume ended — "
        "the overlapped pipeline is not visible in the trace "
        f"(dispatch chunks {sorted(disp)}, consume chunks {sorted(cons)})")

    print(f"PASS: request {rid}: timings {timings}, "
          f"{len(rec['chunks'])} chunks in flight recorder, "
          f"overlap visible on chunk pairs {sorted(overlapped)[:4]} "
          f"({len(evs)} spans exported)")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
