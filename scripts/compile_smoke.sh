#!/usr/bin/env bash
# compile_smoke.sh — end-to-end compile-observability smoke target (ISSUE 13).
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with `--warmup auto`
# and `--transfer-guard strict`, runs one completion, and asserts:
#
#   * GET /debug/compile reports FULL declared bucket coverage
#     (contract.full) and a warmup report with full_coverage=true;
#   * ZERO unexpected compiles anywhere, and the first real request
#     compiled NOTHING (the compile totals before and after the completion
#     are identical) — the warmed TTFT therefore sits far below the
#     cold-boot compile bill the warmup report records (asserted:
#     ttft_ms < warmup seconds * 1000, a generous bound that still fails
#     loudly if warmup silently stops covering the serving shapes);
#   * the transfer tallies show boundary uploads + per-chunk downloads and
#     /health carries the compile object with unexpected_compiles == 0;
#   * the strict transfer guard survived the whole run (any implicit
#     steady-state upload would have errored the request).
#
# Finishes with a SIGTERM drain. SMOKE TARGET, not a pytest test (lives
# outside tests/, exempt from the tier-1 run). CPU-only, ~2 min (the
# warmup pass pays the XLA compile bill up front — that is the point).
# Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_compile_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--kv-layout", "paged", "--page-size", "8",
     "--warmup", "auto", "--transfer-guard", "strict"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


try:
    # warmup runs before the server binds readiness: the wait below covers
    # the whole precompile pass (CPU XLA is slow — that is what it costs)
    deadline = time.time() + 300
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    st, doc = get("/debug/compile")
    doc = json.loads(doc)
    assert st == 200
    warm = doc["warmup"]
    assert warm and warm["full_coverage"], f"warmup coverage: {warm}"
    assert doc["contract"]["full"], f"bucket coverage incomplete: " \
        f"{doc['contract']}"
    assert doc["unexpected"] == 0, f"unexpected compiles: {doc['totals']}"
    compiles_before = doc["compiles"]

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    t0 = time.perf_counter()
    conn.request("POST", "/v1/chat/completions", json.dumps(
        {"messages": [{"role": "user", "content": "hello compile ledger"}],
         "max_tokens": 12, "temperature": 0.0}),
        {"Content-Type": "application/json"})
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}: {payload}"
    assert payload["usage"]["completion_tokens"] > 0
    ttft_ms = payload["timings"]["ttft_ms"]

    st, doc = get("/debug/compile")
    doc = json.loads(doc)
    assert doc["compiles"] == compiles_before, (
        f"warmed first request still compiled "
        f"{doc['compiles'] - compiles_before} computations: "
        f"{doc['entries'][-5:]}")
    assert doc["unexpected"] == 0
    # the warmed TTFT must sit far below the compile bill warmup absorbed
    # (a cold boot pays ~that bill on its first request)
    assert ttft_ms < warm["seconds"] * 1000, (
        f"warmed ttft {ttft_ms}ms not below the {warm['seconds']}s "
        "cold-boot compile bill — warmup stopped covering serving shapes?")
    tr = doc["transfers"]
    assert tr["sites"].get("h2d.prefill", {}).get("bytes", 0) > 0
    assert tr["sites"].get("d2h.decode_tokens", {}).get("bytes", 0) > 0
    assert doc["device_memory"]["buffers"] > 0

    st, h = get("/health")
    h = json.loads(h)
    assert st == 200 and h["compile"]["unexpected_compiles"] == 0
    assert h["compile"]["full_coverage"] is True
    assert h["build"]["warmup"] == "auto"

    st, m = get("/metrics")
    assert st == 200
    assert re.search(r"^dllama_jit_compiles_total\{", m, re.M)
    assert not re.search(
        r'^dllama_jit_unexpected_compiles_total\{[^}]*\} [1-9]', m, re.M)
    print(f"PASS: compile serve OK — {warm['buckets']} buckets warmed in "
          f"{warm['seconds']}s with full coverage; warmed first-request "
          f"ttft {ttft_ms}ms, zero compiles, zero unexpected, strict "
          "transfer guard clean")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
