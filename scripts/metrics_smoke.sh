#!/usr/bin/env bash
# metrics_smoke.sh — end-to-end telemetry smoke target.
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model, waits for
# /health/ready, scrapes /metrics, runs ONE chat completion, scrapes
# again, and asserts dllama_tokens_generated_total advanced by exactly the
# completion's token count — proving the registry, the exposition endpoint,
# and the scheduler instrumentation agree end to end. Also checks the
# X-Request-Id response header and finishes with a SIGTERM drain.
#
# This is a SMOKE TARGET, not a pytest test: it is exempt from the tier-1
# `-m 'not slow'` pytest run (it lives outside tests/) and is meant for CI
# smoke stages or manual runs:
#
#     scripts/metrics_smoke.sh
#
# CPU-only, no model download, ~1 min (XLA compile dominates). Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--log-format", "json"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def counter(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    st, before_text = get("/metrics")
    assert st == 200, f"/metrics -> {st}"
    before = counter(before_text, "dllama_tokens_generated_total")

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 8, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    rid = resp.getheader("X-Request-Id")
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}"
    assert rid and body.get("request_id") == rid, "X-Request-Id missing/mismatched"
    done = body["usage"]["completion_tokens"]
    assert done > 0

    st, after_text = get("/metrics")
    assert st == 200
    after = counter(after_text, "dllama_tokens_generated_total")
    # >= (not ==): the scheduler counts tokens at emit time, so a completion
    # that ends on a stop string can emit a few past what the client consumed
    assert after >= before + done, (
        f"token counter did not advance correctly: {before} -> {after}, "
        f"completion produced {done}")
    print(f"PASS: dllama_tokens_generated_total {before:.0f} -> {after:.0f} "
          f"(+{done} tokens), request {rid}")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
