#!/usr/bin/env bash
# hybrid_smoke.sh — end-to-end hybrid chunked-prefill + preemption smoke
# target (ISSUE 12).
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with a FIXED
# --prefill-budget, then:
#
#   * streams a long-running completion and admits a LONG prompt mid-stream:
#     asserts the running stream KEPT EMITTING inside the joiner's
#     admission window (tokens arrive between the join's submit and its
#     first token — the fused hybrid step never freezes decoders for a
#     whole prefill) with a bounded max inter-token gap, and that the
#     dllama_prefill_budget_tokens gauge reports the armed budget;
#   * fills both slots with priority-0 streams and fires a priority-high
#     completion: asserts a preemption fires (dllama_preemptions_total),
#     the suspended stream RESUMES and finishes its full budget
#     (dllama_resumed_total), and GET /debug/kv still audits clean —
#     preempt-to-pages released the slot without corrupting the pool;
#   * finishes with a SIGTERM drain.
#
# SMOKE TARGET, not a pytest test (lives outside tests/, exempt from the
# tier-1 run). CPU-only, ~2 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_hybrid_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--prefill-budget", "16", "--preempt", "on"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)

LONG = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
        "lambda mu nu xi omicron pi rho sigma tau upsilon phi chi psi "
        "omega one two three four five six seven eight nine ten eleven")
# the measured join: SAME words reordered — identical token count (so the
# warm join above compiles every hybrid slice shape the measured one
# needs) but a different prefix (so the radix cache cannot map it and the
# admission really prefills)
LONG2 = " ".join(reversed(LONG.split()))


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def metric(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


def labeled(text, name):
    return sum(float(m) for m in
               re.findall(rf'^{name}\{{[^}}]*\}} ([0-9.e+-]+)$', text, re.M))


def complete(body, out):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}: {payload}"
    out.append(payload)


def stream(body, stamps, done):
    """SSE client: stamp every delta arrival (perf_counter)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({**body, "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200, f"stream -> {resp.status}"
    for raw in resp:
        for line in raw.splitlines():
            if line.startswith(b"data: ") and b"delta" in line:
                stamps.append(time.perf_counter())
    conn.close()
    done.set()


try:
    deadline = time.time() + 120
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    # ---- warm-up: compile the decode AND hybrid-slice shapes (a warm
    # stream + a mid-stream join) so the measured leg times serving, not XLA
    warm_out = []
    st1, d1 = [], threading.Event()
    t = threading.Thread(target=stream, args=(
        {"messages": [{"role": "user", "content": "warm stream"}],
         "max_tokens": 48, "temperature": 0.0}, st1, d1))
    t.start()
    time.sleep(0.5)
    complete({"messages": [{"role": "user", "content": LONG}],
              "max_tokens": 2, "temperature": 0.0}, warm_out)
    d1.wait(timeout=240)
    t.join(timeout=10)

    # ---- measured leg: long prompt admitted mid-stream
    stamps, done = [], threading.Event()
    t = threading.Thread(target=stream, args=(
        {"messages": [{"role": "user", "content": "tell me a story"}],
         "max_tokens": 64, "temperature": 0.0}, stamps, done))
    t.start()
    while len(stamps) < 4:  # the stream is really decoding
        assert not done.is_set(), "probe stream finished before the join"
        time.sleep(0.01)
    t_sub = time.perf_counter()
    join_out = []
    complete({"messages": [{"role": "user", "content": LONG2}],
              "max_tokens": 2, "temperature": 0.0}, join_out)
    ttft_ms = join_out[0]["timings"]["ttft_ms"]
    done.wait(timeout=240)
    t.join(timeout=10)
    t_first = t_sub + ttft_ms / 1000.0
    during = [ts for ts in stamps if t_sub <= ts <= t_first]
    assert len(during) >= 1, (
        f"running stream froze for the whole admission (ttft {ttft_ms}ms, "
        f"0 tokens in the window) — hybrid step not engaging?")
    gaps = [(b - a) * 1000.0 for a, b in zip(stamps, stamps[1:])
            if a >= t_sub and b <= t_first + 0.2]
    assert not gaps or max(gaps) < 2000.0, f"unbounded ITL gap: {max(gaps)}ms"

    st, m1 = get("/metrics")
    assert st == 200
    assert metric(m1, "dllama_prefill_budget_tokens") == 16.0, (
        "dllama_prefill_budget_tokens gauge missing or not armed")

    # ---- preemption leg: both slots busy at priority 0, a high-priority
    # completion preempts one, the victim resumes and finishes
    sa, da = [], threading.Event()
    sb, db = [], threading.Event()
    ta = threading.Thread(target=stream, args=(
        {"messages": [{"role": "user", "content": "low one"}],
         "max_tokens": 48, "temperature": 0.0, "priority": 0}, sa, da))
    tb = threading.Thread(target=stream, args=(
        {"messages": [{"role": "user", "content": "low two"}],
         "max_tokens": 48, "temperature": 0.0, "priority": 0}, sb, db))
    ta.start(); tb.start()
    while len(sa) < 2 or len(sb) < 2:
        time.sleep(0.01)
    hi_out = []
    complete({"messages": [{"role": "user", "content": "urgent"}],
              "max_tokens": 4, "temperature": 0.0, "priority": "high"},
             hi_out)
    da.wait(timeout=240); db.wait(timeout=240)
    ta.join(timeout=10); tb.join(timeout=10)

    st, m2 = get("/metrics")
    assert st == 200
    pre = labeled(m2, "dllama_preemptions_total")
    res = metric(m2, "dllama_resumed_total")
    assert pre >= 1, "no preemption fired for the high-priority request"
    assert res >= 1, "preempted stream never resumed"

    st, kv = get("/debug/kv")
    kv = json.loads(kv)
    assert st == 200 and kv["audit"]["ok"], f"/debug/kv audit: {kv}"
    print(f"PASS: hybrid serve OK — {len(during)} tokens flowed during a "
          f"{ttft_ms:.0f}ms admission (budget gauge 16), "
          f"{pre:.0f} preemption(s) with {res:.0f} resume(s); "
          f"/debug/kv audit clean")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
