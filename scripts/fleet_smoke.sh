#!/usr/bin/env bash
# fleet_smoke.sh — the ISSUE 17/19 acceptance drill: the fleet
# observability plane survives a mid-stream SIGKILL failover over the
# real wire.
#
# Boots TWO `python -m dllama_tpu serve` replicas (real CLI, tiny fixture
# model, paged layout) plus one `python -m dllama_tpu router`, streams a
# completion tagged with a client X-Request-Id, SIGKILLs the replica
# serving it mid-stream, then — after the stream resumes and finishes on
# the survivor — asserts the three observability surfaces reconcile:
#
#   1. GET /router/trace returns ONE merged Perfetto/Chrome file: the
#      router's own track (pid 1) plus the survivor's offset-shifted
#      track, timestamps globally sorted, the survivor's clock entry
#      aligned within its NTP-lite uncertainty, and the drill's trace id
#      tying spans on BOTH tracks — connect / proxy / failover.attempt /
#      resume / journal on the router track and the survivor's own
#      request span under the same id.
#   2. GET /metrics (and its /router/metrics alias) parses as one
#      exposition: survivor series relabeled replica="127.0.0.1:PORT",
#      counters and histogram buckets pre-aggregated into dllama_fleet_*
#      families, and a dllama_fleet_scrape_age_seconds staleness gauge
#      per scraped replica.
#   3. GET /router/requests/{rid} joins both legs under one trace id:
#      forward -> died_mid_stream on the victim (unreachable, SIGKILLed),
#      resume -> ok on the survivor, with the survivor's flight-recorder
#      timeline showing the SAME req_id finished.
#
# SMOKE TARGET, not a pytest test (lives outside tests/, exempt from the
# tier-1 run). CPU-only, ~2 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_fleet_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


ports = [free_port(), free_port()]
rport = free_port()

replicas = {
    p: subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
         "--tokenizer", tpath, "--slots", "2", "--port", str(p),
         "--kv-layout", "paged", "--page-size", "8", "--kv-pages", "56"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for p in ports
}
router = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "router", "--port", str(rport),
     "--replica", f"127.0.0.1:{ports[0]}",
     "--replica", f"127.0.0.1:{ports[1]}",
     "--poll-s", "0.2", "--failover-max", "2", "--log-format", "json"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

RID = "req-fleet-smoke-1"


def get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


BODY = {"messages": [
            {"role": "system", "content": "You are a terse assistant."},
            {"role": "user", "content": "stream me a dozen tokens"}],
        "stream": True, "max_tokens": 12, "temperature": 0.0, "seed": 11}


def stream(port, body, on_frames=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json", "X-Request-Id": RID})
    resp = conn.getresponse()
    assert resp.status == 200, f"stream -> {resp.status}: {resp.read()!r}"
    raw = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        raw += chunk
        if on_frames is not None:
            on_frames(raw.count(b"data: "))
    conn.close()
    return raw.decode()


procs = list(replicas.values()) + [router]
try:
    deadline = time.time() + 300  # two first-boot XLA compiles on CPU
    while True:
        try:
            st, body = get(rport, "/router/replicas")
            reps = json.loads(body)["replicas"] if st == 200 else []
        except (OSError, ValueError):
            reps = []
        if len(reps) == 2 and all(r["ready"] and r["config_ok"]
                                  for r in reps):
            break
        for proc in procs:
            if proc.poll() is not None:
                sys.exit("FAIL: a process exited before the mesh was ready")
        if time.time() > deadline:
            sys.exit("FAIL: router mesh never became ready")
        time.sleep(0.25)

    # the drill: SIGKILL whichever replica holds the inflight stream the
    # moment real content frames are on the wire
    killed = {"port": None}

    def assassin(n_frames):
        if killed["port"] is None and n_frames >= 3:
            st, body = get(rport, "/router/replicas")
            for r in json.loads(body)["replicas"]:
                if r["inflight"] > 0:
                    p = int(r["id"].rsplit(":", 1)[1])
                    replicas[p].kill()
                    killed["port"] = p
                    return

    raw = stream(rport, BODY, on_frames=assassin)
    assert killed["port"] is not None, (
        "the drill never found an inflight replica to SIGKILL — "
        "the stream finished too fast to interrupt")
    replicas[killed["port"]].wait(timeout=10)
    assert raw.rstrip().endswith("data: [DONE]"), "stream never finished"
    survivor = next(p for p in ports if p != killed["port"])
    victim_rid = f"127.0.0.1:{killed['port']}"
    survivor_rid = f"127.0.0.1:{survivor}"

    # (3 first — it hands us the trace id) cross-hop postmortem join
    st, body = get(rport, f"/router/requests/{RID}")
    assert st == 200, f"/router/requests/{RID} -> {st}"
    pm = json.loads(body)
    tid = pm["trace_id"]
    assert tid and len(tid) == 16, f"postmortem trace id malformed: {tid!r}"
    rec = pm["router"]
    assert rec["outcome"] == "ok" and rec["stream"] is True, rec
    kinds = [(a["kind"], a["outcome"], a["replica"])
             for a in rec["attempts"]]
    assert ("forward", "died_mid_stream", victim_rid) in kinds, kinds
    assert ("resume", "ok", survivor_rid) in kinds, kinds
    assert pm["replicas"][victim_rid] == {"error": "unreachable"}, (
        pm["replicas"][victim_rid])
    leg = pm["replicas"][survivor_rid]
    assert leg.get("req_id") == RID and leg.get("state") == "finished", leg

    # (1) ONE merged Perfetto trace, offset-aligned, one trace id across
    # both the router track and the survivor's shifted track
    st, body = get(rport, "/router/trace")
    assert st == 200, f"/router/trace -> {st}"
    merged = json.loads(body)
    other = merged["otherData"]
    assert other["replicas_merged"] >= 1, other  # victim is dead
    clk = other["clock"][survivor_rid]
    assert clk["aligned"] is True, clk
    assert abs(clk["offset_s"]) <= max(clk["uncertainty_s"], 0.5), clk
    events = merged["traceEvents"]
    body_ts = [e["ts"] for e in events if e.get("ph") != "M"]
    assert body_ts == sorted(body_ts), "merged trace not globally sorted"
    ours = [e for e in events
            if (e.get("args") or {}).get("trace_id") == tid]
    pids = {e["pid"] for e in ours}
    assert 1 in pids and any(p > 1 for p in pids), (
        f"trace {tid} missing a router or replica leg: pids={pids}")
    router_names = {e["name"] for e in ours if e["pid"] == 1}
    for want in ("connect", "proxy", "failover.attempt", "resume",
                 "journal"):
        assert want in router_names, (want, router_names)

    # (2) federated exposition: relabeled survivor series + fleet rollups
    # + per-replica scrape staleness (the victim, SIGKILLed mid-scrape
    # cadence, must read STALE via its last-known series — not vanish)
    st, mtext = get(rport, "/metrics")
    assert st == 200, f"/metrics -> {st}"
    assert f'replica="{survivor_rid}"' in mtext, (
        "survivor series not relabeled")
    assert "dllama_fleet_" in mtext, "no pre-aggregated fleet families"
    assert (f'dllama_fleet_scrape_age_seconds{{replica="{survivor_rid}"}}'
            in mtext), "no staleness gauge for the survivor"
    assert mtext.endswith("\n"), "exposition must end with a newline"
    st2, mtext2 = get(rport, "/router/metrics")
    assert st2 == 200, "/router/metrics alias gone"

    # fleet join sees the survivor's clock too
    st, body = get(rport, "/router/fleet")
    fleet = json.loads(body)
    assert st == 200 and fleet["fleet"]["replicas"] == 2, fleet
    surv = next(r for r in fleet["replicas"] if r["id"] == survivor_rid)
    assert surv["clock"] is not None, surv

    print(f"PASS: fleet smoke OK — SIGKILL of :{killed['port']} mid-stream; "
          f"postmortem joined forward/died_mid_stream + resume/ok under "
          f"trace {tid}; merged trace carries both legs "
          f"(pids={sorted(pids)}) with survivor clock offset "
          f"{clk['offset_s']:+.4f}s (±{clk['uncertainty_s']:.4f}s); "
          f"federation relabeled replica=\"{survivor_rid}\"")
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PY
