#!/usr/bin/env bash
# slo_smoke.sh — end-to-end SLO & saturation observability smoke (ISSUE 7).
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with SLO targets armed
# (--slo-ttft-ms / --slo-itl-ms, loose enough for a CPU box), waits for
# /health/ready, runs ONE chat completion, then asserts GET /debug/perf
# shows the whole join populated:
#
#   * a TTFT window with count >= 1 and non-null p50/p95/p99,
#   * scheduler time-ledger totals that are nonzero AND partition loop
#     wall time (covered ≈ wall within 2%),
#   * a priced roofline view (chunks > 0, bandwidth attainment non-null),
#   * SLO accounting against the armed targets (attainment = 1.0),
#   * process self-metrics (uptime/RSS/threads) here and on /health.
#
# This is a SMOKE TARGET, not a pytest test: exempt from the tier-1
# `-m 'not slow'` run (it lives outside tests/), meant for CI smoke stages
# or manual runs:
#
#     scripts/slo_smoke.sh
#
# CPU-only, no model download, ~1 min (XLA compile dominates). Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_slo_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--slo-ttft-ms", "120000", "--slo-itl-ms", "120000",
     "--log-format", "json"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 8, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}"
    assert body["usage"]["completion_tokens"] > 0

    st, text = get("/debug/perf")
    assert st == 200, f"/debug/perf -> {st}"
    doc = json.loads(text)
    assert doc["mode"] == "continuous", doc.get("mode")

    win = doc["window"]["ttft"]
    assert win["count"] >= 1, f"empty TTFT window: {win}"
    for p in ("p50", "p95", "p99"):
        assert win[p] is not None and win[p] > 0, f"TTFT {p} missing: {win}"

    led = doc["ledger"]
    covered, wall = led["covered_s"], led["wall_s"]
    assert wall > 0 and covered > 0, led
    resid = abs(covered - wall) / wall
    assert resid <= 0.02, f"ledger partition broken: covered={covered} wall={wall}"
    assert led["seconds"]["decode_wait"] > 0, "no decode time attributed"
    assert led["seconds"]["prefill"] > 0, "no prefill time attributed"

    roof = doc["roofline"]
    assert roof["priced"] and roof["window_chunks"] > 0, roof
    assert roof["bandwidth_attainment"] is not None, roof
    assert roof["throughput_tok_s"] >= roof["goodput_tok_s"] >= 0, roof

    slo = doc["slo"]
    assert slo["enabled"] and slo["targets"]["ttft_ms"] == 120000.0, slo
    assert slo["attainment"] == 1.0, f"tiny greedy request missed a 2-min SLO? {slo}"

    proc_m = doc["process"]
    assert proc_m["uptime_s"] > 0 and proc_m["threads"] >= 2, proc_m
    st, htext = get("/health")
    assert st == 200 and json.loads(htext)["process"]["rss_bytes"] > 0

    print(f"PASS: /debug/perf joined — ttft window n={win['count']} "
          f"p50={win['p50']}ms, ledger residual {resid:.4%} "
          f"(decode_wait {led['seconds']['decode_wait']:.3f}s of "
          f"{wall:.3f}s wall), roofline chunks={roof['window_chunks']} "
          f"attainment={roof['bandwidth_attainment']}, "
          f"slo attainment={slo['attainment']}")
finally:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
