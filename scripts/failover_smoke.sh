#!/usr/bin/env bash
# failover_smoke.sh — the ISSUE 16 acceptance drill: ZERO-LOSS mid-stream
# failover over the real wire.
#
# Boots TWO `python -m dllama_tpu serve` replicas (real CLI, tiny fixture
# model, paged layout + a small host-RAM KV spill tier) plus one
# `python -m dllama_tpu router` fronting them with --failover-max 2, then:
#
#   1. streams a pinned greedy completion to an uninterrupted baseline
#      (include_token_ids on, fixed seed) and records every token id;
#   2. re-streams the SAME request through the router and SIGKILLs the
#      replica serving it the moment its first content frames arrive;
#   3. asserts the client's single SSE stream still completed with EXACTLY
#      the baseline's token ids and text — zero lost, zero duplicated —
#      with at most one in-band `: retrying` comment as the only evidence,
#      the router's failovers{outcome="resumed"} counter advancing, and the
#      survivor's /debug/kv audit clean (device AND host tier reconciled)
#      after the resume re-prefilled the journaled prefix.
#
# SMOKE TARGET, not a pytest test (lives outside tests/, exempt from the
# tier-1 run). CPU-only, ~2 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_failover_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


ports = [free_port(), free_port()]
rport = free_port()

replicas = {
    p: subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
         "--tokenizer", tpath, "--slots", "2", "--port", str(p),
         "--kv-layout", "paged", "--page-size", "8",
         "--kv-pages", "56", "--kv-host-pages", "4"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for p in ports
}
router = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "router", "--port", str(rport),
     "--replica", f"127.0.0.1:{ports[0]}",
     "--replica", f"127.0.0.1:{ports[1]}",
     "--poll-s", "0.2", "--failover-max", "2"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


BODY = {"messages": [
            {"role": "system", "content": "You are a terse assistant."},
            {"role": "user", "content": "stream me a dozen tokens"}],
        "stream": True, "max_tokens": 12, "temperature": 0.0, "seed": 11,
        "include_token_ids": True}


def parse(raw):
    """-> (token_ids, text, finish_reason, saw_done, retry_comments)"""
    ids, text, finish = [], [], None
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        ev = json.loads(line[6:])
        if "error" in ev:
            finish = "error"
            continue
        ids.extend(ev.get("token_ids") or [])
        ch = (ev.get("choices") or [{}])[0]
        text.append((ch.get("delta") or {}).get("content") or "")
        finish = ch.get("finish_reason") or finish
    return (ids, "".join(text), finish,
            raw.rstrip().endswith("data: [DONE]"),
            raw.count(": retrying"))


def stream(port, body, on_frames=None):
    """Stream a completion; call on_frames(n_data_frames) after each read."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200, f"stream -> {resp.status}: {resp.read()!r}"
    raw = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        raw += chunk
        if on_frames is not None:
            on_frames(raw.count(b"data: "))
    conn.close()
    return raw.decode()


procs = list(replicas.values()) + [router]
try:
    deadline = time.time() + 300  # two first-boot XLA compiles on CPU
    while True:
        try:
            st, body = get(rport, "/router/replicas")
            reps = json.loads(body)["replicas"] if st == 200 else []
        except (OSError, ValueError):
            reps = []
        if len(reps) == 2 and all(r["ready"] and r["config_ok"]
                                  for r in reps):
            break
        for proc in procs:
            if proc.poll() is not None:
                sys.exit("FAIL: a process exited before the mesh was ready")
        if time.time() > deadline:
            sys.exit("FAIL: router mesh never became ready")
        time.sleep(0.25)

    # (1) uninterrupted baseline, straight off a replica
    base_ids, base_text, base_fin, base_done, _ = parse(
        stream(ports[0], BODY))
    assert base_done and base_fin in ("stop", "length"), (
        f"baseline did not terminate cleanly: {base_fin}")
    assert base_ids, "baseline produced no token ids"

    st, mtext = get(rport, "/metrics")
    resumed0 = 0.0
    m = re.search(r'dllama_router_failovers_total\{outcome="resumed"\} '
                  r'([0-9.e+-]+)', mtext)
    if m:
        resumed0 = float(m.group(1))

    # (2) same request through the router; SIGKILL the serving replica the
    # moment real content frames are on the wire (role delta + >=2 tokens)
    killed = {"port": None}

    def assassin(n_frames):
        if killed["port"] is None and n_frames >= 3:
            # whichever replica holds an inflight stream is the victim
            st, body = get(rport, "/router/replicas")
            for r in json.loads(body)["replicas"]:
                if r["inflight"] > 0:
                    p = int(r["id"].rsplit(":", 1)[1])
                    replicas[p].kill()
                    killed["port"] = p
                    return

    raw = stream(rport, BODY, on_frames=assassin)
    assert killed["port"] is not None, (
        "the drill never found an inflight replica to SIGKILL — "
        "the stream finished too fast to interrupt")
    replicas[killed["port"]].wait(timeout=10)
    ids, text, fin, done, retries = parse(raw)

    # (3) zero loss, zero duplication, bit-exact vs the baseline
    assert done and fin == base_fin, f"failover stream ended {fin!r}"
    assert ids == base_ids, (
        f"token loss/duplication across failover:\n  base {base_ids}\n"
        f"  got  {ids}")
    assert text == base_text, "text diverged across failover"
    assert retries <= 1, f"{retries} retry comments (max 1 allowed)"

    st, mtext = get(rport, "/metrics")
    m = re.search(r'dllama_router_failovers_total\{outcome="resumed"\} '
                  r'([0-9.e+-]+)', mtext)
    assert m and float(m.group(1)) >= resumed0 + 1, (
        "failovers{outcome=resumed} never advanced")

    # (4) the survivor that absorbed the resume audits clean, both tiers
    survivor = next(p for p in ports if p != killed["port"])
    st, body = get(survivor, "/debug/kv")
    kv = json.loads(body)
    assert st == 200 and kv.get("audit", {}).get("ok") is True, (
        f"survivor /debug/kv audit not clean: {kv.get('audit')}")

    print(f"PASS: failover smoke OK — SIGKILL of :{killed['port']} "
          f"mid-stream, client stream stayed byte-identical to the "
          f"uninterrupted baseline ({len(base_ids)} tokens, "
          f"finish={base_fin}, {retries} retry comment), survivor "
          f":{survivor} KV audit clean")
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PY
