#!/usr/bin/env bash
# overlap_smoke.sh — end-to-end overlapped-decode-pipeline smoke target.
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with the overlapped
# decode pipeline explicitly enabled (`--overlap on`), waits for
# /health/ready, runs ONE chat completion, scrapes /metrics, and asserts
# the dllama_decode_host_gap_seconds histogram populated — proving the
# scheduler really drove decode through the dispatch/consume pipeline and
# the host-gap instrumentation end to end. Finishes with a SIGTERM drain.
#
# This is a SMOKE TARGET, not a pytest test: it is exempt from the tier-1
# `-m 'not slow'` pytest run (it lives outside tests/) and is meant for CI
# smoke stages or manual runs:
#
#     scripts/overlap_smoke.sh
#
# CPU-only, no model download, ~1 min (XLA compile dominates). Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_osmoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--overlap", "on",
     "--port", str(port), "--log-format", "json"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def counter(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 16, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}"
    done = body["usage"]["completion_tokens"]
    assert done > 0

    st, metrics_text = get("/metrics")
    assert st == 200, f"/metrics -> {st}"
    gaps = counter(metrics_text, "dllama_decode_host_gap_seconds_count")
    chunks = counter(metrics_text, "dllama_decode_chunk_seconds_count")
    # a multi-chunk completion must have recorded at least one inter-chunk
    # host gap (the gap is stamped at every dispatch after the first consume)
    assert chunks >= 2, f"expected >=2 decode chunks, saw {chunks}"
    assert gaps >= 1, (
        "dllama_decode_host_gap_seconds never populated: the overlapped "
        "pipeline's dispatch-time instrumentation did not run")
    print(f"PASS: {done} tokens over {chunks:.0f} chunks, "
          f"{gaps:.0f} host-gap samples recorded (--overlap on)")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
