#!/usr/bin/env bash
# spec_smoke.sh — end-to-end speculative-serving smoke target (ISSUE 11).
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with --spec-k armed,
# runs one SPECULATING greedy completion and one spec_k=0 sampled
# completion CONCURRENTLY (mixed spec/non-spec traffic in one batch), and
# asserts:
#
#   * the acceptance counters advanced: dllama_spec_cycles_total and
#     dllama_spec_tokens_total{kind="emitted"} are live, and the greedy
#     response's `timings.spec` object reports its per-request record;
#   * the spec_k=0 request carries NO spec object (per-request opt-out);
#   * GET /debug/kv answers 200 with a CLEAN audit — spec verify wrote
#     k+1 draft rows past live positions all run long and no draft ever
#     landed in a shared page (the write-horizon invariant, through the
#     real serving surface with the paged default + radix cache ON).
#
# Finishes with a SIGTERM drain. SMOKE TARGET, not a pytest test (lives
# outside tests/, exempt from the tier-1 run). CPU-only, ~1 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_spec_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--kv-layout", "paged", "--page-size", "8", "--spec-k", "4"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def metric(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


def complete(body, out):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=180)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}: {payload}"
    assert payload["usage"]["completion_tokens"] > 0
    out.append(payload)


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    # concurrent mixed traffic: a greedy request speculating at the
    # --spec-k default, and a sampled request opting out via body spec_k=0
    spec_out, plain_out = [], []
    t1 = threading.Thread(target=complete, args=(
        {"messages": [{"role": "user",
                       "content": "one two three one two three one two"}],
         "max_tokens": 24, "temperature": 0.0}, spec_out))
    t2 = threading.Thread(target=complete, args=(
        {"messages": [{"role": "user", "content": "tell me something new"}],
         "max_tokens": 16, "temperature": 0.9, "seed": 7, "spec_k": 0},
        plain_out))
    t1.start(); t2.start(); t1.join(); t2.join()

    spec_t = spec_out[0]["timings"]
    assert "spec" in spec_t and spec_t["spec"]["cycles"] > 0, (
        f"greedy request carried no spec record: {spec_t}")
    assert spec_t["spec"]["spec_k"] == 4
    assert "spec" not in plain_out[0]["timings"], (
        "spec_k=0 request must not carry a spec record")

    st, m1 = get("/metrics")
    assert st == 200
    cycles = metric(m1, "dllama_spec_cycles_total")
    assert cycles > 0, "dllama_spec_cycles_total never advanced"
    assert re.search(r'dllama_spec_tokens_total\{kind="emitted"\} [1-9]',
                     m1), "no emitted-labelled spec tokens in /metrics"

    st, perf = get("/debug/perf")
    perf = json.loads(perf)
    assert st == 200 and perf.get("spec", {}).get("cycles", 0) > 0, (
        f"/debug/perf spec record missing: {perf.get('spec')}")

    st, kv = get("/debug/kv")
    kv = json.loads(kv)
    assert st == 200 and kv["audit"]["ok"], f"/debug/kv audit: {kv}"
    print(f"PASS: spec serve OK — {cycles:.0f} verify cycles, per-request "
          f"tokens/cycle={spec_t['spec']['tokens_per_cycle']}; "
          f"/debug/kv audit clean with draft writes all run long")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
