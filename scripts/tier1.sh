#!/usr/bin/env bash
# Tier-1 verify — the EXACT command from ROADMAP.md, wrapped so builders and
# CI run the same line (drift between "what I ran" and "what the roadmap
# says" is how green-locally/red-in-CI happens). Prints DOTS_PASSED (the
# count of passing tests that fit in the time budget) and exits with
# pytest's status (124 = the suite hit the timeout, which the budgeted
# full-suite run is allowed to do).
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
