#!/usr/bin/env bash
# analysis_smoke.sh — proves the invariant analyzer (ISSUE 14) actually
# gates: a pristine copy of the tree passes, then a seeded violation in a
# temp file is caught with the CORRECT file:line and exit 1. A gate that
# cannot fail is decoration; this script is the analyzer's own drill.
#
# Pure host, stdlib-only, seconds: copies the analyzed file set to a temp
# root, runs `python -m dllama_tpu.analysis --root` twice.
set -euo pipefail
cd "$(dirname "$0")/.."

tmp="$(mktemp -d)"
trap 'rm -rf "$tmp"' EXIT

# the analyzed file set (Project.from_disk + the gate/doc extras)
cp -r dllama_tpu "$tmp/dllama_tpu"
rm -rf "$tmp"/dllama_tpu/__pycache__ "$tmp"/dllama_tpu/*/__pycache__ \
       "$tmp"/dllama_tpu/*/*/__pycache__ 2>/dev/null || true
cp README.md bench.py "$tmp/"
mkdir -p "$tmp/experiments" "$tmp/scripts"
cp experiments/perfdiff.py experiments/aot_check.py "$tmp/experiments/"
cp scripts/hybrid_smoke.sh scripts/compile_smoke.sh \
   scripts/analysis_smoke.sh scripts/router_smoke.sh \
   scripts/failover_smoke.sh scripts/chaos_soak.sh "$tmp/scripts/"

echo "analysis_smoke: pristine copy must pass"
python -m dllama_tpu.analysis --root "$tmp"

# seed one violation per family shape: an off-catalog fault point (line 3
# of the seeded file) and an unscoped cached-jit dispatch (line 11)
seed="$tmp/dllama_tpu/engine/_seeded_violation.py"
cat > "$seed" <<'PY'
import jax
from dllama_tpu.utils import faults
faults.fire("not.a.real.point")


class Seeded:
    def __init__(self):
        self._decode = jax.jit(lambda x: x)

    def decode(self, x):
        return self._decode(x)
PY

echo "analysis_smoke: seeded violations must be caught at file:line"
set +e
out="$(python -m dllama_tpu.analysis --root "$tmp" 2>&1)"
rc=$?
set -e
echo "$out"
[ "$rc" -eq 1 ] || {
    echo "analysis_smoke: expected exit 1 on a seeded violation, got $rc" >&2
    exit 1; }
echo "$out" | grep -q "_seeded_violation.py:3: catalog-fault" || {
    echo "analysis_smoke: catalog-fault not reported at line 3" >&2
    exit 1; }
echo "$out" | grep -q "_seeded_violation.py:11: jit-scope" || {
    echo "analysis_smoke: jit-scope not reported at line 11" >&2
    exit 1; }
echo "analysis_smoke: PASS (pristine clean; seeded catalog-fault + jit-scope caught, exit 1)"
