#!/usr/bin/env bash
# radix_smoke.sh — end-to-end radix prefix-cache smoke target (ISSUE 9).
#
# Boots `python -m dllama_tpu serve` (the real CLI, not an in-process
# server) on a freshly generated tiny fixture model with the paged layout
# (where --radix-cache auto resolves ON), runs TWO chat completions that
# share a long system prompt, and asserts:
#
#   * the second completion HIT the tree: dllama_radix_hit_tokens_total
#     advanced and dllama_radix_lookups_total{outcome="hit"} is live;
#   * GET /debug/radix shows an enabled cache with live nodes/pages;
#   * GET /debug/kv answers 200 with a CLEAN audit — the tree's page refs
#     reconcile exactly against the pool refcounts through the real
#     serving surface.
#
# Finishes with a SIGTERM drain. SMOKE TARGET, not a pytest test (lives
# outside tests/, exempt from the tier-1 run). CPU-only, ~1 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_radix_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))

with socket.socket() as s:  # pick a free port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

proc = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
     "--tokenizer", tpath, "--slots", "2", "--port", str(port),
     "--kv-layout", "paged", "--page-size", "8", "--radix-cache", "auto"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
)


def get(path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def metric(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


def complete(user):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [
                     {"role": "system", "content":
                      "You are a careful, thorough assistant who always "
                      "answers in complete sentences and cites sources "
                      "whenever they are available to you."},
                     {"role": "user", "content": user}],
                     "max_tokens": 6, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}: {body}"
    assert body["usage"]["completion_tokens"] > 0
    return body


try:
    deadline = time.time() + 120  # first-boot XLA compiles on CPU are slow
    while True:
        try:
            if get("/health/ready")[0] == 200:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            sys.exit("FAIL: server exited before becoming ready")
        if time.time() > deadline:
            sys.exit("FAIL: server never became ready")
        time.sleep(0.25)

    complete("hello there")  # cold: prefills + inserts the shared prefix
    st, m0 = get("/metrics")
    assert st == 200
    hits0 = metric(m0, "dllama_radix_hit_tokens_total")

    complete("different question")  # warm: must map the system prefix
    st, m1 = get("/metrics")
    hits1 = metric(m1, "dllama_radix_hit_tokens_total")
    assert hits1 > hits0, (
        f"radix hit counter never advanced ({hits0} -> {hits1}); the "
        "second completion should have mapped the shared system prompt")
    assert re.search(r'dllama_radix_lookups_total\{outcome="hit"\} [1-9]',
                     m1), "no hit-labelled lookup in /metrics"

    st, radix = get("/debug/radix")
    radix = json.loads(radix)
    assert st == 200 and radix["enabled"], f"/debug/radix: {radix}"
    assert radix["stats"]["nodes"] > 0 and radix["stats"]["pages"] > 0

    st, kv = get("/debug/kv")
    kv = json.loads(kv)
    assert st == 200 and kv["audit"]["ok"], f"/debug/kv audit: {kv}"
    assert kv["audit"]["radix_pages"] > 0, (
        "audit reconciled without any tree refs — radix not live?")
    print(f"PASS: radix serve OK — saved {hits1 - hits0:.0f} prefill tokens "
          f"on the warm request; tree nodes={radix['stats']['nodes']} "
          f"pages={radix['stats']['pages']}; /debug/kv audit clean")
finally:
    proc.send_signal(signal.SIGTERM)  # exercises the graceful drain path
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
PY
