#!/usr/bin/env bash
# perf_gate.sh OLD.json NEW.json [extra perfdiff args] — the mechanical
# bench regression gate (ISSUE 7): diffs two BENCH_r*.json records
# field-by-field with per-metric directions and tolerances
# (experiments/perfdiff.py owns the rule table) and exits with the verdict:
#
#   0  no gated metric regressed (a self-diff always passes)
#   1  at least one gated regression (the printed table names them)
#   2  usage / unreadable / malformed input
#
# $PERFDIFF_SCALE multiplies every trend tolerance (e.g. 2 on a noisy CPU
# fallback host); invariant ceilings (ledger residual) are never scaled.
# Typical round-close usage:   scripts/perf_gate.sh BENCH_r06.json BENCH_r07.json
set -euo pipefail
cd "$(dirname "$0")/.."

if [ $# -lt 2 ]; then
  echo "usage: scripts/perf_gate.sh OLD.json NEW.json [--json] [--scale F]" >&2
  exit 2
fi
exec python experiments/perfdiff.py "$1" "$2" \
  ${PERFDIFF_SCALE:+--scale "$PERFDIFF_SCALE"} "${@:3}"
