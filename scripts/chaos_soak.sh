#!/usr/bin/env bash
# chaos_soak.sh — the ISSUE 6 acceptance soak: >=200 mixed greedy/sampled/
# penalized/deadline requests through a warm-restart-enabled paged scheduler
# under a seeded randomized fault schedule (crashes, delays, pool-alloc
# failures, NaN injections). Asserts: 100% terminal finish reasons, a clean
# PagePool.audit() with zero leaked pages, /health recovered to live+ready,
# and restart/recovered/timeout counters reconciled against the flight
# recorder. Exit 0 = survived.
#
#   CHAOS_REQUESTS=200 CHAOS_SEED=0 scripts/chaos_soak.sh
#
# CPU-only and hermetic (tiny random-weight model, no model files). The
# fast bounded variant runs in tier-1 as tests/test_chaos.py.
#
# ISSUE 16 additions: the in-proc soak now runs with the host-RAM KV spill
# tier armed (CHAOS_HOST_PAGES device-overflow pages, default 6) so every
# radix eviction under pool pressure exercises the d2h spill path, and
# CHAOS_MESH=N (N>=2) runs a SECOND, multi-replica soak: a router over N
# real CLI replica subprocesses under randomized SIGKILL/SIGSTOP/slow-poll,
# asserting 100% terminal streams with zero duplicate/dropped token
# positions, clean /debug/kv audits on every survivor (device AND host
# tier), and router failover counters reconciled against the client view.
#
#   CHAOS_REQUESTS=200 CHAOS_SEED=0 CHAOS_MESH=3 scripts/chaos_soak.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# DLLAMA_LOCK_AUDIT=1 (ISSUE 14): the soak's five-plus concurrent threads
# (clients, worker, watchdog, scrapes) run with the lock-order sanitizer
# armed — a rank inversion raises at the acquisition, with both sites named
env JAX_PLATFORMS=cpu DLLAMA_POOL_AUDIT=1 DLLAMA_LOCK_AUDIT=1 \
    python experiments/chaos.py \
    --requests "${CHAOS_REQUESTS:-200}" \
    --seed "${CHAOS_SEED:-0}" \
    --clients "${CHAOS_CLIENTS:-4}" \
    --kv-host-pages "${CHAOS_HOST_PAGES:-6}"

if [ "${CHAOS_MESH:-0}" -gt 0 ]; then
    env JAX_PLATFORMS=cpu DLLAMA_POOL_AUDIT=1 \
        python experiments/chaos.py \
        --mesh "${CHAOS_MESH}" \
        --requests "${CHAOS_MESH_REQUESTS:-30}" \
        --seed "${CHAOS_SEED:-0}" \
        --clients "${CHAOS_CLIENTS:-3}" \
        --kv-host-pages "${CHAOS_HOST_PAGES:-4}"
fi
