#!/usr/bin/env bash
# chaos_soak.sh — the ISSUE 6 acceptance soak: >=200 mixed greedy/sampled/
# penalized/deadline requests through a warm-restart-enabled paged scheduler
# under a seeded randomized fault schedule (crashes, delays, pool-alloc
# failures, NaN injections). Asserts: 100% terminal finish reasons, a clean
# PagePool.audit() with zero leaked pages, /health recovered to live+ready,
# and restart/recovered/timeout counters reconciled against the flight
# recorder. Exit 0 = survived.
#
#   CHAOS_REQUESTS=200 CHAOS_SEED=0 scripts/chaos_soak.sh
#
# CPU-only and hermetic (tiny random-weight model, no model files). The
# fast bounded variant runs in tier-1 as tests/test_chaos.py.
set -euo pipefail
cd "$(dirname "$0")/.."

# DLLAMA_LOCK_AUDIT=1 (ISSUE 14): the soak's five-plus concurrent threads
# (clients, worker, watchdog, scrapes) run with the lock-order sanitizer
# armed — a rank inversion raises at the acquisition, with both sites named
exec env JAX_PLATFORMS=cpu DLLAMA_POOL_AUDIT=1 DLLAMA_LOCK_AUDIT=1 \
    python experiments/chaos.py \
    --requests "${CHAOS_REQUESTS:-200}" \
    --seed "${CHAOS_SEED:-0}" \
    --clients "${CHAOS_CLIENTS:-4}"
