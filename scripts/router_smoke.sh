#!/usr/bin/env bash
# router_smoke.sh — end-to-end multi-replica router smoke target (ISSUE 15).
#
# Boots TWO `python -m dllama_tpu serve` replicas (the real CLI, tiny
# fixture model, paged layout so the radix cache is ON) plus one
# `python -m dllama_tpu router` process fronting them, and drills the
# subsystem's three claims over the wire:
#
#   * prefix-affinity: concurrent completions sharing a system prompt all
#     land on the SAME replica (X-Replica-Id agrees), and the router's
#     /metrics shows dllama_router_affinity_hits_total advancing;
#   * failover: SIGKILL of the pinned replica — the router's health view
#     flips (dllama_replica_healthy 0, /router/replicas not ready) and the
#     same-prefix traffic keeps completing on the survivor, zero failures;
#   * drain: SIGTERM of the router and the surviving replica exits both
#     cleanly (the graceful-drain path, exit code 0).
#
# SMOKE TARGET, not a pytest test (lives outside tests/, exempt from the
# tier-1 run). CPU-only, ~2 min. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.getcwd())
from tests.test_serve import make_tiny_files  # the tier-1 fixture model

tmp = tempfile.mkdtemp(prefix="dllama_router_smoke_")
mpath, tpath, _cfg = make_tiny_files(__import__("pathlib").Path(tmp))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


ports = [free_port(), free_port()]
rport = free_port()

replicas = [
    subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "serve", "--model", mpath,
         "--tokenizer", tpath, "--slots", "2", "--port", str(p),
         "--kv-layout", "paged", "--page-size", "8"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for p in ports
]
router = subprocess.Popen(
    [sys.executable, "-m", "dllama_tpu", "router", "--port", str(rport),
     "--replica", f"127.0.0.1:{ports[0]}",
     "--replica", f"127.0.0.1:{ports[1]}",
     "--poll-s", "0.2"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


def metric(text, name):
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


SHARED = ("You are a careful, thorough assistant who always answers in "
          "complete sentences and cites sources whenever available.")


def complete(user):
    conn = http.client.HTTPConnection("127.0.0.1", rport, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [
                     {"role": "system", "content": SHARED},
                     {"role": "user", "content": user}],
                     "max_tokens": 6, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    rid = resp.getheader("X-Replica-Id") or ""
    conn.close()
    assert resp.status == 200, f"completion -> {resp.status}: {body}"
    assert body["usage"]["completion_tokens"] > 0
    assert body["timings"].get("replica") == rid, (
        "timings.replica and X-Replica-Id must agree")
    return rid


procs = replicas + [router]
try:
    deadline = time.time() + 180  # two first-boot XLA compiles on CPU
    while True:
        try:
            ready = get(rport, "/health/ready")[0] == 200
        except OSError:
            ready = False
        if ready:
            break
        for proc in procs:
            if proc.poll() is not None:
                sys.exit("FAIL: a process exited before the mesh was ready")
        if time.time() > deadline:
            sys.exit("FAIL: router mesh never became ready")
        time.sleep(0.25)

    # (1) shared system prompt -> every completion lands on ONE replica
    rids = {complete(f"question {i}") for i in range(4)}
    assert len(rids) == 1, f"affinity scattered the shared prefix: {rids}"
    pinned = rids.pop()
    st, mtext = get(rport, "/metrics")
    assert st == 200
    hits = metric(mtext, "dllama_router_affinity_hits_total")
    assert hits >= 3, f"affinity hits never advanced: {hits}"
    assert re.search(r'dllama_router_requests_total\{[^}]*outcome="ok"',
                     mtext), "no ok-labelled router request in /metrics"

    # (2) SIGKILL the pinned replica: health flips, traffic survives
    victim_idx = next(i for i, p in enumerate(ports)
                      if f"127.0.0.1:{p}" == pinned)
    replicas[victim_idx].kill()
    replicas[victim_idx].wait(timeout=10)
    survivor_rid = complete("after the kill")  # reroutes on first touch
    assert survivor_rid != pinned, "request answered by a dead replica?"
    for i in range(2):
        assert complete(f"post-failover {i}") == survivor_rid
    deadline = time.time() + 10
    while True:  # poller flips the gauge within ~poll_s
        st, mtext = get(rport, "/metrics")
        down = re.search(
            rf'dllama_replica_healthy\{{replica="{re.escape(pinned)}"\}} 0',
            mtext)
        if down:
            break
        if time.time() > deadline:
            sys.exit("FAIL: dllama_replica_healthy never flipped to 0 "
                     "for the killed replica")
        time.sleep(0.25)
    st, reg = get(rport, "/router/replicas")
    reps = {r["id"]: r for r in json.loads(reg)["replicas"]}
    assert reps[pinned]["ready"] is False, "registry still routes the dead"
    st, _ = get(rport, "/health/ready")
    assert st == 200, "router must stay ready on the surviving replica"

    # (3) SIGTERM drains the router and the surviving replica cleanly
    router.send_signal(signal.SIGTERM)
    assert router.wait(timeout=30) == 0, "router drain exited non-zero"
    survivor = replicas[1 - victim_idx]
    survivor.send_signal(signal.SIGTERM)
    assert survivor.wait(timeout=30) == 0, "replica drain exited non-zero"
    print(f"PASS: router smoke OK — shared prefix pinned to {pinned} "
          f"({hits:.0f} affinity hits), SIGKILL failover to {survivor_rid} "
          "with zero failed requests, health flipped, drains clean")
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PY
