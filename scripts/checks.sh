#!/usr/bin/env bash
# checks.sh — static hygiene gate for CI and pre-commit:
#
#   1. `python -m compileall` over the package, tests, and bench — syntax
#      errors fail here in milliseconds instead of mid-suite;
#   2. observability catalog drift check — every metric registered in
#      dllama_tpu/obs/instruments.py, every span/event name in
#      dllama_tpu/obs/trace.{SPAN,EVENT}_CATALOG, and every fault-injection
#      point in dllama_tpu/utils/faults.POINTS must appear in README.md.
#      The catalogs are the single definition sites; this keeps the docs
#      from silently rotting when an instrument, a trace point, or a fault
#      point is added. (These syncs genuinely need the live registry
#      import, so they stay here.)
#   3. the repo-native invariant analyzer (ISSUE 14) as a HARD gate:
#      `python -m dllama_tpu.analysis` — jit-dispatch discipline,
#      device-state writes, single-site catalogs, the steady-state
#      transfer lint, the static lock-order graph, and the textual
#      contracts this script used to grep for (paged routes, bench
#      records, perfdiff rules, the AOT inventory), all with file:line
#      diagnostics. scripts/analysis_smoke.sh drills that the gate can
#      actually fail.
#
# Pure host: imports only dllama_tpu.obs/analysis (stdlib-only — no jax,
# no model), so it runs anywhere in seconds. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q dllama_tpu tests scripts bench.py
echo "checks: compileall OK"

python - <<'PY'
import sys

from dllama_tpu.obs import metrics  # noqa: F401  (registry core)
from dllama_tpu.obs import instruments  # noqa: F401  (registers every metric)
from dllama_tpu.obs import trace

with open("README.md", encoding="utf-8") as f:
    readme = f.read()

missing = []
for name in metrics.REGISTRY.names():
    if name not in readme:
        missing.append(f"metric:{name}")
# the paged-KV pool gauges are load-bearing for capacity operations (ISSUE 5
# acceptance reads dllama_kv_pages_shared), and the radix prefix-cache
# series are what scripts/radix_smoke.sh and the bench radix record assert
# on (ISSUE 9): their REMOVAL from the registry must fail here too, not
# just their absence from the README
# ...the speculative-decoding acceptance series are what
# scripts/spec_smoke.sh and the bench spec_batch record assert on
# (ISSUE 11): removal from the registry must fail here too
# ...and the hybrid/preemption series are what scripts/hybrid_smoke.sh and
# the bench hybrid record assert on (ISSUE 12): removal must fail here too
# ...and the compile-ledger / transfer series are what
# scripts/compile_smoke.sh, the bench compile record, and the perfdiff
# zero-ceilings assert on (ISSUE 13): removal must fail here too
# ...and the router / aio-front-end series are what
# scripts/router_smoke.sh, the bench router record, and the test_aio
# bounded-thread drill assert on (ISSUE 15): removal must fail here too
# ...and the failover / host-spill-tier series are what
# scripts/failover_smoke.sh, the chaos mesh, and the test_paged_kv host
# drills assert on (ISSUE 16): removal must fail here too
# ...and the clock-offset / federation-scrape series are what
# scripts/fleet_smoke.sh, the bench fleet_obs record, and the
# test_fleet_obs merge/federation drills assert on (ISSUE 17): removal
# must fail here too
# ...and the scrape-staleness / client-seat SLO series are what the
# federated /metrics staleness contract and GET /router/fleet
# reconciliation stand on (ISSUE 19): removal must fail here too
for name in ("dllama_kv_pages_total", "dllama_kv_pages_used",
             "dllama_kv_pages_shared",
             "dllama_radix_lookups_total", "dllama_radix_hit_tokens_total",
             "dllama_radix_nodes", "dllama_radix_pages",
             "dllama_spec_cycles_total", "dllama_spec_tokens_total",
             "dllama_spec_accepted_length",
             "dllama_prefill_budget_tokens", "dllama_preemptions_total",
             "dllama_resumed_total",
             "dllama_jit_compiles_total", "dllama_jit_compile_seconds_total",
             "dllama_jit_unexpected_compiles_total",
             "dllama_transfers_total", "dllama_transfer_bytes_total",
             "dllama_device_live_buffers", "dllama_device_live_bytes",
             "dllama_router_requests_total",
             "dllama_router_affinity_hits_total",
             "dllama_replica_healthy", "dllama_frontend_connections",
             "dllama_router_failovers_total",
             "dllama_kv_host_pages_total", "dllama_kv_host_pages_used",
             "dllama_kv_spill_total",
             "dllama_replica_clock_offset_seconds",
             "dllama_replica_clock_uncertainty_seconds",
             "dllama_router_federation_scrape_seconds",
             "dllama_fleet_scrape_age_seconds",
             "dllama_router_ttft_seconds", "dllama_router_itl_seconds",
             "dllama_router_slo_attainment"):
    if name not in metrics.REGISTRY.names():
        missing.append(f"unregistered:{name}")
for name in sorted(trace.SPAN_CATALOG):
    if name not in readme:
        missing.append(f"span:{name}")
for name in sorted(trace.EVENT_CATALOG):
    if name not in readme:
        missing.append(f"event:{name}")

# fault-injection points (utils/faults.POINTS is the single definition
# site, armed sites call fire()/flag() with these names): each must be
# documented in the README Operations section AND in the faults.py
# docstring table — an undrillable failure path is not a failure path
from dllama_tpu.utils import faults
for name in sorted(faults.POINTS):
    if name not in readme:
        missing.append(f"fault:{name}")
    if name not in (faults.__doc__ or ""):
        missing.append(f"fault-docstring:{name}")

if missing:
    sys.exit("README observability-catalog drift — document these in the "
             "README tables: " + ", ".join(missing))

# scheduler time-ledger states: the README ledger table must match
# obs/perf.LEDGER_STATES EXACTLY (both directions — a renamed state with a
# stale doc row is attribution lying to the operator). The table is the one
# whose header row is "| Ledger state |".
import re

from dllama_tpu.obs import perf

rows, in_table = [], False
for line in readme.splitlines():
    if line.startswith("| Ledger state |"):
        in_table = True
        continue
    if in_table:
        if not line.startswith("|"):
            break
        m = re.match(r"^\| `([a-z_]+)` \|", line)
        if m:
            rows.append(m.group(1))
readme_states, catalog_states = set(rows), set(perf.LEDGER_STATES)
if readme_states != catalog_states:
    sys.exit("ledger state-label drift between obs/perf.LEDGER_STATES and "
             f"the README ledger table: catalog-only="
             f"{sorted(catalog_states - readme_states)} readme-only="
             f"{sorted(readme_states - catalog_states)}")

# compile-fn catalog (ISSUE 13): the README "Compile fn" bucket table must
# match obs/compile.COMPILE_FNS EXACTLY (both directions, like the ledger
# check) — a renamed dispatch-site label with a stale doc row is a contract
# lying to the operator. The table is the one whose header row starts
# "| Compile fn |".
from dllama_tpu.obs import compile as compile_obs

rows, in_table = [], False
for line in readme.splitlines():
    if line.startswith("| Compile fn |"):
        in_table = True
        continue
    if in_table:
        if not line.startswith("|"):
            break
        m = re.match(r"^\| `([a-z_]+)` \|", line)
        if m:
            rows.append(m.group(1))
readme_fns, catalog_fns = set(rows), set(compile_obs.COMPILE_FNS)
if readme_fns != catalog_fns:
    sys.exit("compile-fn label drift between obs/compile.COMPILE_FNS and "
             f"the README bucket table: catalog-only="
             f"{sorted(catalog_fns - readme_fns)} readme-only="
             f"{sorted(readme_fns - catalog_fns)}")

print(f"checks: catalog drift OK ({len(metrics.REGISTRY.names())} metrics, "
      f"{len(trace.SPAN_CATALOG)} spans, {len(trace.EVENT_CATALOG)} events, "
      f"{len(faults.POINTS)} fault points, "
      f"{len(perf.LEDGER_STATES)} ledger states, "
      f"{len(compile_obs.COMPILE_FNS)} compile fns all documented)")
PY

# everything textual that used to be grep'd here — the paged-route README
# table (ISSUE 8), the hybrid/compile bench records and perfdiff rules
# (ISSUES 12/13), the AOT inventory — plus the new invariant rules
# (ISSUE 14) run as ONE analyzer pass with real file:line diagnostics
python -m dllama_tpu.analysis
echo "checks: invariant analyzer OK (jit/device-state/catalog/transfer/lock rules + repo gates)"
