#!/usr/bin/env bash
# checks.sh — static hygiene gate for CI and pre-commit:
#
#   1. `python -m compileall` over the package, tests, and bench — syntax
#      errors fail here in milliseconds instead of mid-suite;
#   2. observability catalog drift check — every metric registered in
#      dllama_tpu/obs/instruments.py, every span/event name in
#      dllama_tpu/obs/trace.{SPAN,EVENT}_CATALOG, and every fault-injection
#      point in dllama_tpu/utils/faults.POINTS must appear in README.md.
#      The catalogs are the single definition sites; this keeps the docs
#      from silently rotting when an instrument, a trace point, or a fault
#      point is added.
#
# Pure host: imports only dllama_tpu.obs (stdlib-only — no jax, no model),
# so it runs anywhere in <1s. Exit 0 = PASS.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q dllama_tpu tests scripts bench.py
echo "checks: compileall OK"

python - <<'PY'
import sys

from dllama_tpu.obs import metrics  # noqa: F401  (registry core)
from dllama_tpu.obs import instruments  # noqa: F401  (registers every metric)
from dllama_tpu.obs import trace

with open("README.md", encoding="utf-8") as f:
    readme = f.read()

missing = []
for name in metrics.REGISTRY.names():
    if name not in readme:
        missing.append(f"metric:{name}")
# the paged-KV pool gauges are load-bearing for capacity operations (ISSUE 5
# acceptance reads dllama_kv_pages_shared), and the radix prefix-cache
# series are what scripts/radix_smoke.sh and the bench radix record assert
# on (ISSUE 9): their REMOVAL from the registry must fail here too, not
# just their absence from the README
# ...the speculative-decoding acceptance series are what
# scripts/spec_smoke.sh and the bench spec_batch record assert on
# (ISSUE 11): removal from the registry must fail here too
# ...and the hybrid/preemption series are what scripts/hybrid_smoke.sh and
# the bench hybrid record assert on (ISSUE 12): removal must fail here too
# ...and the compile-ledger / transfer series are what
# scripts/compile_smoke.sh, the bench compile record, and the perfdiff
# zero-ceilings assert on (ISSUE 13): removal must fail here too
for name in ("dllama_kv_pages_total", "dllama_kv_pages_used",
             "dllama_kv_pages_shared",
             "dllama_radix_lookups_total", "dllama_radix_hit_tokens_total",
             "dllama_radix_nodes", "dllama_radix_pages",
             "dllama_spec_cycles_total", "dllama_spec_tokens_total",
             "dllama_spec_accepted_length",
             "dllama_prefill_budget_tokens", "dllama_preemptions_total",
             "dllama_resumed_total",
             "dllama_jit_compiles_total", "dllama_jit_compile_seconds_total",
             "dllama_jit_unexpected_compiles_total",
             "dllama_transfers_total", "dllama_transfer_bytes_total",
             "dllama_device_live_buffers", "dllama_device_live_bytes"):
    if name not in metrics.REGISTRY.names():
        missing.append(f"unregistered:{name}")
for name in sorted(trace.SPAN_CATALOG):
    if name not in readme:
        missing.append(f"span:{name}")
for name in sorted(trace.EVENT_CATALOG):
    if name not in readme:
        missing.append(f"event:{name}")

# fault-injection points (utils/faults.POINTS is the single definition
# site, armed sites call fire()/flag() with these names): each must be
# documented in the README Operations section AND in the faults.py
# docstring table — an undrillable failure path is not a failure path
from dllama_tpu.utils import faults
for name in sorted(faults.POINTS):
    if name not in readme:
        missing.append(f"fault:{name}")
    if name not in (faults.__doc__ or ""):
        missing.append(f"fault-docstring:{name}")

if missing:
    sys.exit("README observability-catalog drift — document these in the "
             "README tables: " + ", ".join(missing))

# scheduler time-ledger states: the README ledger table must match
# obs/perf.LEDGER_STATES EXACTLY (both directions — a renamed state with a
# stale doc row is attribution lying to the operator). The table is the one
# whose header row is "| Ledger state |".
import re

from dllama_tpu.obs import perf

rows, in_table = [], False
for line in readme.splitlines():
    if line.startswith("| Ledger state |"):
        in_table = True
        continue
    if in_table:
        if not line.startswith("|"):
            break
        m = re.match(r"^\| `([a-z_]+)` \|", line)
        if m:
            rows.append(m.group(1))
readme_states, catalog_states = set(rows), set(perf.LEDGER_STATES)
if readme_states != catalog_states:
    sys.exit("ledger state-label drift between obs/perf.LEDGER_STATES and "
             f"the README ledger table: catalog-only="
             f"{sorted(catalog_states - readme_states)} readme-only="
             f"{sorted(readme_states - catalog_states)}")

# compile-fn catalog (ISSUE 13): the README "Compile fn" bucket table must
# match obs/compile.COMPILE_FNS EXACTLY (both directions, like the ledger
# check) — a renamed dispatch-site label with a stale doc row is a contract
# lying to the operator. The table is the one whose header row starts
# "| Compile fn |".
from dllama_tpu.obs import compile as compile_obs

rows, in_table = [], False
for line in readme.splitlines():
    if line.startswith("| Compile fn |"):
        in_table = True
        continue
    if in_table:
        if not line.startswith("|"):
            break
        m = re.match(r"^\| `([a-z_]+)` \|", line)
        if m:
            rows.append(m.group(1))
readme_fns, catalog_fns = set(rows), set(compile_obs.COMPILE_FNS)
if readme_fns != catalog_fns:
    sys.exit("compile-fn label drift between obs/compile.COMPILE_FNS and "
             f"the README bucket table: catalog-only="
             f"{sorted(catalog_fns - readme_fns)} readme-only="
             f"{sorted(readme_fns - catalog_fns)}")

print(f"checks: catalog drift OK ({len(metrics.REGISTRY.names())} metrics, "
      f"{len(trace.SPAN_CATALOG)} spans, {len(trace.EVENT_CATALOG)} events, "
      f"{len(faults.POINTS)} fault points, "
      f"{len(perf.LEDGER_STATES)} ledger states, "
      f"{len(compile_obs.COMPILE_FNS)} compile fns all documented)")
PY

# paged flash-decode kernel (ISSUE 8): the op must stay registered in the
# AOT Mosaic gate's inventory — deleting the aot_check cases would let a
# Mosaic rejection survive to a live window while kernel_select still
# routes the kernel by default. Textual check (no jax import: this script
# stays sub-second).
grep -q "paged_decode_attention" experiments/aot_check.py || {
    echo "checks: paged_decode_attention missing from the AOT gate" \
         "(experiments/aot_check.py op inventory)" >&2; exit 1; }
grep -q "fused scatter" experiments/aot_check.py || {
    echo "checks: the AOT gate lost its fused-scatter paged cases" >&2
    exit 1; }

# ...and the README routing table must name every route kernel_select can
# resolve the paged layout to (engine/kernel_select.PAGED_ROUTES is the
# definition site; both directions checked textually)
for route in paged_kernel paged_gather; do
    grep -q "\"$route\"" dllama_tpu/engine/kernel_select.py || {
        echo "checks: route '$route' missing from engine/kernel_select.py" \
             "(PAGED_ROUTES drifted?)" >&2; exit 1; }
    grep -q "| \`$route\` |" README.md || {
        echo "checks: README 'Paged KV cache' routing table lost its" \
             "'$route' row" >&2; exit 1; }
done
python - <<'PY'
import re

with open("dllama_tpu/engine/kernel_select.py", encoding="utf-8") as f:
    m = re.search(r"PAGED_ROUTES\s*=\s*\(([^)]*)\)", f.read())
assert m, "PAGED_ROUTES tuple missing from engine/kernel_select.py"
routes = set(re.findall(r'"([a-z_]+)"', m.group(1)))
with open("README.md", encoding="utf-8") as f:
    readme_routes = set(re.findall(r"^\| `([a-z_]+)` \|", f.read(), re.M))
extra = {r for r in readme_routes if r.startswith("paged_")} - routes
missing = routes - readme_routes
if extra or missing:
    raise SystemExit(
        "README paged-routing drift vs kernel_select.PAGED_ROUTES: "
        f"readme-only={sorted(extra)} catalog-only={sorted(missing)}")
print(f"checks: paged kernel AOT registration + routing table OK "
      f"({len(routes)} routes)")
PY

# hybrid chunked prefill + preemption (ISSUE 12): the bench record and the
# perf gate rules must keep covering the fused-step regression surface, and
# the smoke target must keep existing. Textual (sub-second) checks.
grep -q "def bench_hybrid" bench.py || {
    echo "checks: bench.py lost its hybrid record (bench_hybrid)" >&2
    exit 1; }
grep -q "hybrid.stall_reduction_x" experiments/perfdiff.py || {
    echo "checks: perfdiff rules lost hybrid.stall_reduction_x" >&2
    exit 1; }
grep -q "hybrid.ttft_overhead_x" experiments/perfdiff.py || {
    echo "checks: perfdiff rules lost hybrid.ttft_overhead_x" >&2; exit 1; }
test -x scripts/hybrid_smoke.sh || {
    echo "checks: scripts/hybrid_smoke.sh missing or not executable" >&2
    exit 1; }
echo "checks: hybrid record + perf-gate rules + smoke target OK"

# compile & device-traffic observability (ISSUE 13): the bench record, the
# perfdiff zero-ceilings, and the smoke target must keep existing —
# deleting any of them would un-gate the zero-recompile / zero-upload
# invariants silently. Textual (sub-second) checks.
grep -q "def bench_compile" bench.py || {
    echo "checks: bench.py lost its compile record (bench_compile)" >&2
    exit 1; }
grep -q "compile.steady.unexpected_compiles" experiments/perfdiff.py || {
    echo "checks: perfdiff rules lost compile.steady.unexpected_compiles" >&2
    exit 1; }
grep -q "compile.steady.upload_bytes" experiments/perfdiff.py || {
    echo "checks: perfdiff rules lost compile.steady.upload_bytes" >&2
    exit 1; }
grep -q "compile.warmup_ttft_ratio" experiments/perfdiff.py || {
    echo "checks: perfdiff rules lost compile.warmup_ttft_ratio" >&2
    exit 1; }
test -x scripts/compile_smoke.sh || {
    echo "checks: scripts/compile_smoke.sh missing or not executable" >&2
    exit 1; }
echo "checks: compile record + zero-ceiling rules + smoke target OK"
