"""Fast seeded mini-chaos soak (ISSUE 6, tier-1): a bounded run of the same
harness `scripts/chaos_soak.sh` drives at acceptance scale
(experiments/chaos.py). Mixed greedy/sampled/penalized/deadline traffic
through a warm-restart-enabled paged scheduler under a seeded fault
schedule; run_chaos() itself asserts the robustness contract — 100%
terminal finishes, clean PagePool.audit() with zero leaked pages, /health
recovered, and restart/recovered/timeout counters reconciled against the
flight recorder."""

import importlib.util
import pathlib


def _load_chaos():
    """experiments/ is not a package; load the harness by path so the test
    and the CLI soak share one implementation."""
    path = pathlib.Path(__file__).resolve().parents[1] / "experiments" / "chaos.py"
    spec = importlib.util.spec_from_file_location("dllama_chaos", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mini_chaos_soak_terminal_audit_recovery():
    chaos = _load_chaos()
    # bounded iterations + hard per-client drain deadlines inside run_chaos
    # keep this inside the tier-1 window (~15 s on CPU)
    report = chaos.run_chaos(n_requests=30, seed=1, clients=3,
                             client_deadline_s=90.0)
    assert report["ok"], report["problems"]
    # the soak must actually have exercised the self-healing machinery:
    # faults fired, and every submitted request has a recorded outcome
    assert report["faults_injected"] > 0
    assert sum(report["finish_reasons"].values()) == 30
