"""CPU smoke of the TPU-window scripts (VERDICT r3 next-round #2).

The watcher (experiments/tpu_watch.sh) fires experiments/tpu_session.sh
unattended on the first live tunnel window; a trivial crash in any stage
would burn scarce TPU time. These tests execute each stage's ACTUAL main
path end-to-end on CPU — tiny shapes, interpret-mode Pallas — so an import
error, bad flag, or shape typo is caught in CI, never in a window. The
numbers produced here are meaningless; only completion + parity markers are
asserted. (Reference analog: the window scripts are this repo's equivalent
of the reference's dllama-run measurement drivers, dllama.cpp:54-104.)
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(argv, extra_env=None, timeout=900):
    env = dict(os.environ)
    # repo-only PYTHONPATH skips the axon sitecustomize (which would serialize
    # behind a tunnel probe); CPU platform so no test touches the real chip
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # scripts run single-device, like the window
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable] + argv,
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_tpu_validate_smoke():
    p = _run(["experiments/tpu_validate.py"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "TOTAL ALL PASS" in p.stdout


def test_kbench_suite_smoke():
    p = _run(["experiments/kbench.py", "suite", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "KBENCH DONE" in p.stdout
    assert "FAILED" not in p.stdout, p.stdout
    # the tile sweep measured at least one (tk, tn) combo
    assert "tile tk=" in p.stdout, p.stdout


def test_ebench_smoke():
    p = _run(["experiments/ebench.py", "4"], {"EBENCH_TINY": "1"})
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "EBENCH DONE fails=0" in p.stdout, p.stdout


def test_abench_smoke():
    p = _run(["experiments/abench.py", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "ABENCH DONE fails=0" in p.stdout, p.stdout


def test_collectives_table_smoke():
    p = _run(["experiments/collectives_table.py", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "COLLECTIVES DONE" in p.stdout, p.stdout
    assert "FAILED" not in p.stdout, p.stdout


def test_tpu_session_shell_end_to_end():
    """The WHOLE tpu_session.sh (shell plumbing: stage sequence, env, tee
    paths, timeouts) in smoke mode — a stage-wiring typo must fail CI, not a
    live window."""
    env = dict(os.environ)
    env["TPU_SESSION_SMOKE"] = "1"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        ["sh", "experiments/tpu_session.sh"], cwd=REPO, capture_output=True,
        text=True, timeout=2400, env=env,
    )
    assert p.returncode == 0, f"stdout:\n{p.stdout[-3000:]}\nstderr:\n{p.stderr[-2000:]}"
    for marker in ("TOTAL ALL PASS", "KBENCH DONE", "EBENCH DONE fails=0",
                   "ABENCH DONE fails=0", "== done"):
        assert marker in p.stdout, f"missing {marker!r}:\n{p.stdout[-3000:]}"


def test_aot_mosaic_acceptance():
    """Every production Pallas kernel (incl. the shard_map'd TP paths) must
    AOT-compile for the v5e/v6e targets via the local libtpu — the committed
    Mosaic-acceptance gate (VERDICT r3 missing #2 / next-round #8). A
    regression here means a live window would hit a Mosaic rejection."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".md") as tmp:
        p = _run(["experiments/aot_check.py", "--md", tmp.name])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "ALL PRODUCTION KERNELS ACCEPT" in p.stdout, p.stdout
