"""CPU smoke of the TPU-window scripts (VERDICT r3 next-round #2).

The watcher (experiments/tpu_watch.sh) fires experiments/tpu_session.sh
unattended on the first live tunnel window; a trivial crash in any stage
would burn scarce TPU time. These tests execute each stage's ACTUAL main
path end-to-end on CPU — tiny shapes, interpret-mode Pallas — so an import
error, bad flag, or shape typo is caught in CI, never in a window. The
numbers produced here are meaningless; only completion + parity markers are
asserted. (Reference analog: the window scripts are this repo's equivalent
of the reference's dllama-run measurement drivers, dllama.cpp:54-104.)
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one tiny batched-dot Pallas kernel AOT-compiled for v5e via the local
#: libtpu — the capability the acceptance gate's blockdot kernels stand on.
#: A libtpu whose Mosaic predates batched dot support (rejects with "Only 2D
#: tensors supported in dot"), or that cannot initialize off-GCP at all,
#: cannot run the gate: that is an environment defect, not a kernel
#: regression, so the gate test skips with the probe's verdict.
_MOSAIC_PROBE = r"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.experimental import pallas as pl
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
repl = NamedSharding(Mesh(topo.devices[:1], ("x",)), P())
S = jax.ShapeDtypeStruct
def kernel(x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
fn = pl.pallas_call(kernel, out_shape=S((2, 8, 128), jnp.float32))
jax.jit(fn).trace(S((2, 8, 128), jnp.bfloat16, sharding=repl),
                  S((2, 128, 128), jnp.bfloat16, sharding=repl)
                  ).lower().compile()
print("MOSAIC_BATCHED_DOT_OK")
"""

_MOSAIC_REASON = None


def _mosaic_aot_unusable():
    """'' when the local libtpu can compile the gate's kernels; else the
    skip reason naming the environmental condition (cached)."""
    global _MOSAIC_REASON
    if _MOSAIC_REASON is None:
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        try:
            p = subprocess.run([sys.executable, "-c", _MOSAIC_PROBE],
                               capture_output=True, text=True, timeout=240,
                               env=env, cwd=REPO)
        except subprocess.TimeoutExpired:
            _MOSAIC_REASON = "libtpu topology-AOT probe timed out"
            return _MOSAIC_REASON
        if p.returncode == 0 and "MOSAIC_BATCHED_DOT_OK" in p.stdout:
            _MOSAIC_REASON = ""
        elif "Only 2D tensors supported in dot" in p.stdout + p.stderr:
            _MOSAIC_REASON = ("installed libtpu's Mosaic lacks batched-dot "
                              "support (rejects with 'Only 2D tensors "
                              "supported in dot')")
        else:
            _MOSAIC_REASON = ("libtpu topology AOT unavailable in this "
                              "environment: "
                              + (p.stderr or p.stdout).strip()[-200:])
    return _MOSAIC_REASON


def _run(argv, extra_env=None, timeout=900):
    env = dict(os.environ)
    # repo-only PYTHONPATH skips the axon sitecustomize (which would serialize
    # behind a tunnel probe); CPU platform so no test touches the real chip
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # scripts run single-device, like the window
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable] + argv,
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_tpu_validate_smoke():
    p = _run(["experiments/tpu_validate.py"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "TOTAL ALL PASS" in p.stdout


def test_kbench_suite_smoke():
    p = _run(["experiments/kbench.py", "suite", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "KBENCH DONE" in p.stdout
    assert "FAILED" not in p.stdout, p.stdout
    # the tile sweep measured at least one (tk, tn) combo
    assert "tile tk=" in p.stdout, p.stdout


def test_ebench_smoke():
    p = _run(["experiments/ebench.py", "4"], {"EBENCH_TINY": "1"})
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "EBENCH DONE fails=0" in p.stdout, p.stdout


def test_abench_smoke():
    p = _run(["experiments/abench.py", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "ABENCH DONE fails=0" in p.stdout, p.stdout


def test_collectives_table_smoke():
    p = _run(["experiments/collectives_table.py", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "COLLECTIVES DONE" in p.stdout, p.stdout
    assert "FAILED" not in p.stdout, p.stdout


def test_hbm_traffic_smoke():
    p = _run(["experiments/hbm_traffic.py", "--smoke"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "HBM TRAFFIC DONE" in p.stdout, p.stdout
    assert "FAILED" not in p.stdout, p.stdout


def test_q40_weight_floor_matches_written_file(tmp_path):
    """The artifact's floor must equal the Q40 bytes a real .m file carries:
    write the tiny preset through the actual writer and compare the on-disk
    payload — file size minus header minus the non-Q40 (f32) tensor bytes —
    against q40_weight_bytes. Independent of the tensor_plan loop the floor
    itself uses."""
    import numpy as np

    sys.path.insert(0, REPO)
    try:
        from experiments.hbm_traffic import PRESETS, q40_weight_bytes
        from dllama_tpu.models import formats
        from dllama_tpu.ops.quant import FloatType
    finally:
        sys.path.pop(0)

    cfg = PRESETS["tiny"]
    rng = np.random.default_rng(0)
    tensors = {n: (rng.standard_normal(s) * 0.05).astype(np.float32)
               for n, s, _ in formats.tensor_plan(cfg)}
    path = tmp_path / "tiny.m"
    formats.save_model(str(path), cfg, tensors)
    _cfg2, header_size = formats.read_header(str(path))
    f32_bytes = sum(
        FloatType.F32.nbytes(int(np.prod(shape)))
        for _n, shape, ft in formats.tensor_plan(cfg) if ft == FloatType.F32)
    on_disk_q40 = path.stat().st_size - header_size - f32_bytes
    floor = q40_weight_bytes(cfg)
    assert floor == on_disk_q40 > 0, (floor, on_disk_q40)


def test_probe_smoke():
    """The compute probe (tunnel gate for the watcher + every session stage)."""
    p = _run(["experiments/probe.py"], {"PROBE_ALLOW_CPU": "1"})
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "PROBE OK" in p.stdout
    # and without the escape hatch a CPU backend must NOT count as up
    p = _run(["experiments/probe.py"])
    assert p.returncode != 0


def test_canary_flash_smoke():
    p = _run(["experiments/canary_flash.py"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "FLASH CANARY OK" in p.stdout


def test_tpu_validate_single_group():
    """Per-group invocation (the session bounds each group's timeout so a
    wedge costs one group, not the stage): q40 alone must pass and must not
    touch flash/engine paths."""
    p = _run(["experiments/tpu_validate.py", "q40"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "TOTAL ALL PASS" in p.stdout
    assert "flash" not in p.stdout and "engine" not in p.stdout
    # a typo'd group must error, not pass-with-zero-checks
    p = _run(["experiments/tpu_validate.py", "q4O"])
    assert p.returncode != 0 and "TOTAL ALL PASS" not in p.stdout


def test_kbench_no_flash():
    """--no-flash (set when the flash canary hangs) skips the flash section
    but still delivers the q40 rows and the tile sweep."""
    p = _run(["experiments/kbench.py", "suite", "--smoke", "--no-flash"])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "flash bench SKIPPED" in p.stdout
    assert "flash decode" not in p.stdout
    assert "tile tk=" in p.stdout and "KBENCH DONE" in p.stdout


def test_bench_partial_snapshot_recovery(tmp_path, monkeypatch, capsys):
    """A tunnel wedge mid-bench blocks the worker forever inside one RPC; the
    parent must then emit the worker's last partial snapshot instead of
    degrading to the CPU fallback (losing every TPU number — the round-3
    failure mode)."""
    import json as _json

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)

    partial = {"metric": "tokens/sec/chip, PARTIAL", "value": 123.0,
               "unit": "tok/s", "vs_baseline": 0.5, "partial": True,
               "device": "TPU v5 lite0"}  # real snapshots carry the device

    def fake_run_worker(env, timeout_s):
        # the worker "wedged" after snapshotting one preset
        with open(env["BENCH_PARTIAL_PATH"], "w") as f:
            _json.dump(partial, f)
        return None

    monkeypatch.setattr(bench, "probe_tpu", lambda t: True)
    monkeypatch.setattr(bench, "run_worker", fake_run_worker)
    monkeypatch.setenv("BENCH_ATTN", "auto")  # skip the parent's flash canary
    snap = tmp_path / "partial.json"
    monkeypatch.setenv("BENCH_PARTIAL_PATH", str(snap))
    # keep the recovered record's persistence out of the real repo file
    monkeypatch.setenv("BENCH_LAST_TPU_PATH", str(tmp_path / "last.json"))
    rc = bench.main()
    assert rc == 0
    out = capsys.readouterr().out
    rec = _json.loads(out.strip().splitlines()[-1])
    assert rec["value"] == 123.0 and rec.get("partial") is True
    assert not snap.exists()  # consumed on recovery, not left to go stale


def test_bench_last_tpu_record_attach(tmp_path, monkeypatch, capsys):
    """A TPU record captured in an earlier watcher window must surface
    (clearly labeled) in a later run against a dead tunnel, and a TPU
    success must persist one."""
    import json as _json

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)

    last = tmp_path / "last_tpu_bench.json"
    monkeypatch.setenv("BENCH_LAST_TPU_PATH", str(last))
    monkeypatch.setenv("BENCH_PARTIAL_PATH", str(tmp_path / "p.json"))

    # 1. TPU success persists the record
    full = {"metric": "tok/s", "value": 200.0, "unit": "tok/s",
            "vs_baseline": 0.4, "device": "TPU v5 lite0"}
    monkeypatch.setattr(bench, "probe_tpu", lambda t: True)
    monkeypatch.setattr(bench, "run_worker", lambda env, t: dict(full))
    monkeypatch.setenv("BENCH_ATTN", "auto")
    assert bench.main() == 0
    capsys.readouterr()
    saved = _json.loads(last.read_text())
    assert saved["value"] == 200.0 and "recorded_at_utc" in saved

    # a partial must not overwrite the full record
    bench._save_last_tpu_record({"value": 1.0, "partial": True,
                                 "device": "TPU v5 lite0"})
    assert _json.loads(last.read_text())["value"] == 200.0

    # a CPU-backend record (worker fell back after the probe passed) must
    # neither be persisted as TPU evidence nor read back as one
    bench._save_last_tpu_record({"value": 2.0, "device": "TFRT_CPU_0"})
    assert _json.loads(last.read_text())["value"] == 200.0
    last.write_text("null")  # truncation-repaired file: tolerated, not trusted
    assert bench._load_last_tpu_record() is None
    last.write_text(_json.dumps(saved))

    # a probe-ok-but-CPU-worker run must come out marked tpu_unavailable,
    # not masquerade as the round's TPU record (watch_done.sh keys off this)
    monkeypatch.setattr(
        bench, "run_worker",
        lambda env, t: {"metric": "cpu", "value": 3.0, "unit": "tok/s",
                        "vs_baseline": 0.0, "device": "TFRT_CPU_0"})
    monkeypatch.setenv("BENCH_BUDGET_S", "301")
    assert bench.main() == 0
    out = capsys.readouterr().out
    rec = _json.loads(out.strip().splitlines()[-1])
    assert rec["tpu_unavailable"] is True
    assert rec["last_tpu_record"]["value"] == 200.0
    monkeypatch.delenv("BENCH_BUDGET_S")

    # 2. dead tunnel: CPU fallback attaches the persisted record
    monkeypatch.setattr(bench, "probe_tpu", lambda t: False)
    cpu_rec = {"metric": "cpu", "value": 5.0, "unit": "tok/s", "vs_baseline": 0.0}
    monkeypatch.setattr(bench, "run_worker", lambda env, t: dict(cpu_rec))
    monkeypatch.setenv("BENCH_BUDGET_S", "301")  # skip probe retry sleep
    assert bench.main() == 0
    out = capsys.readouterr().out
    rec = _json.loads(out.strip().splitlines()[-1])
    assert rec["tpu_unavailable"] is True
    assert rec["last_tpu_record"]["value"] == 200.0

    # 3. evidence ranking: a PARTIAL record that measured the 8b north star
    # (non-null vs_baseline_config) outranks a later FULL record that did
    # not — the session's quick 1b record must never destroy 8b evidence —
    # while a full 8b record supersedes the partial one
    last2 = tmp_path / "last2.json"
    monkeypatch.setenv("BENCH_LAST_TPU_PATH", str(last2))
    bench._save_last_tpu_record({"value": 9.0, "partial": True,
                                 "vs_baseline_config": "8b 32-slot serving",
                                 "device": "TPU v5 lite0"})
    bench._save_last_tpu_record({"value": 7.0, "device": "TPU v5 lite0"})
    assert _json.loads(last2.read_text())["value"] == 9.0  # 1b full lost
    bench._save_last_tpu_record({"value": 11.0,
                                 "vs_baseline_config": "8b 48-slot serving",
                                 "device": "TPU v5 lite0"})
    assert _json.loads(last2.read_text())["value"] == 11.0  # 8b full wins


def test_bench_worker_writes_partial_snapshot(tmp_path):
    """The worker itself must snapshot as it goes (tiny preset, CPU)."""
    part = tmp_path / "partial.json"
    p = _run(["bench.py", "--worker"],
             {"BENCH_PRESET": "tiny", "BENCH_DECODE_TOKENS": "8",
              "BENCH_SPEC": "0", "BENCH_ADMIT": "0",
              "BENCH_PARTIAL_PATH": str(part)}, timeout=600)
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    import json as _json

    rec = _json.loads(part.read_text())
    assert rec["partial"] is True and rec["value"] > 0


def test_watch_done_condition(tmp_path):
    """The watcher's stop-watching condition (experiments/watch_done.sh):
    only a FULL real-TPU bench record ends the watch — not an empty dir, not
    a CPU fallback, not a wedge partial snapshot."""
    def done():
        return subprocess.run(
            ["sh", "experiments/watch_done.sh", str(tmp_path)], cwd=REPO
        ).returncode == 0

    assert not done()  # no logs at all
    (tmp_path / "bench_1.log").write_text(
        '{"vs_baseline": 0.0, "vs_baseline_config": "8b 32-slot serving", '
        '"tpu_unavailable": true}\n')
    assert not done()  # CPU fallback record
    (tmp_path / "bench_2.log").write_text(
        '{"vs_baseline": 0.4, "vs_baseline_config": "8b 32-slot serving", '
        '"partial": true}\n')
    assert not done()  # wedge partial snapshot
    (tmp_path / "bench_3.log").write_text(
        '{"vs_baseline": 0.0, "vs_baseline_config": null}\n')
    assert not done()  # quick-bench 1b record: north star not measured
    (tmp_path / "bench_4.log").write_text(
        '{"vs_baseline": 0.6, "vs_baseline_config": "8b 32-slot serving '
        '(kernels=auto)"}\n')
    assert done()  # full TPU record incl. the 8b serving sweep


def test_tpu_session_shell_end_to_end():
    """The WHOLE tpu_session.sh (shell plumbing: stage sequence, env, tee
    paths, timeouts) in smoke mode — a stage-wiring typo must fail CI, not a
    live window."""
    env = dict(os.environ)
    env["TPU_SESSION_SMOKE"] = "1"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        ["sh", "experiments/tpu_session.sh"], cwd=REPO, capture_output=True,
        text=True, timeout=2400, env=env,
    )
    assert p.returncode == 0, f"stdout:\n{p.stdout[-3000:]}\nstderr:\n{p.stderr[-2000:]}"
    # "flash canary ok" is deliberately NOT a substring of "control canary
    # ok": each canary's success must be asserted independently
    for marker in ("control canary ok", "flash canary ok",
                   "quick bench skipped (smoke)", "TOTAL ALL PASS", "KBENCH DONE",
                   "EBENCH DONE fails=0", "ABENCH DONE fails=0",
                   # the full group list: a failing canary would degrade
                   # VGROUPS to just q40, which must not pass CI silently
                   "VALIDATE STAGE CLEAN (groups: q40 q80 flash engine spec)",
                   "== done"):
        assert marker in p.stdout, f"missing {marker!r}:\n{p.stdout[-3000:]}"


def test_aot_mosaic_acceptance():
    """Every production Pallas kernel (incl. the shard_map'd TP paths) must
    AOT-compile for the v5e/v6e targets via the local libtpu — the committed
    Mosaic-acceptance gate (VERDICT r3 missing #2 / next-round #8). A
    regression here means a live window would hit a Mosaic rejection."""
    reason = _mosaic_aot_unusable()
    if reason:
        # xfail, not skip: the gate WOULD fail on this libtpu for the
        # probed environmental reason; it reactivates where the probe
        # compiles
        pytest.xfail(reason)
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".md") as tmp:
        p = _run(["experiments/aot_check.py", "--md", tmp.name])
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-2000:]}"
    assert "ALL PRODUCTION KERNELS ACCEPT" in p.stdout, p.stdout


def test_decide_smoke(tmp_path):
    """decide.py parses the session's logs into default recommendations; a
    stage with no log prints NO LOG and the script always exits 0."""
    p = _run(["experiments/decide.py", str(tmp_path)])  # empty dir: all NO LOG
    assert p.returncode == 0 and "DECIDE DONE" in p.stdout
    assert p.stdout.count("NO LOG") == 4  # kbench/ebench/abench/bench
    # against the repo's real smoke logs (written by the session smoke test)
    p2 = _run(["experiments/decide.py"])
    assert p2.returncode == 0 and "DECIDE DONE" in p2.stdout
