"""ISSUE 14: the repo-native invariant analyzer + lock-order sanitizer.

Three layers, mirroring the acceptance criteria:

* **red fixtures** — every rule family has a minimal snippet that trips
  exactly its rule (and a suppression/compliant variant that goes green):
  an analyzer rule without a committed red test is a rule nobody knows
  still fires;
* **clean pass** — the LIVE repo analyzes to zero findings (and stays
  jax-free and fast): the gate merges at zero, so any regression is the
  offender's diff, not pre-existing noise;
* **lock sanitizer units** — utils/locks: an out-of-rank acquisition
  raises with BOTH hold sites named, reentrant RLock re-entry is legal,
  and with the audit off the factories return plain threading locks
  (zero overhead).

Pure host — no jax import, no model, sub-second per test (the CLI
round-trip test spawns one interpreter).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

import dllama_tpu
from dllama_tpu.analysis.core import RULE_CATALOG, Diagnostic, Project, run
from dllama_tpu.utils import locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(dllama_tpu.__file__)))

#: context-file rules that fire on minimal in-memory projects simply
#: because bench.py/README/perfdiff aren't part of the fixture
_CONTEXT_RULES = {"gate-routes", "gate-bench", "gate-perfdiff", "gate-aot",
                  "gate-scripts", "doc-rules", "doc-ranks", "lock-unranked"}


def findings(files: dict, keep_context: bool = False) -> list[Diagnostic]:
    diags = run(Project(files))
    if not keep_context:
        diags = [d for d in diags if d.rule not in _CONTEXT_RULES]
    return diags


def rules_of(diags) -> list[str]:
    return [d.rule for d in diags]


# ------------------------------------------------------------- jit rules


JIT_BAD = '''
import jax
from dllama_tpu.obs import compile as compile_obs


class E:
    def __init__(self):
        self._decode = jax.jit(self._decode_impl)

    @staticmethod
    def _decode_impl(x):
        return x

    def decode(self, x):
        return self._decode(x)
'''


def test_jit_scope_red():
    diags = findings({"dllama_tpu/engine/fake.py": JIT_BAD})
    assert rules_of(diags) == ["jit-scope"]
    assert diags[0].line == 15  # the `return self._decode(x)` line
    assert "self._decode" in diags[0].message


def test_jit_scope_green_under_scope():
    ok = JIT_BAD.replace(
        "        return self._decode(x)",
        "        with compile_obs.LEDGER.scope(\"decode\", \"n1\"):\n"
        "            return self._decode(x)")
    assert findings({"dllama_tpu/engine/fake.py": ok}) == []


def test_jit_scope_suppression_green_with_reason():
    ok = JIT_BAD.replace(
        "    def decode(self, x):",
        "    def decode(self, x):  # dllama: allow[jit-scope] warm thunk")
    assert findings({"dllama_tpu/engine/fake.py": ok}) == []


def test_jit_scope_bare_suppression_is_a_finding():
    bare = JIT_BAD.replace(
        "    def decode(self, x):",
        "    def decode(self, x):  # dllama: allow[jit-scope]")
    assert rules_of(findings({"dllama_tpu/engine/fake.py": bare})) \
        == ["suppress-reason"]


def test_jit_scope_docstring_mention_is_not_a_suppression():
    doc = JIT_BAD.replace(
        "    def decode(self, x):",
        '    def decode(self, x):\n        "# dllama: allow[jit-scope] prose"')
    assert "jit-scope" in rules_of(findings(
        {"dllama_tpu/engine/fake.py": doc}))


def test_jit_scope_impl_functions_are_not_dispatch_sites():
    impl = '''
import jax

def _body(x):
    return helper(x)

helper = jax.jit(lambda x: x)
_fused = jax.jit(_body)
'''
    # helper() inside _body (an impl handed to jax.jit) is traced code
    assert findings({"dllama_tpu/engine/fake.py": impl}) == []


def test_jit_scope_factory_table_dispatch():
    fac = '''
import jax

def make_decoder():
    return jax.jit(lambda x: x)


class E:
    def __init__(self):
        self._decoders = {}
        self._decoders[1] = make_decoder()

    def go(self, x):
        return self._decoders[1](x)
'''
    diags = findings({"dllama_tpu/engine/fake.py": fac})
    assert rules_of(diags) == ["jit-scope"]
    assert "self._decoders[...]" in diags[0].message


def test_jit_label_red():
    bad = '''
from dllama_tpu.obs import compile as compile_obs

def go():
    with compile_obs.LEDGER.scope("not_a_label", "k"):
        pass
'''
    diags = findings({"dllama_tpu/engine/fake.py": bad})
    assert rules_of(diags) == ["jit-label"]
    assert "not_a_label" in diags[0].message


# ------------------------------------------------------- dev-state rule


DEV_TMPL = '''
import jax.numpy as jnp


class E:
    def {name}(self, slot):
        {body}
'''


def _dev(name, body):
    return {"dllama_tpu/engine/fake.py":
            DEV_TMPL.format(name=name, body=body)}


def test_dev_state_red_bulk_upload():
    diags = findings(_dev("oops", "self._pos_dev = jnp.asarray(self.pos)"))
    assert rules_of(diags) == ["dev-state"]
    assert "_pos_dev" in diags[0].message


@pytest.mark.parametrize("body", [
    "self._pos_dev = self._pos_dev.at[slot].set(0)",  # surgical row write
    "(x, self._keys_dev, self._pos_dev) = self._decode(slot)",  # jit carry
    "self._last_dev = nxt" .replace("nxt", "slot"),  # local carry name
])
def test_dev_state_green_sanctioned_shapes(body):
    assert findings(_dev("step", body)) == []


def test_dev_state_green_in_sanctioned_fns():
    for fn in ("__init__", "warm_restart", "_sync_vectors"):
        assert findings(_dev(fn, "self._pos_dev = jnp.zeros(4)")) == []


def test_dev_state_red_outside_engine_ignored():
    # the rule is scoped to engine/ modules
    files = {"dllama_tpu/serve/fake.py":
             DEV_TMPL.format(name="oops",
                             body="self._pos_dev = jnp.asarray(self.pos)")}
    assert findings(files) == []


# -------------------------------------------------------- catalog rules


def test_catalog_metric_red_and_sited_green():
    bad = 'from dllama_tpu.obs import metrics\nX = metrics.counter("x", "h")\n'
    diags = findings({"dllama_tpu/serve/fake.py": bad})
    assert rules_of(diags) == ["catalog-metric"]
    # the same text IS the single registration site in instruments.py
    assert findings({"dllama_tpu/obs/instruments.py": bad}) == []


def test_catalog_span_event_red():
    bad = '''
from dllama_tpu.obs import trace

def go():
    trace.TRACER.event("bogus.event")
    tr = trace.TRACER
    tr.span_at("bogus.span", 0.0, 1.0)
    trace.TRACER.event("drain.begin")   # cataloged: green
'''
    diags = findings({"dllama_tpu/serve/fake.py": bad})
    assert sorted(rules_of(diags)) == ["catalog-event", "catalog-span"]


def test_catalog_fault_red():
    bad = ('from dllama_tpu.utils import faults\n'
           'faults.fire("definitely.not.a.point")\n')
    diags = findings({"dllama_tpu/serve/fake.py": bad})
    assert rules_of(diags) == ["catalog-fault"]
    assert diags[0].line == 2


# ------------------------------------------------------- transfer rule


def test_transfer_note_red_and_green():
    tmpl = '''
import numpy as np
from dllama_tpu.obs import compile as compile_obs


class E:
    def decode_consume(self, chunk):
        toks = np.asarray(chunk.toks)
        {note}
        return toks
'''
    red = {"dllama_tpu/engine/batch.py": tmpl.format(note="pass")}
    diags = findings(red)
    assert rules_of(diags) == ["transfer-note"]
    assert "decode_consume" in diags[0].message
    green = {"dllama_tpu/engine/batch.py": tmpl.format(
        note='compile_obs.note_transfer("d2h", "decode_tokens", 4)')}
    assert findings(green) == []


def test_transfer_note_is_site_level_not_function_level():
    """A note_transfer elsewhere in the function must NOT bless a distant
    unannotated transfer (the annotation windows to its site)."""
    far = '''
import numpy as np
from dllama_tpu.obs import compile as compile_obs


class E:
    def decode_consume(self, chunk):
        toks = np.asarray(chunk.toks)
        compile_obs.note_transfer("d2h", "decode_tokens", 4)
        a = 1
        b = 2
        c = 3
        d = 4
        e = 5
        stray = np.asarray(chunk.other)  # 6 statements from the note
        return toks, stray
'''
    diags = findings({"dllama_tpu/engine/batch.py": far})
    assert rules_of(diags) == ["transfer-note"]
    assert diags[0].line == 15  # the stray np.asarray line


def test_transfer_note_compound_stmt_does_not_self_annotate():
    """An `if` holding both a transfer and a note deep inside must not
    annotate its own out-of-window transfers from the outer level."""
    nested = '''
import numpy as np
from dllama_tpu.obs import compile as compile_obs


class E:
    def decode_consume(self, chunk):
        if chunk.spec:
            stray = np.asarray(chunk.other)
            a = 1
            b = 2
            c = 3
            d = 4
            e = 5
            compile_obs.note_transfer("d2h", "spec_counts", 4)
'''
    diags = findings({"dllama_tpu/engine/batch.py": nested})
    assert rules_of(diags) == ["transfer-note"]


def test_broken_source_does_not_crash_the_analyzer():
    # an unterminated string fails tokenize (comment scan skips) and
    # ast.parse; the analyzer must degrade to ONE parse-error diagnostic
    # per broken file — other files keep being analyzed
    from dllama_tpu.analysis.core import Source

    src = Source("dllama_tpu/engine/broken.py", "x = '''unterminated\n")
    assert src.suppressions == {}
    diags = run(Project({
        "dllama_tpu/engine/broken.py": "def broken(:\n",
        "dllama_tpu/serve/fake.py":
            'from dllama_tpu.utils import faults\nfaults.fire("nope")\n',
    }))
    by_rule = {d.rule: d for d in diags}
    assert by_rule["parse-error"].path == "dllama_tpu/engine/broken.py"
    assert by_rule["parse-error"].line == 1
    assert "catalog-fault" in by_rule  # the healthy file was still checked


def test_gate_routes_required_routes_are_pinned():
    """Deleting a shipped route from BOTH the tuple and the README must
    still fail (the old checks.sh pin, kept)."""
    ksel = 'PAGED_ROUTES = ("paged_kernel",)\n'  # paged_gather gone
    readme = ("## Paged KV cache\n\n| Route | When |\n|---|---|\n"
              "| `paged_kernel` | x |\n")
    diags = [d for d in run(Project({
        "dllama_tpu/engine/kernel_select.py": ksel, "README.md": readme}))
        if d.rule == "gate-routes"]
    assert any("paged_gather" in d.message for d in diags)


def test_transfer_note_only_guards_steady_fns():
    other = '''
import numpy as np


class E:
    def release(self, chunk):
        return np.asarray(chunk.toks)
'''
    assert findings({"dllama_tpu/engine/batch.py": other}) == []


# ----------------------------------------------------------- lock rules


LOCKS_TMPL = '''
from dllama_tpu.utils import locks


class A:
    def __init__(self):
        self._metrics = locks.make_lock("obs.metrics")
        self._pool = locks.make_rlock("engine.pool")
        self._sched = locks.make_lock("scheduler.metrics")

    def f(self):
        {body}
'''


def test_lock_order_red_inversion():
    body = ("with self._pool:\n"
            "            with self._sched:\n"
            "                pass")
    diags = findings({"dllama_tpu/serve/fake.py":
                      LOCKS_TMPL.format(body=body)})
    assert rules_of(diags) == ["lock-order"]
    assert "scheduler.metrics" in diags[0].message
    assert "engine.pool" in diags[0].message


def test_lock_leaf_red():
    body = ("with self._metrics:\n"
            "            with self._pool:\n"
            "                pass")
    diags = findings({"dllama_tpu/serve/fake.py":
                      LOCKS_TMPL.format(body=body)})
    assert rules_of(diags) == ["lock-leaf"]


def test_lock_order_green_ascending_and_reentrant():
    body = ("with self._sched:\n"
            "            with self._pool:\n"
            "                with self._pool:\n"
            "                    with self._metrics:\n"
            "                        pass")
    assert findings({"dllama_tpu/serve/fake.py":
                     LOCKS_TMPL.format(body=body)}) == []


def test_lock_order_crosses_function_calls():
    # f holds the metrics leaf and calls g, which takes the pool lock —
    # the edge is interprocedural, not lexical
    body = ("with self._metrics:\n"
            "            self.g()\n\n"
            "    def g(self):\n"
            "        with self._pool:\n"
            "            pass")
    diags = findings({"dllama_tpu/serve/fake.py":
                      LOCKS_TMPL.format(body=body)})
    assert rules_of(diags) == ["lock-leaf"]


def test_lock_unranked_red():
    bad = ('from dllama_tpu.utils import locks\n'
           '_X = locks.make_lock("not.ranked")\n')
    diags = run(Project({"dllama_tpu/serve/fake.py": bad}))
    assert "lock-unranked" in rules_of(diags)


# ------------------------------------------------------------ gate rules


def test_gate_routes_drift_red():
    ksel = 'PAGED_ROUTES = ("paged_kernel", "paged_gather")\n'
    readme = ("# x\n\n## Paged KV cache\n\n"
              "| Route | When |\n|---|---|\n| `paged_kernel` | x |\n"
              "| `paged_stale` | x |\n")
    diags = [d for d in run(Project({
        "dllama_tpu/engine/kernel_select.py": ksel,
        "README.md": readme,
    })) if d.rule == "gate-routes"]
    msgs = " | ".join(d.message for d in diags)
    assert "paged_gather" in msgs     # catalog-only: README lost it
    assert "paged_stale" in msgs      # readme-only: no such route


def test_gate_bench_red():
    diags = [d for d in run(Project({"bench.py": "def bench_other():\n"
                                     "    pass\n"}))
             if d.rule == "gate-bench"]
    msgs = " ".join(d.message for d in diags)
    assert "bench_hybrid" in msgs and "bench_compile" in msgs


def test_doc_rules_drift_red():
    readme = ("| Rule | Checks |\n|---|---|\n| `jit-scope` | x |\n"
              "| `no-such-rule` | x |\n")
    diags = [d for d in run(Project({"README.md": readme}))
             if d.rule == "doc-rules"]
    msgs = " ".join(d.message for d in diags)
    assert "no-such-rule" in msgs            # row naming no rule
    assert "`dev-state`" in msgs             # rule missing a row


# -------------------------------------------------- live repo: clean pass


def test_live_repo_zero_findings_fast_and_jaxfree():
    t0 = time.monotonic()
    project = Project.from_disk(REPO)
    diags = run(project)
    dt = time.monotonic() - t0
    assert diags == [], "\n".join(str(d) for d in diags)
    # the acceptance bound is <5s; leave slack for loaded CI boxes
    assert dt < 10.0, f"analyzer took {dt:.1f}s"
    # the analyzer itself never imports jax (conftest pre-imports it in
    # this process, so prove it on the module graph instead: nothing in
    # dllama_tpu.analysis imports jax)
    import dllama_tpu.analysis.rules_jit as rj

    for mod in list(sys.modules):
        if mod.startswith("dllama_tpu.analysis"):
            assert "jax" not in getattr(sys.modules[mod], "__dict__", {}), mod
    assert rj is not None


def test_cli_json_roundtrip():
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["count"] == 0 and doc["findings"] == []
    assert doc["rules"] == len(RULE_CATALOG)
    assert doc["seconds"] < 5.0, doc  # the acceptance bound, end to end


def test_lock_graph_cli_is_acyclic_and_ascending():
    from dllama_tpu.analysis.rules_locks import build_graph
    from dllama_tpu.utils.locks import LOCK_RANKS

    edges, reentrant, _ca, _mg = build_graph(Project.from_disk(REPO))
    assert edges, "the live lock graph cannot be empty"
    for holder, acquired, rel, line in edges:
        if holder == acquired:
            assert holder in reentrant, (holder, rel, line)
            continue
        assert LOCK_RANKS[holder] < LOCK_RANKS[acquired], \
            f"descending edge {holder}->{acquired} at {rel}:{line}"


# ------------------------------------------------- runtime lock sanitizer


@pytest.fixture
def armed_locks():
    was = locks.armed()
    locks.configure(True)
    yield
    locks.configure(was)


def test_lock_audit_inversion_raises_with_both_sites(armed_locks):
    hi = locks.make_lock("obs.metrics")
    lo = locks.make_lock("scheduler.metrics")
    with hi:
        with pytest.raises(locks.LockOrderError) as ei:
            lo.acquire()
    msg = str(ei.value)
    assert "scheduler.metrics" in msg and "obs.metrics" in msg
    # BOTH hold sites named: the held lock's acquisition point (this
    # file) and the violating acquisition's
    assert msg.count("test_analysis.py") == 2
    assert "LEAF" in msg  # obs.metrics is a leaf lock; the message says so
    assert locks.held_names() == []  # nothing leaked


def test_lock_audit_equal_rank_distinct_objects_raise(armed_locks):
    a = locks.make_lock("obs.metrics")
    b = locks.make_lock("obs.metrics")
    with a:
        with pytest.raises(locks.LockOrderError):
            b.acquire()


def test_lock_audit_reentrant_rlock_ok(armed_locks):
    pool = locks.make_rlock("engine.pool")
    with pool:
        with pool:  # the radix tree / audit hook shape
            assert locks.held_names() == ["engine.pool", "engine.pool"]
    assert locks.held_names() == []


def test_lock_audit_ascending_ok_and_timeout_surface(armed_locks):
    lo = locks.make_lock("scheduler.metrics")
    hi = locks.make_lock("obs.metrics")
    with lo, hi:
        assert locks.held_names() == ["scheduler.metrics", "obs.metrics"]
    assert lo.acquire(timeout=0.5) is True  # Lock.acquire surface intact
    assert lo.locked()
    lo.release()
    # a second thread blocks on the held lock without tripping the audit
    # (per-thread stacks)
    lo.acquire()
    got = []
    t = threading.Thread(
        target=lambda: got.append(lo.acquire(blocking=False)))
    t.start()
    t.join()
    assert got == [False]
    lo.release()


def test_lock_audit_off_is_plain_threading_lock():
    was = locks.armed()
    locks.configure(False)
    try:
        lk = locks.make_lock("obs.metrics")
        rl = locks.make_rlock("engine.pool")
        assert type(lk) is type(threading.Lock())
        assert type(rl) is type(threading.RLock())
    finally:
        locks.configure(was)


def test_lock_audit_unknown_name_raises():
    with pytest.raises(ValueError):
        locks.make_lock("nope.nope")
    with pytest.raises(ValueError):
        locks.make_rlock("nope.nope")


def test_suite_runs_with_audit_armed():
    # tests/conftest.py arms DLLAMA_LOCK_AUDIT=1 before any dllama import;
    # every lock the stack created in this process is therefore audited
    assert os.environ.get("DLLAMA_LOCK_AUDIT") == "1"
    assert locks.armed()
