"""Tokenizer tests: BPE encode/decode, `.t` roundtrip, chat templates, EOS
detection — the structural port of tokenizer-test.cpp with a synthetic
byte-level vocabulary (the reference's golden llama3 cases need a real
tokenizer file and sit behind its DEV_TESTS gate, tokenizer-test.cpp:5)."""

import pytest

from dllama_tpu.tokenizer.chat import (
    ChatItem,
    ChatTemplate,
    ChatTemplateType,
    EosDetector,
    EosResult,
    chat_stops,
)
from dllama_tpu.tokenizer.tokenizer import Tokenizer


def make_tokenizer():
    # ids 0-255: raw bytes; 256+: merges; bos splits regular/special vocab
    vocab = [bytes([i]) for i in range(256)]
    scores = [0.0] * 256
    merges = {b"he": 1.0, b"ll": 2.0, b"llo": 3.0, b"hello": 4.0, b" w": 1.0, b"or": 1.5, b"world": 0.5, b"orld": 2.5}
    for piece, score in merges.items():
        vocab.append(piece)
        scores.append(score)
    bos_id = len(vocab)
    vocab += [b"<s>", b"</s>", b"<|eot|>"]
    scores += [0.0, 0.0, 0.0]
    return Tokenizer(vocab, scores, bos_id, [bos_id + 1, bos_id + 2], chat_template=None)


def test_encode_merges_best_pairs():
    t = make_tokenizer()
    toks = t.encode("hello", add_bos=False)
    assert toks == [t._regular_index[b"hello"]]


def test_encode_with_bos_and_bytes():
    t = make_tokenizer()
    toks = t.encode("hex", add_bos=True)
    assert toks[0] == t.bos_id
    assert t.decode_all(toks) == "hex"


def test_encode_special_tokens():
    t = make_tokenizer()
    eot = t.vocab.index(b"<|eot|>")
    toks = t.encode("hi<|eot|>x", add_bos=False, add_special_tokens=True)
    assert eot in toks
    # with special matching off, it tokenizes as raw bytes
    toks2 = t.encode("<|eot|>", add_bos=False, add_special_tokens=False)
    assert eot not in toks2


def test_streaming_decode_utf8_split():
    t = make_tokenizer()
    # "é" = 0xC3 0xA9 split across two tokens; neither alone is valid UTF-8
    assert t.decode(0xC3) is None
    assert t.decode(0xA9) == "é"
    # emoji split 1+3 bytes
    b = "🚀".encode("utf-8")
    assert t.decode(b[0]) is None
    assert t.decode(b[1]) is None
    assert t.decode(b[2]) is None
    assert t.decode(b[3]) == "🚀"


def test_decode_skips_bos_flushes_on_eos():
    t = make_tokenizer()
    assert t.decode(t.bos_id) is None
    assert t.decode(ord("a")) == "a"
    assert t.decode(t.eos_ids[0]) is None


def test_t_file_roundtrip(tmp_path):
    t = make_tokenizer()
    t.chat_template = "<|start_header_id|>{{...}}"
    path = str(tmp_path / "test.t")
    t.save(path)
    t2 = Tokenizer.load(path)
    assert t2.vocab == t.vocab
    assert t2.scores == pytest.approx(t.scores)
    assert t2.bos_id == t.bos_id
    assert t2.eos_ids == t.eos_ids
    assert t2.chat_template == t.chat_template
    assert t2.encode("hello world", add_bos=False) == t.encode("hello world", add_bos=False)


def test_chat_template_llama3():
    ct = ChatTemplate(ChatTemplateType.UNKNOWN, "x<|start_header_id|>y", "<|eot_id|>")
    assert ct.type == ChatTemplateType.LLAMA3
    out = ct.generate([ChatItem("system", "sys"), ChatItem("user", "hi")])
    assert out.content == (
        "<|start_header_id|>system<|end_header_id|>\n\nsys<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def test_chat_template_llama2():
    ct = ChatTemplate(ChatTemplateType.UNKNOWN, "... [INST] ...", "</s>")
    out = ct.generate([ChatItem("system", "S"), ChatItem("user", "U"), ChatItem("assistant", "A"), ChatItem("user", "U2")])
    assert out.content == "[INST] <<SYS>>\nS\n<</SYS>>\n\nU [/INST]</s>A</s>[INST] U2 [/INST]</s>"


def test_chat_template_deepseek3_think_prompt():
    ct = ChatTemplate(ChatTemplateType.UNKNOWN, "...<｜Assistant｜>...", "<eos>")
    out = ct.generate([ChatItem("user", "hi")])
    assert out.content.endswith("<｜Assistant｜><think>\n")
    assert out.public_prompt == "<think>\n"


def test_eos_detector_exact_and_partial():
    det = EosDetector([42], ["<|eot|>"], padding_left=2, padding_right=2)
    # partial match buffers
    assert det.append(1, "<|e") == EosResult.MAYBE_EOS
    assert det.append(2, "ot|>") == EosResult.EOS
    assert det.get_delta() is None  # stop was at position 0 -> nothing to emit

    det.reset()
    # text then stop within left padding
    assert det.append(1, "a") == EosResult.NOT_EOS
    assert det.get_delta() == "a"
    det.reset()
    assert det.append(3, "a<|eot|>") == EosResult.EOS
    assert det.get_delta() == "a"


def test_eos_detector_cross_token_stop_never_leaks():
    """VERDICT round-1 repro: stop "<eos>" arriving as "<e" + "os>" must emit
    nothing and terminate the stream (reference tokenizer.cpp:583-628)."""
    det = EosDetector([42], ["<eos>"], padding_left=2, padding_right=2)
    assert det.append(1, "<e") == EosResult.MAYBE_EOS
    assert det.get_delta() is None  # partial stop prefix must be held, not emitted
    assert det.append(2, "os>") == EosResult.EOS
    assert det.get_delta() is None


def test_eos_detector_held_text_flushes_when_match_dies():
    det = EosDetector([42], ["<eos>"], padding_left=2, padding_right=2)
    assert det.append(1, "abc<e") == EosResult.MAYBE_EOS
    assert det.get_delta() == "abc"  # safe text streams immediately
    assert det.append(2, "xyz") == EosResult.NOT_EOS
    assert det.get_delta() == "<exyz"  # dead partial match flushes in full


def test_eos_detector_flush_releases_partial_at_stream_end():
    det = EosDetector([42], ["<eos>"])
    assert det.append(1, "hi<e") == EosResult.MAYBE_EOS
    assert det.get_delta() == "hi"
    assert det.flush() == "<e"
    assert det.flush() is None


def test_eos_detector_stop_mid_piece_swallows_tail():
    det = EosDetector([42], ["<eos>"])
    assert det.append(1, "ok<eos>junk") == EosResult.EOS
    assert det.get_delta() == "ok"


def test_eos_detector_multiple_stops_longest_hold():
    det = EosDetector([42], ["STOP", "SToo"])
    assert det.append(1, "a ST") == EosResult.MAYBE_EOS
    assert det.get_delta() == "a "
    assert det.append(2, "OP") == EosResult.EOS
    assert det.get_delta() is None


def test_eos_detector_stop_token_id():
    det = EosDetector([42], ["</s>"])
    assert det.append(42, None) == EosResult.EOS


def test_eos_detector_long_text_passes_through():
    det = EosDetector([42], ["<stop>"], padding_left=1, padding_right=1)
    assert det.append(1, "this is a long piece") == EosResult.NOT_EOS
    assert det.get_delta() == "this is a long piece"


def test_chat_stops_from_tokenizer():
    t = make_tokenizer()
    assert chat_stops(t) == ["</s>", "<|eot|>"]


def test_special_ids_survive_t_roundtrip(tmp_path):
    """ADVICE r1: head-special vocabs (sentencepiece CONTROL at ids 0-2 plus a
    USER_DEFINED token mid-vocab) must keep their special set across save/load
    — the layout heuristic alone would demote <unk> to a merge candidate."""
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + [b"<tool>", b"he"]
    scores = [0.0] * len(vocab)
    specials = [0, 1, 2, 259]  # <unk>, bos, eos, <tool> — but NOT "he"
    t = Tokenizer(vocab, scores, bos_id=1, eos_ids=[2], special_ids=specials)
    path = str(tmp_path / "sp.t")
    t.save(path)
    t2 = Tokenizer.load(path)
    assert t2._special_ids == sorted(specials)
    assert t2.regular_vocab_size == len(vocab) - len(specials)
    # <unk> (id 0) must not act as a merge candidate after the roundtrip
    assert 0 not in t2._regular_index.values()
    # heuristic-matching sets write no extension key: file loads with defaults
    vocab3 = [bytes([i]) for i in range(256)] + [b"<s>", b"</s>"]
    t3 = Tokenizer(vocab3, [0.0] * 258, 256, [257])
    p3 = str(tmp_path / "plain.t")
    t3.save(p3)
    raw = open(p3, "rb").read()
    import struct as _s
    header_size = _s.unpack("<i", raw[4:8])[0]
    keys = [_s.unpack("<ii", raw[8 + 8 * i : 16 + 8 * i])[0] for i in range((header_size - 8) // 8)]
    assert 100 not in keys  # SPECIAL_IDS key absent -> reference-readable
    assert Tokenizer.load(p3)._special_ids == [256, 257]
