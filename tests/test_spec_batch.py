"""Speculative decoding as a first-class citizen of continuous batching
(ISSUE 11): per-request spec_k, overlap-composed spec cycles, mixed-batch
isolation, keep_rows rewind under over-acceptance, warm-restart resume of a
spec stream, and the paged draft-write safety invariant.

The central contract: with fixed prompts/seeds, greedy token streams are
BIT-IDENTICAL spec-on vs spec-off through the scheduler, across
--overlap {on,off} x {dense,paged} x radix {on,off} — speculation only
changes how many verify forwards it takes to produce them. Sampled and
penalized requests ride spec cycles one exactly-sampled token at a time, so
a spec neighbor can never perturb their streams either.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine, PoolAuditError
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.serve.scheduler import Scheduler

CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)

#: a draftable prompt: the greedy continuation of a periodic pattern settles
#: into its own loop, so the n-gram proposer gets real acceptance
REP = [5, 6, 7, 5, 6, 7, 5, 6]


def _make_sched(overlap=True, spec=0, kv_layout="dense", radix="off",
                n_slots=3, chunk=3, **kw):
    ekw = dict(kv_layout=kv_layout)
    if kv_layout == "paged":
        ekw.update(page_size=8, radix_cache=radix)
    eng = BatchEngine(CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32,
                      spec=spec, **ekw)
    return Scheduler(eng, chunk=chunk, overlap=overlap, **kw)


def _workload(sched):
    """Mixed traffic: greedy draftable, sampled, penalized — staggered."""
    r1 = sched.submit(REP, 0.0, 0.9, 14, frozenset(), seed=1)
    it1 = r1.tokens()
    head = [next(it1), next(it1)]  # r1 decodes before the others join
    r2 = sched.submit([9, 8, 7], 1.1, 0.9, 10, frozenset(), seed=42)
    r3 = sched.submit([4, 5], 0.9, 0.8, 8, frozenset(), seed=7,
                      presence=0.5, frequency=0.3)
    out2 = list(r2.tokens())
    out3 = list(r3.tokens())
    out1 = head + list(it1)
    return [(out1, r1.finish_reason), (out2, r2.finish_reason),
            (out3, r3.finish_reason)]


_REF = None


def _reference():
    """The spec-off stream set every configuration must reproduce (dense,
    overlap on, spec 0 — memoized: each engine costs a compile inside the
    time-budgeted tier-1 window)."""
    global _REF
    if _REF is None:
        sched = _make_sched(overlap=True, spec=0)
        try:
            _REF = _workload(sched)
        finally:
            sched.shutdown()
    return _REF


@pytest.mark.parametrize("overlap,kv_layout,radix", [
    (True, "dense", "off"),
    (False, "dense", "off"),
    (True, "paged", "on"),
    (False, "paged", "off"),
])
def test_greedy_parity_spec_on_vs_off(overlap, kv_layout, radix):
    """BIT-EXACT streams and finish reasons vs the spec-off reference,
    with spec cycles verifiably running (acceptance criterion #3)."""
    sched = _make_sched(overlap=overlap, spec=4, kv_layout=kv_layout,
                        radix=radix)
    try:
        got = _workload(sched)
        stats = sched.latency_summary()["spec"]
    finally:
        sched.shutdown()
    assert got == _reference()
    assert stats["cycles"] > 0 and stats["emitted"] > 0
    if kv_layout == "paged":
        # draft rows wrote k+1 rows past live positions all run long —
        # DLLAMA_POOL_AUDIT=1 (armed suite-wide) already audited every
        # release; one final explicit audit closes the drill
        report = sched.engine.pool.audit(raise_on_fail=False)
        assert report["ok"], report["problems"]


def test_mixed_batch_isolation_sampled_stream_untouched():
    """A sampled request's stream is identical whether its batch-mate
    speculates or not (key-advance discipline: exactly one split per
    emitted token on both paths)."""
    ref = _reference()
    sched = _make_sched(overlap=True, spec=4)
    try:
        got = _workload(sched)
    finally:
        sched.shutdown()
    assert got[1] == ref[1]  # sampled
    assert got[2] == ref[2]  # penalized (rides the counts-carrying cycle)


def test_per_request_spec_k_mixes_and_clamps():
    """spec_k is per-request: a spec_k=0 greedy request next to a spec_k=4
    one gets the same stream as the all-plain run; explicit values clamp
    to the engine's compile-time capacity."""
    sched = _make_sched(overlap=True, spec=4, n_slots=2, chunk=4)
    try:
        r1 = sched.submit(REP, 0.0, 0.9, 12, frozenset(), seed=1, spec_k=4)
        r2 = sched.submit(list(REP), 0.0, 0.9, 12, frozenset(), seed=2,
                          spec_k=0)
        assert r1.spec_k == 4 and r2.spec_k == 0
        out1, out2 = list(r1.tokens()), list(r2.tokens())
        # same prompt, both greedy => identical streams regardless of who
        # speculated; r1 carries a per-request acceptance record, r2 none
        assert out1 == out2
        t1, t2 = r1.timings(), r2.timings()
        assert t1["spec"]["cycles"] > 0 and t1["spec"]["tokens"] > 0
        assert "spec" not in t2
        # clamping: above-capacity asks fold down, None means the default
        r3 = sched.submit([1, 2], 0.0, 0.9, 2, frozenset(), seed=3,
                          spec_k=99)
        assert r3.spec_k == 4
        list(r3.tokens())
        r4 = sched.submit([1, 2], 0.0, 0.9, 2, frozenset(), seed=3)
        assert r4.spec_k == 4  # --spec-k serving default
        list(r4.tokens())
    finally:
        sched.shutdown()


def test_eos_overrun_rewinds_spec_acceptance():
    """An EOS emitted mid-cycle (the model accepted drafts PAST the stop)
    cuts the stream at the EOS token, and keep_rows/slot_tokens record only
    the truly-emitted prefix — reused rows replay bit-exact."""
    sched = _make_sched(overlap=True, spec=4, n_slots=2, chunk=4)
    try:
        probe = sched.submit(REP, 0.0, 0.9, 12, frozenset(), seed=0)
        ref = list(probe.tokens())
        # stop on a mid-stream token at its FIRST occurrence (so the ref
        # prefix up to it is exactly what the stopped stream must emit)
        cut = next(i for i, t in enumerate(ref) if i >= 2 and t not in ref[:i])
        eos = ref[cut]
        req = sched.submit(list(REP), 0.0, 0.9, 40, frozenset([eos]), seed=0)
        got = list(req.tokens())
        assert got == ref[: cut + 1] and req.finish_reason == "stop"
        if sched._radix is None:
            slot = [s for s, t in sched.slot_tokens.items() if t][0]
            assert sched.slot_tokens[slot] == list(REP) + got[:-1]
            assert int(sched.engine.pos[slot]) == len(REP) + len(got) - 1
        follow = list(REP) + got + [11, 12]
        r2 = sched.submit(follow, 0.0, 0.9, 6, frozenset(), seed=5)
        warm = list(r2.tokens())
    finally:
        sched.shutdown()
    cold = _make_sched(overlap=True, spec=0, n_slots=2, chunk=4)
    try:
        r3 = cold.submit(follow, 0.0, 0.9, 6, frozenset(), seed=5)
        assert list(r3.tokens()) == warm, "reused overrun rows changed output"
    finally:
        cold.shutdown()


def test_warm_restart_resumes_spec_streams():
    """A worker crash mid-stream warm-restarts and resumes BOTH a greedy
    spec stream and a sampled one bit-exact, with speculation still live
    after the restart (the resumed slot keeps its per-request spec_k)."""
    from dllama_tpu.utils import faults

    def run(crash):
        sched = _make_sched(overlap=True, spec=4, n_slots=2, chunk=3,
                            restart_max=2)
        sched.restart_backoff_s = 0.01
        try:
            r1 = sched.submit(REP, 0.0, 0.9, 16, frozenset(), seed=1)
            r2 = sched.submit([9, 8, 7], 1.0, 0.9, 12, frozenset(), seed=9)
            it1, it2 = r1.tokens(), r2.tokens()
            head1 = [next(it1) for _ in range(3)]
            head2 = [next(it2) for _ in range(2)]
            if crash:
                faults.install("engine.decode", "raise", times=1)
            out1 = head1 + list(it1)
            out2 = head2 + list(it2)
            assert r1.finish_reason == "length"
            assert r2.finish_reason == "length"
            if crash:
                assert sched.restart_count >= 1, "crash never fired"
                stats = sched.latency_summary()["spec"]
                assert stats["cycles"] > 0
            return out1, out2
        finally:
            faults.clear()
            sched.shutdown()

    assert run(crash=True) == run(crash=False)


def test_draft_writes_never_land_in_shared_pages():
    """The paged draft-write safety drill (tentpole piece 3): spec verify
    writes K+1 rows past the live position, so (a) the pre-dispatch
    cow_writable splits any shared page covering the writable range, and
    (b) PagePool.audit()'s write-horizon check catches the corruption when
    that protection is bypassed."""
    eng = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32,
                      spec=4, kv_layout="paged", page_size=8)
    pool = eng.pool
    eng.add(0, list(range(1, 11)), temperature=0.0)  # pos 10: mid-page
    # manufacture the hazard: share slot 0's CURRENT boundary block (the
    # page its next decode/spec rows land in) with slot 1's table — the
    # state a missed admission-COW or a buggy prefix share would leave
    blk = int(eng.pos[0]) // pool.page_size
    page = int(pool.tables[0, blk])
    with pool._mu:
        pool.refcount[page] += 1
        pool.tables[1, 0] = page
        pool.n_blocks[1] = 1
        pool._publish()
    # (b) the audit names the violation while the share is in place
    with pytest.raises(PoolAuditError, match="shared inside the writable"):
        pool.audit()
    # (a) a spec dispatch COWs the shared page BEFORE any draft write: the
    # cycle runs clean and the writable range is exclusive again
    emit, adv = eng.spec_step()
    assert adv[0] >= 1
    assert int(pool.tables[0, blk]) != page, "shared page was not split"
    assert pool.audit()["ok"]
    # slot 1's artificial claim still holds the ORIGINAL bytes' page
    assert int(pool.tables[1, 0]) == page

    # cleanup so the suite-wide release audit stays meaningful
    with pool._mu:
        pool._decref(page)
        pool.tables[1, 0] = 0
        pool.n_blocks[1] = 0
        pool._publish()
    eng.release(0)
    assert pool.audit()["ok"]


def test_overlap_alternation_advances_row_limit_frozen_slot():
    """Regression (review finding): under overlap, the spec/plain
    alternation toggle must only be consumed by a dispatch that actually
    launches — an aborted pipelined mode-switch dispatch used to eat the
    plain-decode turn, so every launched chunk was spec and a slot near
    its row limit (frozen out of verify cycles) starved forever behind a
    steady greedy spec batch-mate."""
    import threading

    sched = _make_sched(overlap=True, spec=4, n_slots=2, chunk=3)
    try:
        # near-limit request first: pos reaches seq_len-5 right after its
        # commit, inside the K+1 no-verify window — spec cycles freeze it
        near = sched.submit(list(range(1, CFG.seq_len - 5)), 0.0, 0.9, 40,
                            frozenset(), seed=2, spec_k=0)
        spec = sched.submit(REP, 0.0, 0.9, 24, frozenset(), seed=1, spec_k=4)
        done = {}

        def drain(name, req):
            done[name] = (list(req.tokens()), req.finish_reason)

        threads = [threading.Thread(target=drain, args=("near", near)),
                   threading.Thread(target=drain, args=("spec", spec))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), (
            "streams never finished: the frozen slot starved "
            f"(finished: {sorted(done)})")
        # the near-limit request reaches the context edge: 'length' with
        # its full room emitted (the commit token + the 6 decode rows
        # from pos=len(prompt) to seq_len)
        toks, fin = done["near"]
        assert fin == "length" and len(toks) == 7
        assert done["spec"][1] == "length" and len(done["spec"][0]) == 24
    finally:
        sched.shutdown()


def test_spec_acceptance_telemetry_counters():
    """The dllama_spec_* series move when cycles run: cycles, drafted,
    accepted, emitted, and the accepted-length histogram all advance, and
    the engine's spec_stats() mirror agrees with the per-request records."""
    from dllama_tpu.obs import instruments as ins

    c0 = ins.SPEC_CYCLES.value()
    e0 = ins.SPEC_TOKENS.labels(kind="emitted").value()
    d0 = ins.SPEC_TOKENS.labels(kind="drafted").value()
    sched = _make_sched(overlap=True, spec=4, n_slots=2, chunk=4)
    try:
        req = sched.submit(REP, 0.0, 0.9, 16, frozenset(), seed=1)
        out = list(req.tokens())
        stats = sched.latency_summary()["spec"]
    finally:
        sched.shutdown()
    assert len(out) == 16
    assert stats["cycles"] >= 1
    assert ins.SPEC_CYCLES.value() - c0 == stats["cycles"]
    assert ins.SPEC_TOKENS.labels(kind="emitted").value() - e0 == stats["emitted"]
    assert ins.SPEC_TOKENS.labels(kind="drafted").value() - d0 == stats["drafted"]
    # the request's own record covers every token it emitted via cycles
    t = req.timings()
    assert t["spec"]["tokens"] <= stats["emitted"]
    assert t["spec"]["tokens_per_cycle"] is not None


def test_single_engine_spec_guard_names_batched_alternative():
    """decode_spec_greedy_n on a batch>1 engine raises a clean ValueError
    pointing at the batched path (was a bare assert)."""
    from dllama_tpu.engine.engine import InferenceEngine

    eng = InferenceEngine(CFG, PARAMS, cache_dtype=jnp.float32, batch=2)
    with pytest.raises(ValueError, match="BatchEngine"):
        eng.decode_spec_greedy_n([1, 2, 3], 4, 4)
