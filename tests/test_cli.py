"""CLI tests: info / inference modes end-to-end on a tiny on-disk model."""

import numpy as np

from dllama_tpu.cli.main import build_parser, main
from tests.test_serve import make_tiny_files


def test_parser_flags_match_reference_defaults():
    args = build_parser().parse_args(["inference", "--model", "x.m"])
    # reference defaults: temp 0.8, topp 0.9, port 9990 (app.cpp:23-40)
    assert args.temperature == 0.8
    assert args.topp == 0.9
    # --port parses as a None sentinel since ISSUE 15 (the default is
    # per-mode: 9990 serve — the reference's — vs 9980 router), so an
    # EXPLICIT --port 9990 to a router is honored instead of remapped;
    # cmd_serve/cmd_router resolve it
    assert args.port is None
    assert args.mesh == "auto"


def test_cli_info(tmp_path, capsys):
    mpath, tpath, cfg = make_tiny_files(tmp_path)
    assert main(["info", "--model", mpath]) == 0
    out = capsys.readouterr().out
    assert "dim=64" in out and "layers=2" in out and "Q40" in out


def test_cli_inference_generates(tmp_path, capsys):
    mpath, tpath, cfg = make_tiny_files(tmp_path)
    rc = main([
        "inference", "--model", mpath, "--tokenizer", tpath,
        "--prompt", "hello", "--steps", "6", "--temperature", "0", "--seed", "1",
        "--no-mesh",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "Decode:" in err and "tok/s" in err


def test_cli_inference_missing_prompt_errors(tmp_path, capsys):
    mpath, tpath, _ = make_tiny_files(tmp_path)
    assert main(["inference", "--model", mpath, "--tokenizer", tpath]) == 1


def test_cli_inference_report_and_trace(tmp_path, capsys):
    mpath, tpath, cfg = make_tiny_files(tmp_path)
    trace_dir = str(tmp_path / "trace")
    rc = main([
        "inference", "--model", mpath, "--tokenizer", tpath,
        "--prompt", "hello", "--steps", "4", "--temperature", "0", "--seed", "1",
        "--no-mesh", "--report", "--trace", trace_dir,
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "params" in err and "kv-cache" in err  # memory report
    assert "ms/token" in err and "kB/token/chip" in err
    import os
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)  # profiler wrote
