"""Pallas kernel equivalence tests (interpret mode on CPU).

The analog of the reference's nn-cpu-ops-test.cpp: every fused kernel is
checked against the pure-jnp reference implementation with calibrated
tolerances (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.ops.quant import QTensor
from dllama_tpu.ops.pallas.q40_matmul import q40_matmul, supported


@pytest.mark.parametrize(
    "m,k,n",
    [
        (1, 256, 256),  # decode GEMV shape (row-padded to 8 inside)
        (8, 512, 384),
        (16, 1024, 512),
        (128, 256, 1280),  # prefill chunk
        (3, 512, 256),  # odd batch -> pad path
    ],
)
def test_q40_matmul_matches_dequant_dot(rng, m, k, n):
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    w = QTensor.quantize(rng.standard_normal((k, n)).astype(np.float32) * 0.1)
    assert supported(x.shape, w)
    got = q40_matmul(x, w, interpret=True)
    want = jnp.dot(x, w.dequantize(jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)


def test_q40_matmul_batched_lead_dims(rng):
    x = jnp.asarray(rng.standard_normal((2, 4, 256)), jnp.bfloat16)
    w = QTensor.quantize(rng.standard_normal((256, 256)).astype(np.float32) * 0.1)
    got = q40_matmul(x, w, interpret=True)
    assert got.shape == (2, 4, 256)
    assert got.dtype == jnp.bfloat16
    want = jnp.dot(x, w.dequantize(jnp.bfloat16), preferred_element_type=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=8e-2, rtol=8e-2
    )


def test_q40_matmul_exact_on_roundtrip_values(rng):
    """Inputs already on the Q40 grid -> kernel must be exact vs dequant-dot
    (same accumulation dtype), like the reference's epsilon-0 identity cases."""
    w0 = rng.standard_normal((128, 256)).astype(np.float32)
    w = QTensor.quantize(w0)
    x = jnp.eye(128, dtype=jnp.float32)
    got = q40_matmul(x, w, interpret=True)
    want = w.dequantize(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0, rtol=0)
