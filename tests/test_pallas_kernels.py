"""Pallas kernel equivalence tests (interpret mode on CPU).

The analog of the reference's nn-cpu-ops-test.cpp: every fused kernel is
checked against the pure-jnp reference implementation with calibrated
tolerances (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.ops.quant import QTensor
from dllama_tpu.ops.pallas.q40_matmul import q40_matmul, supported


@pytest.mark.parametrize(
    "m,k,n",
    [
        (1, 256, 256),  # decode GEMV shape (row-padded to 8 inside)
        (8, 512, 384),
        (16, 1024, 512),
        (128, 256, 1280),  # prefill chunk
        (3, 512, 256),  # odd batch -> pad path
    ],
)
def test_q40_matmul_matches_dequant_dot(rng, m, k, n):
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    w = QTensor.quantize(rng.standard_normal((k, n)).astype(np.float32) * 0.1)
    assert supported(x.shape, w)
    got = q40_matmul(x, w, interpret=True)
    want = jnp.dot(x, w.dequantize(jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)


def test_q40_matmul_batched_lead_dims(rng):
    x = jnp.asarray(rng.standard_normal((2, 4, 256)), jnp.bfloat16)
    w = QTensor.quantize(rng.standard_normal((256, 256)).astype(np.float32) * 0.1)
    got = q40_matmul(x, w, interpret=True)
    assert got.shape == (2, 4, 256)
    assert got.dtype == jnp.bfloat16
    want = jnp.dot(x, w.dequantize(jnp.bfloat16), preferred_element_type=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=8e-2, rtol=8e-2
    )


def test_q40_matmul_exact_on_roundtrip_values(rng):
    """Inputs already on the Q40 grid -> kernel must be exact vs dequant-dot
    (same accumulation dtype), like the reference's epsilon-0 identity cases."""
    w0 = rng.standard_normal((128, 256)).astype(np.float32)
    w = QTensor.quantize(w0)
    x = jnp.eye(128, dtype=jnp.float32)
    got = q40_matmul(x, w, interpret=True)
    want = w.dequantize(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0, rtol=0)


# ---------------------------------------------------------------- flash attn


@pytest.mark.parametrize(
    "b,t,hq,hkv,hd,s,pos",
    [
        (1, 1, 8, 4, 64, 256, 0),  # decode at start
        (1, 1, 8, 4, 64, 256, 200),  # decode deep in the cache
        (1, 16, 8, 8, 64, 128, 0),  # MHA prefill chunk
        (2, 64, 8, 2, 128, 256, 64),  # GQA batched prefill mid-sequence
        (1, 3, 4, 4, 64, 128, 5),  # odd T -> row-pad path
        (1, 1, 8, 4, 64, 1024, 3),  # decode in a long cache: most kv tiles pruned
    ],
)
def test_flash_attention_matches_jnp(rng, b, t, hq, hkv, hd, s, pos):
    from dllama_tpu.ops.layers import gqa_attention
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    q = jnp.asarray(rng.standard_normal((b, t, hq, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    got = flash_gqa_attention(q, k, v, jnp.int32(pos), interpret=True)
    want = gqa_attention(q, k, v, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("t,pos", [
    (1, 0), (1, 511), (1, 512), (1, 800), (1, 1023), (1, 1500), (1, 2047),
    # prefill chunks: horizon = pos + t picks the covering view, incl. a
    # chunk that ENDS exactly on / just past a bucket boundary
    (16, 0), (16, 496), (16, 497), (64, 960), (64, 1980),
])
def test_flash_attention_bucketed_matches_unbucketed(rng, t, pos):
    """s_buckets dispatches to a power-of-two cache view covering
    max(pos)+t; output must be identical to the full-S grid at every
    position, especially ON the bucket boundaries (horizon 512 rides the
    512 view, horizon 513 the 1024 one)."""
    from dllama_tpu.ops.pallas.flash_attention import _s_buckets, flash_gqa_attention

    assert _s_buckets(2048) == (512, 1024, 2048)
    assert _s_buckets(512) == ()  # nothing to bucket

    q = jnp.asarray(rng.standard_normal((1, t, 8, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 4, 2048, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 4, 2048, 64)), jnp.float32)
    want = flash_gqa_attention(q, k, v, jnp.int32(pos), interpret=True)
    got = flash_gqa_attention(q, k, v, jnp.int32(pos), interpret=True,
                              s_buckets=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0, rtol=0)


def test_flash_attention_bf16_io(rng):
    from dllama_tpu.ops.layers import gqa_attention
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    q = jnp.asarray(rng.standard_normal((1, 8, 8, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 4, 128, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 4, 128, 64)), jnp.bfloat16)
    got = flash_gqa_attention(q, k, v, jnp.int32(32), interpret=True)
    assert got.dtype == jnp.bfloat16
    want = gqa_attention(q, k, v, jnp.int32(32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_attention_in_model_forward(rng):
    """Full forward with the Pallas attn_fn vs the jnp default — end-to-end
    parity, the analog of swapping kernels under the reference executor."""
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import KVCache, forward, random_params
    from dllama_tpu.ops.layers import build_rope_cache
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention
    from functools import partial

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=256, seq_len=64)
    params = random_params(cfg, seed=1, dtype=jnp.float32, quantize=False)
    rope = build_rope_cache(cfg)
    toks = jnp.asarray(rng.integers(0, 256, (1, 8)), jnp.int32)

    cache0 = KVCache.create(cfg, 1, jnp.float32)
    ref_logits, _ = forward(cfg, params, toks, jnp.int32(0), cache0, rope)
    cache1 = KVCache.create(cfg, 1, jnp.float32)
    got_logits, _ = forward(
        cfg, params, toks, jnp.int32(0), cache1, rope,
        attn_fn=partial(flash_gqa_attention, interpret=True),
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), atol=1e-4, rtol=1e-4
    )


# ------------------------------------------------------------------ rms norm


@pytest.mark.parametrize("shape", [(1, 1, 256), (2, 16, 512), (5, 384)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_norm_pallas_matches_jnp(rng, shape, dtype):
    from dllama_tpu.ops.layers import rms_norm as rms_ref
    from dllama_tpu.ops.pallas.rms_norm import rms_norm as rms_pallas

    x = jnp.asarray(rng.standard_normal(shape), dtype)
    w = jnp.asarray(rng.standard_normal(shape[-1]) * 0.5 + 1.0, jnp.float32)
    got = rms_pallas(x, w, 1e-5, interpret=True)
    want = rms_ref(x, w, 1e-5)
    assert got.dtype == want.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=2e-2, rtol=2e-2
    )


@pytest.mark.parametrize("style", ["blockdot", "maskdot", "loopdot", "deq"])
def test_q40_styles_agree(rng, style):
    """Every decode-kernel style computes the same product (maskdot and
    loopdot are the plain-dot fallbacks for blockdot's batched dot_general)."""
    from dllama_tpu.ops.pallas import q40_matmul as qmod

    x = jnp.asarray(rng.standard_normal((3, 512)), jnp.float32)
    w = QTensor.quantize(rng.standard_normal((512, 384)).astype(np.float32) * 0.1)
    want = jnp.dot(x, w.dequantize(jnp.float32))
    old = qmod.STYLE
    try:
        qmod.STYLE = style
        got = q40_matmul(x, w, interpret=True)
    finally:
        qmod.STYLE = old
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)


class TestDispatchKnobs:
    """Contracts for the measurement-session knobs: prefill GEMM routing
    (ops.matmul.XLA_PREFILL_MIN_M) and blockdot tile overrides."""

    def test_xla_prefill_routing_threshold(self, monkeypatch):
        """Pins that the threshold actually ROUTES (not merely that both
        paths agree numerically): the fused kernel is stubbed to raise, so a
        prefill-shaped (t>1) call with m>=threshold must bypass it while
        decode-shaped calls — t==1 at ANY slot count, and 2-D calls — must
        hit it (ADVICE r3: flattened-m routing would starve batched decode)."""
        from dllama_tpu.ops import matmul as mm
        from dllama_tpu.ops.pallas import q40_matmul as qm

        w = QTensor.quantize((np.random.default_rng(0).standard_normal((256, 256)) * 0.05).astype(np.float32))
        x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 64, 256)), jnp.bfloat16)
        ref = np.asarray(mm.matmul(x, w, backend="xla"), np.float32)
        monkeypatch.setattr(mm, "XLA_PREFILL_MIN_M", 32)

        def boom(*a, **k):
            raise AssertionError("fused kernel must not run at prefill m >= threshold")

        monkeypatch.setattr(qm, "q40_matmul", boom)
        got = np.asarray(mm.matmul(x, w, backend="pallas"), np.float32)  # routed
        np.testing.assert_allclose(got, ref, atol=3e-2, rtol=3e-2)
        # decode-shaped calls must invoke the fused kernel even when the
        # flattened row count crosses the threshold (64 slots x t=1), and
        # for plain 2-D calls (no seq axis)
        for shape in ((64, 1, 256), (8, 256)):
            xd = jnp.asarray(
                np.random.default_rng(2).standard_normal(shape), jnp.bfloat16
            )
            with pytest.raises(AssertionError, match="fused kernel"):
                mm.matmul(xd, w, backend="pallas")

    def test_blockdot_tile_override_matches_default(self, monkeypatch):
        from dllama_tpu.ops.pallas import q40_matmul as qm

        w = QTensor.quantize((np.random.default_rng(3).standard_normal((256, 256)) * 0.05).astype(np.float32))
        x = jnp.asarray(np.random.default_rng(4).standard_normal((8, 256)), jnp.bfloat16)
        monkeypatch.setattr(qm, "STYLE", "blockdot")
        want = np.asarray(qm.q40_matmul(x, w, interpret=True), np.float32)
        monkeypatch.setattr(qm, "BLOCKDOT_TK", 128)
        monkeypatch.setattr(qm, "BLOCKDOT_TN", 128)
        got = np.asarray(qm.q40_matmul(x, w, interpret=True), np.float32)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    def test_invalid_tile_override_falls_back(self, monkeypatch):
        from dllama_tpu.ops.pallas import q40_matmul as qm

        w = QTensor.quantize((np.random.default_rng(5).standard_normal((256, 256)) * 0.05).astype(np.float32))
        x = jnp.asarray(np.random.default_rng(6).standard_normal((8, 256)), jnp.bfloat16)
        monkeypatch.setattr(qm, "STYLE", "blockdot")
        monkeypatch.setattr(qm, "BLOCKDOT_TK", 16)   # divides k=256 but NOT
        # Q_BLOCK-aligned (16 % 32 != 0): the alignment clause must reject it
        monkeypatch.setattr(qm, "BLOCKDOT_TN", 100)  # does not divide n: ignored
        got = np.asarray(qm.q40_matmul(x, w, interpret=True), np.float32)
        ref = np.asarray(w.dequantize(jnp.float32), np.float32)
        want = np.asarray(x, np.float32) @ ref
        np.testing.assert_allclose(got, want, atol=3e-2, rtol=3e-2)


# ---------------------------------------------------------------- q80 matmul


@pytest.mark.parametrize("m", [1, 8, 64])
def test_q80_matmul_matches_dequant_dot(rng, m):
    """Fused Q80 kernels (blockdot m<=16, deq m>16) vs the XLA dequant dot."""
    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul, supported
    from dllama_tpu.ops.quant import Q8Tensor, quantize_q80_np

    k, n = 128, 256
    w = (rng.standard_normal((n, k)) * 0.1).astype(np.float32)
    codes, scales = quantize_q80_np(w.reshape(-1))
    qt = Q8Tensor.from_file_layout(codes, scales, n, k)
    assert supported((m, k), qt)
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    got = q80_matmul(x, qt, interpret=True)
    want = jnp.dot(x, qt.dequantize(jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-3, rtol=1e-3)


def test_q80_matmul_stacked_layer_index(rng):
    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul
    from dllama_tpu.ops.quant import Q8Tensor, quantize_q80_np

    k, n, L = 128, 128, 3
    layers = []
    for _ in range(L):
        w = (rng.standard_normal((n, k)) * 0.1).astype(np.float32)
        codes, scales = quantize_q80_np(w.reshape(-1))
        layers.append(Q8Tensor.from_file_layout(codes, scales, n, k))
    st = Q8Tensor(jnp.stack([l.codes for l in layers]),
                  jnp.stack([l.scales for l in layers]))
    x = jnp.asarray(rng.standard_normal((8, k)), jnp.float32)
    for li in range(L):
        got = q80_matmul(x, st, jnp.int32(li), interpret=True)
        want = jnp.dot(x, layers[li].dequantize(jnp.float32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-3, rtol=1e-3)


def test_flash_attention_bucketed_vector_pos(rng):
    """Bucketed dispatch under PER-ROW positions (batched decode): the
    horizon is max(pos) + t, so the batch rides the view covering its
    deepest slot and every row stays exact."""
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    q = jnp.asarray(rng.standard_normal((2, 1, 8, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 4, 2048, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, 2048, 64)), jnp.float32)
    for pos in ([3, 300], [500, 511], [100, 1900]):
        pv = jnp.asarray(pos, jnp.int32)
        want = flash_gqa_attention(q, k, v, pv, interpret=True)
        got = flash_gqa_attention(q, k, v, pv, interpret=True, s_buckets=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=0, rtol=0)


def test_q80_matmul_bf16_and_odd_rows(rng):
    """q80 kernels: bf16 activations keep exactness of int8 codes, and odd
    row counts take the pad path."""
    from dllama_tpu.ops.pallas.q80_matmul import q80_matmul
    from dllama_tpu.ops.quant import Q8Tensor

    k, n = 256, 128
    w = Q8Tensor.quantize((rng.standard_normal((k, n)) * 0.1).astype(np.float32))
    for m, dt in ((3, jnp.float32), (8, jnp.bfloat16), (2, jnp.bfloat16)):
        x = jnp.asarray(rng.standard_normal((m, k)), dt)
        got = q80_matmul(x, w, interpret=True)
        want = jnp.dot(x, w.dequantize(dt),
                       preferred_element_type=jnp.float32).astype(dt)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   atol=5e-2, rtol=5e-2)
        assert got.dtype == dt and got.shape == (m, n)
