"""HTTP API server tests: OpenAI contract, SSE streaming, prefix cache.

End-to-end over a real socket with a tiny on-disk model — the analog of the
reference's api-client example against dllama-api, but automated."""

import http.client
import json
import threading

import numpy as np
import pytest

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.formats import save_model, tensor_plan
from dllama_tpu.tokenizer.tokenizer import Tokenizer


def make_tiny_files(tmp_path, seed=0):
    vocab = [bytes([i]) for i in range(256)]
    scores = [0.0] * 256
    for piece, score in {b"he": 1.0, b"ll": 2.0, b"hello": 4.0}.items():
        vocab.append(piece)
        scores.append(score)
    bos_id = len(vocab)
    vocab += [b"<s>", b"</s>"]
    scores += [0.0, 0.0]
    tok = Tokenizer(
        vocab, scores, bos_id, [bos_id + 1],
        chat_template="...<|start_header_id|>...",
    )
    tpath = str(tmp_path / "tok.t")
    tok.save(tpath)

    cfg = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=len(vocab), seq_len=512,
    )
    rng = np.random.default_rng(seed)
    tensors = {}
    for name, shape, ft in tensor_plan(cfg):
        if name.endswith(("rms_att", "rms_ffn")) or name == "final_norm":
            tensors[name] = np.ones(shape, np.float32)
        else:
            tensors[name] = (rng.standard_normal(shape) * 0.05).astype(np.float32)
    mpath = str(tmp_path / "model.m")
    save_model(mpath, cfg, tensors)
    return mpath, tpath, cfg


@pytest.fixture(scope="module", params=["aio", "threads"])
def server(tmp_path_factory, request):
    """The whole HTTP contract matrix runs against BOTH front-ends (ISSUE
    15): the selectors event loop (`aio`, the default) and the
    thread-per-connection baseline (`threads`) must serve identical
    semantics."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    tmp_path = tmp_path_factory.mktemp("serve")
    mpath, tpath, cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0,
                             frontend=request.param)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield httpd.server_address[1], api
    httpd.shutdown()


def post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, json.dumps(body), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_models_endpoint(server):
    port, _ = server
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/v1/models")
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200
    assert body["object"] == "list"
    assert body["data"][0]["id"] == "dllama-tpu"


def test_chat_completion_contract(server):
    port, _ = server
    status, data = post(port, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8, "temperature": 0.0,
    })
    assert status == 200
    body = json.loads(data)
    assert body["object"] == "chat.completion"
    choice = body["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] <= 8
    assert body["usage"]["total_tokens"] == body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"]


def test_chat_completion_deterministic_with_temp0(server):
    port, _ = server
    req = {"messages": [{"role": "user", "content": "abc"}], "max_tokens": 6, "temperature": 0.0}
    _, d1 = post(port, "/v1/chat/completions", req)
    _, d2 = post(port, "/v1/chat/completions", req)
    assert json.loads(d1)["choices"][0]["message"] == json.loads(d2)["choices"][0]["message"]


def test_prefix_cache_reuses_kv(server):
    port, api = server
    first = {"messages": [{"role": "user", "content": "one"}], "max_tokens": 4, "temperature": 0.0}
    _, d1 = post(port, "/v1/chat/completions", first)
    reply = json.loads(d1)["choices"][0]["message"]["content"]
    cached_pos = api.cache.pos
    assert cached_pos > 0
    assert api.cache.messages[-1] == ("assistant", reply)

    # extending the conversation must resolve to a delta (start_pos == cached)
    extended = {
        "messages": first["messages"]
        + [{"role": "assistant", "content": reply}, {"role": "user", "content": "two"}],
        "max_tokens": 4,
        "temperature": 0.0,
    }
    delta, start_pos, add_bos = api.cache.resolve(
        [(m["role"], str(m["content"])) for m in extended["messages"]]
    )
    assert start_pos == cached_pos and not add_bos
    assert [r for r, _ in delta] == ["user"]
    status, d2 = post(port, "/v1/chat/completions", extended)
    assert status == 200
    # a fresh unrelated conversation resets the cache
    _, _ = post(port, "/v1/chat/completions", {"messages": [{"role": "user", "content": "zzz"}], "max_tokens": 2})
    assert api.cache.messages[0] == ("user", "zzz")


def test_streaming_sse(server):
    port, _ = server
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(
        "POST", "/v1/chat/completions",
        json.dumps({"messages": [{"role": "user", "content": "hi"}], "max_tokens": 5,
                    "temperature": 0.0, "stream": True}),
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    conn.close()
    events = [line[6:] for line in raw.splitlines() if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    # streamed text == non-streamed text for the same deterministic request
    _, d = post(port, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hi"}], "max_tokens": 5, "temperature": 0.0,
    })
    assert text == json.loads(d)["choices"][0]["message"]["content"]


def test_bad_requests(server):
    port, _ = server
    status, data = post(port, "/v1/chat/completions", {"messages": []})
    assert status == 400
    status, _ = post(port, "/nope", {})
    assert status == 404


def test_penalties_param_single_tier(server):
    """presence/frequency penalties are honored (deterministic seed: the
    penalized and plain completions must differ) on the single-engine tier."""
    port, _ = server
    base = {"messages": [{"role": "user", "content": "hello hello hello"}],
            "temperature": 0.0, "max_tokens": 12, "seed": 3}
    st1, d1 = post(port, "/v1/chat/completions", base)
    st2, d2 = post(port, "/v1/chat/completions",
                   dict(base, frequency_penalty=0.8, presence_penalty=0.5))
    assert st1 == st2 == 200
    plain, pen = json.loads(d1), json.loads(d2)
    assert plain["choices"][0]["message"] != pen["choices"][0]["message"]


def test_penalties_on_batched_tier(tmp_path):
    """The continuous-batching tier honors penalties too: penalized and
    plain greedy completions differ (per-slot counts in the fused
    multi-slot scan)."""
    import threading

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    mpath, tpath, cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = {"messages": [{"role": "user", "content": "hello hello"}],
                "temperature": 0.0, "max_tokens": 10, "seed": 3}
        st1, d1 = post(httpd.server_address[1], "/v1/chat/completions", base)
        st2, d2 = post(httpd.server_address[1], "/v1/chat/completions",
                       dict(base, frequency_penalty=0.9, presence_penalty=0.5))
        assert st1 == st2 == 200
        plain, pen = json.loads(d1), json.loads(d2)
        assert plain["choices"][0]["message"] != pen["choices"][0]["message"]
    finally:
        httpd.shutdown()


def test_legacy_completions_endpoint(server):
    """POST /v1/completions: raw prompt (no chat template), text choices,
    greedy determinism, and explicit stop strings."""
    port, _ = server
    body = {"prompt": "hello", "temperature": 0.0, "max_tokens": 8, "seed": 1}
    st1, d1 = post(port, "/v1/completions", body)
    st2, d2 = post(port, "/v1/completions", body)
    assert st1 == st2 == 200
    r1, r2 = json.loads(d1), json.loads(d2)
    assert r1["object"] == "text_completion"
    assert r1["choices"][0]["text"] == r2["choices"][0]["text"]
    assert r1["usage"]["completion_tokens"] <= 8
    # bad prompt -> 400
    st3, _ = post(port, "/v1/completions", {"prompt": ""})
    assert st3 == 400


def test_legacy_completions_stream(server):
    port, _ = server
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": "hi", "temperature": 0.0,
                             "max_tokens": 6, "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read().decode()
    conn.close()
    assert resp.status == 200
    assert '"object": "text_completion"' in data
    assert "data: [DONE]" in data


def test_legacy_completions_batched_tier(tmp_path):
    import threading

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    mpath, tpath, cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        st, d = post(httpd.server_address[1], "/v1/completions",
                     {"prompt": "abc", "temperature": 0.0, "max_tokens": 6,
                      "seed": 2})
        assert st == 200
        r = json.loads(d)
        assert r["object"] == "text_completion"
        assert r["choices"][0]["finish_reason"] in ("stop", "length")
    finally:
        httpd.shutdown()


def test_stream_validation_errors_before_headers(server):
    """A stream request with an invalid body must get a clean HTTP 400, not
    a corrupted chunked stream (validation runs before headers go out)."""
    port, _ = server
    st, data = post(port, "/v1/completions",
                    {"prompt": "", "stream": True})
    assert st == 400 and b"prompt" in data
    st2, data2 = post(port, "/v1/chat/completions",
                      {"messages": [], "stream": True})
    assert st2 == 400 and b"messages" in data2
    # malformed shapes too (a TypeError after headers would corrupt the stream)
    for bad in ("hi", [{"content": "x"}]):
        st3, _ = post(port, "/v1/chat/completions",
                      {"messages": bad, "stream": True})
        assert st3 == 400
