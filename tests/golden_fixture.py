"""Deterministic golden fixtures: an in-repo byte-level BPE in the llama3
tiktoken format, and a tiny seeded `.m` checkpoint.

The reference pins encode goldens against the real llama3 vocabulary
(tokenizer-test.cpp:44-80, gated behind DEV_TESTS because it needs the 128k
vocab file). This environment has no network, so the fixture vocabulary is
*trained here* — classic byte-pair merging over a fixed multilingual corpus
(ASCII, UTF-8 accents, CJK, emoji — the same stress classes as the reference
cases) with deterministic tie-breaking. The training is pure arithmetic: the
resulting ranks are stable across platforms/versions, so the golden token ids
in test_golden.py pin the WHOLE pipeline — tiktoken-format parsing
(convert_llama3_tokenizer), score assignment, special-token scan, and the
heap-BPE merge order (python and native) — exactly like the reference's
dev tests pin its tokenizer.cpp.
"""

from __future__ import annotations

import base64

CORPUS = (
    "The quick brown fox jumps over the lazy dog. "
    "Pack my box with five dozen liquor jugs!? "
    "user assistant system header the and ing er est ly tion "
    "hello world hello there what is the meaning of life? "
    "<|start_header_id|>user<|end_header_id|> nonsense plain text form "
    "!!&&@(*x)^^! punctuation (parens) [brackets] {braces} *stars* "
    "Zwölf Boxkämpfer jagen Viktor quer über den großen Sylter Deich. "
    "Voyez le brick géant que j'examine près du wharf. "
    "Стремглав наш банк грозит, вчуже объём. "
    "色は匂へど 散りぬるを 我が世誰ぞ 常ならむ "
    "天地玄黄 宇宙洪荒 日月盈昃 辰宿列张 "
    "😃!😇x 😀😃😄😁 🚀🌍✨ ❤️🔥 "
    "numbers 0123456789 12345 3.14159 2026-07-30 "
).encode("utf-8") * 2


def train_bpe(corpus: bytes = CORPUS, n_merges: int = 700) -> list[bytes]:
    """Greedy byte-pair merging; ties broken by smallest pair bytes. Returns
    the rank-ordered vocab: 256 single bytes, then one token per merge."""
    seq: list[bytes] = [bytes([b]) for b in corpus]
    vocab: list[bytes] = [bytes([i]) for i in range(256)]
    for _ in range(n_merges):
        counts: dict[tuple[bytes, bytes], int] = {}
        for a, b in zip(seq, seq[1:]):
            counts[(a, b)] = counts.get((a, b), 0) + 1
        if not counts:
            break
        pair = min(counts, key=lambda p: (-counts[p], p))
        if counts[pair] < 2:
            break
        merged = pair[0] + pair[1]
        vocab.append(merged)
        out: list[bytes] = []
        i = 0
        while i < len(seq):
            if i + 1 < len(seq) and seq[i] == pair[0] and seq[i + 1] == pair[1]:
                out.append(merged)
                i += 2
            else:
                out.append(seq[i])
                i += 1
        seq = out
    return vocab


def write_tiktoken_file(path: str, vocab: list[bytes] | None = None) -> None:
    """The llama3 `tokenizer.model` wire format: `base64(token) rank` lines."""
    vocab = vocab or train_bpe()
    with open(path, "w", encoding="utf-8") as f:
        for rank, token in enumerate(vocab):
            f.write(f"{base64.b64encode(token).decode()} {rank}\n")


def naive_bpe_encode(vocab: list[bytes], scores: list[float], data: bytes) -> list[int]:
    """Independent O(n^2) reference encoder: seed with the longest-prefix
    single-byte path, then repeatedly apply the single best-scoring merge.
    Used as a differential oracle against the production heap/native BPE."""
    index = {v: i for i, v in enumerate(vocab)}
    toks = [index[bytes([b])] for b in data]
    while True:
        best = None
        for j in range(len(toks) - 1):
            tid = index.get(vocab[toks[j]] + vocab[toks[j + 1]])
            if tid is not None and (best is None or scores[tid] > best[0]):
                best = (scores[tid], tid, j)
        if best is None:
            break
        _, tid, j = best
        toks[j : j + 2] = [tid]
    return toks
