"""Continuous-batching serving tier tests: scheduler semantics + the HTTP
server with n_slots > 0 handling concurrent requests correctly."""

import http.client
import json
import threading
from concurrent.futures import ThreadPoolExecutor

import pytest

from tests.test_serve import make_tiny_files, post


@pytest.fixture(scope="module")
def cserver(tmp_path_factory):
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    tmp_path = tmp_path_factory.mktemp("cserve")
    mpath, tpath, cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=3)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield httpd.server_address[1], api
    api.scheduler.shutdown()
    httpd.shutdown()


def _req(content, max_tokens=8, temperature=0.0):
    return {
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "temperature": temperature,
    }


def test_single_request_roundtrip(cserver):
    port, api = cserver
    status, data = post(port, "/v1/chat/completions", _req("hello"))
    assert status == 200
    out = json.loads(data)
    assert out["choices"][0]["finish_reason"] in ("stop", "length")
    assert out["usage"]["completion_tokens"] >= 1


def test_concurrent_requests_all_complete_and_match_serial(cserver):
    port, api = cserver
    prompts = ["hello", "hell", "lo there", "he he", "xyz"]

    # serial references (greedy -> deterministic regardless of batching)
    serial = {}
    for p in prompts:
        _, data = post(port, "/v1/chat/completions", _req(p))
        serial[p] = json.loads(data)["choices"][0]["message"]["content"]

    with ThreadPoolExecutor(max_workers=5) as ex:
        futs = {p: ex.submit(post, port, "/v1/chat/completions", _req(p)) for p in prompts}
        results = {p: f.result(timeout=300) for p, f in futs.items()}
    for p, (status, data) in results.items():
        assert status == 200
        got = json.loads(data)["choices"][0]["message"]["content"]
        assert got == serial[p], f"prompt {p!r}: batched {got!r} != serial {serial[p]!r}"


def test_streaming_in_continuous_mode(cserver):
    port, api = cserver
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    body = dict(_req("hello"), stream=True)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    assert "data: [DONE]" in raw
    deltas = [json.loads(line[5:]) for line in raw.splitlines()
              if line.startswith("data:") and "[DONE]" not in line]
    finish = [d["choices"][0].get("finish_reason") for d in deltas]
    assert any(f in ("stop", "length") for f in finish)


def test_scheduler_direct_budget_and_eos():
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)
    eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=4)
    try:
        # budget finish
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 5, eos_ids=frozenset())
        toks = list(r1.tokens())
        assert len(toks) == 5 and r1.finish_reason == "length"
        # eos finish: use whatever token the model emits first as the eos id
        r2 = sched.submit([4, 5], 0.0, 0.9, 50, eos_ids=frozenset())
        first = next(iter(r2.tokens()))
        sched.cancel(r2)
        list(r2.tokens())
        r3 = sched.submit([4, 5], 0.0, 0.9, 50, eos_ids=frozenset([first]))
        toks3 = list(r3.tokens())
        assert toks3[-1] == first and r3.finish_reason == "stop"
        # slot is recycled
        assert eng.free_slot() is not None
    finally:
        sched.shutdown()


def test_scheduler_latency_metrics():
    """TTFT / inter-token marks are stamped and aggregated (VERDICT r1 #10)."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=9, dtype=jnp.float32, quantize=False)
    be = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
    sched = Scheduler(be, chunk=2)
    try:
        req = sched.submit([1, 2, 3], 0.0, 0.9, 6, frozenset(), seed=5)
        toks = list(req.tokens())
        assert len(toks) == 6
        assert req.ttft_ms is not None and req.ttft_ms >= 0
        assert req.itl_ms is not None and req.itl_ms >= 0
        agg = sched.latency_summary()
        assert agg["completed"] == 1
        assert agg["ttft_ms_mean"] == pytest.approx(req.ttft_ms)
    finally:
        sched.shutdown()


def test_copy_prefix_rows_engine_level():
    """Deterministic coverage of the cross-slot KV copy itself (the
    scheduler test below can satisfy its reuse assertion through same-slot
    matching when request A finishes early): copy an ACTIVE slot's prefix
    rows into another slot, delta-prefill there, and match a cold engine."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=96)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)
    system = list(range(1, 21))
    delta = [40, 41, 42]

    eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
    assert eng.supports_cross_slot_copy
    eng.add(0, system + [30], temperature=0.0, seed=0)  # slot 0 active donor
    eng.copy_prefix_rows(0, 1, len(system))
    f_shared = eng.add(1, delta, temperature=0.0, start_pos=len(system), seed=1)
    toks_shared = eng.decode(6)[:, 1]

    cold = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
    cold.add(0, system + [30], temperature=0.0, seed=0)  # same batch-mate
    f_cold = cold.add(1, system + delta, temperature=0.0, seed=1)
    toks_cold = cold.decode(6)[:, 1]
    assert f_shared == f_cold
    assert [int(t) for t in toks_shared] == [int(t) for t in toks_cold]


def test_cross_slot_prefix_share():
    """Two requests with a common system prompt on DIFFERENT slots: the
    second reuses the first slot's KV rows (cross-slot copy when A is still
    decoding, same-slot LCP reuse if A finished first — both count) — and
    its output is identical to a cold run."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=128)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)
    system = list(range(1, 25))  # 24-token shared "system prompt"
    p_a = system + [30, 31]
    p_b = system + [40, 41]

    eng = BatchEngine(cfg, params, n_slots=3, cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=2)
    try:
        r_a = sched.submit(p_a, 0.0, 0.9, 24, eos_ids=frozenset(), seed=0)
        it = r_a.tokens()
        first_a = [next(it), next(it)]  # A is decoding on its slot
        r_b = sched.submit(p_b, 0.0, 0.9, 8, eos_ids=frozenset(), seed=0)
        got_b = list(r_b.tokens())
        got_a = first_a + list(it)
        # B's admission must have reused the shared system prefix from A's
        # slot (A was still active: reuse len(system) tokens via copy)
        assert sched.reused_prefix_tokens >= len(system)
    finally:
        sched.shutdown()

    # cold reference for B
    eng2 = BatchEngine(cfg, params, n_slots=3, cache_dtype=jnp.float32)
    sched2 = Scheduler(eng2, chunk=2)
    try:
        cold_b = list(sched2.submit(p_b, 0.0, 0.9, 8, eos_ids=frozenset(), seed=0).tokens())
        cold_a = list(sched2.submit(p_a, 0.0, 0.9, 24, eos_ids=frozenset(), seed=0).tokens())
    finally:
        sched2.shutdown()
    assert got_b == cold_b, "cross-slot shared prefix changed B's output"
    assert got_a == cold_a


def test_interleaved_admission_matches_synchronous_and_records_stalls():
    """A long prompt joining a running batch under STRICT interleaving
    (budget 0: one prefill chunk per decode chunk, VERDICT r3 #4) streams
    tokens identical to the legacy synchronous admission, and the decode-gap
    metric records the stalls admission work inserted between decode chunks.
    (The default paced budget is covered by
    test_admission_pacing_budget_and_deadline; budget 0 here keeps this
    test exercising the strict path its name describes.)"""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=128)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)
    long_prompt = list(range(1, 31))  # 30 tokens = 4+ chunks at chunk width 8

    def run(interleave):
        eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32,
                          max_prefill_chunk=8)
        # prefill_budget=0 pins the legacy phase-split path this test
        # A/Bs (the hybrid fused step is covered by tests/test_hybrid.py)
        sched = Scheduler(eng, chunk=2, admit_interleave=interleave,
                          admit_stall_budget_ms=0.0, prefill_budget=0)
        try:
            r1 = sched.submit([1, 2, 3], 0.0, 0.9, 40, eos_ids=frozenset(), seed=1)
            it = r1.tokens()
            first = [next(it), next(it)]  # r1 is decoding before r2 arrives
            r2 = sched.submit(long_prompt, 0.0, 0.9, 8, eos_ids=frozenset(), seed=2)
            toks2 = list(r2.tokens())
            toks1 = first + list(it)
            return toks1, toks2, sched.latency_summary()
        finally:
            sched.shutdown()

    il1, il2, ilsum = run(True)
    sy1, sy2, sysum = run(False)
    assert il1 == sy1 and il2 == sy2  # greedy output independent of admission mode
    # the admission ran while r1 decoded, so at least one decode-gap sample
    # was recorded in each mode
    assert ilsum["admission_gaps"] >= 1
    assert ilsum["admission_stall_ms_max"] is not None


def test_admission_pacing_budget_and_deadline():
    """Paced admission (VERDICT r4 weak #3): the stall budget controls how
    many prefill chunks run between decode chunks — budget 0 is strict
    one-chunk interleaving (many small stalls), an unbounded budget pumps the
    whole admission in one visit (one big stall, the synchronous TTFT floor),
    and an expired TTFT deadline overrides the budget. Greedy output is
    identical in every mode."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=128)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)
    long_prompt = list(range(1, 31))  # 30 tokens = 5 pow-2 chunks at width 8

    def run(**kw):
        eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32,
                          max_prefill_chunk=8)
        # prefill_budget=0: this test drives the legacy pacing knobs
        sched = Scheduler(eng, chunk=2, prefill_budget=0, **kw)
        try:
            r1 = sched.submit([1, 2, 3], 0.0, 0.9, 40, eos_ids=frozenset(), seed=1)
            it = r1.tokens()
            first = [next(it), next(it)]  # r1 decodes before the join
            r2 = sched.submit(long_prompt, 0.0, 0.9, 8, eos_ids=frozenset(), seed=2)
            toks2 = list(r2.tokens())
            toks1 = first + list(it)
            return (toks1, toks2), sched.latency_summary()["admission_gaps"]
        finally:
            sched.shutdown()

    strict, strict_gaps = run(admit_stall_budget_ms=0.0)
    paced, paced_gaps = run(admit_stall_budget_ms=1e9)
    dead, dead_gaps = run(admit_stall_budget_ms=0.0, admit_ttft_deadline_ms=0.0)
    assert strict == paced == dead  # pacing never changes tokens
    # strict: every prefill chunk is a separate decode-gap visit; unbounded
    # budget / expired deadline: the whole admission lands in one visit
    assert strict_gaps >= 3
    assert paced_gaps <= 2
    assert dead_gaps <= 2

    # a BURST of overdue joiners must not drain as one mega-stall: the
    # deadline override applies per admission, so each lands in its own
    # visit with a decode chunk between (>= 2 gap samples, not 1). The
    # large budget is the regression trigger: an overdue commit must yield
    # the visit even when the budget clock says there is time left
    eng = BatchEngine(cfg, params, n_slots=3, cache_dtype=jnp.float32,
                      max_prefill_chunk=8)
    sched = Scheduler(eng, chunk=2, admit_stall_budget_ms=1e9,
                      admit_ttft_deadline_ms=0.0, prefill_budget=0)
    try:
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 40, eos_ids=frozenset(), seed=1)
        it = r1.tokens()
        _ = [next(it), next(it)]
        j1 = sched.submit(long_prompt, 0.0, 0.9, 8, eos_ids=frozenset(), seed=2)
        j2 = sched.submit(list(range(31, 61)), 0.0, 0.9, 8,
                          eos_ids=frozenset(), seed=3)
        list(j1.tokens()), list(j2.tokens()), list(it)
        assert sched.latency_summary()["admission_gaps"] >= 2
    finally:
        sched.shutdown()


def test_scheduler_prefix_cache_reuses_slot_rows():
    """Second turn of a conversation prefills only the delta (VERDICT r2 #6):
    the slot's kept KV rows are matched by token prefix and BatchEngine.add
    starts from the cached position — and the continuation is identical to a
    cold prefill of the full prompt."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=2, dtype=jnp.float32, quantize=False)

    turn1 = [1, 2, 3, 4, 5]

    def run_turn2(sched, turn2):
        req = sched.submit(turn2, 0.0, 0.9, 4, eos_ids=frozenset(), seed=0)
        return list(req.tokens())

    # warm scheduler: turn 1 completes, then turn 2 extends it
    eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=4)
    try:
        r1 = sched.submit(turn1, 0.0, 0.9, 4, eos_ids=frozenset(), seed=0)
        gen1 = list(r1.tokens())
        # the conversation so far, as its KV rows saw it (last token unfed)
        fed = turn1 + gen1[:-1]
        turn2 = turn1 + gen1 + [7, 8]
        warm = run_turn2(sched, turn2)
        assert sched.reused_prefix_tokens == len(fed)
        # cold engine: full prefill of the same turn-2 prompt
        eng2 = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32)
        sched2 = Scheduler(eng2, chunk=4)
        try:
            cold = run_turn2(sched2, turn2)
            assert sched2.reused_prefix_tokens == 0
        finally:
            sched2.shutdown()
        assert warm == cold
    finally:
        sched.shutdown()


def test_scheduler_spec_matches_plain_greedy():
    """The scheduler's speculative cycles must stream the same greedy tokens
    as plain chunked decode, including the near-seq_len fallback to
    decode() (spec_step freezes slots without a K+1 window)."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    prompt = [1, 2, 3, 1, 2, 3, 1, 2]

    def run(spec):
        eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32,
                          spec=spec)
        sched = Scheduler(eng, chunk=4)
        try:
            # budget large enough that the request runs into the seq_len
            # region where spec_step would freeze the slot (pos > 64-K-1)
            req = sched.submit(prompt, 0.0, 0.9, 54, eos_ids=frozenset())
            return list(req.tokens()), req.finish_reason
        finally:
            sched.shutdown()

    want, want_fin = run(0)
    got, got_fin = run(6)
    assert got == want and got_fin == want_fin == "length"


def test_scheduler_spec_survives_mixed_penalized_batch():
    """One penalized request in the batch must not disable speculation for
    everyone (VERDICT r4 next #6): the scheduler alternates spec cycles with
    decode chunks, and BOTH streams match their spec=0 runs exactly."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    p_plain, p_pen = [1, 2, 3, 1, 2, 3, 1, 2], [9, 8, 7]

    def run(spec):
        eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32,
                          spec=spec)
        sched = Scheduler(eng, chunk=4)
        try:
            r1 = sched.submit(p_plain, 0.0, 0.9, 16, eos_ids=frozenset())
            r2 = sched.submit(p_pen, 0.0, 0.9, 16, eos_ids=frozenset(),
                              presence=0.6, frequency=0.4)
            out2 = list(r2.tokens())  # drain penalized first: r1 keeps the
            out1 = list(r1.tokens())  # batch mixed while r2 is in flight
            return out1, out2
        finally:
            sched.shutdown()

    want1, want2 = run(0)
    got1, got2 = run(6)
    assert got1 == want1
    assert got2 == want2
