"""Engine tests: chunked prefill == one-shot, generation, sampling."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.engine.engine import GenerationStats, InferenceEngine
from dllama_tpu.engine.sampling import Sampler, sample
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params

TINY = LlamaConfig(
    dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=64
)


def make_engine(seed=0, **kw):
    params = random_params(TINY, seed=seed, dtype=jnp.float32, quantize=False)
    kw.setdefault("cache_dtype", jnp.float32)
    return InferenceEngine(TINY, params, **kw)


def test_chunked_prefill_matches_single_step():
    e1 = make_engine(max_prefill_chunk=4)
    e2 = make_engine(max_prefill_chunk=64)
    prompt = np.arange(1, 14, dtype=np.int32)[None]  # 13 tokens -> chunks 4,4,4,1
    l1 = np.asarray(e1.prefill(prompt))
    l2 = np.asarray(e2.prefill(prompt))
    assert e1.pos == e2.pos == 13
    np.testing.assert_allclose(l1, l2, atol=1e-5, rtol=1e-4)


def test_generate_greedy_deterministic():
    e = make_engine()
    sampler = Sampler(temperature=0.0)
    toks1 = list(e.generate([1, 2, 3], 10, sampler, stats=GenerationStats()))
    e2 = make_engine()
    toks2 = list(e2.generate([1, 2, 3], 10, sampler))
    assert toks1 == toks2
    assert len(toks1) == 10
    assert all(0 <= t < TINY.vocab_size for t in toks1)


def test_decode_greedy_n_matches_stepwise():
    """Fused on-device scan decode == host-loop greedy decode."""
    e1 = make_engine()
    sampler = Sampler(temperature=0.0)
    toks1 = list(e1.generate([1, 2, 3], 9, sampler))

    e2 = make_engine()
    logits = e2.prefill(np.array([[1, 2, 3]], dtype=np.int32))
    first = int(np.asarray(jnp.argmax(logits, -1))[0])
    rest = e2.decode_greedy_n(np.array([first]), 8)[:, 0].tolist()
    assert [first] + rest == toks1


def test_generate_respects_seq_len():
    e = make_engine(max_seq_len=16)
    sampler = Sampler(temperature=0.0)
    toks = list(e.generate([1, 2, 3], 100, sampler))
    assert e.pos <= 16


def test_reset_prefix_reuse():
    """reset(pos) replays from a cached prefix — the engine-level primitive
    under the API server's NaiveCache (dllama-api.cpp:264-309)."""
    e = make_engine()
    prompt = np.array([[1, 2, 3, 4]], dtype=np.int32)
    l_full = np.asarray(e.prefill(prompt))
    e.reset(2)
    l_replay = np.asarray(e.prefill(prompt[:, 2:]))
    np.testing.assert_allclose(l_full, l_replay, atol=1e-5, rtol=1e-4)


def test_sample_greedy_vs_temperature():
    logits = jnp.asarray(np.log(np.array([[0.05, 0.05, 0.8, 0.1]], dtype=np.float32)))
    key = jax.random.PRNGKey(0)
    assert int(sample(logits, key, temperature=0.0)[0]) == 2
    # topp=0.5 nucleus keeps only token 2
    for s in range(5):
        assert int(sample(logits, jax.random.PRNGKey(s), temperature=1.0, topp=0.5)[0]) == 2


def test_sample_distribution_roughly_matches():
    probs = np.array([0.1, 0.2, 0.3, 0.4], dtype=np.float32)
    logits = jnp.asarray(np.log(probs)[None].repeat(2000, 0))
    keys = jax.random.PRNGKey(7)
    toks = np.asarray(sample(logits, keys, temperature=1.0, topp=0.0))
    freq = np.bincount(toks, minlength=4) / len(toks)
    np.testing.assert_allclose(freq, probs, atol=0.05)


def test_decode_sample_n_greedy_matches_decode_greedy_n():
    """temp=0 through the fused sampled path == the greedy fused path."""
    e1, e2 = make_engine(), make_engine()
    p = np.array([[1, 2, 3]], np.int32)
    l1, l2 = e1.prefill(p), e2.prefill(p)
    first = np.asarray(jnp.argmax(l1, -1)).astype(np.int32)
    s = Sampler(temperature=0.0, topp=0.9, seed=3)
    got = e1.decode_sample_n(first, 6, s)
    want = e2.decode_greedy_n(first, 6)
    np.testing.assert_array_equal(got, want)


def test_decode_sample_n_reproducible_with_seed():
    e1, e2 = make_engine(), make_engine()
    p = np.array([[1, 2, 3]], np.int32)
    e1.prefill(p), e2.prefill(p)
    a = e1.decode_sample_n(np.array([[5]]), 8, Sampler(0.9, 0.9, seed=11))
    b = e2.decode_sample_n(np.array([[5]]), 8, Sampler(0.9, 0.9, seed=11))
    np.testing.assert_array_equal(a, b)
    c = e1.decode_sample_n(np.array([[5]]), 8, Sampler(0.9, 0.9, seed=12))
    assert not np.array_equal(a, c)  # different seed, different tokens


def test_generate_chunked_equals_unchunked_greedy():
    sampler = Sampler(temperature=0.0, topp=0.9, seed=0)
    outs = []
    for chunk in (1, 4, 64):
        e = make_engine()
        outs.append(list(e.generate([1, 2, 3], 10, sampler, chunk=chunk)))
    assert outs[0] == outs[1] == outs[2]


def test_generate_chunked_stop_rewinds_position():
    """When stop_fn fires mid-chunk, pos must rewind to the valid prefix so a
    chat continuation prefills from the right row."""
    e = make_engine()
    sampler = Sampler(temperature=0.0, topp=0.9, seed=0)
    ref = make_engine()
    full = list(ref.generate([1, 2, 3], 10, sampler, chunk=1))
    stop_idx = 4  # stop on the 5th generated token, mid-chunk for chunk=8
    seen = iter(range(len(full)))

    e2 = make_engine()
    got = list(e2.generate([1, 2, 3], 10, sampler, chunk=8,
                           stop_fn=lambda t: next(seen) >= stop_idx))
    assert got == full[: stop_idx + 1]
    # valid rows: 3 prompt rows + stop_idx decode-written rows
    assert e2.pos == 3 + stop_idx


def test_session_save_load_roundtrip(tmp_path):
    """Checkpoint/resume: save mid-conversation, restore into a fresh engine,
    continuation must match the uninterrupted run (SURVEY §5.4 upgrade)."""
    sampler = Sampler(temperature=0.0, topp=0.9, seed=0)
    ref = make_engine()
    full = list(ref.generate([1, 2, 3], 10, sampler, chunk=1))

    e1 = make_engine()
    first5 = list(e1.generate([1, 2, 3], 5, sampler, chunk=1))
    path = str(tmp_path / "session.npz")
    e1.save_session(path)

    e2 = make_engine()
    e2.load_session(path)
    assert e2.pos == e1.pos
    # continue by feeding the last generated token
    toks = e2.decode_greedy_n(np.array([full[4]]), 5)
    assert first5 + [int(t) for t in toks[:, 0]] == full


def test_session_fingerprint_mismatch(tmp_path):
    e1 = make_engine()
    path = str(tmp_path / "s.npz")
    e1.save_session(path)
    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models.llama import random_params
    import jax.numpy as jnp

    other_cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=1, n_heads=4,
                            n_kv_heads=2, vocab_size=64, seq_len=64)
    e2 = InferenceEngine(other_cfg, random_params(other_cfg, 0, jnp.float32, False),
                         cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="does not match"):
        e2.load_session(path)


def test_session_fingerprint_rejects_different_weights(tmp_path):
    """ADVICE r1: same geometry, different checkpoint -> load_session must
    refuse (the KV cache would not match the weights)."""
    e1 = make_engine(seed=0)
    e1.prefill(np.array([[1, 2, 3]], dtype=np.int32))
    path = str(tmp_path / "sess.npz")
    e1.save_session(path)
    e2 = make_engine(seed=1)  # same shapes, different weights
    with pytest.raises(ValueError, match="does not match"):
        e2.load_session(path)
    e3 = make_engine(seed=0)
    e3.load_session(path)  # same weights: accepted
    assert e3.pos == e1.pos


def test_fused_weights_match_unfused():
    """fuse_weights=True (wqkv/w13 single launches) must reproduce the
    unfused engine's logits and greedy continuation exactly."""
    import numpy as np

    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=True)
    prompt = np.array([[1, 2, 3, 4, 5]], np.int32)
    outs = {}
    for fused in (False, True):
        eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32,
                              fuse_weights=fused)
        logits = eng.prefill(prompt)
        toks = eng.decode_greedy_n(np.asarray(jnp.argmax(logits, -1), np.int32), 8)
        outs[fused] = (np.asarray(logits), [int(t) for t in toks[:, 0]])
    np.testing.assert_allclose(outs[False][0], outs[True][0], atol=1e-5, rtol=1e-5)
    assert outs[False][1] == outs[True][1]


def test_fused_weights_rejects_sharded():
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings
    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=True)
    sh = LlamaShardings(make_mesh(MeshConfig(tp=2)), cfg)
    with pytest.raises(ValueError, match="unsharded"):
        InferenceEngine(cfg, params, shardings=sh, fuse_weights=True)


def test_session_portable_across_fuse_weights(tmp_path):
    """A session saved by an unfused engine must resume on a fused one: the
    weight fingerprint hashes the caller's layout, not the fused copies."""
    import numpy as np

    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=True)
    e1 = InferenceEngine(cfg, params, cache_dtype=jnp.float32)
    e1.prefill(np.array([[1, 2, 3]], np.int32))
    path = str(tmp_path / "s.npz")
    e1.save_session(path)
    e2 = InferenceEngine(cfg, params, cache_dtype=jnp.float32, fuse_weights=True)
    e2.load_session(path)  # must not raise
    assert e2.pos == e1.pos


def test_nucleus_wider_than_candidates_falls_back_to_full_vocab():
    """When the top-K candidate set covers < topp of the mass (nucleus wider
    than K), sampling must fall back to untruncated temperature sampling —
    not silently behave as top-k=K."""
    import numpy as np

    from dllama_tpu.engine import sampling

    v = 64
    flat = jnp.zeros((1, v), jnp.float32)  # uniform: top-4 holds 1/16 of mass
    old = sampling.NUCLEUS_K
    sampling.NUCLEUS_K = 4
    try:
        toks = [
            int(sampling.sample_logits(flat, jax.random.PRNGKey(s), 1.0, 0.9)[0])
            for s in range(64)
        ]
    finally:
        sampling.NUCLEUS_K = old
    # uniform sampling over 64 tokens: hitting only 4 specific ids 64 times
    # has probability (1/16)^64 — any spread beyond 4 ids proves the fallback
    assert len(set(toks)) > 4


def test_nucleus_within_candidates_truncates():
    """Peaked logits with small topp must stay inside the tiny nucleus even
    when the candidate set is clamped."""
    import numpy as np

    from dllama_tpu.engine import sampling

    logits = np.full((1, 64), -10.0, np.float32)
    logits[0, 7] = 10.0
    logits[0, 9] = 9.0
    toks = {
        int(sampling.sample_logits(jnp.asarray(logits), jax.random.PRNGKey(s), 1.0, 0.5)[0])
        for s in range(32)
    }
    assert toks <= {7}  # topp=0.5 keeps only the crossing token


def test_f8_kv_cache_numerics_and_session():
    """f8 (e4m3) KV cache: halves cache bytes at a small accuracy cost. The
    engine path must run end-to-end, stay numerically close to the bf16
    cache on prefill logits, and round-trip through save/load_session."""
    import numpy as np

    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    prompt = np.array([[1, 5, 9, 13, 17, 21]], np.int32)
    l16 = np.asarray(InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16).prefill(prompt), np.float32)
    eng8 = InferenceEngine(cfg, params, cache_dtype=jnp.float8_e4m3fn)
    l8 = np.asarray(eng8.prefill(prompt), np.float32)
    cos = float((l16 * l8).sum() / (np.linalg.norm(l16) * np.linalg.norm(l8) + 1e-9))
    assert cos > 0.98, f"f8 cache logits diverged: cos={cos}"
    toks = eng8.decode_greedy_n(np.array([[int(np.argmax(l8))]]), 6)
    assert toks.shape == (6, 1)

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = d + "/s.npz"
        eng8.save_session(path)
        eng8b = InferenceEngine(cfg, params, cache_dtype=jnp.float8_e4m3fn)
        eng8b.load_session(path)
        assert eng8b.pos == eng8.pos
        assert eng8b.cache.k.dtype == jnp.float8_e4m3fn


def test_load_legacy_bf16_session_format():
    """Sessions saved by the pre-f8 format stored typed arrays directly; npz
    degrades ml_dtypes bf16 to raw void — the loader must re-view them."""
    import tempfile

    import numpy as np

    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16)
    eng.prefill(np.array([[1, 2, 3]], np.int32))
    with tempfile.TemporaryDirectory() as d:
        path = d + "/legacy.npz"
        np.savez_compressed(  # the old writer: typed arrays, no cache_dtype
            path, fingerprint=eng._session_fingerprint(), pos=eng.pos,
            k=np.asarray(eng.cache.k), v=np.asarray(eng.cache.v),
        )
        eng2 = InferenceEngine(cfg, params, cache_dtype=jnp.bfloat16)
        eng2.load_session(path)
        assert eng2.pos == eng.pos
        np.testing.assert_array_equal(
            np.asarray(eng2.cache.k.astype(jnp.float32)),
            np.asarray(eng.cache.k.astype(jnp.float32)),
        )


def test_f8_kv_cache_batch_engine():
    """Continuous-batching tier with the f8 cache: admission + fused decode."""
    import numpy as np

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    be = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float8_e4m3fn)
    be.add(0, [1, 2, 3], temperature=0.0, seed=1)
    be.add(1, [4, 5], temperature=0.0, seed=2)
    toks = be.decode(4)
    assert toks.shape == (4, 2)


def test_exact_topp_escape_hatch_no_fallback():
    """NUCLEUS_K=None (--exact-topp, ADVICE r3) sorts the full vocab: a flat
    distribution that would trip the approx path's wide-nucleus fallback must
    instead be truncated to exactly the topp mass, reference-style."""
    import numpy as np

    from dllama_tpu.engine import sampling

    v = 64
    # strictly decreasing (no sort-tie ambiguity), near-flat: the topp=0.5
    # nucleus spans ~27 tokens — far wider than the approx path's K=4 clamp
    logits = jnp.asarray(-0.01 * np.arange(v, dtype=np.float32))[None]
    old = sampling.NUCLEUS_K
    sampling.NUCLEUS_K = None
    try:
        toks = {
            int(sampling.sample_logits(logits, jax.random.PRNGKey(s), 1.0, 0.5)[0])
            for s in range(256)
        }
    finally:
        sampling.NUCLEUS_K = old
    # wider than any small-K clamp, but never past the exact nucleus boundary
    assert len(toks) > 4
    assert max(toks) <= 33




# ------------------------------------------------- repetition penalties


def test_apply_penalties_semantics():
    """mu[j] = logit[j] - presence*1[c>0] - frequency*c[j] (OpenAI)."""
    from dllama_tpu.engine.sampling import apply_penalties

    logits = jnp.zeros((1, 4))
    counts = jnp.asarray([[0, 1, 3, 0]])
    got = np.asarray(apply_penalties(logits, counts, 0.5, 0.25))
    np.testing.assert_allclose(got, [[0.0, -0.75, -1.25, 0.0]])
    # per-row vectors broadcast like temperature/topp
    got2 = np.asarray(apply_penalties(jnp.zeros((2, 4)),
                                      jnp.asarray([[0, 1, 3, 0]] * 2),
                                      jnp.asarray([0.5, 0.0]),
                                      jnp.asarray([0.25, 1.0])))
    np.testing.assert_allclose(got2, [[0.0, -0.75, -1.25, 0.0],
                                      [0.0, -1.0, -3.0, 0.0]])


def test_generate_frequency_penalty_matches_stepwise_reference():
    """Penalized greedy through the fused scan must equal a host-side
    step-by-step replay (engine.step + manual penalty + argmax) — the
    exactness oracle for the in-scan count bookkeeping across chunk
    boundaries. OpenAI semantics: counts cover SAMPLED tokens only (the
    prompt carries no penalty; the first token is penalty-free)."""
    prompt = [1, 2, 3]
    n = 12
    pres, freq = 0.6, 0.4

    # reference: one token at a time, counts maintained on host
    ref_eng = make_engine()
    v = TINY.vocab_size
    counts = np.zeros(v, np.float32)  # sampled tokens only — prompt excluded
    logits = np.asarray(ref_eng.prefill(np.asarray([prompt], np.int32)))[0]
    want = []
    cur = int(np.argmax(logits))  # no sampled tokens yet: penalty-free
    want.append(cur)
    for _ in range(n - 1):
        counts[cur] += 1
        logits = np.asarray(ref_eng.step(np.array([[cur]])))[0]
        cur = int(np.argmax(logits - pres * (counts > 0) - freq * counts))
        want.append(cur)

    got_eng = make_engine()
    sampler = Sampler(temperature=0.0, presence=pres, frequency=freq)
    got = list(got_eng.generate(prompt, n, sampler, chunk=5))  # chunks 5,5,2
    assert got == want

    # and the penalty actually bites: plain greedy differs
    plain = list(make_engine().generate(prompt, n, Sampler(temperature=0.0)))
    assert got != plain
