"""General paged flash-decode kernel (ISSUE 8): interpret-mode parity of
``ops/pallas/paged_attention`` against the jnp block-table gather reference
across page sizes the old ``% 64`` gate rejected ({8, 16, 24}), plus 64;
partial last pages; GQA group > 1; the fused KV scatter landing rows exactly
where ``PagedKVCache``/`_paged_cache_update` expects (bitwise, incl. the
trash-page routing of inactive rows); and the engine-level contract — the
fused kernel's token streams are BIT-IDENTICAL to the gather path's through
the real decode scan.

Numerics note: the attention OUTPUT is online-softmax (flash), so op-level
parity vs the materialized-softmax gather is allclose at f32 tolerance (the
same contract as test_paged_kv's legacy flash test); the scattered POOL
CONTENTS and the engine token streams are exact. Tiny shapes keep the file
inside the fast tier-1 band."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.llama import _paged_cache_update
from dllama_tpu.ops.layers import paged_gqa_attention
from dllama_tpu.ops.pallas.paged_attention import (
    FUSED_SCATTER_MAX_T,
    paged_decode_attention,
    paged_decode_supported,
)


def _setup(rng, page, nb, b=2, t=1, hq=4, hkv=2, hd=64, dtype=jnp.float32):
    npool = b * nb + 1  # +1 trash page, like PagedKVCache.create
    q = jnp.asarray(rng.standard_normal((b, t, hq, hd)), dtype)
    kp = jnp.asarray(rng.standard_normal((npool, hkv, page, hd)), dtype)
    vp = jnp.asarray(rng.standard_normal((npool, hkv, page, hd)), dtype)
    # shuffled tables: physical page order must not matter
    tables = jnp.asarray(
        rng.permutation(npool - 1)[: b * nb].reshape(b, nb), jnp.int32)
    return q, kp, vp, tables


def _reference(q, kp, vp, tables, pos, nk=None, nv=None, active=None):
    """Scatter via the model's own `_paged_cache_update`, then the jnp
    gather attention — the exact pair of dispatches the fused kernel
    replaces."""
    if nk is not None:
        kp = _paged_cache_update(kp, nk, tables, pos, active)
        vp = _paged_cache_update(vp, nv, tables, pos, active)
    return paged_gqa_attention(q, kp, vp, tables, pos), kp, vp


@pytest.mark.parametrize("page,nb,pos", [
    (8, 8, [19, 1]),      # small page the old gate rejected
    (16, 4, [35, 0]),     # pow-2, one slot empty
    (24, 3, [51, 17]),    # non-power-of-2, partial last page both slots
    (64, 2, [63, 127]),   # legacy-tileable size, page-boundary edges
])
def test_read_parity_any_page_size(rng, page, nb, pos):
    """Read-only sweep matches the gather reference for every (page_size,
    horizon) combo — incl. pages the old `% 64` gate rejected."""
    q, kp, vp, tables = _setup(rng, page, nb)
    pos = jnp.asarray(pos, jnp.int32)
    want, _, _ = _reference(q, kp, vp, tables, pos)
    got = paged_decode_attention(q, kp, vp, tables, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("page,nb,t,pos", [
    (8, 8, 1, [19, 1]),    # decode step
    (8, 8, 5, [9, 2]),     # spec-verify chunk crossing a page boundary
    (24, 3, 1, [23, 47]),  # write at the exact last row of a page
])
def test_fused_scatter_parity(rng, page, nb, t, pos):
    """Fused path: pools match `_paged_cache_update` BITWISE (the row lands
    where PagedKVCache expects) and the output reads the just-written rows."""
    q, kp, vp, tables = _setup(rng, page, nb, t=t)
    pos = jnp.asarray(pos, jnp.int32)
    nk = jnp.asarray(rng.standard_normal((2, 2, t, 64)), jnp.float32)
    nv = jnp.asarray(rng.standard_normal((2, 2, t, 64)), jnp.float32)
    want, kp_ref, vp_ref = _reference(q, kp, vp, tables, pos, nk, nv)
    got, kp2, vp2 = paged_decode_attention(q, kp, vp, tables, pos, nk, nv,
                                           interpret=True)
    np.testing.assert_array_equal(np.asarray(kp2), np.asarray(kp_ref))
    np.testing.assert_array_equal(np.asarray(vp2), np.asarray(vp_ref))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_fused_scatter_inactive_rows_hit_trash_page(rng):
    """active=False rows scatter to the trash page (pool page P-1) exactly
    like `_paged_cache_update`'s masked write — live pages untouched."""
    q, kp, vp, tables = _setup(rng, 16, 4)
    pos = jnp.asarray([35, 1], jnp.int32)
    active = jnp.asarray([True, False])
    nk = jnp.asarray(rng.standard_normal((2, 2, 1, 64)), jnp.float32)
    nv = jnp.asarray(rng.standard_normal((2, 2, 1, 64)), jnp.float32)
    _, kp_ref, vp_ref = _reference(q, kp, vp, tables, pos, nk, nv, active)
    _, kp2, vp2 = paged_decode_attention(q, kp, vp, tables, pos, nk, nv,
                                         active, interpret=True)
    np.testing.assert_array_equal(np.asarray(kp2), np.asarray(kp_ref))
    np.testing.assert_array_equal(np.asarray(vp2), np.asarray(vp_ref))
    # slot 1's own pages really kept their old contents (the write went to
    # the trash page, not to its table positions)
    for pg in np.asarray(tables[1]):
        np.testing.assert_array_equal(np.asarray(kp2[pg]), np.asarray(kp[pg]))


def test_gqa_group_gt_one(rng):
    """group 4 (the llama-3 ratio): one kv sweep serves the whole folded
    query group."""
    q, kp, vp, tables = _setup(rng, 8, 8, hq=8, hkv=2)
    pos = jnp.asarray([19, 3], jnp.int32)
    want, _, _ = _reference(q, kp, vp, tables, pos)
    got = paged_decode_attention(q, kp, vp, tables, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_prefill_chunk_pre_scatter_path(rng):
    """t > FUSED_SCATTER_MAX_T takes the XLA pre-scatter branch of the same
    wrapper: identical pools and output as the fused contract."""
    t = FUSED_SCATTER_MAX_T * 2
    q, kp, vp, tables = _setup(rng, 8, 8, t=t)
    pos = jnp.asarray([0, 3], jnp.int32)
    nk = jnp.asarray(rng.standard_normal((2, 2, t, 64)), jnp.float32)
    nv = jnp.asarray(rng.standard_normal((2, 2, t, 64)), jnp.float32)
    want, kp_ref, vp_ref = _reference(q, kp, vp, tables, pos, nk, nv)
    got, kp2, vp2 = paged_decode_attention(q, kp, vp, tables, pos, nk, nv,
                                           interpret=True)
    np.testing.assert_array_equal(np.asarray(kp2), np.asarray(kp_ref))
    np.testing.assert_array_equal(np.asarray(vp2), np.asarray(vp_ref))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_capability_check():
    """The explicit capability contract that replaced the %64 tileability
    gate: any 8-row-aligned page (incl. odd sizes), hd >= 8, 16/32-bit
    pools; f8 and sub-sublane pages route to the gather fallback."""
    assert paged_decode_supported((32, 128), 8)
    assert paged_decode_supported((32, 128), 24)   # old gate: rejected
    assert paged_decode_supported((32, 128), 120)  # old gate: rejected
    assert paged_decode_supported((32, 128), 128, kv_dtype=jnp.float32)
    assert not paged_decode_supported((32, 128), 12)   # not sublane-aligned
    assert not paged_decode_supported((32, 4), 128)    # head dim too small
    assert not paged_decode_supported((32, 128), 128,
                                      kv_dtype=jnp.float8_e4m3fn)


def test_engine_streams_bit_exact_kernel_vs_gather():
    """The serving contract: with the SAME engine construction, routing
    attention through the fused kernel (attn_impl='flash' -> paged_kernel)
    yields BIT-IDENTICAL greedy and sampled token streams to the jnp gather
    route — through the real decode scan, scatter fused and all."""
    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)

    def run(attn_impl, spec=0):
        eng = BatchEngine(cfg, params, n_slots=2, cache_dtype=jnp.float32,
                          kv_layout="paged", page_size=8, attn_impl=attn_impl,
                          spec=spec)
        eng.add(0, [1, 2, 3, 4, 5], temperature=0.0, seed=0)
        eng.add(1, [9, 8, 7], temperature=0.7, seed=42)
        if spec:
            toks, counts = eng.spec_step()
            return eng.attn_route, np.asarray(toks), np.asarray(counts)
        return eng.attn_route, np.asarray(eng.decode(10))

    route_g, toks_g = run("jnp")
    route_k, toks_k = run("flash")
    assert (route_g, route_k) == ("paged_gather", "paged_kernel")
    np.testing.assert_array_equal(toks_g, toks_k)
    # batched spec verify (t = k+1 > 1): the fused scatter's multi-row
    # page RMW through the real propose/verify cycle, same emissions
    rg, eg, ag = run("jnp", spec=4)
    rk, ek, ak = run("flash", spec=4)
    assert (rg, rk) == ("paged_gather", "paged_kernel")
    np.testing.assert_array_equal(ag, ak)
    np.testing.assert_array_equal(eg, ek)
