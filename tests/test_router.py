"""Multi-replica router tests (ISSUE 15): affinity, least-loaded fallback,
failover (mid-queue reroute, mid-stream clean error), drain redirection,
all-saturated shedding — against controllable stub replicas for precise
failure timing, plus one end-to-end test over two REAL engine replicas.
"""

import http.client
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dllama_tpu.obs import instruments as ins


# --------------------------------------------------------------------------
# stub replicas: the full surface the router consumes (/health, /v1/models,
# completions stream + non-stream), with scripted failure modes
# --------------------------------------------------------------------------

class StubState:
    def __init__(self, rid, model="stub-model", version="1.0"):
        self.rid = rid
        self.model = model
        self.version = version
        self.ready = True
        self.draining = False
        self.saturated = False      # completions answer 429 + Retry-After
        self.abort_after = None     # stream: emit N events, then cut the socket
        self.ntokens = 3
        self.stream_delay = 0.0     # seconds between stream events
        self.resume_overlap = 0     # resume: re-emit N already-journaled
        #                             frames (drills the dedup seam)
        self.served = []            # parsed bodies, in arrival order
        # mesh observability surface (ISSUE 17)
        self.clock_skew = 0.0       # seconds added to the reported clock
        self.trace_epoch = None     # /health clock.trace_epoch_s
        self.metrics_text = None    # /metrics body (None = tiny default)
        self.trace_export = None    # /debug/trace payload (None = 404)
        self.timelines = {}         # req_id -> /debug/requests/{id} payload
        self.header_log = []        # inbound POST headers, lower-cased keys
        self.lock = threading.Lock()


def make_stub(state: StubState):
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _json(self, status, payload, headers=None):
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Replica-Id", state.rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path.startswith("/health"):
                self._json(200, {
                    "live": True,
                    "ready": state.ready and not state.draining,
                    "draining": state.draining,
                    "queue_depth": 0, "busy_slots": 0,
                    "build": {"version": state.version},
                    "clock": {
                        "monotonic_s": time.monotonic() + state.clock_skew,
                        "trace_epoch_s": state.trace_epoch,
                    },
                })
            elif self.path == "/v1/models":
                self._json(200, {"object": "list",
                                 "data": [{"id": state.model}]})
            elif self.path == "/metrics":
                text = state.metrics_text or (
                    "# HELP dllama_stub_requests_total bodies served\n"
                    "# TYPE dllama_stub_requests_total counter\n"
                    f"dllama_stub_requests_total {len(state.served)}\n")
                data = text.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/debug/trace":
                if state.trace_export is None:
                    self._json(404, {"error": {"message": "tracing off"}})
                else:
                    self._json(200, state.trace_export)
            elif self.path.startswith("/debug/requests/"):
                tl = state.timelines.get(self.path.rsplit("/", 1)[1])
                if tl is None:
                    self._json(404, {"error": {"message": "unknown"}})
                else:
                    self._json(200, tl)
            else:
                self._json(404, {"error": {"message": "nope"}})

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            with state.lock:
                state.served.append(body)
                state.header_log.append(
                    {k.lower(): v for k, v in self.headers.items()})
            if state.saturated:
                self._json(429, {"error": {"message": "queue full"}},
                           {"Retry-After": "3"})
                return
            if body.get("stream"):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("X-Replica-Id", state.rid)
                self.end_headers()

                def chunk(p: bytes):
                    self.wfile.write(f"{len(p):x}\r\n".encode() + p + b"\r\n")
                    self.wfile.flush()

                # the stream contract a real replica honors (ISSUE 16): the
                # i-th token of THIS stream is deterministic (100+i here —
                # the stub's stand-in for greedy decode), `resume` re-enters
                # at len(resume.tokens), identity (id/created) comes from
                # the resume body when present, and frames carry
                # position/token_ids when `include_token_ids` asks for them
                resume = body.get("resume") or {}
                start = len(resume.get("tokens") or [])
                if start and state.resume_overlap:
                    # a sloppy survivor replaying frames the client already
                    # has — the ROUTER's journal must suppress these
                    start = max(0, start - state.resume_overlap)
                want_ids = bool(body.get("include_token_ids"))
                cid = resume.get("id") or f"chatcmpl-{state.rid}"
                emitted = 0
                for i in range(start, state.ntokens):
                    if state.stream_delay:
                        time.sleep(state.stream_delay)
                    if state.abort_after is not None \
                            and emitted >= state.abort_after:
                        # mid-stream death: cut the connection, no [DONE].
                        # shutdown() (not close()) — rfile/wfile still hold
                        # fd refs, so close() alone would defer the FIN
                        self.connection.shutdown(socket.SHUT_RDWR)
                        return
                    ev = {"id": cid, "created": 111,
                          "choices": [{"index": 0,
                                       "delta": {"content": f"t{i}"},
                                       "finish_reason": None}]}
                    if want_ids:
                        ev["position"], ev["token_ids"] = i, [100 + i]
                    chunk(b"data: " + json.dumps(ev).encode() + b"\n\n")
                    emitted += 1
                fin = {"id": cid, "created": 111,
                       "choices": [{"index": 0, "delta": {},
                                    "finish_reason": "stop"}]}
                chunk(b"data: " + json.dumps(fin).encode() + b"\n\n")
                chunk(b"data: [DONE]\n\n")
                chunk(b"")
            else:
                self._json(200, {
                    "object": "chat.completion", "model": state.model,
                    "choices": [{"index": 0, "message":
                                 {"role": "assistant", "content": "ok"},
                                 "finish_reason": "stop"}],
                    "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                              "total_tokens": 2},
                    "timings": {"replica": state.rid},
                })

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


@pytest.fixture
def mesh():
    """Two stub replicas + a started router (poller effectively inert:
    poll_s=30 — tests drive _poll_one directly when they need a refresh)."""
    from dllama_tpu.serve.router import make_router

    a, b = StubState("stub-a"), StubState("stub-b")
    ha, hb = make_stub(a), make_stub(b)
    server, router = make_router(
        [f"127.0.0.1:{ha.server_address[1]}",
         f"127.0.0.1:{hb.server_address[1]}"],
        poll_s=30.0)
    router.start()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_address[1]
    yield port, router, (a, b), (ha, hb)
    router.stop()
    server.shutdown()
    server.server_close()
    for h in (ha, hb):
        try:
            h.shutdown()
            h.server_close()
        except OSError:
            pass


def rpost(port, path, body, timeout=30, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 dict({"Content-Type": "application/json"}, **(headers or {})))
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, headers


def rget(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


SHARED = [{"role": "system", "content":
           "You are a helpful assistant with a long shared preamble."},
          {"role": "user", "content": "hi"}]


def test_handshake_and_health(mesh):
    port, router, (a, b), _ = mesh
    st, data = rget(port, "/health")
    assert st == 200
    h = json.loads(data)
    assert h["mode"] == "router" and h["ready"]
    assert len(h["replicas"]) == 2
    assert all(r["ready"] and r["config_ok"] for r in h["replicas"])
    assert h["mesh"]["model"] == "stub-model"
    st, data = rget(port, "/v1/models")
    assert st == 200
    assert json.loads(data)["data"][0]["id"] == "stub-model"
    st, data = rget(port, "/router/replicas")
    assert st == 200 and len(json.loads(data)["replicas"]) == 2


def test_affinity_pins_shared_prefix(mesh):
    port, router, (a, b), _ = mesh
    hits0 = ins.ROUTER_AFFINITY_HITS.value()
    for i in range(4):
        msgs = [SHARED[0], {"role": "user", "content": f"turn {i}"}]
        st, data, headers = rpost(port, "/v1/chat/completions",
                                  {"messages": msgs, "max_tokens": 4})
        assert st == 200
        assert headers.get("X-Replica-Id") in ("stub-a", "stub-b")
    served = (len(a.served), len(b.served))
    # every request shares the system prompt -> one replica got ALL of them
    assert sorted(served) == [0, 4], served
    assert ins.ROUTER_AFFINITY_HITS.value() - hits0 >= 3


def test_least_loaded_spreads_distinct_prefixes(mesh):
    port, router, (a, b), _ = mesh
    for i in range(6):
        msgs = [{"role": "system", "content": f"totally distinct prefix {i}"},
                {"role": "user", "content": "hi"}]
        st, _, _ = rpost(port, "/v1/chat/completions",
                         {"messages": msgs, "max_tokens": 4})
        assert st == 200
    # distinct fingerprints have no warm pin: load-based pick with LRU
    # tie-break must use BOTH replicas
    assert len(a.served) >= 1 and len(b.served) >= 1


def test_replica_kill_mid_queue_reroutes_zero_lost(mesh):
    port, router, (a, b), (ha, hb) = mesh
    # pin the shared prefix to whichever replica answers first
    st, _, h1 = rpost(port, "/v1/chat/completions",
                      {"messages": SHARED, "max_tokens": 4})
    assert st == 200
    pinned = h1["X-Replica-Id"]
    victim, survivor = ((a, ha), (b, hb)) if pinned == "stub-a" \
        else ((b, hb), (a, ha))
    # kill the pinned replica outright: connections now refused
    victim[1].shutdown()
    victim[1].server_close()
    # every queued/new request still completes — rerouted, zero lost
    for i in range(3):
        st, data, h2 = rpost(port, "/v1/chat/completions",
                             {"messages": SHARED, "max_tokens": 4})
        assert st == 200, data
        assert h2["X-Replica-Id"] == survivor[0].rid
    # the failed attempt was counted and the replica marked down (registry
    # ids are host:port — map the victim stub through its server port)
    victim_reg = f"127.0.0.1:{victim[1].server_address[1]}"
    st, data = rget(port, "/router/replicas")
    reps = {r["id"]: r for r in json.loads(data)["replicas"]}
    assert reps[victim_reg]["ready"] is False
    assert ins.REPLICA_HEALTHY.labels(replica=victim_reg).value() == 0.0


def test_replica_death_mid_stream_fails_exactly_once(mesh):
    # --failover-max 0: the pre-ISSUE-16 exactly-once error contract must
    # survive as the explicit opt-out (and the unresumable fallback)
    port, router, (a, b), _ = mesh
    router.failover_max = 0
    # pin, then script the pinned stub to die after 2 stream events
    st, _, h1 = rpost(port, "/v1/chat/completions",
                      {"messages": SHARED, "max_tokens": 4})
    pinned = a if h1["X-Replica-Id"] == "stub-a" else b
    pinned.abort_after = 2
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": SHARED, "stream": True,
                             "max_tokens": 8}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200  # stream started before the death
    raw = resp.read().decode()
    conn.close()
    events = [line[6:] for line in raw.splitlines()
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"  # the stream ENDED cleanly
    finishes = [json.loads(e)["choices"][0].get("finish_reason")
                for e in events[:-1] if "choices" in e]
    # exactly one terminal finish, and it is "error"
    assert [f for f in finishes if f] == ["error"]
    # in-band error event carries the request id
    errs = [json.loads(e) for e in events[:-1] if "error" in e]
    assert errs and errs[-1]["error"].get("request_id")


def stream_raw(port, body, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    return raw


def sse_events(raw):
    return [json.loads(line[6:]) for line in raw.splitlines()
            if line.startswith("data: ") and line[6:] != "[DONE]"]


def assemble(raw):
    """-> (content, token_ids, finish_reason, stream_ids) across all data
    frames — the client's total view of one SSE stream."""
    content, ids, finish, cids = "", [], None, set()
    for e in sse_events(raw):
        if "error" in e:
            continue
        ch = (e.get("choices") or [{}])[0]
        content += (ch.get("delta") or {}).get("content") or ""
        ids += e.get("token_ids", [])
        if ch.get("finish_reason"):
            finish = ch["finish_reason"]
        if e.get("id"):
            cids.add(e["id"])
    return content, ids, finish, cids


def test_midstream_failover_resumes_and_suppresses_duplicates(mesh):
    """ISSUE 16 journal seam: the pinned replica dies after 2 token frames;
    the survivor is scripted to REPLAY one already-delivered frame — the
    client must still see every position exactly once, one `stop` finish,
    one stream id, and at most one `: retrying` comment."""
    port, router, (a, b), _ = mesh
    st, _, h1 = rpost(port, "/v1/chat/completions",
                      {"messages": SHARED, "max_tokens": 4})
    victim, survivor = (a, b) if h1["X-Replica-Id"] == "stub-a" else (b, a)
    victim.abort_after = 2
    survivor.resume_overlap = 1
    retried0 = ins.ROUTER_FAILOVERS.labels(outcome="retried").value()
    resumed0 = ins.ROUTER_FAILOVERS.labels(outcome="resumed").value()
    raw = stream_raw(port, {"messages": SHARED, "stream": True,
                            "max_tokens": 8})
    assert raw.rstrip().splitlines()[-1] == "data: [DONE]"
    evs = sse_events(raw)
    tok = [(e["position"], e["token_ids"]) for e in evs if "token_ids" in e]
    assert [p for p, _ in tok] == list(range(a.ntokens)), tok
    assert [t for _, ids in tok for t in ids] == \
        [100 + i for i in range(a.ntokens)]
    finishes = [e["choices"][0].get("finish_reason")
                for e in evs if "choices" in e]
    assert [f for f in finishes if f] == ["stop"]
    assert len({e["id"] for e in evs if "id" in e}) == 1
    assert raw.count(": retrying") == 1
    # the survivor was handed the journaled prefix + the pinned seed
    rb = survivor.served[-1]
    assert rb["resume"]["tokens"] == [100, 101]
    assert rb["include_token_ids"] is True
    assert rb.get("seed") is not None
    assert ins.ROUTER_FAILOVERS.labels(
        outcome="retried").value() - retried0 == 1
    assert ins.ROUTER_FAILOVERS.labels(
        outcome="resumed").value() - resumed0 == 1


def test_failover_budget_exhaustion_fails_exactly_once(mesh):
    """Every replica dies on every attempt: after --failover-max resumes
    the stream must fail EXACTLY once (finish_reason=error, in-band error,
    [DONE]) with no token ever duplicated across the dead attempts."""
    port, router, (a, b), _ = mesh
    a.abort_after = 1
    b.abort_after = 1
    ex0 = ins.ROUTER_FAILOVERS.labels(outcome="exhausted").value()
    raw = stream_raw(port, {"messages": SHARED, "stream": True,
                            "max_tokens": 8})
    assert raw.rstrip().splitlines()[-1] == "data: [DONE]"
    evs = sse_events(raw)
    poss = [e["position"] for e in evs if "token_ids" in e]
    assert poss == sorted(set(poss)), f"duplicate/reordered tokens: {poss}"
    finishes = [e["choices"][0].get("finish_reason")
                for e in evs if "choices" in e]
    assert [f for f in finishes if f] == ["error"]
    assert any("error" in e for e in evs)
    assert ins.ROUTER_FAILOVERS.labels(
        outcome="exhausted").value() - ex0 == 1


def test_drain_redirects_new_traffic(mesh):
    port, router, (a, b), _ = mesh
    st, _, h1 = rpost(port, "/v1/chat/completions",
                      {"messages": SHARED, "max_tokens": 4})
    pinned, other = (a, b) if h1["X-Replica-Id"] == "stub-a" else (b, a)
    served_before = len(other.served)
    # drain the pinned replica and refresh the router's view synchronously
    pinned.draining = True
    for rep in router.replicas:
        router._poll_one(rep)
    for i in range(2):
        st, _, h2 = rpost(port, "/v1/chat/completions",
                          {"messages": SHARED, "max_tokens": 4})
        assert st == 200
        assert h2["X-Replica-Id"] == other.rid  # redirected while draining
    assert len(other.served) == served_before + 2


def test_all_saturated_sheds_with_retry_after(mesh):
    port, router, (a, b), _ = mesh
    a.saturated = b.saturated = True
    st, data, headers = rpost(port, "/v1/chat/completions",
                              {"messages": SHARED, "max_tokens": 4})
    assert st == 429
    assert int(headers.get("Retry-After", 0)) >= 3  # upstream's hint honored
    assert b"saturated" in data


def test_router_drain_sheds_503(mesh):
    port, router, _, _ = mesh
    router.drain()
    st, data, headers = rpost(port, "/v1/chat/completions",
                              {"messages": SHARED, "max_tokens": 4})
    assert st == 503 and headers.get("Retry-After")
    st, _ = rget(port, "/health/ready")
    assert st == 503


def test_stream_passthrough_forwards_tokens_incrementally(mesh):
    """The router must forward SSE frames as they arrive, not buffer the
    stream: http.client's read(n) on a chunked response blocks until n
    bytes or EOF, which would hold every token delta (and heartbeat)
    hostage until the stream ended — the read1 regression this pins."""
    port, router, (a, b), _ = mesh
    for stub in (a, b):
        stub.ntokens = 20
        stub.stream_delay = 0.1  # ~2s stream end to end
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": SHARED, "stream": True,
                             "max_tokens": 30}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    t0 = time.monotonic()
    first = resp.read1(4096)
    t_first = time.monotonic() - t0
    rest = resp.read()
    conn.close()
    assert first.startswith(b"data: ")
    assert t_first < 1.0, f"first frame buffered for {t_first:.2f}s"
    assert b"[DONE]" in (first + rest)


def test_health_answers_while_streams_saturate_workers():
    """Control-plane GETs ride the aio front-end's dedicated pool: /health
    and /metrics must answer even when EVERY request worker is parked on a
    long-lived proxied stream — an LB probe queued behind them would flag
    a healthy router dead and restart it, killing the streams."""
    from dllama_tpu.serve.router import make_router

    a = StubState("stub-a")
    a.ntokens = 100
    a.stream_delay = 0.05  # ~5s per stream
    ha = make_stub(a)
    server, router = make_router([f"127.0.0.1:{ha.server_address[1]}"],
                                 poll_s=30.0, workers=2)
    try:
        router.start()
        threading.Thread(target=server.serve_forever, daemon=True).start()
        port = server.server_address[1]

        def stream():
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request("POST", "/v1/chat/completions",
                         json.dumps({"messages": SHARED, "stream": True,
                                     "max_tokens": 50}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            conn.close()

        streams = [threading.Thread(target=stream, daemon=True)
                   for _ in range(2)]
        for t in streams:
            t.start()
        time.sleep(0.5)  # both workers now own a live stream
        t0 = time.monotonic()
        st, _ = rget(port, "/health/ready")
        assert st == 200
        assert time.monotonic() - t0 < 2.0, "probe starved behind streams"
        st, _ = rget(port, "/metrics")
        assert st == 200
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        ha.shutdown()
        ha.server_close()


def test_config_handshake_quarantines_mismatch():
    """A replica serving a different (model, version) than the mesh must
    never be routed to — the root/worker handshake verdict."""
    from dllama_tpu.serve.router import make_router

    a = StubState("stub-a")
    c = StubState("stub-c", model="other-model", version="9.9")
    ha, hc = make_stub(a), make_stub(c)
    server, router = make_router(
        [f"127.0.0.1:{ha.server_address[1]}",
         f"127.0.0.1:{hc.server_address[1]}"], poll_s=30.0)
    try:
        router.start()
        bad = router.replicas[1]
        assert bad.config_ok is False
        assert router.mesh_model == "stub-model"
        rep, _ = router.pick(None, exclude=set())
        assert rep is router.replicas[0]  # quarantined never picked
        router.release(rep)
    finally:
        router.stop()
        server.server_close()
        for h in (ha, hc):
            h.shutdown()
            h.server_close()


# --------------------------------------------------------------------------
# end-to-end: two REAL engine replicas behind the router
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def real_mesh(tmp_path_factory):
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server
    from dllama_tpu.serve.router import make_router
    from tests.test_serve import make_tiny_files

    tmp = tmp_path_factory.mktemp("router_real")
    mpath, tpath, _cfg = make_tiny_files(tmp)
    servers = []
    for i in range(2):
        loaded = load_model(mpath, tpath, mesh=None)
        httpd, api = make_server(loaded, host="127.0.0.1", port=0,
                                 n_slots=2, kv_layout="paged", page_size=8)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append((httpd, api))
    rserver, router = make_router(
        [f"127.0.0.1:{h.server_address[1]}" for h, _ in servers],
        poll_s=30.0)
    router.start()
    threading.Thread(target=rserver.serve_forever, daemon=True).start()
    yield rserver.server_address[1], router, servers
    router.stop()
    rserver.shutdown()
    rserver.server_close()
    for httpd, api in servers:
        try:
            if api.scheduler is not None:
                api.scheduler.shutdown()
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass


def test_real_mesh_trace_propagation_and_postmortem(real_mesh):
    """ISSUE 17 e2e over real engines: the router mints ONE trace id for a
    proxied request, the replica adopts it from the X-Dllama-Trace hop
    header into its flight recorder, GET /router/trace merges both
    processes' spans under that id on one clock-aligned timeline, and
    GET /router/requests/{id} joins the router's routing record with the
    replica's own timeline.  Runs BEFORE the failover drill below — that
    one kills a replica for good (module-scoped mesh)."""
    from tests.test_metrics import parse_exposition

    port, router, servers = real_mesh
    for rep in router.replicas:
        router._poll_one(rep)  # poll_s=30: capture clock + trace epoch now
    rid = "req-obs-e2e-1"
    st, data, headers = rpost(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "trace me"}],
         "max_tokens": 4, "temperature": 0.0},
        headers={"X-Request-Id": rid})
    assert st == 200, data

    # cross-hop postmortem: router journal joined with the replica timeline
    st, data = rget(port, f"/router/requests/{rid}")
    assert st == 200, data
    pm = json.loads(data)
    tid = pm["trace_id"]
    assert tid and len(tid) == 16 and tid != rid
    assert pm["router"]["outcome"] == "ok"
    assert [a["kind"] for a in pm["router"]["attempts"]] == ["forward"]
    serving = pm["router"]["attempts"][0]["replica"]
    assert serving in {r.rid for r in router.replicas}
    leg = pm["replicas"][serving]
    assert leg["req_id"] == rid and leg["trace_id"] == tid
    assert leg["state"] == "finished"

    # merged mesh trace: both replicas merged, offsets aligned and tiny
    # (same host), router + replica spans under the SAME trace id
    st, data = rget(port, "/router/trace")
    assert st == 200
    merged = json.loads(data)
    assert merged["otherData"]["replicas_merged"] == 2
    clocks = merged["otherData"]["clock"]
    assert set(clocks) == {r.rid for r in router.replicas}
    for c in clocks.values():
        assert c["aligned"] is True
        assert abs(c["offset_s"]) <= max(c["uncertainty_s"], 0.25)
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)
    traced = [e for e in body if e.get("args", {}).get("trace_id") == tid]
    pids = {e["pid"] for e in traced}
    assert 1 in pids and any(p > 1 for p in pids), pids
    names = {e["name"] for e in traced}
    assert "connect" in names        # the router's own leg
    assert "request" in names        # the replica's span joined the trace

    # federation: one grammar-clean exposition with replica-labeled series
    # and pre-aggregated fleet counters
    st, data = rget(port, "/router/metrics")
    assert st == 200
    fams, samples = parse_exposition(data.decode())
    assert fams["dllama_fleet_requests_finished_total"] == "counter"
    assert any(n == "dllama_requests_finished_total"
               and f'replica="{serving}"' in lbl
               for (n, lbl) in samples)


def test_real_mesh_affinity_and_failover(real_mesh):
    port, router, servers = real_mesh
    # (1) shared system prompt pins every request to ONE warm replica
    ids = set()
    for i in range(3):
        msgs = [{"role": "system", "content":
                 "Shared preamble for the warm-path routing test."},
                {"role": "user", "content": f"q{i}"}]
        st, data, headers = rpost(port, "/v1/chat/completions",
                                  {"messages": msgs, "max_tokens": 4,
                                   "temperature": 0.0})
        assert st == 200, data
        body = json.loads(data)
        assert body["choices"][0]["finish_reason"] in ("stop", "length")
        assert headers.get("X-Replica-Id") == body["timings"]["replica"]
        ids.add(headers["X-Replica-Id"])
    assert len(ids) == 1, f"affinity scattered the shared prefix: {ids}"
    warm_rid = ids.pop()
    # (2) kill the warm replica: same-prefix traffic fails over, zero lost
    victim = next((h, a) for h, a in servers
                  if f"127.0.0.1:{h.server_address[1]}" == warm_rid
                  or a.replica_id == warm_rid)
    victim[0].shutdown()
    victim[0].server_close()
    st, data, headers = rpost(port, "/v1/chat/completions",
                              {"messages": [
                                  {"role": "system", "content":
                                   "Shared preamble for the warm-path "
                                   "routing test."},
                                  {"role": "user", "content": "after"}],
                               "max_tokens": 4, "temperature": 0.0})
    assert st == 200, data
    survivor_rid = headers["X-Replica-Id"]
    assert survivor_rid != warm_rid
    # (3) the survivor's paged-KV allocator stayed clean through it all
    shost, sport = survivor_rid.split(":")
    conn = http.client.HTTPConnection(shost, int(sport), timeout=10)
    conn.request("GET", "/debug/kv")
    resp = conn.getresponse()
    kv = json.loads(resp.read())
    conn.close()
    assert resp.status == 200
    assert kv["layout"] == "paged" and kv["audit"]["ok"] is True


# --------------------------------------------------------------------------
# mid-stream failover over REAL engines (ISSUE 16): bit-exact resume
# --------------------------------------------------------------------------

class SeverProxy:
    """TCP forwarder that can cut the wire mid-SSE. Armed via
    cut_after_frames=N it forwards the first N data frames verbatim then
    severs the connection MID-frame — from the router's seat exactly the
    death a SIGKILLed replica produces (EOF/RST, no terminal frame), minus
    the process machinery an in-proc test can't have."""

    def __init__(self, target_port: int):
        self.target_port = target_port
        self.cut_after_frames = None  # None = fully transparent
        self.lsock = socket.socket()
        self.lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.lsock.bind(("127.0.0.1", 0))
        self.lsock.listen(16)
        self.port = self.lsock.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._stop:
            try:
                cli, _ = self.lsock.accept()
            except OSError:
                return
            srv = socket.socket()
            try:
                srv.connect(("127.0.0.1", self.target_port))
            except OSError:
                cli.close()
                continue
            threading.Thread(target=self._pump_up, args=(cli, srv),
                             daemon=True).start()
            threading.Thread(target=self._pump_down, args=(srv, cli),
                             daemon=True).start()

    def _pump_up(self, cli, srv):
        try:
            while True:
                d = cli.recv(65536)
                if not d:
                    break
                srv.sendall(d)
        except OSError:
            pass
        try:
            srv.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def _pump_down(self, srv, cli):
        buf = b""
        frames = 0
        try:
            while True:
                d = srv.recv(65536)
                if not d:
                    break
                if self.cut_after_frames is None:
                    cli.sendall(d)
                    continue
                buf += d
                while True:
                    seg, sep, rest = buf.partition(b"\n\n")
                    if not sep:
                        break
                    buf = rest
                    if b"data: " in seg:
                        frames += 1
                        if frames > self.cut_after_frames:
                            # a few bytes of the doomed frame carry the
                            # previous chunk's terminator, so everything
                            # already relayed parses; then cut hard
                            cli.sendall(seg[:8])
                            cli.shutdown(socket.SHUT_RDWR)
                            srv.close()
                            return
                    cli.sendall(seg + sep)
            if buf:
                cli.sendall(buf)
        except OSError:
            pass
        try:
            cli.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def close(self):
        self._stop = True
        try:
            self.lsock.close()
        except OSError:
            pass


@pytest.fixture(scope="module")
def failover_real(tmp_path_factory):
    """Two REAL engine replicas (paged KV + a small host spill tier), one
    of them behind a severable wire, fronted by a started router."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server
    from dllama_tpu.serve.router import make_router
    from tests.test_serve import make_tiny_files

    tmp = tmp_path_factory.mktemp("router_failover")
    mpath, tpath, _cfg = make_tiny_files(tmp)
    servers = []
    for i in range(2):
        loaded = load_model(mpath, tpath, mesh=None)
        httpd, api = make_server(loaded, host="127.0.0.1", port=0,
                                 n_slots=2, kv_layout="paged", page_size=8,
                                 kv_host_pages=4)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append((httpd, api))
    a_port = servers[0][0].server_address[1]
    b_port = servers[1][0].server_address[1]
    proxy = SeverProxy(a_port)  # replica A is the victim behind the wire
    rserver, router = make_router(
        [f"127.0.0.1:{proxy.port}", f"127.0.0.1:{b_port}"], poll_s=30.0)
    router.start()
    threading.Thread(target=rserver.serve_forever, daemon=True).start()
    yield (rserver.server_address[1], router, a_port, b_port, proxy)
    router.stop()
    rserver.shutdown()
    rserver.server_close()
    proxy.close()
    for httpd, api in servers:
        try:
            if api.scheduler is not None:
                api.scheduler.shutdown()
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass


RESUME_MSGS = [{"role": "system", "content":
                "Failover drill shared preamble, long enough to matter."},
               {"role": "user", "content": "continue the drill"}]


def _resume_bit_exact(a_port, b_port, body):
    """Uninterrupted stream on replica A; resume at the midpoint on
    replica B (which never saw the prompt) — the continuation must be
    bit-exact: same token ids, same text, same finish, positions picking
    up exactly where the journal stops, stream identity preserved."""
    base_raw = stream_raw(a_port, body)
    content, ids, finish, _ = assemble(base_raw)
    assert len(ids) >= 2, f"stream too short to split: {ids}"
    # split at a FRAME boundary (one frame may carry several token ids —
    # held stop-prefix bytes ride the next text-bearing frame), mid-way
    # through the token frames; the suffix is everything from that frame
    # on, finish/flush frames included
    frames = sse_events(base_raw)
    tok_idx = [i for i, e in enumerate(frames) if "token_ids" in e]
    assert len(tok_idx) >= 2, f"too few token frames: {frames}"
    mid = tok_idx[len(tok_idx) // 2]
    k = frames[mid]["position"]
    assert k >= 1
    suffix = "".join(
        ((e.get("choices") or [{}])[0].get("delta") or {}).get("content")
        or "" for e in frames[mid:])
    rbody = dict(body)
    rbody["resume"] = {"tokens": ids[:k], "id": "chatcmpl-drill",
                       "created": 1234}
    r_raw = stream_raw(b_port, rbody)
    c2, ids2, fin2, cids2 = assemble(r_raw)
    assert ids2 == ids[k:], f"resume diverged: {ids2} vs {ids[k:]}"
    assert c2 == suffix
    assert fin2 == finish
    assert cids2 == {"chatcmpl-drill"}  # identity from the resume body
    assert '"role"' not in r_raw  # the role delta is never re-sent
    first = next(e for e in sse_events(r_raw) if "token_ids" in e)
    assert first["position"] == k


def test_cross_replica_resume_bit_exact_greedy(failover_real):
    _, _, a_port, b_port, _ = failover_real
    _resume_bit_exact(a_port, b_port, {
        "messages": RESUME_MSGS, "stream": True, "max_tokens": 10,
        "temperature": 0.0, "include_token_ids": True})


def test_cross_replica_resume_bit_exact_sampled(failover_real):
    _, _, a_port, b_port, _ = failover_real
    _resume_bit_exact(a_port, b_port, {
        "messages": RESUME_MSGS, "stream": True, "max_tokens": 10,
        "temperature": 0.9, "top_p": 0.95, "seed": 7,
        "include_token_ids": True})


def test_sampled_resume_without_seed_rejected(failover_real):
    _, _, a_port, _, _ = failover_real
    st, data, _ = rpost(a_port, "/v1/chat/completions", {
        "messages": RESUME_MSGS, "stream": False, "max_tokens": 4,
        "temperature": 0.8,
        "resume": {"tokens": [1, 2], "id": "x", "created": 1}})
    assert st == 400
    assert b"seed" in data


def test_router_kill_mid_stream_bit_exact(failover_real):
    """The acceptance drill: a replica's wire dies mid-stream behind the
    router; with --failover-max >= 1 the client's completed stream is
    byte-identical to the uninterrupted run — zero duplicated, zero
    dropped tokens — and the survivor's KV audit stays clean. LAST in
    this module: it marks the proxied replica down."""
    from dllama_tpu.serve.router import Router

    rport, router, a_port, b_port, proxy = failover_real
    body = {"messages": [{"role": "system", "content":
                          "kill-drill preamble nobody else uses"},
                         {"role": "user", "content": "go"}],
            "stream": True, "max_tokens": 10, "temperature": 0.0,
            "seed": 11, "include_token_ids": True}
    # uninterrupted baseline straight off the victim replica
    content, ids, finish, _ = assemble(stream_raw(a_port, body))
    assert len(ids) >= 5, f"stream too short for a mid-stream kill: {ids}"
    # pin the prompt to the proxied victim, then arm the wire cut: the
    # role delta + 2 token frames get through, the 4th frame dies mid-byte
    fp = Router.fingerprint(body, False)
    with router._mu:
        router._affinity[fp] = f"127.0.0.1:{proxy.port}"
    resumed0 = ins.ROUTER_FAILOVERS.labels(outcome="resumed").value()
    proxy.cut_after_frames = 3
    raw = stream_raw(rport, body)
    c2, ids2, fin2, cids2 = assemble(raw)
    assert ids2 == ids, f"token loss/dup across failover: {ids2} vs {ids}"
    assert c2 == content
    assert fin2 == finish
    assert len(cids2) == 1  # one stream identity end to end
    assert raw.count(": retrying") == 1
    assert ins.ROUTER_FAILOVERS.labels(
        outcome="resumed").value() - resumed0 == 1
    # the survivor's paged-KV pool (device + host tier) reconciles
    st, data = rget(b_port, "/debug/kv")
    kv = json.loads(data)
    assert st == 200 and kv["audit"]["ok"] is True
