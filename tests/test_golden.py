"""Golden-artifact tests — the reference's dev tokenizer tests and macbeth.sh
determinism check, runnable offline (see tests/golden_fixture.py for why the
vocabulary is trained in-repo).

* encode goldens pin exact token ids for the reference's own test strings
  (tokenizer-test.cpp:44-80's case0/1/2 shapes: chat headers between special
  tokens, dense punctuation, emoji split across tokens) through the REAL
  llama3-tiktoken converter path (convert_llama3_tokenizer).
* a differential oracle checks the production BPE (python heap loop and the
  native C++ one, whichever is active) against an independent O(n^2) encoder
  on multilingual + random-bytes input.
* a committed tiny `.m` (tests/fixtures/golden_tiny.m, seed 20260730) pins a
  temperature-0 continuation — the macbeth.sh analog: fails if the file
  format, Q40 numerics, or the forward pass drift between rounds.
"""

import os

import jax
import numpy as np
import pytest

from tests.golden_fixture import naive_bpe_encode, train_bpe, write_tiktoken_file

FIXTURE_M = os.path.join(os.path.dirname(__file__), "fixtures", "golden_tiny.m")

# reference test strings (tokenizer-test.cpp:48-66) under the in-repo vocab
GOLDEN_ENCODES = {
    "<|start_header_id|>user<|end_header_id|>\n\nhello<|eot_id|>"
    "<|start_header_id|>assistant<|end_header_id|>\n\n": [
        801, 807, 330, 256, 808, 10, 10, 320, 810, 807,
        97, 115, 115, 105, 265, 268, 116, 808, 10, 10,
    ],
    "!!&&@(*x)^^!": [801, 33, 33, 38, 38, 64, 40, 42, 120, 41, 94, 94, 33],
    "\U0001f603!\U0001f607x": [801, 263, 131, 33, 263, 135, 120],
    "Zwölf Boxkämpfer": [
        801, 90, 119, 195, 182, 108, 102, 342, 287, 107, 195, 164, 327, 102, 256,
    ],
    "天地玄黄": [
        801, 229, 164, 169, 229, 156, 176, 231, 142, 132, 233, 187, 132,
    ],
}

GOLDEN_PROMPT = [801, 799, 777, 46]
GOLDEN_CONTINUATION = [573, 932, 583, 990, 121, 209, 314, 633, 274, 831,
                       499, 615, 643, 349, 143, 357]


@pytest.fixture(scope="module")
def llama3_tok(tmp_path_factory):
    from dllama_tpu.tools.convert_tokenizer import convert_llama3_tokenizer

    path = tmp_path_factory.mktemp("golden") / "tokenizer.model"
    write_tiktoken_file(str(path))
    return convert_llama3_tokenizer(str(path))


def test_vocab_is_deterministic():
    v = train_bpe()
    assert len(v) == 801
    assert v[256:260] == [b"er", b"e ", b"\xf0\x9f", b"er "]  # first merges pinned


def test_golden_encodes(llama3_tok):
    for text, want in GOLDEN_ENCODES.items():
        got = llama3_tok.encode(text, add_bos=True, add_special_tokens=True)
        assert got == want, f"{text!r}: {got} != {want}"


def test_golden_roundtrip_through_t_file(llama3_tok, tmp_path):
    """Converter output -> .t file -> runtime load must preserve encodes and
    chat-eos detection (the converter-vs-runtime agreement VERDICT r2 #3)."""
    from dllama_tpu.tokenizer.tokenizer import Tokenizer

    path = tmp_path / "golden.t"
    llama3_tok.save(str(path))
    tok2 = Tokenizer.load(str(path))
    for text, want in GOLDEN_ENCODES.items():
        assert tok2.encode(text, add_bos=True, add_special_tokens=True) == want
    assert tok2.is_eos(810)  # <|eot_id|>
    assert tok2.bos_id == 801


def test_streaming_decoder_emoji(llama3_tok):
    """dev_testDecoderEmoji semantics: partial UTF-8 buffers across tokens,
    complete codepoints flush (tokenizer-test.cpp:72-90)."""
    llama3_tok.reset_decoder()
    ids = llama3_tok.encode("\U0001f603!\U0001f607x", add_bos=False)
    pieces = [llama3_tok.decode(t) for t in ids]
    assert pieces == [None, "\U0001f603", "!", None, "\U0001f607", "x"]
    assert llama3_tok.decode_all(ids) == "\U0001f603!\U0001f607x"


def test_production_bpe_matches_independent_oracle(llama3_tok):
    """Differential test: the production encoder (heap BPE; native C++ when
    loaded) against the O(n^2) oracle, on text AND raw random bytes."""
    rng = np.random.default_rng(0)
    samples = [
        "hello world, the meaning of life!",
        "éèê 宴会 \U0001f680\U0001f30d",
        "mixed 12345 !!&& über",
    ]
    vocab_n = llama3_tok.regular_vocab_size
    scores = llama3_tok.scores
    for s in samples:
        data = s.encode("utf-8")
        want = naive_bpe_encode(list(llama3_tok.vocab[:vocab_n]), scores, data)
        got = llama3_tok.encode(s, add_bos=False, add_special_tokens=False)
        assert got == want, s
    for _ in range(5):
        data = bytes(rng.integers(0, 256, 64, dtype=np.uint8))
        want = naive_bpe_encode(list(llama3_tok.vocab[:vocab_n]), scores, data)
        got = llama3_tok.encode(data, add_bos=False, add_special_tokens=False)
        assert got == want


def test_golden_model_temp0_continuation():
    """macbeth.sh analog: the committed .m + greedy decode must reproduce the
    pinned continuation bit-for-bit (CPU: CI's platform)."""
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models import formats

    assert jax.devices()[0].platform == "cpu"
    cfg, hs = formats.read_header(FIXTURE_M)
    params = formats.load_params(FIXTURE_M, cfg, hs)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32)
    logits = eng.prefill(np.asarray([GOLDEN_PROMPT], np.int32))
    tok = int(np.argmax(np.asarray(logits)[0]))
    ids = [tok] + [int(t) for t in eng.decode_greedy_n(np.array([[tok]]), 15)[:, 0]]
    assert ids == GOLDEN_CONTINUATION


def test_golden_model_fused_weights_continuation():
    """The fused wqkv/w13 engine must reproduce the same pinned continuation
    from the committed .m — fusion composes with the file-load path exactly."""
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models import formats

    cfg, hs = formats.read_header(FIXTURE_M)
    params = formats.load_params(FIXTURE_M, cfg, hs)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32, fuse_weights=True)
    logits = eng.prefill(np.asarray([GOLDEN_PROMPT], np.int32))
    tok = int(np.argmax(np.asarray(logits)[0]))
    ids = [tok] + [int(t) for t in eng.decode_greedy_n(np.array([[tok]]), 15)[:, 0]]
    assert ids == GOLDEN_CONTINUATION
