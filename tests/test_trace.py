"""Request-flow tracer tests (dllama_tpu/obs/trace.py): ring bounding and
eviction, span nesting + req_id correlation, the Chrome trace-event export
contract, the disabled no-op fast path, flight-recorder lifecycle, and
concurrent writers.

Pure host — no engine, no model — so the whole file runs in milliseconds
(tier-1 is time-budgeted; the HTTP /debug endpoints are covered in
tests/test_metrics.py on its already-booted server, and end-to-end through
the real CLI by scripts/trace_smoke.sh)."""

import json
import threading

import numpy as np
import pytest

from dllama_tpu.obs import trace


def events(tr):
    return tr.export_chrome()["traceEvents"]


def spans(tr):
    return [e for e in events(tr) if e.get("ph") == "X"]


def per_track_ts(doc):
    by_tid = {}
    for e in doc["traceEvents"]:
        if e.get("ph") in ("X", "i"):
            by_tid.setdefault(e["tid"], []).append(e["ts"])
    return by_tid


# ------------------------------------------------------------ ring buffer


def test_ring_bounds_and_evicts_oldest():
    tr = trace.Tracer(8)
    for i in range(50):
        tr.event(f"e{i}", track="t")
    evs = [e for e in events(tr) if e["ph"] == "i"]
    assert len(evs) == 8
    # eviction is FIFO: the survivors are exactly the newest 8
    assert [e["name"] for e in evs] == [f"e{i}" for i in range(42, 50)]
    assert tr.stats()["dropped"] == 42
    assert tr.stats()["events"] == 8


def test_reset_clears_events_and_requests():
    tr = trace.Tracer(16)
    tr.event("e", track="t")
    tr.req_submit("req_1")
    tr.reset()
    assert tr.stats() == {"enabled": True, "capacity": 16, "events": 0,
                          "dropped": 0, "requests": 0}
    assert tr.request_timeline("req_1") is None


# ------------------------------------------------------- spans and export


def test_span_nesting_and_req_id_correlation():
    tr = trace.Tracer(64)
    with tr.span("outer", req_id="req_1", track="work"):
        with tr.span("inner", req_id="req_1", track="work", step=3):
            pass
    sp = spans(tr)
    # the inner span ENDS first (so enters the ring first) but the export is
    # start-ordered: outer leads, and at equal-ts ties the longer span wins
    assert [s["name"] for s in sp] == ["outer", "inner"]
    assert sp[0]["ts"] <= sp[1]["ts"]
    # nesting: inner is contained in outer
    assert sp[0]["ts"] + sp[0]["dur"] >= sp[1]["ts"] + sp[1]["dur"]
    # both carry the req_id in args — the grep key across spans/logs/metrics
    assert all(s["args"]["req_id"] == "req_1" for s in sp)
    assert sp[1]["args"]["step"] == 3


def test_span_end_merges_extra_args():
    tr = trace.Tracer(8)
    s = tr.span("s", track="t", a=1)
    s.end(b=2)
    (sp,) = spans(tr)
    assert sp["args"]["a"] == 1 and sp["args"]["b"] == 2


def test_chrome_export_is_valid_json_and_ts_nondecreasing_per_track():
    tr = trace.Tracer(64)
    now = tr.now()
    # recorded OUT of start order on purpose: the export must sort
    tr.span_at("late", now + 0.020, now + 0.030, track="x")
    tr.span_at("early", now, now + 0.010, track="x")
    tr.span_at("other", now + 0.005, now + 0.006, track="y")
    tr.event("mark", track="y")
    doc = json.loads(json.dumps(tr.export_chrome()))  # JSON round-trips
    for tid, ts in per_track_ts(doc).items():
        assert ts == sorted(ts), f"track {tid} ts not monotone: {ts}"
    # tracks are named via thread_name metadata (what Perfetto displays)
    meta = {e["args"]["name"] for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert meta == {"x", "y"}
    # complete events have non-negative durations
    assert all(e["dur"] >= 0 for e in doc["traceEvents"] if e.get("ph") == "X")


def test_args_are_sanitized_to_json_scalars():
    tr = trace.Tracer(8)
    tr.event("e", track="t", n=np.int32(4), f=np.float64(0.5),
             arr=np.arange(2), none=None, ok="s")
    text = json.dumps(tr.export_chrome())  # must not raise on numpy types
    (ev,) = [e for e in json.loads(text)["traceEvents"] if e.get("ph") == "i"]
    assert ev["args"]["n"] == 4 and ev["args"]["f"] == 0.5
    assert isinstance(ev["args"]["arr"], str)  # exotic types degrade to str


# --------------------------------------------------------- disabled mode


def test_disabled_mode_emits_nothing_and_allocates_no_spans():
    prev = trace.TRACER
    try:
        tr = trace.configure(0)
        assert tr is trace.TRACER and not tr.enabled
        # span() hands back ONE shared null span — no per-call allocation
        assert tr.span("x", big=1) is tr.span("y")
        with tr.span("z"):
            pass
        tr.span_at("s", 0.0, 1.0, track="t")
        tr.event("e")
        tr.req_submit("req_1", prompt_tokens=3)
        tr.req_admitted("req_1", slot=0)
        tr.req_prefill_done("req_1", tokens=3)
        tr.req_first_token("req_1")
        tr.req_chunk("req_1", 1, 4)
        tr.req_mark("req_1", state="decoding")
        tr.req_end("req_1", "stop")
        assert tr.export_chrome() == {"traceEvents": []}
        assert tr.requests_summary() == []
        assert tr.request_timeline("req_1") is None
        assert tr.stats()["events"] == 0 and tr.stats()["enabled"] is False
    finally:
        trace.TRACER = prev


def test_configure_swaps_the_global_tracer():
    prev = trace.TRACER
    try:
        tr = trace.configure(16)
        assert trace.TRACER is tr and tr.enabled and tr.capacity == 16
        tr0 = trace.configure(0)
        assert trace.TRACER is tr0 and tr0 is trace.NULL_TRACER
    finally:
        trace.TRACER = prev


# ------------------------------------------------------- flight recorder


def test_flight_recorder_lifecycle_and_derived_timings():
    tr = trace.Tracer(64)
    t0 = tr.now()
    tr.req_submit("req_a", prompt_tokens=7, t=t0)
    tr.req_admitted("req_a", slot=1, reused_tokens=2, t=t0 + 0.010)
    tr.req_prefill_done("req_a", tokens=7, reused=2, t=t0 + 0.030)
    tr.req_first_token("req_a", t=t0 + 0.035)
    tr.req_chunk("req_a", 5, 4, t=t0 + 0.040)
    tr.req_chunk("req_a", 6, 4, t=t0 + 0.045)
    tr.req_end("req_a", "stop", t=t0 + 0.050,
               queue_wait_ms=10.0, ttft_ms=35.0, e2e_ms=50.0, decode_tokens=9)
    rec = tr.request_timeline("req_a")
    assert rec["state"] == "finished" and rec["finish_reason"] == "stop"
    assert rec["prompt_tokens"] == 7 and rec["slot"] == 1
    assert rec["reused_tokens"] == 2
    assert rec["queue_wait_ms"] == pytest.approx(10.0)
    assert rec["prefill"]["tokens"] == 7
    assert rec["prefill"]["ms"] == pytest.approx(20.0)
    assert rec["ttft_ms"] == pytest.approx(35.0)
    assert rec["e2e_ms"] == pytest.approx(50.0)
    assert rec["decode_tokens"] == 9
    assert [c["chunk"] for c in rec["chunks"]] == [5, 6]
    assert [c["tokens"] for c in rec["chunks"]] == [4, 4]
    # internal monotonic marks never leak into the JSON payload
    assert not any(k.startswith("_") for k in rec)
    # the lifecycle auto-emits the request-track spans
    names = [s["name"] for s in spans(tr)]
    assert {"queue.wait", "prefill", "request"} <= set(names)
    # and the list view summarizes it
    (summary,) = tr.requests_summary()
    assert summary["req_id"] == "req_a" and summary["chunks"] == 2
    assert "slot" not in summary  # detail keys stay in the full record


def test_flight_recorder_derives_timings_without_explicit_overrides():
    tr = trace.Tracer(64)
    t0 = tr.now()
    tr.req_submit("req_b", t=t0)
    tr.req_admitted("req_b", t=t0 + 0.004)
    tr.req_first_token("req_b", t=t0 + 0.008)
    tr.req_end("req_b", "length", t=t0 + 0.016)
    rec = tr.request_timeline("req_b")
    assert rec["queue_wait_ms"] == pytest.approx(4.0, abs=0.01)
    assert rec["ttft_ms"] == pytest.approx(8.0, abs=0.01)
    assert rec["e2e_ms"] == pytest.approx(16.0, abs=0.01)


def test_request_ring_bounded_evicts_oldest():
    tr = trace.Tracer(64, max_requests=4)
    for i in range(10):
        tr.req_submit(f"req_{i}")
        tr.req_end(f"req_{i}", "stop")
    ids = [r["req_id"] for r in tr.requests_summary()]
    assert ids == [f"req_{i}" for i in range(6, 10)]
    assert tr.request_timeline("req_0") is None
    assert tr.request_timeline("req_9") is not None


def test_chunk_list_bounded_keeps_the_tail():
    tr = trace.Tracer(8, max_chunks_per_request=16)
    tr.req_submit("req_x")
    for i in range(50):
        tr.req_chunk("req_x", i, 4)
    rec = tr.request_timeline("req_x")
    assert len(rec["chunks"]) == 16
    assert rec["chunks_dropped"] == 34
    # the TAIL survives: a postmortem cares how the request ended
    assert [c["chunk"] for c in rec["chunks"]] == list(range(34, 50))


def test_empty_req_id_records_nothing():
    tr = trace.Tracer(8)
    tr.req_submit("", prompt_tokens=3)
    tr.req_chunk("", 1, 4)
    tr.req_end("", "stop")
    assert tr.requests_summary() == []
    assert tr.stats()["events"] == 0  # no auto-spans either


# ------------------------------------------------------------ concurrency


def test_concurrent_writers_ring_stays_bounded_and_exports_clean():
    tr = trace.Tracer(256)
    errors = []

    def work(k):
        try:
            for i in range(120):
                with tr.span(f"s{k}", track=f"tr{k % 3}", i=i):
                    pass
                if i % 7 == 0:
                    tr.event(f"ev{k}", track=f"tr{k % 3}")
                rid = f"req_{k}_{i}"
                tr.req_submit(rid, prompt_tokens=1)
                tr.req_chunk(rid, i, 1)
                tr.req_end(rid, "stop")
        except Exception as e:  # noqa: BLE001 — surfaced via the list
            errors.append(e)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    doc = json.loads(json.dumps(tr.export_chrome()))
    recorded = [e for e in doc["traceEvents"] if e.get("ph") in ("X", "i")]
    assert len(recorded) == 256  # ring bound honored under contention
    for tid, ts in per_track_ts(doc).items():
        assert ts == sorted(ts)
    assert len(tr.requests_summary()) == tr.max_requests
