"""Hybrid chunked-prefill/decode fused step, SLO-driven budgets, priority/
fair-queue scheduling, and preempt-to-pages (ISSUE 12).

Contracts driven here:

* token streams are BIT-EXACT hybrid-on vs the legacy phase-split path
  (--prefill-budget 0) across {greedy, sampled, penalized, spec} x
  {dense, paged} x overlap {on, off} x radix {on, off} — fusing a prefill
  slice into the decode launch changes WHEN prompt rows are written, never
  what any slot computes;
* a preempted request's stream is BYTE-IDENTICAL to its uninterrupted run
  (greedy and sampled, incl. across a warm restart), with clean pool
  audits (DLLAMA_POOL_AUDIT=1 is armed suite-wide by conftest);
* weighted fair queueing bounds a backlogged tenant's wait (no starvation
  behind another tenant's flood) and priority classes admit strictly
  first;
* the --prefill-budget auto controller shrinks the budget when the
  windowed ITL p95 violates --slo-itl-ms and grows it under headroom.

Tiny config + memoized workloads, same discipline as test_paged_kv.py.
Engines are SESSION-SHARED across the scheduler matrix (keyed on the
shapes that force a rebuild: layout, spec, n_slots): every run after the
first reuses the resident jitted callables via engine.warm_restart() —
decode state, page pool, and radix tree rebuilt, ZERO recompiles — which
is what keeps this suite from displacing the tier-1 tail past the time
budget (the PR 11 regression ISSUE 13 calls out). Every submit is seeded,
so shared PRNG/admission counters cannot leak between runs.
"""

import time

import jax.numpy as jnp
import pytest

from dllama_tpu.engine.batch import BatchEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.obs import perf
from dllama_tpu.serve.scheduler import Scheduler
from dllama_tpu.utils import faults

CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)
PAGE = 8

LONG_PROMPT = [int(x) % 90 + 1 for x in range(7, 31)]  # 24 tokens: several
# budget-4 slices, so the admission really rides multiple hybrid chunks

_ENGINES: dict = {}


def _engine(layout, spec=0, n_slots=3):
    """Session-shared engine (one XLA compile set per key). Reuse goes
    through warm_restart(): decode state + pool + an EMPTY radix tree are
    rebuilt against the resident weights while the jitted callables — and
    their compiles — survive, so no run sees another run's cache."""
    key = (layout, spec, n_slots)
    eng = _ENGINES.get(key)
    if eng is None:
        eng = _ENGINES[key] = BatchEngine(
            CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32, spec=spec,
            kv_layout=layout, page_size=PAGE, radix_cache="auto",
            max_prefill_chunk=8)
        return eng
    if eng.pool is not None and eng.radix is None:
        # a radix="off" run disabled the tree for its scheduler's lifetime;
        # restore it so warm_restart rebuilds it against the fresh pool
        from dllama_tpu.engine.radix import RadixCache

        eng.radix = RadixCache(eng.pool)
    eng.warm_restart()
    return eng


def _sched(layout, *, overlap=True, spec=0, radix="auto", budget="auto",
           n_slots=3, chunk=3, **kw):
    eng = _engine(layout, spec, n_slots)
    if radix == "off" and eng.radix is not None:
        eng.radix = None  # per-run opt-out; _engine restores it on reuse
    return Scheduler(eng, chunk=chunk, overlap=overlap,
                     prefill_budget=budget, **kw)


def _mixed_workload(sched):
    """Greedy decoders running, then a long sampled joiner and a penalized
    one — the join paths are exactly where hybrid differs from phase-split."""
    r1 = sched.submit([1, 2, 3, 1, 2, 3], 0.0, 0.9, 12, frozenset(), seed=1)
    it1 = r1.tokens()
    head = [next(it1), next(it1)]  # r1 decodes before the others join
    r2 = sched.submit(LONG_PROMPT, 1.1, 0.9, 8, frozenset(), seed=42)
    r3 = sched.submit([4, 5], 0.9, 0.8, 6, frozenset(), seed=7,
                      presence=0.5, frequency=0.3)
    out2 = list(r2.tokens())
    out3 = list(r3.tokens())
    out1 = head + list(it1)
    return [(out1, r1.finish_reason), (out2, r2.finish_reason),
            (out3, r3.finish_reason)]


_RUNS: dict = {}


def _run(layout, overlap=True, spec=0, radix="auto", budget="auto"):
    key = (layout, overlap, spec, radix, budget)
    if key in _RUNS:
        return _RUNS[key]
    sched = _sched(layout, overlap=overlap, spec=spec, radix=radix,
                   budget=budget)
    try:
        _RUNS[key] = _mixed_workload(sched)
        if budget != 0:
            # the joiner's prefill really rode fused chunks (the whole
            # point — without this the parity below proves nothing)
            assert sched.ledger.totals["hybrid"] > 0.0
        if sched.engine.pool is not None:
            assert sched.engine.pool.audit()["ok"]
        return _RUNS[key]
    finally:
        sched.shutdown()


# ------------------------------------------------------------------- parity


def test_hybrid_bit_exact_paged():
    """Paged layout (radix on, the serving default): hybrid-on streams are
    bit-identical to --prefill-budget 0, overlap on AND off. (One legacy
    reference run serves every axis: legacy overlap-invariance is already
    test_overlap's proven contract, so hybrid runs compare transitively
    against the single overlap-on legacy baseline — each dropped engine
    build buys the time-budgeted tier-1 ~10s of tail coverage.)"""
    legacy = _run("paged", budget=0)
    assert _run("paged") == legacy
    assert _run("paged", overlap=False) == legacy


def test_hybrid_bit_exact_radix_off():
    """Radix off (per-slot prefix cache): same parity (radix on/off token
    invariance is test_radix's proven contract — the paged legacy run is
    the one reference)."""
    assert _run("paged", radix="off") == _run("paged", budget=0)


def test_hybrid_bit_exact_dense():
    """Dense layout: hybrid fuses through the batch-axis slice prefill
    (dense == paged is the PR 5 contract, so the paged legacy run is the
    reference)."""
    assert _run("dense") == _run("paged", budget=0)


def test_hybrid_bit_exact_with_spec():
    """Spec engine (K=2): hybrid chunks are plain chunks that drain the
    spec pipeline at mode switches — streams stay bit-exact vs budget 0
    and vs the non-spec run (greedy spec is exact)."""
    legacy = _run("paged", spec=2, budget=0)
    assert _run("paged", spec=2) == legacy


# --------------------------------------------------------------- preemption


def _preempt_run(seed, temperature, crash=False):
    """Low-priority request (1 slot) preempted by a high-priority arrival;
    optionally a worker crash while it sits suspended. Returns its stream."""
    sched = _sched("paged", n_slots=1, chunk=2)
    if crash:
        sched.restart_max = 3
        sched.restart_backoff_s = 0.01
    try:
        lo = sched.submit([1, 2, 3], temperature, 0.9, 18, frozenset(),
                          seed=seed, priority=0, tenant="batch")
        it = lo.tokens()
        first = next(it)
        # slow chunks so the high-pri arrival lands mid-stream, not after
        faults.install("engine.decode", "delay", ms=15, times=80)
        hi = sched.submit([9, 8, 7], 0.0, 0.9, 10 if crash else 4,
                          frozenset(), seed=6, priority=2,
                          tenant="interactive")
        hit = hi.tokens()
        first_hi = next(hit)
        if crash:
            # the crash must land while lo is PARKED: hi is still running
            # (10 slow chunks), so poll for the preempted record and then
            # arm a worker crash — lo's resume record is host-side and must
            # survive the restart (the dead radix tree just costs it a
            # re-prefill at resume)
            deadline = time.monotonic() + 30
            while not any(r.preempted for r in sched._backlog):
                assert lo.finish_reason is None, "lo finished unpreempted"
                assert time.monotonic() < deadline, "preemption never parked"
                time.sleep(0.002)
            faults.install("scheduler.loop", "raise", times=1)
        out_hi = [first_hi] + list(hit)
        assert hi.finish_reason == "length"
        assert sched.preempt_count >= 1, "high-priority arrival never preempted"
        out_lo = [first] + list(it)
        assert lo.finish_reason == "length"
        assert sched.resume_count >= 1
        if crash:
            assert sched.health()["restarts"] == 1
        assert sched.engine.pool.audit()["ok"]
        return out_lo
    finally:
        faults.clear()
        sched.shutdown()


def _uninterrupted(seed, temperature):
    sched = _sched("paged", n_slots=1, chunk=2)
    try:
        r = sched.submit([1, 2, 3], temperature, 0.9, 18, frozenset(),
                         seed=seed)
        return list(r.tokens())
    finally:
        sched.shutdown()


def test_preempt_resume_bit_exact_greedy_and_sampled():
    """Preempt -> park -> resume: the stream is byte-identical to the
    uninterrupted run — greedy trivially, sampled because the resume
    replays the recorded PRNG key advanced to the interruption point."""
    assert _preempt_run(5, 0.0) == _uninterrupted(5, 0.0)
    assert _preempt_run(11, 0.8) == _uninterrupted(11, 0.8)


@pytest.mark.slow
def test_preempt_survives_warm_restart():
    """A request preempted to pages survives a worker crash while suspended
    (its resume record is host-side; the dead tree just costs a re-prefill)
    and still resumes byte-identical."""
    assert _preempt_run(13, 0.7, crash=True) == _uninterrupted(13, 0.7)


def test_preempt_off_never_fires():
    sched = _sched("paged", n_slots=1, chunk=2, preempt="off")
    try:
        lo = sched.submit([1, 2, 3], 0.0, 0.9, 10, frozenset(), seed=5,
                          priority=0)
        it = lo.tokens()
        next(it)
        hi = sched.submit([9, 8, 7], 0.0, 0.9, 2, frozenset(), seed=6,
                          priority=2)
        list(hi.tokens())
        list(it)
        assert sched.preempt_count == 0
        # without preemption the high-pri request simply waited for the slot
        assert hi.finish_reason == "length"
    finally:
        sched.shutdown()


# ------------------------------------------------- priorities & fair queue


def _hold_worker(sched, warm_seed=99):
    """Run one request to warm compiles, then slow decode chunks so a batch
    of submissions lands in the backlog while the slot is busy."""
    w = sched.submit([5, 6], 0.0, 0.9, 2, frozenset(), seed=warm_seed)
    list(w.tokens())


def test_wfq_starvation_bound():
    """One tenant flooding the queue cannot starve another: with equal
    weights the interleave is ~1:1, so tenant B's single request admits
    before the flood's tail (the WFQ virtual-time bound)."""
    sched = _sched("paged", n_slots=1, chunk=2)
    try:
        _hold_worker(sched)
        faults.install("engine.decode", "delay", ms=10, times=200)
        runner = sched.submit([7, 7, 7], 0.0, 0.9, 10, frozenset(), seed=1,
                              tenant="A")
        it = runner.tokens()
        next(it)  # tenant A occupies the slot; everything below backlogs
        flood = [sched.submit([2, 2, 2], 0.0, 0.9, 2, frozenset(), seed=s,
                              tenant="A") for s in range(2, 6)]
        b = sched.submit([3, 3, 3], 0.0, 0.9, 2, frozenset(), seed=9,
                         tenant="B")
        list(b.tokens())
        for r in flood:
            list(r.tokens())
        list(it)
        finished = sorted(flood + [b], key=lambda r: r.finished_at)
        # B was submitted LAST but must not finish last — the bound: at
        # most one A request (the one charged before B arrived) precedes it
        assert finished.index(b) <= 1, (
            f"tenant B starved behind the flood (position "
            f"{finished.index(b)} of {len(finished)})")
    finally:
        faults.clear()
        sched.shutdown()


@pytest.mark.slow
def test_tenant_weights_skew_service():
    """A 4x-weighted tenant is charged 1/4 the virtual time per request, so
    its backlog drains ahead of an equal flood from a weight-1 tenant."""
    sched = _sched("paged", n_slots=1, chunk=2,
                   tenant_weights={"paid": 4.0, "free": 1.0})
    try:
        _hold_worker(sched)
        faults.install("engine.decode", "delay", ms=10, times=200)
        runner = sched.submit([7, 7, 7], 0.0, 0.9, 8, frozenset(), seed=1)
        it = runner.tokens()
        next(it)
        free = [sched.submit([2, 2, 2], 0.0, 0.9, 2, frozenset(), seed=s,
                             tenant="free") for s in range(2, 5)]
        paid = [sched.submit([3, 3, 3], 0.0, 0.9, 2, frozenset(), seed=s,
                             tenant="paid") for s in range(5, 8)]
        for r in free + paid + [runner]:
            list(r.tokens())
        order = sorted(free + paid, key=lambda r: r.finished_at)
        # all three paid requests finish inside the first four slots: the
        # 4x weight buys ~4 admissions per free admission
        assert sum(1 for r in order[:4] if r.tenant == "paid") >= 3
    finally:
        faults.clear()
        sched.shutdown()


def test_wfq_idle_tenant_banks_no_credit():
    """Start-time fair queueing unit (no engine): a tenant idle while
    another worked gets ONE immediate pick (smallest finish tag), then its
    tag snaps to the virtual clock — its flood alternates with the active
    tenant instead of draining first on banked credit."""
    from dllama_tpu.serve.scheduler import Request

    s = object.__new__(Scheduler)  # policy state only; worker never starts
    s._backlog, s._tenant_vt, s._vt_now = [], {}, 0.0
    s.tenant_weights = {}
    mk = lambda t: Request([1, 2, 3], 0.0, 0.9, 2, frozenset(), tenant=t)
    for _ in range(20):  # tenant A works while B idles
        s._charge_tenant(mk("A"))
    assert s._tenant_vt["A"] == 100.0 and s._vt_now == 95.0
    s._backlog = [mk("B") for _ in range(5)] + [mk("A")]
    picks = []
    for _ in range(5):
        r = s._select_next()
        s._charge_tenant(r)
        picks.append(r.tenant)
    assert picks[0] == "B"  # one immediate pick, bounded
    assert picks[1:].count("A") >= 1 and picks[1:].count("B") >= 1, (
        f"no alternation after the idle return: {picks}")
    # and B's tag really snapped past the clock, not accumulated from 0
    assert s._tenant_vt["B"] >= 95.0


def test_priority_classes_admit_strictly_first():
    sched = _sched("paged", n_slots=1, chunk=2)
    try:
        _hold_worker(sched)
        faults.install("engine.decode", "delay", ms=10, times=120)
        runner = sched.submit([7, 7], 0.0, 0.9, 6, frozenset(), seed=1,
                              priority=2)  # not preemptible by the others
        it = runner.tokens()
        next(it)
        low = sched.submit([2, 2], 0.0, 0.9, 2, frozenset(), seed=2,
                           priority=0)
        norm = sched.submit([3, 3], 0.0, 0.9, 2, frozenset(), seed=3,
                            priority=1)
        high = sched.submit([4, 4], 0.0, 0.9, 2, frozenset(), seed=4,
                            priority=2)
        for r in (low, norm, high, runner):
            list(r.tokens())
        order = sorted((low, norm, high), key=lambda r: r.admitted_at)
        assert [r.priority for r in order] == [2, 1, 0]
    finally:
        faults.clear()
        sched.shutdown()


# ------------------------------------------------------ budget controller


def test_budget_controller_shrinks_and_grows():
    """Pure controller: p95 over the ITL target halves the budget, ample
    headroom doubles it, the band between holds, and no target holds."""
    t = [0.0]
    now = lambda: t[0]
    win = perf.WindowQuantiles(window_s=60.0, now_fn=now)
    ctl = perf.PrefillBudgetController(
        perf.SloPolicy(itl_ms=50.0), lo=16, hi=256, start=64,
        interval_s=0.0, now_fn=now)
    for _ in range(20):
        win.observe(0.100)  # 100 ms >> 50 ms target
    t[0] += 1.0
    assert ctl.update(win) == 32
    t[0] += 1.0
    assert ctl.update(win) == 16
    t[0] += 1.0
    assert ctl.update(win) == 16  # floor
    win2 = perf.WindowQuantiles(window_s=60.0, now_fn=now)
    for _ in range(20):
        win2.observe(0.010)  # 10 ms << 0.6 * 50 ms
    t[0] += 1.0
    assert ctl.update(win2) == 32
    t[0] += 1.0
    assert ctl.update(win2) == 64
    win3 = perf.WindowQuantiles(window_s=60.0, now_fn=now)
    for _ in range(20):
        win3.observe(0.040)  # inside the hold band (0.6..1.0 of target)
    t[0] += 1.0
    assert ctl.update(win3) == 64
    # rate limit: updates inside interval_s hold the current value
    ctl2 = perf.PrefillBudgetController(
        perf.SloPolicy(itl_ms=50.0), start=64, interval_s=10.0, now_fn=now)
    assert ctl2.update(win) == 32  # first evaluation reacts immediately
    t[0] += 0.5
    assert ctl2.update(win) == 32  # rate-limited: no second halving yet
    t[0] += 10.0
    assert ctl2.update(win) == 16
    # no target: auto holds the start value
    ctl3 = perf.PrefillBudgetController(perf.SloPolicy(), start=64,
                                        interval_s=0.0, now_fn=now)
    assert ctl3.update(win) == 64


@pytest.mark.slow
def test_budget_honors_itl_slo_under_long_prompt_flood():
    """Integration: an impossible ITL target + a flood of long prompts
    drives the windowed p95 over target, and the auto budget SHRINKS while
    admissions keep landing — the SLO knob really steers the hybrid step."""
    sched = _sched("paged", n_slots=3, chunk=2, slo_itl_ms=1e-3)
    assert sched._budget_ctl is not None
    sched._budget_ctl.interval_s = 0.0  # every chunk may re-evaluate
    try:
        start = sched._budget_now
        bg = sched.submit([1, 2, 3], 0.0, 0.9, 40, frozenset(), seed=1)
        it = bg.tokens()
        next(it)
        deadline = time.monotonic() + 60
        shrunk = False
        s = 0
        while time.monotonic() < deadline and not shrunk:
            r = sched.submit([(7 * s + k) % 90 + 1 for k in range(20)],
                             0.0, 0.9, 2, frozenset(), seed=100 + s)
            list(r.tokens())  # each finish feeds the ITL window a violation
            s += 1
            shrunk = sched._budget_now < start
        assert shrunk, (f"budget never shrank from {start} despite ITL "
                        "violations")
        assert sched._budget_now >= sched._budget_ctl.lo
    finally:
        sched.shutdown()


# ----------------------------------------------------------- observability


def test_hybrid_ledger_state_and_summary():
    """The hybrid dispatch work is billed to the new exclusive `hybrid`
    ledger state, and latency_summary/health expose the live budget and
    preemption counters."""
    assert "hybrid" in perf.LEDGER_STATES
    sched = _sched("paged")
    try:
        _mixed_workload(sched)
        snap = sched.ledger.snapshot()
        assert snap["seconds"]["hybrid"] > 0.0
        s = sched.latency_summary()["hybrid"]
        assert s["mode"] == "auto" and s["prefill_budget"] >= 1
        h = sched.health()
        assert {"prefill_budget", "preemptions", "resumed",
                "preempted_waiting"} <= set(h)
    finally:
        sched.shutdown()


def test_api_priority_tenant_parsing():
    """Body-field validation: ints 0..2 and low/normal/high names for
    `priority`, bounded strings for `tenant`; malformed values are clean
    ApiError 400s (prevalidate runs these before stream headers)."""
    from dllama_tpu.serve.api import (
        ApiError,
        _parse_priority,
        _parse_tenant,
    )

    assert _parse_priority({}) == 1
    assert _parse_priority({"priority": 0}) == 0
    assert _parse_priority({"priority": "high"}) == 2
    assert _parse_priority({"priority": "low"}) == 0
    # (floats truncate via int(), matching the spec_k parser's convention)
    for bad in (3, -1, "urgent", [1]):
        with pytest.raises(ApiError):
            _parse_priority({"priority": bad})
    assert _parse_tenant({}) == ""
    assert _parse_tenant({"tenant": "acme"}) == "acme"
    for bad in (7, "x" * 65, ["t"]):
        with pytest.raises(ApiError):
            _parse_tenant({"tenant": bad})


def test_prefill_budget_zero_restores_phase_split():
    """--prefill-budget 0: no hybrid chunks at all (the ledger's hybrid
    bucket stays empty) — the A/B baseline the bench record compares."""
    sched = _sched("paged", budget=0)
    try:
        _mixed_workload(sched)
        assert sched.ledger.totals["hybrid"] == 0.0
        assert sched.latency_summary()["hybrid"]["mode"] == "off"
    finally:
        sched.shutdown()
