"""Pipeline-parallel tests: GPipe schedule over a 4-stage virtual mesh must be
bit-for-bit equivalent to the single-device forward (same layers, same cache
semantics — the schedule only reorders work)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward, random_params
from dllama_tpu.ops.layers import build_rope_cache
from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
from dllama_tpu.parallel.pipeline import make_pp_forward, put_pp


def tiny_cfg():
    return LlamaConfig(dim=64, hidden_dim=128, n_layers=4, n_heads=4, n_kv_heads=2,
                       vocab_size=128, seq_len=32)


@pytest.mark.parametrize("n_micro,quantize", [(1, False), (2, False), (2, True)])
def test_pp_forward_matches_single_device(rng, n_micro, quantize):
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=quantize)
    rope = build_rope_cache(cfg)
    batch = 2
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, 5)), jnp.int32)

    ref_cache = KVCache.create(cfg, batch, jnp.float32)
    ref_logits, ref_cache = forward(cfg, params, toks, jnp.int32(0), ref_cache, rope)

    pp_params, pp_cache = put_pp(params, KVCache.create(cfg, batch, jnp.float32), mesh)
    fn = jax.jit(make_pp_forward(cfg, mesh, n_micro=n_micro))
    got_logits, got_cache = fn(pp_params, toks, jnp.int32(0), pp_cache, rope)

    tol = dict(atol=2e-4, rtol=2e-4) if quantize else dict(atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits), **tol)
    np.testing.assert_allclose(np.asarray(got_cache.k), np.asarray(ref_cache.k), **tol)
    np.testing.assert_allclose(np.asarray(got_cache.v), np.asarray(ref_cache.v), **tol)


def test_pp_decode_after_prefill(rng):
    """Prefill then a decode step, both through the pipeline — cache handoff
    across calls must stay consistent with the reference path."""
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
    params = random_params(cfg, seed=4, dtype=jnp.float32, quantize=False)
    rope = build_rope_cache(cfg)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)), jnp.int32)
    nxt = jnp.asarray([[7]], jnp.int32)

    ref_cache = KVCache.create(cfg, 1, jnp.float32)
    _, ref_cache = forward(cfg, params, toks, jnp.int32(0), ref_cache, rope)
    ref_logits, _ = forward(cfg, params, nxt, jnp.int32(4), ref_cache, rope)

    pp_params, pp_cache = put_pp(params, KVCache.create(cfg, 1, jnp.float32), mesh)
    fn = jax.jit(make_pp_forward(cfg, mesh, n_micro=1))
    _, pp_cache = fn(pp_params, toks, jnp.int32(0), pp_cache, rope)
    got_logits, _ = fn(pp_params, nxt, jnp.int32(4), pp_cache, rope)

    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), atol=1e-5, rtol=1e-5
    )


def test_pp_rejects_indivisible_layers():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(pp=3), devices=jax.devices()[:3])
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_forward(cfg, mesh)


#: pp composed with auto axes (tp/dp) runs a PARTIAL-MANUAL shard_map —
#: only 'pp' manual, tp/dp left to GSPMD. jaxlib 0.4.36's SPMD partitioner
#: cannot place the `axis_index("pp")` the schedule needs there: it lowers
#: to a PartitionId instruction that the partial-auto pass rejects with
#: "UNIMPLEMENTED: PartitionId instruction is not supported for SPMD
#: partitioning" (and the sharded-iota alternative trips a stronger
#: manual-subgroup check and aborts the process). Pure pp meshes (fully
#: manual) are unaffected. Probed at runtime so the pin lifts itself on a
#: jaxlib where partial-manual axis_index lowers.
_PARTIAL_MANUAL_REASON = None


def _partial_manual_axis_index_unusable():
    global _PARTIAL_MANUAL_REASON
    if _PARTIAL_MANUAL_REASON is None:
        from dllama_tpu.parallel import shard_map as _shard_map
        devs = jax.devices()
        if len(devs) < 4:
            _PARTIAL_MANUAL_REASON = "needs 4 virtual devices"
            return _PARTIAL_MANUAL_REASON
        mesh = make_mesh(MeshConfig(tp=2, pp=2), devices=devs[:4])
        from functools import partial
        from jax.sharding import PartitionSpec as P

        @jax.jit
        @partial(_shard_map, mesh=mesh, in_specs=(P("pp"),),
                 out_specs=P("pp"), axis_names=frozenset({"pp"}),
                 check_vma=False)
        def probe(x):
            return x + jax.lax.axis_index("pp").astype(x.dtype)

        try:
            probe(jnp.zeros((2, 4), jnp.float32))
            _PARTIAL_MANUAL_REASON = ""
        except Exception as e:  # XlaRuntimeError: UNIMPLEMENTED PartitionId
            _PARTIAL_MANUAL_REASON = (
                "installed jaxlib cannot lower axis_index inside a partial-"
                f"manual shard_map (auto tp/dp + manual pp): {repr(e)[:120]}")
    return _PARTIAL_MANUAL_REASON


@pytest.mark.parametrize("mesh_spec", ["pp=2", "tp=2,pp=2", "dp=1,tp=2,pp=4"])
def test_engine_pp_through_loader_matches_single_device(tmp_path, mesh_spec):
    """VERDICT r1 #7: `--mesh tp=N,pp=M` through the normal load_model/CLI
    path (shard-direct load -> pp-sharded layer stacks -> GPipe step inside
    the engine) must match single-device logits."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models import formats
    from dllama_tpu.ops.quant import FloatType

    if ("tp=" in mesh_spec or "dp=" in mesh_spec):
        reason = _partial_manual_axis_index_unusable()
        if reason:
            # xfail, not skip: this is a triaged environmental failure —
            # the code path is EXPECTED to break on this jaxlib, and the
            # pin lifts itself (test runs again) where the probe lowers
            pytest.xfail(reason)

    cfg = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
        vocab_size=128, seq_len=64, weight_type=FloatType.Q40,
    )
    rng = np.random.default_rng(1)
    tensors = {
        n: (rng.standard_normal(s) * 0.05).astype(np.float32)
        for n, s, _ in formats.tensor_plan(cfg)
    }
    path = str(tmp_path / "tiny.m")
    formats.save_model(path, cfg, tensors)

    prompt = np.array([[5, 9, 2, 7, 1, 3]], dtype=np.int32)
    ref = load_model(path, mesh=None, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.engine.prefill(prompt))
    ref_l2 = np.asarray(ref.engine.decode_step(np.array([[11]])))

    loaded = load_model(path, mesh=mesh_spec, cache_dtype=jnp.float32)
    wq = loaded.engine.params["layers"]["wq"]
    pp = loaded.shardings.mesh.shape["pp"]
    assert wq.packed.sharding.shard_shape(wq.packed.shape)[0] == cfg.n_layers // pp
    got = np.asarray(loaded.engine.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=2e-3, rtol=1e-2)
    got_l2 = np.asarray(loaded.engine.decode_step(np.array([[11]])))
    np.testing.assert_allclose(got_l2, ref_l2, atol=2e-3, rtol=1e-2)


def test_pp_sp_composition_rejected():
    from dllama_tpu.parallel.sharding import LlamaShardings

    cfg = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
        vocab_size=128, seq_len=64,
    )
    mesh = make_mesh(MeshConfig(pp=2, sp=2))
    with pytest.raises(ValueError, match="pp x sp"):
        LlamaShardings(mesh, cfg)


def test_engine_pp_micro_batched_prefill():
    """VERDICT r2 weak #8: GPipe microbatching is reachable from the engine —
    a pp mesh with pp_micro=2 and batch=2 matches the pp_micro=1 logits."""
    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.parallel.sharding import LlamaShardings

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
                      vocab_size=256, seq_len=64)
    params = random_params(cfg, seed=5, dtype=jnp.float32, quantize=True)
    prompt = np.array([[3, 1, 4, 1], [5, 9, 2, 6]], dtype=np.int32)

    outs = []
    for micro in (1, 2):
        sh = LlamaShardings(make_mesh(MeshConfig(pp=2)), cfg)
        eng = InferenceEngine(cfg, params, batch=2, cache_dtype=jnp.float32,
                              shardings=sh, pp_micro=micro)
        outs.append(np.asarray(eng.step(prompt)))
    np.testing.assert_allclose(outs[0], outs[1], atol=2e-4, rtol=1e-3)

    with pytest.raises(ValueError, match="divide"):
        sh = LlamaShardings(make_mesh(MeshConfig(pp=2)), cfg)
        InferenceEngine(cfg, params, batch=3, cache_dtype=jnp.float32,
                        shardings=sh, pp_micro=2)
