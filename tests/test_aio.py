"""Aio front-end drills (ISSUE 15): the ≥256-concurrent-SSE-streams
concurrency drill with a bounded thread count, event-loop disconnect
detection (no polling thread), the thread-tier mid-stream disconnect
regression, and SSE keep-alive heartbeats."""

import http.client
import json
import re
import selectors
import socket
import threading
import time

import pytest

from dllama_tpu.utils import faults


@pytest.fixture(scope="module")
def tiny_loaded(tmp_path_factory):
    from dllama_tpu.engine.loader import load_model
    from tests.test_serve import make_tiny_files

    tmp = tmp_path_factory.mktemp("aio")
    mpath, tpath, _ = make_tiny_files(tmp)
    return mpath, tpath


def _boot(mpath, tpath, **kw):
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, api


def _metric(text: str, name: str) -> float:
    m = re.search(rf"^{name} ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


def _scrape(port: int) -> str:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    conn.close()
    return text


def _stream_request_bytes(port: int, max_tokens: int = 2) -> bytes:
    body = json.dumps({"messages": [{"role": "user", "content": "hi"}],
                       "max_tokens": max_tokens, "temperature": 0.0,
                       "stream": True}).encode()
    return (b"POST /v1/chat/completions HTTP/1.1\r\n"
            b"Host: 127.0.0.1:%d\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % (port, len(body))) + body


N_STREAMS = 260  # acceptance floor is 256


def test_concurrency_drill_256_streams_bounded_threads(tiny_loaded):
    """≥256 concurrent SSE streams on the aio front-end: every stream
    completes with [DONE], and the server's thread count stays a constant
    of the configuration (asserted via dllama_process_threads mid-flight —
    thread-per-connection would sit at 256+)."""
    mpath, tpath = tiny_loaded
    httpd, api = _boot(mpath, tpath, n_slots=4, frontend="aio")
    try:
        port = httpd.server_address[1]
        req = _stream_request_bytes(port)
        sel = selectors.DefaultSelector()
        bufs: dict[socket.socket, bytearray] = {}
        for i in range(N_STREAMS):
            s = socket.create_connection(("127.0.0.1", port), timeout=60)
            s.sendall(req)
            s.setblocking(False)
            bufs[s] = bytearray()
            sel.register(s, selectors.EVENT_READ)
        # wait until every stream has its SSE headers — 260 live
        # connections, most queued behind 4 slots
        deadline = time.monotonic() + 120
        headered = set()
        done: set = set()
        threads_mid = None
        while len(done) < N_STREAMS and time.monotonic() < deadline:
            for key, _ in sel.select(timeout=1.0):
                s = key.fileobj
                try:
                    data = s.recv(65536)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if not data:
                    sel.unregister(s)
                    s.close()
                    done.add(s)  # server closed after [DONE] (or died: the
                    # buffer assertion below catches that)
                    continue
                bufs[s] += data
                if b"text/event-stream" in bufs[s]:
                    headered.add(s)
                if b"data: [DONE]" in bufs[s] and s not in done:
                    done.add(s)
                    sel.unregister(s)
                    s.close()
            if threads_mid is None and len(headered) >= N_STREAMS \
                    and len(done) < N_STREAMS // 2:
                # every connection is live (headers out), most still
                # streaming/queued: THE moment thread-per-connection would
                # be at 260+ threads
                text = _scrape(port)
                threads_mid = _metric(text, "dllama_process_threads")
                # the gauge is labeled per server (the registry outlives
                # servers — earlier tests' series linger at 0): read THIS
                # server's series
                m = re.search(
                    r'^dllama_frontend_connections\{server="127\.0\.0\.1:'
                    + str(port) + r'"\} ([0-9.e+-]+)$', text, re.M)
                assert m and float(m.group(1)) >= N_STREAMS, \
                    "connections gauge never reflected the live streams"
        assert len(done) == N_STREAMS, \
            f"only {len(done)}/{N_STREAMS} streams completed"
        incomplete = [bytes(b) for b in bufs.values()
                      if b"data: [DONE]" not in b]
        assert not incomplete, \
            f"{len(incomplete)} streams closed without [DONE]"
        assert threads_mid is not None, "never observed the mid-flight state"
        # loop + pump + <=8 workers + scheduler worker/watchdog + test
        # harness threads — nowhere near one-per-connection
        assert threads_mid < 64, \
            f"{threads_mid} threads for {N_STREAMS} streams"
    finally:
        if api.scheduler is not None:
            api.scheduler.shutdown()
        httpd.shutdown()
        httpd.server_close()


def _kv_audit_ok(port: int) -> bool:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/debug/kv")
    resp = conn.getresponse()
    kv = json.loads(resp.read())
    conn.close()
    return resp.status == 200 and (kv["audit"] is None or kv["audit"]["ok"])


def _disconnect_mid_stream(httpd, api):
    """Open a stream with a huge budget, hang up mid-decode, and assert the
    request is cancelled and the paged pool audits clean."""
    port = httpd.server_address[1]
    before = api.scheduler.latency_summary()["completed"]
    faults.install("engine.decode", "delay", ms=50.0)
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        s.sendall(_stream_request_bytes(port, max_tokens=4096))
        # read the headers + at least one token event, then hang up
        buf = b""
        deadline = time.monotonic() + 30
        while b"data: " not in buf and time.monotonic() < deadline:
            buf += s.recv(4096)
        assert b"text/event-stream" in buf
        s.close()  # mid-stream client hangup
        deadline = time.monotonic() + 15.0
        cancelled = None
        while time.monotonic() < deadline:
            with api.scheduler._metrics_lock:
                recent = list(api.scheduler._completed)[before:]
            cancelled = next((r for r in recent
                              if r.finish_reason == "cancelled"), None)
            if cancelled is not None:
                break
            time.sleep(0.02)
    finally:
        faults.clear()
    assert cancelled is not None, "hangup did not cancel the stream"
    assert cancelled.produced < 400  # nowhere near the budget
    # pages freed, allocator clean (the /debug/kv audit reconciles
    # refcounts vs block tables vs free list)
    assert _kv_audit_ok(port)


def test_aio_disconnect_cancels_via_event_loop(tiny_loaded):
    """aio tier: the event loop's EOF signal (no polling thread) cancels a
    mid-stream hangup and frees its pages."""
    mpath, tpath = tiny_loaded
    httpd, api = _boot(mpath, tpath, n_slots=2, frontend="aio",
                       kv_layout="paged", page_size=8)
    try:
        _disconnect_mid_stream(httpd, api)
    finally:
        api.scheduler.shutdown()
        httpd.shutdown()
        httpd.server_close()


def test_threads_disconnect_regression_mid_stream(tiny_loaded):
    """threads tier (regression, ISSUE 15 satellite): the MSG_PEEK probe
    still cancels a mid-STREAM hangup and frees its pages — the pre-aio
    probe path stays covered now that aio is the default."""
    mpath, tpath = tiny_loaded
    httpd, api = _boot(mpath, tpath, n_slots=2, frontend="threads",
                       kv_layout="paged", page_size=8)
    try:
        _disconnect_mid_stream(httpd, api)
    finally:
        api.scheduler.shutdown()
        httpd.shutdown()
        httpd.server_close()


@pytest.mark.parametrize("frontend", ["aio", "threads"])
def test_sse_heartbeat_on_idle_stream(tiny_loaded, frontend):
    """A slow-decode stream emits `: keep-alive` SSE comment frames on the
    --sse-heartbeat-s cadence (both front-ends), and they terminate once
    the stream ends."""
    mpath, tpath = tiny_loaded
    httpd, api = _boot(mpath, tpath, n_slots=2, frontend=frontend,
                       sse_heartbeat_s=0.05)
    try:
        port = httpd.server_address[1]
        faults.install("engine.decode", "delay", ms=150.0)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request("POST", "/v1/chat/completions",
                         json.dumps({"messages": [
                             {"role": "user", "content": "hi"}],
                             "max_tokens": 3, "temperature": 0.0,
                             "stream": True}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            raw = resp.read().decode()
            conn.close()
        finally:
            faults.clear()
        assert raw.count(": keep-alive") >= 1, raw[:400]
        assert "data: [DONE]" in raw
        # heartbeats are comments — they must not disturb the event stream
        events = [ln for ln in raw.splitlines() if ln.startswith("data: ")]
        assert events[-1] == "data: [DONE]"
    finally:
        api.scheduler.shutdown()
        httpd.shutdown()
        httpd.server_close()


def test_x_replica_id_and_timings_replica(tiny_loaded):
    """Every response carries X-Replica-Id and `timings.replica` (default
    identity: host:port) for end-to-end attribution through the router."""
    mpath, tpath = tiny_loaded
    httpd, api = _boot(mpath, tpath, n_slots=2, frontend="aio",
                       replica_id="replica-7")
    try:
        port = httpd.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user",
                                               "content": "hi"}],
                                 "max_tokens": 3, "temperature": 0.0}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.getheader("X-Replica-Id") == "replica-7"
        assert body["timings"]["replica"] == "replica-7"
        # health GETs carry it too (any response does)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Replica-Id") == "replica-7"
        conn.close()
    finally:
        api.scheduler.shutdown()
        httpd.shutdown()
        httpd.server_close()
