"""Failure-path coverage for the supervised serving core, driven end to end
through the deterministic fault-injection module (utils/faults.py) — worker
crash, overload shedding, stall watchdog, graceful drain, loader corruption.
All CPU-only and fast: the faults make the failures happen on demand instead
of by luck."""

import json
import threading
import time

import jax.numpy as jnp
import pytest

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.utils import faults

TINY = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                   vocab_size=96, seq_len=64)


@pytest.fixture(autouse=True)
def _clean_faults():
    """No fault plan may leak between tests (or into other test files)."""
    faults.clear()
    yield
    faults.clear()


def make_sched(n_slots=2, **kw):
    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.serve.scheduler import Scheduler

    params = random_params(TINY, seed=2, dtype=jnp.float32, quantize=False)
    eng = BatchEngine(TINY, params, n_slots=n_slots, cache_dtype=jnp.float32)
    return Scheduler(eng, chunk=2, **kw)


def drain_tokens(req, timeout=2.0):
    """Consume a request's queue with a HARD deadline (unlike req.tokens(),
    which blocks forever — the exact hang supervision must prevent).
    Returns (tokens, exception_or_None)."""
    toks, deadline = [], time.monotonic() + timeout
    while True:
        item = req.out.get(timeout=max(0.01, deadline - time.monotonic()))
        if isinstance(item, BaseException):
            return toks, item
        if isinstance(item, int):
            toks.append(item)
        else:  # _END sentinel
            return toks, None


# --------------------------------------------------------------- faults unit


def test_fault_spec_parse_and_windows():
    fs = faults.parse("engine.decode:raise:after=2:times=1, scheduler.queue:delay:ms=7")
    assert fs[0].point == "engine.decode" and fs[0].after == 2 and fs[0].times == 1
    assert fs[1].action == "delay" and fs[1].ms == 7.0
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.parse("nope.where:raise")
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.parse("engine.decode:explode")
    with pytest.raises(ValueError, match="unknown fault option"):
        faults.parse("engine.decode:raise:frobnicate=1")

    # after=1, times=1: hit 0 skipped, hit 1 fires, hit 2+ disarmed
    faults.install("engine.prefill", "raise", after=1, times=1)
    faults.fire("engine.prefill")
    with pytest.raises(faults.InjectedFault):
        faults.fire("engine.prefill")
    faults.fire("engine.prefill")


def test_fault_env_configure(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "scheduler.loop:raise:after=1000000")
    faults.configure_from_env()
    assert faults.active("scheduler.loop")
    faults.configure(None)
    assert not faults.active("scheduler.loop")


# ------------------------------------------------------- crash supervision


def test_worker_crash_fails_all_inflight_and_health_goes_unhealthy():
    """The tentpole acceptance drill: kill the worker mid-decode — every
    in-flight request terminates with finish_reason='error' within 2 s (no
    hung client queues) and health reports unhealthy."""
    from dllama_tpu.serve.scheduler import SchedulerUnhealthy

    sched = make_sched(n_slots=2)
    try:
        # warm-up: compile every step shape BEFORE arming the fault, so the
        # 2 s bound measures supervision latency, not XLA compile time
        warm = sched.submit([9, 8, 7], 0.0, 0.9, 3, eos_ids=frozenset(), seed=0)
        assert drain_tokens(warm, timeout=60.0)[1] is None

        faults.install("engine.decode", "raise")
        t0 = time.monotonic()
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 50, eos_ids=frozenset(), seed=1)
        r2 = sched.submit([4, 5], 0.0, 0.9, 50, eos_ids=frozenset(), seed=2)
        toks1, exc1 = drain_tokens(r1, timeout=2.0)
        toks2, exc2 = drain_tokens(r2, timeout=2.0)
        took = time.monotonic() - t0
        assert isinstance(exc1, faults.InjectedFault)
        assert isinstance(exc2, faults.InjectedFault)
        assert r1.finish_reason == "error" and r2.finish_reason == "error"
        assert took < 2.0, f"clients unblocked too slowly: {took:.2f}s"

        h = sched.health()
        assert h["live"] is False and h["ready"] is False
        assert h["crashed"] and "InjectedFault" in h["crashed"]
        # a dead worker must refuse new work immediately, not queue it forever
        with pytest.raises(SchedulerUnhealthy):
            sched.submit([1], 0.0, 0.9, 4, eos_ids=frozenset())
    finally:
        faults.clear()
        sched.shutdown()


def test_worker_crash_unblocks_queued_requests_too():
    """Requests still waiting in the pending queue at crash time must fail
    fast as well — they have no slot, only a queue a client is blocked on."""
    sched = make_sched(n_slots=1)
    try:
        warm = sched.submit([9, 8, 7], 0.0, 0.9, 3, eos_ids=frozenset())
        assert drain_tokens(warm, timeout=60.0)[1] is None  # compile warm-up
        faults.install("engine.decode", "raise")
        running = sched.submit([1, 2, 3], 0.0, 0.9, 50, eos_ids=frozenset())
        queued = sched.submit([4, 5, 6], 0.0, 0.9, 50, eos_ids=frozenset())
        _, exc_r = drain_tokens(running, timeout=2.0)
        _, exc_q = drain_tokens(queued, timeout=2.0)
        assert isinstance(exc_r, faults.InjectedFault)
        assert isinstance(exc_q, faults.InjectedFault)
        assert queued.finish_reason == "error"
    finally:
        faults.clear()
        sched.shutdown()


def test_prefill_fault_fails_only_that_request():
    """An admission-time failure is per-request: the joiner errors, the
    batch keeps decoding, and health stays live."""
    sched = make_sched(n_slots=2)
    try:
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 30, eos_ids=frozenset(), seed=1)
        it = r1.tokens()
        first = [next(it), next(it)]  # r1 decoding before the faulty join
        faults.install("engine.prefill", "raise", times=1)
        r2 = sched.submit([7, 8, 9], 0.0, 0.9, 8, eos_ids=frozenset(), seed=2)
        toks2, exc2 = drain_tokens(r2, timeout=5.0)
        assert isinstance(exc2, faults.InjectedFault) and r2.finish_reason == "error"
        rest = list(it)
        assert len(first) + len(rest) == 30 and r1.finish_reason == "length"
        assert sched.health()["live"] is True
    finally:
        faults.clear()
        sched.shutdown()


# ------------------------------------------------------------ load shedding


def test_queue_full_sheds_without_perturbing_running():
    """--max-queue=1, slot busy, one request queued: the next submit is shed
    with QueueFull while the running generation streams to completion."""
    from dllama_tpu.serve.scheduler import QueueFull

    sched = make_sched(n_slots=1, max_queue=1)
    try:
        running = sched.submit([1, 2, 3], 0.0, 0.9, 40, eos_ids=frozenset(), seed=1)
        it = running.tokens()
        got = [next(it)]  # the slot is definitely busy now
        waiting = sched.submit([4, 5], 0.0, 0.9, 4, eos_ids=frozenset(), seed=2)
        # pending depth == max_queue: shed
        deadline = time.monotonic() + 2.0
        while sched.pending.qsize() < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        if sched.pending.qsize() >= 1:  # not yet admitted (single slot busy)
            with pytest.raises(QueueFull) as ei:
                sched.submit([6], 0.0, 0.9, 4, eos_ids=frozenset())
            assert ei.value.retry_after_s > 0
        got += list(it)
        assert len(got) == 40 and running.finish_reason == "length"
        toks_w, exc_w = drain_tokens(waiting, timeout=5.0)
        assert exc_w is None and len(toks_w) == 4  # the queued one still ran
    finally:
        sched.shutdown()


def test_injected_queue_overflow():
    """The scheduler.queue fault forces the shed path deterministically,
    busy or not — the drill for the API tier's 429 mapping."""
    from dllama_tpu.serve.scheduler import QueueFull

    sched = make_sched(n_slots=2)
    try:
        faults.install("scheduler.queue", "raise", times=1)
        with pytest.raises(QueueFull):
            sched.submit([1, 2], 0.0, 0.9, 4, eos_ids=frozenset())
        req = sched.submit([1, 2], 0.0, 0.9, 4, eos_ids=frozenset())  # disarmed
        # 30s: first token may pay a cold decode compile when this test runs
        # early in a (re)ordered suite
        toks, exc = drain_tokens(req, timeout=30.0)
        assert exc is None and len(toks) == 4
    finally:
        faults.clear()
        sched.shutdown()


# ---------------------------------------------------------------- watchdog


def test_watchdog_flags_stall_and_recovers():
    """A decode chunk delayed past the stall deadline flips health to
    unhealthy (stalled=True); when the chunk finally lands, the watchdog
    clears the flag and the request still completes."""
    from dllama_tpu.obs import metrics

    stalls0 = (metrics.REGISTRY.sample("dllama_watchdog_stalls_total") or 0.0)
    sched = make_sched(n_slots=1, stall_deadline_s=0.15)
    try:
        # warm up: first chunk compiles; only then arm the delay so compile
        # time can't be mistaken for (or mask) the injected stall
        warm = sched.submit([9, 8], 0.0, 0.9, 2, eos_ids=frozenset())
        assert drain_tokens(warm, timeout=30.0)[1] is None
        # the warm-up compile itself may out-run the tight deadline; wait for
        # the watchdog to clear the flag (stalled submit-rejection is ALSO
        # supervision — a stalled scheduler sheds instead of queueing)
        deadline = time.monotonic() + 3.0
        while sched.stalled and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not sched.stalled
        faults.install("engine.decode", "delay", ms=700.0, times=1)
        req = sched.submit([1, 2, 3], 0.0, 0.9, 6, eos_ids=frozenset())
        saw_stall = False
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            h = sched.health()
            if h["stalled"]:
                saw_stall = True
                assert h["live"] is False
                # a stalled scheduler sheds new work instead of queueing
                # requests it may never serve
                from dllama_tpu.serve.scheduler import SchedulerUnhealthy

                with pytest.raises(SchedulerUnhealthy, match="stalled"):
                    sched.submit([5], 0.0, 0.9, 2, eos_ids=frozenset())
                break
            time.sleep(0.01)
        assert saw_stall, "watchdog never flagged the delayed chunk"
        toks, exc = drain_tokens(req, timeout=5.0)
        assert exc is None and len(toks) == 6
        deadline = time.monotonic() + 2.0
        while sched.health()["stalled"] and time.monotonic() < deadline:
            time.sleep(0.01)
        h = sched.health()
        assert h["stalled"] is False and h["live"] is True
        # >= 1: the un-armed warm-up compile may legitimately trip it too
        assert h["stall_count"] >= 1
        # the stall/recover transitions are exported too: every trip counted,
        # and this test saw at least one full stall -> recover cycle
        stalls = metrics.REGISTRY.sample("dllama_watchdog_stalls_total")
        recoveries = metrics.REGISTRY.sample("dllama_watchdog_recoveries_total")
        assert stalls >= stalls0 + h["stall_count"]
        assert recoveries is not None and recoveries >= 1
    finally:
        faults.clear()
        sched.shutdown()


def test_shutdown_join_timeout_is_surfaced(caplog):
    """shutdown() with a worker stuck in a device chunk: no silent return —
    a warning is logged and /health reports join_failed / live=false."""
    import logging

    sched = make_sched(n_slots=1)
    sched.join_timeout_s = 0.05
    try:
        faults.install("engine.decode", "delay", ms=600.0, times=1)
        req = sched.submit([1, 2, 3], 0.0, 0.9, 4, eos_ids=frozenset())
        time.sleep(0.1)  # worker is inside the delayed chunk now
        with caplog.at_level(logging.WARNING, logger="dllama_tpu.serve"):
            sched.shutdown()
        assert sched.join_failed is True
        assert any("failed to join" in r.message for r in caplog.records)
        h = sched.health()
        assert h["live"] is False and h["join_failed"] is True
    finally:
        faults.clear()
        sched._thread.join(timeout=5.0)  # let the delayed chunk finish


# ------------------------------------------------------------------- drain


def test_drain_completes_inflight_then_rejects_new():
    from dllama_tpu.serve.scheduler import SchedulerDraining

    sched = make_sched(n_slots=1)
    try:
        req = sched.submit([1, 2, 3], 0.0, 0.9, 30, eos_ids=frozenset(), seed=1)
        it = req.tokens()
        got = [next(it)]  # in flight
        done = {}
        t = threading.Thread(target=lambda: done.setdefault("clean", sched.drain(10.0)))
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched._draining.is_set() and time.monotonic() < deadline:
            time.sleep(0.001)
        with pytest.raises(SchedulerDraining):
            sched.submit([4], 0.0, 0.9, 4, eos_ids=frozenset())
        got += list(it)  # the in-flight request runs to its budget
        t.join(timeout=10.0)
        assert not t.is_alive() and done["clean"] is True
        assert len(got) == 30 and req.finish_reason == "length"
    finally:
        sched.shutdown()


def test_drain_waits_out_the_commit_window():
    """ISSUE 14 regression: during admission commit the worker briefly
    holds the request in NO container (popped from the in-flight list,
    slot not yet assigned) while add_commit does device work — a
    concurrent drain() polling exactly then used to read _busy() False,
    declare the system idle, and cut the request mid-commit (surfaced as
    an intermittent 503 by the DLLAMA_LOCK_AUDIT timing perturbation).
    The time-ledger state join closes the window; this pins it OPEN with
    a slowed commit and asserts drain waits for the request instead."""
    sched = make_sched(n_slots=1)
    try:
        eng = sched.engine
        in_commit = threading.Event()
        orig = eng.add_commit

        def slow_commit(adm, *a, **kw):
            in_commit.set()
            time.sleep(0.4)  # hold the no-container window wide open
            return orig(adm, *a, **kw)

        eng.add_commit = slow_commit
        req = sched.submit([1, 2, 3], 0.0, 0.9, 4, eos_ids=frozenset(),
                           seed=1)
        assert in_commit.wait(10.0)
        # the worker is INSIDE the window right now: no slots, no
        # in-flight entry, empty queue — only the ledger state says busy
        assert sched._busy()
        assert sched.drain(10.0) is True  # waits; never cuts the commit
        toks, exc = drain_tokens(req, timeout=5.0)
        assert exc is None and len(toks) == 4
        assert req.finish_reason == "length"
    finally:
        sched.shutdown()


def test_drain_timeout_cuts_stragglers():
    """A request cut off by the drain timeout must surface as a FAILURE to
    its client (SchedulerDraining on the queue), never as a clean-looking
    end-of-stream with silently truncated content."""
    from dllama_tpu.serve.scheduler import SchedulerDraining

    sched = make_sched(n_slots=1)
    try:
        req = sched.submit([1, 2, 3], 0.0, 0.9, 10_000, eos_ids=frozenset())
        next(req.tokens())  # enormous budget: will not finish in the window
        assert sched.drain(0.2) is False
        toks, exc = drain_tokens(req, timeout=2.0)
        assert isinstance(exc, SchedulerDraining)
        assert req.finish_reason == "shutdown"
    finally:
        sched.shutdown()


# ------------------------------------------------------- HTTP end-to-end


@pytest.fixture(scope="module")
def fserver(tmp_path_factory):
    """A dedicated continuous-batching server for failure drills (module-
    scoped: load_model dominates; every test here leaves it healthy except
    the crash test, which runs last via ordering below)."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server
    from tests.test_serve import make_tiny_files

    from tests.test_serve import post

    tmp_path = tmp_path_factory.mktemp("fserve")
    mpath, tpath, _cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=2,
                             max_queue=2, stall_deadline_s=30.0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # warm-up completion: compile prefill/decode shapes ONCE so the timed
    # failure drills below measure supervision, not XLA compile latency
    st, _ = post(httpd.server_address[1], "/v1/chat/completions",
                 {"messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 8, "temperature": 0.0})
    assert st == 200
    yield httpd.server_address[1], api, httpd
    api.scheduler.shutdown()
    httpd.shutdown()


def _get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, json.loads(data), headers


def test_health_endpoints_healthy(fserver):
    port, _api, _ = fserver
    st, body, _ = _get(port, "/health")
    assert st == 200 and body["live"] and body["ready"]
    assert body["mode"] == "continuous" and body["n_slots"] == 2
    assert {"queue_depth", "busy_slots", "last_step_age_s"} <= set(body)
    assert _get(port, "/health/live")[0] == 200
    assert _get(port, "/health/ready")[0] == 200


def test_http_queue_full_gets_429_with_retry_after(fserver):
    from tests.test_serve import post

    port, _api, _ = fserver
    faults.install("scheduler.queue", "raise", times=1)
    try:
        st, data = post(port, "/v1/chat/completions",
                        {"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4, "temperature": 0.0})
        assert st == 429
        assert "queue" in json.loads(data)["error"]["message"]
    finally:
        faults.clear()
    # Retry-After header: raw connection to read headers
    import http.client

    faults.install("scheduler.queue", "raise", times=1)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user", "content": "x"}]}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 429
        assert resp.getheader("Retry-After") is not None
        conn.close()
    finally:
        faults.clear()


def test_http_stream_sheds_before_headers(fserver):
    """Overload on a STREAM request must be a clean 429, never a 200 with a
    poisoned SSE body."""
    import http.client

    port, _api, _ = fserver
    faults.install("scheduler.queue", "raise", times=1)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user", "content": "x"}],
                                 "stream": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 429
        assert resp.getheader("Content-Type") == "application/json"
        resp.read()
        conn.close()
    finally:
        faults.clear()


def test_http_nonstream_disconnect_cancels_request(fserver):
    """A non-streamed client that hangs up mid-generation must cancel its
    scheduler request (not generate to completion into a dead socket)."""
    import http.client

    port, api, _ = fserver
    before = api.scheduler.latency_summary()["completed"]
    # slow each chunk down so the huge budget cannot finish before we hang
    # up — the probe (4 Hz) must be what ends this request, not the budget
    faults.install("engine.decode", "delay", ms=50.0)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 4096, "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    time.sleep(0.2)  # the request is decoding its (huge) budget now
    conn.close()  # hang up without reading the response
    deadline = time.monotonic() + 10.0
    cancelled = None
    while time.monotonic() < deadline:
        with api.scheduler._metrics_lock:
            done = list(api.scheduler._completed)[before:]
        cancelled = next((r for r in done if r.finish_reason == "cancelled"), None)
        if cancelled is not None:
            break
        time.sleep(0.02)
    faults.clear()
    assert cancelled is not None, "disconnect did not cancel the request"
    assert cancelled.produced < 400  # nowhere near the (clamped) budget


def test_http_request_timeout_body_and_header(fserver):
    """`timeout_s` in the body (and the X-Request-Timeout header) ends a
    running completion with finish_reason="timeout" — a clean 200 with the
    deadline fields in `timings`, not an error."""
    import http.client

    from tests.test_serve import post

    port, _api, _ = fserver
    faults.install("engine.decode", "delay", ms=40.0)
    try:
        st, data = post(port, "/v1/chat/completions",
                        {"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4096, "temperature": 0.0,
                         "timeout_s": 0.4})
        assert st == 200
        out = json.loads(data)
        assert out["choices"][0]["finish_reason"] == "timeout"
        assert out["timings"]["timeout_s"] == 0.4
        assert out["timings"]["deadline_exceeded"] is True
        # header form (proxies set it without touching the JSON body)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user", "content": "x"}],
                                 "max_tokens": 4096, "temperature": 0.0}),
                     {"Content-Type": "application/json",
                      "X-Request-Timeout": "0.3"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert out["choices"][0]["finish_reason"] == "timeout"
    finally:
        faults.clear()
    # malformed timeout is a clean 400, stream or not
    st, data = post(port, "/v1/chat/completions",
                    {"messages": [{"role": "user", "content": "x"}],
                     "timeout_s": "soon"})
    assert st == 400 and b"timeout_s" in data


def test_http_debug_kv_default_paged_and_dense_marker(fserver):
    """GET /debug/kv against the serving DEFAULT — kv-layout auto resolves
    to paged since ISSUE 8 — returns the live pool stats plus a clean
    on-demand audit; the dense branch keeps its layout-marker contract
    (pool swapped out under try/finally, read per-request by the handler)."""
    port, api, _ = fserver
    st, body, _ = _get(port, "/debug/kv")
    assert st == 200 and body["layout"] == "paged"
    assert body["audit"]["ok"] is True and body["page_size"] >= 8
    assert body["pool"]["total"] > 0
    eng = api.scheduler.engine
    saved = eng.pool
    try:
        eng.pool = None
        st, body, _ = _get(port, "/debug/kv")
        assert st == 200 and body["layout"] == "dense" and body["audit"] is None
    finally:
        eng.pool = saved


def test_http_drain_503_and_inflight_completes(fserver):
    """graceful_drain over HTTP: in-flight finishes with 200, new requests
    get 503 + Retry-After, then the listener stops. Runs LAST against this
    server (it shuts it down)."""
    import http.client
    from concurrent.futures import ThreadPoolExecutor

    from dllama_tpu.serve.api import graceful_drain
    from tests.test_serve import post

    port, api, httpd = fserver
    # slow chunks: the in-flight request must span the whole drain window
    faults.install("engine.decode", "delay", ms=40.0)
    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(post, port, "/v1/chat/completions",
                        {"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 64, "temperature": 0.0})
        deadline = time.monotonic() + 5.0  # wait until it's really in flight
        while not api.scheduler._busy() and time.monotonic() < deadline:
            time.sleep(0.005)
        dt = threading.Thread(target=graceful_drain, args=(httpd, api, 30.0))
        dt.start()
        deadline = time.monotonic() + 2.0
        while not api.draining and time.monotonic() < deadline:
            time.sleep(0.005)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user", "content": "x"}]}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 503 and b"drain" in body
        assert resp.getheader("Retry-After") is not None
        conn.close()
        st, data = fut.result(timeout=30)
        assert st == 200
        out = json.loads(data)
        assert out["choices"][0]["finish_reason"] in ("stop", "length")
        dt.join(timeout=30)
        assert not dt.is_alive()


def test_http_crash_health_503(tmp_path):
    """Worker crash over HTTP: the in-flight completion gets a 500 (not a
    hang), /health flips to 503, and new completions get 503 too."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server
    from tests.test_serve import make_tiny_files, post

    mpath, tpath, _cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        faults.install("engine.decode", "raise", after=1)
        st, data = post(port, "/v1/chat/completions",
                        {"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 16, "temperature": 0.0})
        assert st == 500
        faults.clear()
        st_h, body, _ = _get(port, "/health")
        assert st_h == 503 and body["live"] is False
        assert body["crashed"] and "InjectedFault" in body["crashed"]
        st2, data2 = post(port, "/v1/chat/completions",
                          {"messages": [{"role": "user", "content": "x"}],
                           "max_tokens": 4})
        assert st2 == 503  # unhealthy scheduler sheds instead of hanging
    finally:
        faults.clear()
        api.scheduler.shutdown()
        httpd.shutdown()


# ------------------------------------------------------------------ loader


def test_loader_truncated_file_is_actionable(tmp_path):
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models.formats import ModelFileError
    from tests.test_serve import make_tiny_files

    mpath, tpath, _cfg = make_tiny_files(tmp_path)
    import os

    full = os.path.getsize(mpath)
    with open(mpath, "r+b") as f:
        f.truncate(full - 1024)
    with pytest.raises(ModelFileError) as ei:
        load_model(mpath, tpath, mesh=None)
    msg = str(ei.value)
    assert "truncated" in msg and mpath in msg
    assert f"{full:,}" in msg  # expected size is named
    assert "wcls" in msg or "layers." in msg or "final_norm" in msg


def test_loader_corrupt_magic_and_short_file(tmp_path):
    from dllama_tpu.models.formats import ModelFileError, read_header

    bad = tmp_path / "bad.m"
    bad.write_bytes(b"\x37\x13\x00\x00" + b"\x00" * 64)
    with pytest.raises(ModelFileError, match="magic"):
        read_header(str(bad))
    short = tmp_path / "short.m"
    short.write_bytes(b"\x01\x02\x03")
    with pytest.raises(ModelFileError, match="8-byte"):
        read_header(str(short))


def test_loader_oversized_file_is_detected(tmp_path):
    from dllama_tpu.models.formats import ModelFileError, read_header, iter_tensors
    from tests.test_serve import make_tiny_files

    mpath, _tpath, cfg = make_tiny_files(tmp_path)
    with open(mpath, "ab") as f:
        f.write(b"\x00" * 257)
    cfg2, header_size = read_header(mpath)
    with pytest.raises(ModelFileError, match="accounts for"):
        list(iter_tensors(mpath, cfg2, header_size))


def test_loader_fault_point(tmp_path):
    from dllama_tpu.models.formats import read_header
    from tests.test_serve import make_tiny_files

    mpath, _tpath, _cfg = make_tiny_files(tmp_path)
    faults.install("loader.read", "raise", times=1)
    with pytest.raises(faults.InjectedFault):
        read_header(mpath)
    cfg, _ = read_header(mpath)  # disarmed: loads fine
    assert cfg.dim == 64


# ------------------------------------------------------- cooperative abort


def test_engine_add_cooperative_abort():
    from dllama_tpu.engine.batch import AdmissionAborted, BatchEngine

    params = random_params(TINY, seed=2, dtype=jnp.float32, quantize=False)
    eng = BatchEngine(TINY, params, n_slots=2, cache_dtype=jnp.float32,
                      max_prefill_chunk=4)
    calls = {"n": 0}

    def abort_after_two():
        calls["n"] += 1
        return calls["n"] >= 2

    with pytest.raises(AdmissionAborted, match="slot 0"):
        eng.add(0, list(range(1, 31)), temperature=0.0, abort=abort_after_two)
    assert not eng.active[0]  # slot still admits fresh work
    first = eng.add(0, [1, 2, 3], temperature=0.0, seed=1)
    assert isinstance(first, int)


# ------------------------------------------------- warm restart (ISSUE 6)


def test_warm_restart_resumes_streams_bit_exact():
    """The ISSUE 6 crash drill: with --restart-max 2, a scheduler.loop crash
    mid-stream warm-restarts the engine in-process (no model reload, no
    external supervisor). The interrupted GREEDY stream resumes bit-exact
    against an uninterrupted reference run, the interrupted SAMPLED stream
    resumes bit-exact too (recorded PRNG key replay), a queued request
    survives untouched, and /health returns to live=true/ready=true."""
    from dllama_tpu.obs import metrics

    # uninterrupted references (separate scheduler, identical params/seeds)
    ref = make_sched(n_slots=2)
    try:
        rg = ref.submit([1, 2, 3, 4, 5], 0.0, 0.9, 24, frozenset(), seed=5)
        ref_greedy, exc = drain_tokens(rg, timeout=60.0)
        assert exc is None
        rs = ref.submit([7, 8, 9], 1.0, 0.9, 20, frozenset(), seed=11)
        ref_sampled, exc = drain_tokens(rs, timeout=60.0)
        assert exc is None
    finally:
        ref.shutdown()

    restarts0 = metrics.REGISTRY.sample("dllama_engine_restarts_total") or 0.0
    recov0 = metrics.REGISTRY.sample("dllama_requests_recovered_total") or 0.0
    sched = make_sched(n_slots=2, restart_max=2, restart_backoff_s=0.01)
    try:
        warm = sched.submit([9, 8, 7], 0.0, 0.9, 3, frozenset(), seed=0)
        assert drain_tokens(warm, timeout=60.0)[1] is None  # compile warm-up
        g = sched.submit([1, 2, 3, 4, 5], 0.0, 0.9, 24, frozenset(), seed=5)
        s = sched.submit([7, 8, 9], 1.0, 0.9, 20, frozenset(), seed=11)
        it = g.tokens()
        head = [next(it) for _ in range(4)]  # mid-stream before the crash
        # queued request: both slots busy, so it waits in the pending queue
        queued = sched.submit([4, 5, 6], 0.0, 0.9, 4, frozenset(), seed=3)
        faults.install("scheduler.loop", "raise", times=1)
        got_g = head + list(it)
        got_s, exc_s = drain_tokens(s, timeout=30.0)
        got_q, exc_q = drain_tokens(queued, timeout=30.0)
        assert got_g == ref_greedy, "resumed greedy stream must be bit-exact"
        assert exc_s is None and got_s == ref_sampled, \
            "resumed sampled stream must be bit-exact (PRNG key replay)"
        assert exc_q is None and len(got_q) == 4  # queued survived untouched
        h = sched.health()
        assert h["live"] is True and h["ready"] is True
        assert h["restarts"] == 1 and h["crashed"] is None
        restarts = metrics.REGISTRY.sample("dllama_engine_restarts_total")
        recovered = metrics.REGISTRY.sample("dllama_requests_recovered_total")
        assert restarts == restarts0 + 1
        assert recovered >= recov0 + 2  # both interrupted streams resumed
    finally:
        faults.clear()
        sched.shutdown()


def _crash_worker_until(sched, n, deadline_s=30.0):
    """Arm scheduler.loop:raise and wait until THIS scheduler has warm-
    restarted >= n times. The fault plan is process-global, so another live
    scheduler (e.g. a module fixture server's idle worker) can consume the
    armed raise first — re-arm until our worker's own counter moves."""
    faults.install("scheduler.loop", "raise", times=1)
    deadline = time.monotonic() + deadline_s
    while sched.health()["restarts"] < n:
        if not faults.pending("scheduler.loop"):
            faults.install("scheduler.loop", "raise", times=1)
        assert time.monotonic() < deadline, f"restart {n} never happened"
        time.sleep(0.01)


def test_second_warm_restart_still_bit_exact():
    """TWO crashes inside one sampled stream: the key replay must advance
    by the tokens emitted since the LAST resume only — after the first
    resume the slot's key is already advanced, so replaying the cumulative
    produced-1 would double-count the pre-first-crash tokens and the
    resumed stream would silently diverge."""
    ref = make_sched(n_slots=1)
    try:
        r = ref.submit([7, 8, 9], 1.0, 0.9, 48, frozenset(), seed=11)
        ref_toks, exc = drain_tokens(r, timeout=60.0)
        assert exc is None and len(ref_toks) == 48
    finally:
        ref.shutdown()

    # generous budget: a stolen-then-re-armed fault can cost an extra
    # restart or two; the budget must never exhaust mid-drill
    sched = make_sched(n_slots=1, restart_max=20, restart_backoff_s=0.01)
    try:
        warm = sched.submit([9, 8], 0.0, 0.9, 2, frozenset())
        assert drain_tokens(warm, timeout=60.0)[1] is None  # compile warm-up
        # slow chunks: both mid-stream crash windows need to stay open
        faults.install("engine.decode", "delay", ms=20.0)
        s = sched.submit([7, 8, 9], 1.0, 0.9, 48, frozenset(), seed=11)
        it = s.tokens()
        got = [next(it) for _ in range(4)]
        _crash_worker_until(sched, 1)
        got += [next(it) for _ in range(6)]  # resumed past crash 1
        _crash_worker_until(sched, 2)
        rest, exc = drain_tokens(s, timeout=60.0)
        assert exc is None
        assert got + rest == ref_toks, \
            "stream resumed across TWO restarts must stay bit-exact"
        assert sched.health()["restarts"] >= 2
    finally:
        faults.clear()
        sched.shutdown()


def test_restart_budget_exhausted_goes_permanently_unhealthy():
    """--restart-max 1 with two crashes inside the window: the first warm-
    restarts, the second exhausts the budget — PR 1 semantics return
    (in-flight requests fail fast, /health permanently unhealthy, submit
    refuses work)."""
    from dllama_tpu.serve.scheduler import SchedulerUnhealthy

    sched = make_sched(n_slots=1, restart_max=1, restart_backoff_s=0.01)
    try:
        warm = sched.submit([9, 8], 0.0, 0.9, 2, frozenset())
        assert drain_tokens(warm, timeout=60.0)[1] is None
        req = sched.submit([1, 2, 3], 0.0, 0.9, 50, frozenset(), seed=1)
        faults.install("scheduler.loop", "raise", times=2)
        _, exc = drain_tokens(req, timeout=10.0)
        assert isinstance(exc, faults.InjectedFault)
        assert req.finish_reason == "error"
        deadline = time.monotonic() + 5.0
        while sched.crashed is None and time.monotonic() < deadline:
            time.sleep(0.01)
        h = sched.health()
        assert h["live"] is False and h["restarts"] == 1
        with pytest.raises(SchedulerUnhealthy):
            sched.submit([1], 0.0, 0.9, 2, frozenset())
    finally:
        faults.clear()
        sched.shutdown()


def test_engine_restart_fault_kills_restart():
    """The engine.restart injection point: a restart that itself dies falls
    back to permanent-unhealthy (the restart path is drillable too)."""
    faults.install("scheduler.loop", "raise", times=1)
    faults.install("engine.restart", "raise", times=1)
    sched = make_sched(n_slots=1, restart_max=3, restart_backoff_s=0.01)
    try:
        deadline = time.monotonic() + 5.0
        while sched.crashed is None and time.monotonic() < deadline:
            time.sleep(0.01)
        h = sched.health()
        assert h["live"] is False
        assert h["crashed"] and "engine.restart" in h["crashed"]
    finally:
        faults.clear()
        sched.shutdown()


# --------------------------------------- NaN guard + per-request deadlines


def test_nan_guard_and_deadlines_one_scheduler():
    """decode.nan fails ONE request (finish_reason='error') while the engine
    stays live; a running request past its timeout_s finishes 'timeout' at a
    chunk boundary with deadline fields in timings(); an expired-in-queue
    request is shed before prefill (zero tokens, clean terminal finish)."""
    from dllama_tpu.obs import metrics

    sched = make_sched(n_slots=1)
    try:
        warm = sched.submit([9, 8], 0.0, 0.9, 2, frozenset())
        assert drain_tokens(warm, timeout=60.0)[1] is None

        # --- decode.nan: per-request failure, engine healthy
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 30, frozenset(), seed=1)
        it = r1.tokens()
        next(it)
        faults.install("decode.nan", "raise", times=1)
        _, exc1 = drain_tokens(r1, timeout=10.0)
        faults.clear()
        assert isinstance(exc1, RuntimeError) and "non-finite" in str(exc1)
        assert r1.finish_reason == "error"
        assert sched.health()["live"] is True

        # --- running request past its deadline: 'timeout' at chunk boundary
        fin_tmo0 = metrics.REGISTRY.sample(
            "dllama_requests_finished_total", {"reason": "timeout"}) or 0.0
        faults.install("engine.decode", "delay", ms=30.0)
        r2 = sched.submit([1, 2, 3], 0.0, 0.9, 10_000, frozenset(),
                          timeout_s=0.4)
        toks2, exc2 = drain_tokens(r2, timeout=15.0)
        assert exc2 is None and r2.finish_reason == "timeout" and toks2
        t = r2.timings()
        assert t["timeout_s"] == 0.4 and t["deadline_exceeded"] is True

        # --- expired in queue: shed before prefill (no tokens, no slot)
        shed_tmo0 = metrics.REGISTRY.sample(
            "dllama_requests_shed_total", {"reason": "timeout"}) or 0.0
        runner = sched.submit([1, 2, 3], 0.0, 0.9, 200, frozenset())
        queued = sched.submit([4, 5], 0.0, 0.9, 5, frozenset(),
                              timeout_s=0.2)
        toks_q, exc_q = drain_tokens(queued, timeout=15.0)
        assert exc_q is None and toks_q == []
        assert queued.finish_reason == "timeout" and queued.slot == -1
        # the shed must happen WHILE the slot is still busy (the saturated-
        # server case deadlines exist for), not after the runner finishes
        assert runner.finish_reason is None, \
            "queued deadline must fire while every slot is busy"
        sched.cancel(runner)
        drain_tokens(runner, timeout=15.0)
        faults.clear()
        fin_tmo = metrics.REGISTRY.sample(
            "dllama_requests_finished_total", {"reason": "timeout"})
        shed_tmo = metrics.REGISTRY.sample(
            "dllama_requests_shed_total", {"reason": "timeout"})
        assert fin_tmo >= fin_tmo0 + 2  # running + queued both counted
        assert shed_tmo == shed_tmo0 + 1  # only the queued one was shed
    finally:
        faults.clear()
        sched.shutdown()
