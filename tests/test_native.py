"""Native (C++) vs Python semantic-equivalence tests.

The contract of dllama_tpu/utils/native.py: every native component is
bit-identical to its numpy/Python fallback. Skipped when no C++ toolchain is
available (the library auto-builds via make on first use)."""

import numpy as np
import pytest

from dllama_tpu.ops.quant import quantize_q40_np, quantize_q80_np
from dllama_tpu.tokenizer.tokenizer import Tokenizer
from dllama_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.available(), reason="native lib unavailable")


@pytest.mark.parametrize("n", [32, 4096, 32 * 1000 + 32])
def test_quantize_q40_bit_identical(rng, n):
    x = (rng.standard_normal(n) * rng.uniform(0.01, 10)).astype(np.float32)
    # include exact-zero and constant blocks (delta==0 edge)
    x[:32] = 0.0
    got_p, got_s = native.quantize_q40(x)
    want_p, want_s = quantize_q40_np(x)
    np.testing.assert_array_equal(got_p, want_p)
    np.testing.assert_array_equal(got_s.view(np.uint16), want_s.view(np.uint16))


@pytest.mark.parametrize("n", [32, 4096])
def test_quantize_q80_bit_identical(rng, n):
    x = (rng.standard_normal(n) * 3).astype(np.float32)
    x[:32] = 0.0
    got_c, got_s = native.quantize_q80(x)
    want_c, want_s = quantize_q80_np(x)
    np.testing.assert_array_equal(got_c, want_c)
    np.testing.assert_array_equal(got_s.view(np.uint16), want_s.view(np.uint16))


def test_quantize_q40_subnormal_and_large_scales(rng):
    """f32->f16 rounding edges: tiny deltas (subnormal halves) and large ones."""
    x = np.concatenate([
        rng.standard_normal(32).astype(np.float32) * 1e-7,
        rng.standard_normal(32).astype(np.float32) * 1e4,
        rng.standard_normal(32).astype(np.float32) * 6e-5,
    ])
    got_p, got_s = native.quantize_q40(x)
    want_p, want_s = quantize_q40_np(x)
    np.testing.assert_array_equal(got_p, want_p)
    np.testing.assert_array_equal(got_s.view(np.uint16), want_s.view(np.uint16))


def _mk_tokenizer(native_on: bool) -> Tokenizer:
    vocab = [bytes([i]) for i in range(256)]
    extra = [b"he", b"ll", b"hell", b"hello", b" wo", b" world", b"ld"]
    scores = [-float(i) for i in range(256)] + [5.0, 4.0, 6.0, 9.0, 3.0, 8.0, 2.0]
    vocab += extra
    specials = [b"<s>", b"</s>", b"<|eot|>"]
    bos = len(vocab)
    vocab += specials
    scores += [0.0] * len(specials)
    t = Tokenizer(vocab, scores, bos, [bos + 1, bos + 2])
    if not native_on:
        t._native_tried = True  # force the pure-Python path
    return t


@pytest.mark.parametrize(
    "text",
    ["hello world", "hello <s>x</s> bye", "", "héllo ✨", "<|eot|>", "aaa<s>"],
)
@pytest.mark.parametrize("add_bos", [True, False])
@pytest.mark.parametrize("add_special", [True, False])
def test_bpe_encode_matches_python(text, add_bos, add_special):
    t_native = _mk_tokenizer(True)
    t_py = _mk_tokenizer(False)
    got = t_native.encode(text, add_bos=add_bos, add_special_tokens=add_special)
    want = t_py.encode(text, add_bos=add_bos, add_special_tokens=add_special)
    assert t_native._native is not None  # really exercised the C++ path
    assert got == want
    assert t_py.decode_all(got).replace("�", "") in (
        text if not add_special else text,
        text,
    ) or True  # decode sanity exercised; exact text checked in test_tokenizer


def test_bpe_encode_error_parity():
    # a vocab that cannot tokenize arbitrary bytes
    vocab = [b"a", b"b", b"<s>"]
    t = Tokenizer(vocab, [0.0, 0.0, 0.0], 2, [2])
    t2 = Tokenizer(vocab, [0.0, 0.0, 0.0], 2, [2])
    t2._native_tried = True
    with pytest.raises(ValueError, match="cannot tokenize"):
        t.encode("xyz")
    with pytest.raises(ValueError, match="cannot tokenize"):
        t2.encode("xyz")


def test_native_write_tensor_roundtrip(tmp_path, rng):
    """write_tensor (native quantize) must produce bytes the Q40 reader maps
    back onto the same grid as the numpy path."""
    import io

    from dllama_tpu.models.formats import write_tensor
    from dllama_tpu.ops.quant import FloatType

    x = rng.standard_normal((64, 96)).astype(np.float32)
    buf_native = io.BytesIO()
    write_tensor(buf_native, x, FloatType.Q40)
    import dllama_tpu.utils.native as nat

    old = nat._lib, nat._tried
    nat._lib, nat._tried = None, True  # force numpy path
    try:
        buf_np = io.BytesIO()
        write_tensor(buf_np, x, FloatType.Q40)
    finally:
        nat._lib, nat._tried = old
    assert buf_native.getvalue() == buf_np.getvalue()


def test_native_q40_shard_matches_numpy():
    """C++ shard decoder == the numpy LazyQ40 path, incl. f16->f32 scale
    widening, on full and partial (row+block) slices."""
    import numpy as np
    import pytest

    from dllama_tpu.models.formats import LazyQ40
    from dllama_tpu.utils import native

    if not native.has_q40_shard():
        pytest.skip("native q40_shard unavailable")
    rng = np.random.default_rng(3)
    n_out, k_in = 96, 256
    nb = k_in // 32
    raw = rng.integers(0, 256, n_out * nb * 18, dtype=np.uint8)
    # plant edge-case scale bit patterns: zero, subnormal, large
    rec = raw.reshape(n_out, nb, 18)
    rec[0, 0, :2] = [0x00, 0x00]
    rec[1, 0, :2] = [0x01, 0x00]  # smallest subnormal
    rec[2, 0, :2] = [0xFF, 0x7B]  # f16 max
    lazy = LazyQ40(raw, n_out, k_in)

    for k2_sl, n_sl in [
        (slice(None), slice(None)),
        (slice(0, 64), slice(32, 96)),
        (slice(64, 128), slice(0, 48)),
    ]:
        kb_sl = slice((k2_sl.start or 0) // 16,
                      None if k2_sl.stop is None else k2_sl.stop // 16)
        got_p = lazy.packed_shard(k2_sl, n_sl)
        got_s = lazy.scales_shard(kb_sl, n_sl)
        old = native._lib, native._tried
        try:
            native._lib, native._tried = None, True  # force python path
            want_p = lazy.packed_shard(k2_sl, n_sl)
            want_s = lazy.scales_shard(kb_sl, n_sl)
        finally:
            native._lib, native._tried = old
        np.testing.assert_array_equal(got_p, want_p)
        np.testing.assert_array_equal(got_s, want_s)
