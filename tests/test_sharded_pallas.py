"""TP-sharded engines with the fused Pallas kernels (interpret mode on CPU)
must match the single-device XLA engine — proof that tensor parallelism keeps
the fused Q40/flash kernels instead of falling back or gathering weights
(the reference capability at stake: the whole TP decomposition,
llm.cpp:133-141 + nn-network.cpp:521-554).

kernels='pallas' on a mesh routes every matmul through
parallel/sharding.pallas_mms (shard_map over 'tp': local kernel + psum for
wo/w2) and attention through pallas_attn (head-sharded flash). Off-TPU the
kernels run in interpret mode — same code path as the real chip minus Mosaic.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
from dllama_tpu.parallel.sharding import LlamaShardings

# sized so the per-shard shapes stay tileable at tp=4 (n_local % 128 == 0 for
# wq/w1/wcls; wk/wv shards fall back to XLA inside the shard_map — also a
# correctness path worth covering)
CFG = LlamaConfig(dim=512, hidden_dim=1024, n_layers=2, n_heads=8, n_kv_heads=4,
                  vocab_size=512, seq_len=128)


@pytest.fixture(scope="module")
def params():
    return random_params(CFG, seed=7, dtype=jnp.float32, quantize=True)


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(tp=4), MeshConfig(dp=2, tp=2)])
def test_tp_pallas_matches_single_device(params, mesh_cfg):
    prompt = np.arange(1, 33, dtype=np.int32)[None]  # 32 tokens: deq-style path

    ref = InferenceEngine(CFG, params, cache_dtype=jnp.float32, kernels="xla",
                          attn_impl="jnp")
    ref_logits = np.asarray(ref.prefill(prompt))

    mesh = make_mesh(mesh_cfg)
    sh = LlamaShardings(mesh, CFG)
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, shardings=sh,
                          kernels="pallas")
    assert eng.backend == "pallas"  # the fused path, not a fallback
    got = np.asarray(eng.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=3e-3, rtol=3e-3)

    # decode steps exercise the blockdot (m<=16) kernel + head-sharded flash
    for tok in (11, 42):
        ref_l = np.asarray(ref.decode_step(np.array([[tok]])))
        got_l = np.asarray(eng.decode_step(np.array([[tok]])))
        np.testing.assert_allclose(got_l, ref_l, atol=3e-3, rtol=3e-3)


def test_tp_pallas_batch_engine_matches(params):
    """The serving tier on a tp mesh with fused kernels: same continuation as
    the unsharded XLA BatchEngine (per-slot seeds make this deterministic)."""
    from dllama_tpu.engine.batch import BatchEngine

    mesh = make_mesh(MeshConfig(tp=4))
    sh = LlamaShardings(mesh, CFG)
    prompt = list(range(1, 9))

    def run(shardings, kernels):
        eng = BatchEngine(CFG, params, n_slots=2, cache_dtype=jnp.float32,
                          shardings=shardings, kernels=kernels)
        first = eng.add(0, prompt, temperature=0.0, seed=123)
        toks = eng.decode(4)
        return [first] + [int(t) for t in toks[:, 0]]

    assert run(None, "xla") == run(sh, "pallas")
