"""Telemetry-core tests: registry semantics, Prometheus text-format grammar
(HELP/TYPE lines, label escaping, histogram _bucket/_sum/_count invariants),
request-id propagation into headers/bodies/logs, and counters moving across
real request lifecycles — admit -> stream -> finish, shed (queue-full via
DLLAMA_FAULTS, draining), and the fault-crash path. All CPU-only against the
tiny fixture model; the HTTP server is module-scoped (load_model dominates)
and the crash drill runs LAST in this file because it kills its worker
(tier-1 runs files in order: -p no:randomly)."""

import http.client
import json
import logging
import re
import threading
import time

import pytest

from dllama_tpu.obs import metrics, new_request_id
from dllama_tpu.obs import instruments as ins
from dllama_tpu.utils import faults

REG = metrics.REGISTRY


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def val(name, labels=None) -> float:
    """Current value of a series, 0.0 when never touched (delta baselines)."""
    v = REG.sample(name, labels)
    if v is None:
        return 0.0
    return v["count"] if isinstance(v, dict) else v


# ------------------------------------------------------- exposition grammar

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r'\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*",?)*\}'
_VALUE = r"(?:-?\d+(?:\.\d+)?(?:e[+-]?\d+)?|\+Inf|-Inf|NaN)"
SAMPLE_RE = re.compile(rf"^({_NAME})({_LABELS})? ({_VALUE})$")


def parse_exposition(text: str):
    """Line-by-line grammar check. Returns (families: name->kind,
    samples: (name, labelstr)->value). Any line fitting neither the comment
    nor the sample grammar is an AssertionError — the scraper's contract."""
    assert text.endswith("\n")
    families, samples = {}, {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            assert re.match(rf"^# HELP {_NAME} \S.*$", line), line
        elif line.startswith("# TYPE "):
            m = re.match(rf"^# TYPE ({_NAME}) (counter|gauge|histogram)$", line)
            assert m, line
            families[m.group(1)] = m.group(2)
        else:
            m = SAMPLE_RE.match(line)
            assert m, f"bad sample line: {line!r}"
            v = m.group(3)
            samples[(m.group(1), m.group(2) or "")] = float(
                v.replace("Inf", "inf"))
    return families, samples


def check_histogram(samples: dict, name: str) -> None:
    """The _bucket/_sum/_count invariants for every label set of `name`:
    cumulative non-decreasing buckets, an le="+Inf" bucket equal to _count,
    and a _sum sample present."""
    by_labels: dict[str, list[tuple[float, float]]] = {}
    for (n, lbl), v in samples.items():
        if n != name + "_bucket":
            continue
        m = re.search(r'le="([^"]+)"', lbl)
        assert m, lbl
        base = re.sub(r',?le="[^"]+"', "", lbl).replace("{}", "")
        by_labels.setdefault(base, []).append(
            (float(m.group(1).replace("Inf", "inf")), v))
    assert by_labels, f"no buckets rendered for {name}"
    for base, buckets in by_labels.items():
        buckets.sort()
        counts = [c for _, c in buckets]
        assert counts == sorted(counts), f"{name}{base}: non-monotone buckets"
        assert buckets[-1][0] == float("inf"), f"{name}{base}: no +Inf bucket"
        count = samples[(name + "_count", base)]
        assert buckets[-1][1] == count, f"{name}{base}: +Inf != _count"
        assert (name + "_sum", base) in samples


# ----------------------------------------------------------- registry unit


def test_counter_gauge_basics():
    reg = metrics.Registry()
    c = reg.counter("t_requests_total", "help", ("reason",))
    c.labels(reason="a").inc()
    c.labels(reason="a").inc(2)
    c.labels(reason="b").inc()
    assert reg.sample("t_requests_total", {"reason": "a"}) == 3
    assert reg.sample("t_requests_total", {"reason": "b"}) == 1
    with pytest.raises(ValueError):
        c.labels(reason="a").inc(-1)  # counters only go up
    g = reg.gauge("t_depth", "help")
    g.set(7)
    g.inc()
    g.dec(3)
    assert reg.sample("t_depth") == 5
    # idempotent re-registration returns the same family; kind conflicts fail
    assert reg.counter("t_requests_total", "help", ("reason",)) is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("t_requests_total", "help", ("reason",))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("t_requests_total", "help", ("other",))


def test_histogram_buckets_and_render_invariants():
    reg = metrics.Registry()
    h = reg.histogram("t_lat_seconds", "help", ("op",), buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 5.0):  # 0.01 lands IN the 0.01 bucket
        h.labels(op="x").observe(v)
    families, samples = parse_exposition(reg.render())
    assert families["t_lat_seconds"] == "histogram"
    assert samples[("t_lat_seconds_bucket", '{op="x",le="0.01"}')] == 2
    assert samples[("t_lat_seconds_bucket", '{op="x",le="0.1"}')] == 3
    assert samples[("t_lat_seconds_bucket", '{op="x",le="1"}')] == 4
    assert samples[("t_lat_seconds_bucket", '{op="x",le="+Inf"}')] == 5
    assert samples[("t_lat_seconds_count", '{op="x"}')] == 5
    assert samples[("t_lat_seconds_sum", '{op="x"}')] == pytest.approx(5.565)
    check_histogram(samples, "t_lat_seconds")


def test_label_escaping():
    reg = metrics.Registry()
    c = reg.counter("t_esc_total", "multi\nline \\ help", ("what",))
    c.labels(what='we"ird\\val\nue').inc()
    text = reg.render()
    assert '# HELP t_esc_total multi\\nline \\\\ help' in text
    assert 't_esc_total{what="we\\"ird\\\\val\\nue"} 1' in text
    parse_exposition(text)  # escaped line still fits the sample grammar


def test_request_id_minting():
    a, b = new_request_id(), new_request_id()
    assert a.startswith("req_") and b.startswith("req_") and a != b
    # well-formed client ids are adopted verbatim; junk is replaced
    assert new_request_id("trace-41.a_b") == "trace-41.a_b"
    assert new_request_id("bad id\n!").startswith("req_")
    assert new_request_id("x" * 200).startswith("req_")


def test_token_timer_throughput_is_total_time_based():
    from dllama_tpu.utils.profiling import TokenTimer

    t = TokenTimer()
    t.ms.extend([100.0, 300.0])  # mean 200ms -> old (wrong) formula said 5.0
    # ... which coincides here; make the asymmetry explicit instead:
    t.ms.append(200.0)  # total 600ms over 3 tokens -> 5.0 tok/s
    assert "5.0 tok/s" in t.summary() and "3 tokens" in t.summary()
    one = TokenTimer()
    one.ms.append(250.0)  # guard: a single token must not crash percentiles
    assert "1 tokens" in one.summary() and "4.0 tok/s" in one.summary()
    assert TokenTimer().summary() == "no tokens timed"
    zero = TokenTimer()
    zero.ms.extend([0.0, 0.0])  # degenerate clock: no division by zero
    assert "0.0 tok/s" in zero.summary()
    # stop() folds the sample onto the registry (one source of truth)
    before = val("dllama_token_latency_seconds")
    rec = TokenTimer()
    rec.start()
    rec.stop()
    assert val("dllama_token_latency_seconds") == before + 1


def test_json_and_text_log_formatters():
    from dllama_tpu.utils.logs import JsonFormatter, TextFormatter

    rec = logging.LogRecord("dllama_tpu.serve", logging.INFO, __file__, 1,
                            "hello %s", ("world",), None)
    rec.request_id = "req_abc"
    out = json.loads(JsonFormatter().format(rec))
    assert out["msg"] == "hello world" and out["request_id"] == "req_abc"
    assert out["level"] == "INFO" and out["logger"] == "dllama_tpu.serve"
    assert re.match(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z$", out["ts"])
    assert "request_id=req_abc" in TextFormatter("%(message)s").format(rec)


# ------------------------------------------------------- HTTP end-to-end


@pytest.fixture(scope="module")
def mserver(tmp_path_factory):
    """Continuous-batching server for telemetry drills (module-scoped:
    load_model dominates). Warm-up completion compiles every step shape so
    the timed tests below measure telemetry, not XLA."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server
    from tests.test_serve import make_tiny_files, post

    tmp_path = tmp_path_factory.mktemp("mserve")
    mpath, tpath, _cfg = make_tiny_files(tmp_path)
    loaded = load_model(mpath, tpath, mesh=None)
    httpd, api = make_server(loaded, host="127.0.0.1", port=0, n_slots=2,
                             max_queue=4,
                             # loose SLO targets (CPU box): the /debug/perf
                             # and postmortem-slo drills below want armed,
                             # attainable targets — not real latency bars
                             slo_ttft_ms=120_000.0, slo_itl_ms=120_000.0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    st, _ = post(httpd.server_address[1], "/v1/chat/completions",
                 {"messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 6, "temperature": 0.0})
    assert st == 200
    yield httpd.server_address[1], api, httpd
    api.scheduler.shutdown()
    httpd.shutdown()


def _get_raw(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, headers


def _post_raw(port, path, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    conn.request("POST", path, json.dumps(body), h)
    resp = conn.getresponse()
    data = resp.read()
    rheaders = dict(resp.getheaders())
    conn.close()
    return resp.status, data, rheaders


def test_metrics_endpoint_serves_valid_exposition(mserver):
    port, _api, _ = mserver
    st, data, headers = _get_raw(port, "/metrics")
    assert st == 200
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    families, samples = parse_exposition(data.decode())
    for name, kind in [
        ("dllama_requests_admitted_total", "counter"),
        ("dllama_requests_finished_total", "counter"),
        ("dllama_tokens_generated_total", "counter"),
        ("dllama_queue_depth", "gauge"),
        ("dllama_busy_slots", "gauge"),
        ("dllama_slots_total", "gauge"),
        ("dllama_model_params_bytes", "gauge"),
        ("dllama_kv_cache_bytes", "gauge"),
        ("dllama_ttft_seconds", "histogram"),
        ("dllama_itl_seconds", "histogram"),
        ("dllama_decode_chunk_seconds", "histogram"),
        ("dllama_prefill_chunk_seconds", "histogram"),
    ]:
        assert families.get(name) == kind, f"{name} missing or mistyped"
    # the warm-up completion already ran: histograms carry real samples
    for h in ("dllama_ttft_seconds", "dllama_decode_chunk_seconds",
              "dllama_prefill_chunk_seconds", "dllama_e2e_latency_seconds",
              "dllama_batch_occupancy"):
        check_histogram(samples, h)
    assert samples[("dllama_slots_total", "")] == 2


def test_request_lifecycle_moves_counters(mserver):
    from tests.test_serve import post

    port, _api, _ = mserver
    before = {
        "admitted": val("dllama_requests_admitted_total"),
        "stop": val("dllama_requests_finished_total", {"reason": "stop"}),
        "length": val("dllama_requests_finished_total", {"reason": "length"}),
        "tokens": val("dllama_tokens_generated_total"),
        "ttft": val("dllama_ttft_seconds"),
        "e2e": val("dllama_e2e_latency_seconds"),
        "http": val("dllama_http_responses_total",
                    {"endpoint": "/v1/chat/completions", "code": "200"}),
    }
    st, data = post(port, "/v1/chat/completions",
                    {"messages": [{"role": "user", "content": "count me"}],
                     "max_tokens": 8, "temperature": 0.0})
    assert st == 200
    done = json.loads(data)["usage"]["completion_tokens"]
    assert val("dllama_requests_admitted_total") == before["admitted"] + 1
    finished = (val("dllama_requests_finished_total", {"reason": "stop"})
                + val("dllama_requests_finished_total", {"reason": "length"}))
    assert finished == before["stop"] + before["length"] + 1
    assert val("dllama_tokens_generated_total") >= before["tokens"] + done
    assert val("dllama_ttft_seconds") == before["ttft"] + 1
    assert val("dllama_e2e_latency_seconds") == before["e2e"] + 1
    assert val("dllama_http_responses_total",
               {"endpoint": "/v1/chat/completions", "code": "200"}) == before["http"] + 1


def test_queue_full_shed_counts_and_correlates(mserver, monkeypatch, caplog):
    """The DLLAMA_FAULTS-armed shed path: 429 carries the would-have-been
    X-Request-Id, the shed counter moves by reason, and the shed log line
    carries the same id (structured field + message text)."""
    port, _api, _ = mserver
    monkeypatch.setenv(faults.ENV_VAR, "scheduler.queue:raise:times=1")
    faults.configure_from_env()
    before = val("dllama_requests_shed_total", {"reason": "queue_full"})
    before_fires = val("dllama_fault_fires_total",
                       {"point": "scheduler.queue", "action": "raise"})
    with caplog.at_level(logging.WARNING, logger="dllama_tpu.serve"):
        st, data, headers = _post_raw(
            port, "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}], "max_tokens": 4})
    assert st == 429
    rid = headers.get("X-Request-Id")
    assert rid and rid.startswith("req_")
    assert json.loads(data)["error"]["request_id"] == rid
    assert val("dllama_requests_shed_total", {"reason": "queue_full"}) == before + 1
    assert val("dllama_fault_fires_total",
               {"point": "scheduler.queue", "action": "raise"}) == before_fires + 1
    shed_logs = [r for r in caplog.records
                 if getattr(r, "request_id", None) == rid]
    assert shed_logs and "shed" in shed_logs[0].getMessage()


def test_draining_shed_counts_by_reason(mserver):
    port, api, _ = mserver
    before = val("dllama_requests_shed_total", {"reason": "draining"})
    api.draining = True
    try:
        st, data, headers = _post_raw(
            port, "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}], "max_tokens": 2})
    finally:
        api.draining = False
    assert st == 503
    assert headers.get("X-Request-Id", "").startswith("req_")
    assert val("dllama_requests_shed_total", {"reason": "draining"}) == before + 1


def test_request_id_propagation_and_logs(mserver, caplog):
    from tests.test_serve import post

    port, _api, _ = mserver
    # server-minted id: header + response JSON + completion log line agree
    with caplog.at_level(logging.INFO, logger="dllama_tpu.serve"):
        st, data, headers = _post_raw(
            port, "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 4, "temperature": 0.0})
    assert st == 200
    rid = headers["X-Request-Id"]
    assert rid.startswith("req_")
    assert json.loads(data)["request_id"] == rid
    assert any(getattr(r, "request_id", None) == rid for r in caplog.records)
    # client-supplied well-formed id is adopted verbatim
    st2, data2, headers2 = _post_raw(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 2},
        headers={"X-Request-Id": "trace-77.abc"})
    assert st2 == 200 and headers2["X-Request-Id"] == "trace-77.abc"
    assert json.loads(data2)["request_id"] == "trace-77.abc"
    # 400s carry an id too
    st3, data3, headers3 = _post_raw(port, "/v1/chat/completions",
                                     {"messages": []})
    assert st3 == 400 and headers3.get("X-Request-Id", "").startswith("req_")
    assert json.loads(data3)["error"]["request_id"] == headers3["X-Request-Id"]


def test_stream_carries_request_id(mserver):
    port, _api, _ = mserver
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 4, "temperature": 0.0,
                             "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    rid = resp.getheader("X-Request-Id")
    conn.close()
    assert resp.status == 200 and rid and rid.startswith("req_")
    assert "data: [DONE]" in raw


def test_health_and_metrics_expose_memory_gauges(mserver):
    port, api, _ = mserver
    st, data, _ = _get_raw(port, "/health")
    body = json.loads(data)
    assert body["model_params_bytes"] > 0
    assert body["kv_cache_bytes"] > 0
    assert val("dllama_model_params_bytes") == body["model_params_bytes"]
    assert val("dllama_kv_cache_bytes") == body["kv_cache_bytes"]
    assert body["model_params_bytes"] == api.model_params_bytes


def test_metrics_scrape_concurrent_with_generation(mserver):
    """/metrics must answer (and parse) while a completion is decoding —
    the scrape path shares no lock with the worker."""
    from tests.test_serve import post

    port, api, _ = mserver
    faults.install("engine.decode", "delay", ms=30.0)
    results = {}

    def run():
        results["resp"] = post(
            port, "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "busy"}],
             "max_tokens": 24, "temperature": 0.0})

    t = threading.Thread(target=run)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while not api.scheduler._busy() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert api.scheduler._busy(), "completion never started"
        for _ in range(3):  # repeated scrapes while tokens are flowing
            st, data, _ = _get_raw(port, "/metrics")
            assert st == 200
            parse_exposition(data.decode())
    finally:
        faults.clear()
        t.join(timeout=60)
    assert results["resp"][0] == 200


def test_build_info_gauge_and_health_build(mserver):
    """dllama_tpu_build_info: value 1, labels carry version/jax/backend/
    overlap; the same payload rides /health as the `build` object. The
    registry is process-global, so an earlier test's single-tier server may
    have registered an overlap="n/a" series too — match THIS server's
    labelset (from /health) rather than whichever series scrapes first."""
    port, _api, _ = mserver
    st, data, _ = _get_raw(port, "/health")
    assert st == 200
    build = json.loads(data)["build"]
    assert build["overlap"] == "on"  # mserver runs the default pipeline
    assert build["backend"] == "cpu" and build["version"] and build["jax"]
    st, data, _ = _get_raw(port, "/metrics")
    assert st == 200
    found = None
    for m in re.finditer(r'^dllama_tpu_build_info\{([^}]*)\} 1$',
                         data.decode(), re.M):
        labels = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
        if labels == build:
            found = labels
    assert found == build, "no build_info series matches /health build"


def test_timings_object_and_flight_recorder(mserver):
    """Non-stream responses carry a span-sourced `timings` object; the same
    request is replayable from GET /debug/requests/{req_id} with prefill
    and per-chunk detail (the flight recorder)."""
    port, _api, _ = mserver
    st, data, _ = _post_raw(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hello there"}],
         "max_tokens": 9, "temperature": 0.0})
    assert st == 200
    body = json.loads(data)
    rid = body["request_id"]
    t = body["timings"]
    # `replica` rides along since ISSUE 15: every response is
    # attributable end to end through the router
    assert set(t) == {"queue_wait_ms", "ttft_ms", "e2e_ms",
                      "decode_tokens", "replica"}
    assert t["decode_tokens"] == body["usage"]["completion_tokens"]
    assert t["e2e_ms"] >= t["ttft_ms"] >= t["queue_wait_ms"] >= 0

    st, data, _ = _get_raw(port, f"/debug/requests/{rid}")
    assert st == 200
    rec = json.loads(data)
    assert rec["state"] == "finished"
    assert rec["finish_reason"] in ("stop", "length")
    assert rec["prompt_tokens"] > 0
    assert rec["prefill"]["tokens"] == rec["prompt_tokens"]
    assert len(rec["chunks"]) >= 1  # at least one fused decode chunk
    assert sum(c["tokens"] for c in rec["chunks"]) >= t["decode_tokens"] - 1
    assert rec["ttft_ms"] == pytest.approx(t["ttft_ms"], abs=1.0)

    st, data, _ = _get_raw(port, "/debug/requests")
    ids = [r["req_id"] for r in json.loads(data)["requests"]]
    assert rid in ids

    st, data, _ = _get_raw(port, "/debug/requests/req_nonexistent")
    assert st == 404


def test_stream_final_event_carries_timings(mserver):
    """The last SSE data event (finish_reason set) carries the same
    `timings` object non-stream responses embed."""
    port, _api, _ = mserver
    st, data, _ = _post_raw(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}],
         "max_tokens": 6, "temperature": 0.0, "stream": True})
    assert st == 200
    payloads = [json.loads(line[len("data: "):])
                for line in data.decode().splitlines()
                if line.startswith("data: ") and line != "data: [DONE]"]
    final = [p for p in payloads
             if p.get("choices") and p["choices"][0].get("finish_reason")]
    assert final, "no finish event in the stream"
    t = final[-1]["timings"]
    # `replica` rides along since ISSUE 15: every response is
    # attributable end to end through the router
    assert set(t) == {"queue_wait_ms", "ttft_ms", "e2e_ms",
                      "decode_tokens", "replica"}
    assert t["decode_tokens"] >= 1


def test_debug_trace_exports_chrome_json_and_skips_admission_counters(mserver):
    """/debug/trace is loadable Chrome trace JSON whose decode spans expose
    the pipeline; /debug/* GETs never move the request-admission counters
    (they are observability reads, not requests)."""
    port, _api, _ = mserver
    # a fresh completion guarantees recent decode spans in the ring
    st, _, _ = _post_raw(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}],
         "max_tokens": 8, "temperature": 0.0})
    assert st == 200
    admitted = val("dllama_requests_admitted_total")
    st, data, _ = _get_raw(port, "/debug/trace")
    assert st == 200
    doc = json.loads(data)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    assert {"decode.dispatch", "decode.consume", "decode.device",
            "prefill.chunk", "queue.wait", "request"} <= names
    # non-decreasing ts per track (the Perfetto-load contract)
    by_tid = {}
    for e in doc["traceEvents"]:
        if e.get("ph") in ("X", "i"):
            by_tid.setdefault(e["tid"], []).append(e["ts"])
    for tid, ts in by_tid.items():
        assert ts == sorted(ts)
    st, _, _ = _get_raw(port, "/debug/requests")
    assert st == 200
    assert val("dllama_requests_admitted_total") == admitted
    # the responses themselves ARE counted (http observability keeps working)
    assert val("dllama_http_responses_total",
               {"endpoint": "/debug/trace", "code": "200"}) >= 1


def test_debug_profile_starts_and_conflicts_409(mserver, tmp_path, monkeypatch):
    """POST /debug/profile starts a duration-capped capture; a second POST
    while one runs is 409 + Retry-After; the slot frees after the timer.
    The jax profiler itself is stubbed — the HTTP/session contract is what
    this test pins (the real capture is exercised by the E2E smoke)."""
    from dllama_tpu.utils import profiling

    monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                        lambda log_dir: None)
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace", lambda: None)
    port, _api, _ = mserver
    st, data, _ = _post_raw(port, "/debug/profile",
                            {"duration_s": 0.3, "dir": str(tmp_path / "p")})
    assert st == 200
    info = json.loads(data)["profiling"]
    assert info["duration_s"] == pytest.approx(0.3)
    assert info["dir"] == str(tmp_path / "p")
    st, data, headers = _post_raw(port, "/debug/profile", {"duration_s": 0.3})
    assert st == 409
    assert "Retry-After" in headers
    assert "already running" in json.loads(data)["error"]["message"]
    deadline = time.time() + 10
    while profiling.profile_status()["active"] and time.time() < deadline:
        time.sleep(0.02)
    assert not profiling.profile_status()["active"]
    # the session is reusable once the timer released it
    st, data, _ = _post_raw(port, "/debug/profile",
                            {"duration_s": 0.05, "dir": str(tmp_path / "p2")})
    assert st == 200
    deadline = time.time() + 10
    while profiling.profile_status()["active"] and time.time() < deadline:
        time.sleep(0.02)
    # malformed duration is a client error, not a wedged session
    st, data, _ = _post_raw(port, "/debug/profile", {"duration_s": "soon"})
    assert st == 400


def test_debug_perf_joins_windows_ledger_roofline(mserver):
    """GET /debug/perf (ISSUE 7): after at least one served request the
    join must show a populated TTFT window with p50/p95/p99, a ledger whose
    per-state seconds partition loop wall time (within 2%), a priced
    roofline view for the decode path, SLO accounting against the armed
    targets, and the process self-metrics — one JSON document, no tracer
    dependency."""
    port, _api, _ = mserver
    st, data, _ = _post_raw(port, "/v1/chat/completions",
                            {"messages": [{"role": "user", "content": "perf"}],
                             "max_tokens": 6, "temperature": 0.0})
    assert st == 200
    st, data, _ = _get_raw(port, "/debug/perf")
    assert st == 200
    doc = json.loads(data)
    assert doc["mode"] == "continuous"
    win = doc["window"]["ttft"]
    assert win["count"] >= 1
    assert win["p50"] is not None and win["p95"] is not None
    assert win["p99"] >= win["p50"] > 0
    led = doc["ledger"]
    assert led["wall_s"] > 0
    assert abs(led["covered_s"] - led["wall_s"]) / led["wall_s"] <= 0.02
    from dllama_tpu.obs import perf as _perf

    # the catalog is the definition site (scripts/checks.sh pins it to the
    # README table); this endpoint must expose exactly those states
    assert set(led["fractions"]) == set(_perf.LEDGER_STATES)
    assert led["seconds"]["decode_wait"] > 0  # decode actually ran
    roof = doc["roofline"]
    assert roof["priced"] and roof["window_chunks"] > 0
    assert roof["bandwidth_attainment"] is not None
    assert roof["throughput_tok_s"] >= roof["goodput_tok_s"] >= 0
    slo = doc["slo"]
    assert slo["enabled"] and slo["targets"]["ttft_ms"] == 120_000.0
    assert slo["attainment"] == 1.0  # targets are 2 minutes on purpose
    proc = doc["process"]
    assert proc["uptime_s"] > 0 and proc["threads"] >= 2
    # the same views land on /metrics as gauges at scrape time
    st, text, _ = _get_raw(port, "/metrics")
    fams, samples = parse_exposition(text.decode())
    assert samples[("dllama_latency_window_seconds",
                    '{metric="ttft",quantile="p50"}')] > 0
    assert ("dllama_scheduler_time_seconds_total",
            '{state="decode_wait"}') in samples
    assert samples[("dllama_slo_attainment", "")] == 1.0
    assert samples[("dllama_process_uptime_seconds", "")] > 0
    assert samples[("dllama_process_rss_bytes", "")] > 0


def test_health_carries_process_self_metrics(mserver):
    port, _api, _ = mserver
    st, data, _ = _get_raw(port, "/health")
    assert st == 200
    proc = json.loads(data)["process"]
    assert proc["uptime_s"] > 0
    assert proc["rss_bytes"] > 0
    assert proc["threads"] >= 2  # worker + this handler at minimum


def test_debug_compile_ledger_transfers_and_health_object(mserver):
    """GET /debug/compile (ISSUE 13): after served traffic the document
    carries the jit ledger (per-fn totals + entries with shape sigs),
    shape-bucket contract coverage, transfer tallies (boundary uploads +
    per-chunk downloads), and live device memory; /health answers the
    compile object (recompile storms visible without a scrape) and the
    dllama_jit_* / dllama_transfer* series render on /metrics."""
    port, _api, _ = mserver
    st, data, _ = _post_raw(port, "/v1/chat/completions",
                            {"messages": [{"role": "user", "content": "jit"}],
                             "max_tokens": 6, "temperature": 0.0})
    assert st == 200
    st, data, _ = _get_raw(port, "/debug/compile")
    assert st == 200
    doc = json.loads(data)
    tot = doc["totals"]
    # the serving flow really billed its dispatch sites
    assert tot["prefill_chunk"]["compiles"] >= 1
    assert tot["decode"]["compiles"] >= 1
    assert tot["commit"]["compiles"] >= 1
    assert doc["unexpected"] == 0
    assert any(e["fn"] == "decode" and e["sig"] for e in doc["entries"])
    cov = doc["contract"]["fns"]
    assert "decode" in cov and cov["decode"]["unexpected_seen"] == []
    tr = doc["transfers"]
    assert tr["sites"]["h2d.prefill"]["bytes"] > 0  # admission uploads
    assert tr["sites"]["d2h.decode_tokens"]["bytes"] > 0  # token fetches
    assert doc["device_memory"]["buffers"] > 0
    assert doc["warmup"] is None  # mserver boots --warmup off
    # /health: the compile object rides the probe
    st, data, _ = _get_raw(port, "/health")
    h = json.loads(data)
    assert h["compile"]["unexpected_compiles"] == 0
    assert h["compile"]["compiles"] >= 1
    assert h["compile"]["warmup"] == "off"
    assert h["build"]["warmup"] == "off"
    # ... and /debug/perf folds the summary
    st, data, _ = _get_raw(port, "/debug/perf")
    assert json.loads(data)["compile"]["unexpected"] == 0
    # the series render in the exposition
    st, text, _ = _get_raw(port, "/metrics")
    fams, samples = parse_exposition(text.decode())
    assert fams["dllama_jit_compiles_total"] == "counter"
    assert fams["dllama_jit_unexpected_compiles_total"] == "counter"
    assert samples[("dllama_jit_compiles_total", '{fn="decode"}')] >= 1
    assert samples[("dllama_transfer_bytes_total",
                    '{direction="d2h",site="decode_tokens"}')] > 0
    assert samples[("dllama_device_live_buffers", "")] > 0
    assert samples[("dllama_device_live_bytes", "")] > 0


def test_postmortem_gains_slo_verdict(mserver):
    """/debug/requests/{req_id} postmortems judge the request's recorded
    marks against the configured SLOs: ttft_ok/itl_ok plus violated_by_ms,
    derived from the flight recorder's own ttft/e2e/decode_tokens."""
    port, _api, _ = mserver
    rid = new_request_id()
    st, _data, _ = _post_raw(port, "/v1/chat/completions",
                             {"messages": [{"role": "user", "content": "slo"}],
                              "max_tokens": 6, "temperature": 0.0},
                             headers={"X-Request-Id": rid})
    assert st == 200
    st, data, _ = _get_raw(port, f"/debug/requests/{rid}")
    assert st == 200
    doc = json.loads(data)
    v = doc["slo"]
    assert v["targets"] == {"ttft_ms": 120_000.0, "itl_ms": 120_000.0}
    assert v["ttft_ok"] is True  # a CPU tiny-model decode beats 2 minutes
    assert v["ok"] is True
    assert v["violated_by_ms"] == {"ttft": None, "itl": None}
    assert v["itl_ms"] == pytest.approx(  # display-rounded to 3 places
        (doc["e2e_ms"] - doc["ttft_ms"]) / (doc["decode_tokens"] - 1),
        abs=1e-3)


def test_crash_path_marks_error_and_counts_fault_fires(mserver):
    """Worker-crash telemetry: finished{reason=error} and
    fault_fires{engine.decode} advance, and /metrics still answers on a dead
    scheduler. Runs LAST against this server (the crash is terminal)."""
    port, api, _ = mserver
    before_err = val("dllama_requests_finished_total", {"reason": "error"})
    before_fires = val("dllama_fault_fires_total",
                       {"point": "engine.decode", "action": "raise"})
    faults.install("engine.decode", "raise")
    st, data, headers = _post_raw(
        port, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "boom"}], "max_tokens": 8})
    faults.clear()
    assert st == 500
    assert headers.get("X-Request-Id", "").startswith("req_")
    assert json.loads(data)["error"]["request_id"] == headers["X-Request-Id"]
    assert val("dllama_requests_finished_total",
               {"reason": "error"}) >= before_err + 1
    assert val("dllama_fault_fires_total",
               {"point": "engine.decode", "action": "raise"}) == before_fires + 1
    st_h, data_h, _ = _get_raw(port, "/health")
    assert st_h == 503
    st_m, data_m, _ = _get_raw(port, "/metrics")  # scrapes outlive the worker
    assert st_m == 200
    parse_exposition(data_m.decode())
