"""Cross-request radix prefix cache tests (ISSUE 9, engine/radix.py).

Three layers:

* tree core over a bare PagePool (no model): insert/walk/split, partial
  boundary matching, LRU + refcount-aware eviction, audit reconciliation
  of tree refs (leaked/duplicate node refs must FAIL the audit);
* engine level: mapping a tree prefix into a slot plus the admission COW
  on divergence inside a shared boundary page;
* scheduler level: BIT-EXACT token streams with the cache on vs off across
  greedy/sampled/penalized/spec and overlap on/off, multi-turn saved-prefill
  accounting, eviction-under-pressure admitting a deferred request, and a
  warm restart dropping the tree cleanly (never stale page refs).

DLLAMA_POOL_AUDIT=1 is armed suite-wide (tests/conftest.py), so every
release in these tests runs the full refcount reconciliation — tree refs
included — making the refcount contract an implicit assertion everywhere.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine, PagePool, PoolAuditError
from dllama_tpu.engine.radix import RadixCache
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.serve.scheduler import Scheduler

CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)


# --------------------------------------------------------------- tree core


def _pool_with_pages(n_pages=16, page=4, slots=2):
    """A bare pool + tree; returns (pool, radix, take) where take(slot, n)
    allocates n fresh pages into `slot`'s table and returns their ids."""
    pool = PagePool(n_pages, page, slots, max_blocks=n_pages)
    radix = RadixCache(pool)

    def take(slot, n):
        start = int(pool.n_blocks[slot])
        pool.grow(slot, (start + n) * page)
        return [int(p) for p in pool.tables[slot, start:start + n]]

    return pool, radix, take


def test_insert_walk_and_miss():
    pool, radix, take = _pool_with_pages()
    toks = list(range(10, 22))  # 3 full pages of 4
    pages = take(0, 3)
    assert radix.insert(toks, pages) == 3
    assert radix.stats()["nodes"] == 1 and radix.stats()["pages"] == 3
    # every tree page took one extra ref on top of the slot's
    assert all(pool.refcount[p] == 2 for p in pages)
    # a prompt extending the inserted prefix maps all 3 pages
    hit = radix.lookup(toks + [77, 78])
    assert hit.rows == 12 and hit.pages == pages and hit.part == 0
    # the cap: at least one token must remain to prefill
    hit = radix.lookup(toks)  # 12 tokens, cap 11 -> 2 full pages + 3 partial
    assert hit.rows == 11 and hit.pages == pages[:2]
    assert hit.part == 3 and hit.boundary == pages[2]
    # unrelated prompt: clean miss
    assert radix.lookup([90, 91, 92, 93, 94]).rows == 0
    assert pool.audit()["ok"]


def test_split_mid_edge_at_page_boundary():
    pool, radix, take = _pool_with_pages()
    a = list(range(1, 13))  # 3 pages
    pages_a = take(0, 3)
    radix.insert(a, pages_a)
    # b shares the first 2 pages, diverges in the third
    b = a[:8] + [60, 61, 62, 63]
    pages_b = pages_a[:2] + take(1, 1)
    radix.insert(b, pages_b)
    # edge split at the page boundary: shared prefix node + two leaves
    st = radix.stats()
    assert st["nodes"] == 3 and st["pages"] == 4
    for toks, page3 in ((a, pages_a[2]), (b, pages_b[2])):
        hit = radix.lookup(toks + [80])
        assert hit.rows == 12 and hit.pages[:2] == pages_a[:2]
        assert hit.pages[2] == page3
    assert pool.audit()["ok"]


def test_partial_boundary_within_first_page():
    """Divergence INSIDE the first page of an edge: no mappable full page,
    but the best child's first page is still offered as a shared boundary
    for the sub-page prefix."""
    pool, radix, take = _pool_with_pages()
    a = [1, 2, 3, 4, 5, 6, 7, 8]
    pages_a = take(0, 2)
    radix.insert(a, pages_a)
    hit = radix.lookup([1, 2, 9, 9, 9])
    assert hit.rows == 2 and hit.pages == [] and hit.part == 2
    assert hit.boundary == pages_a[0]


def test_no_false_boundary_after_mid_edge_divergence():
    """Review regression: a walk that diverges MID-EDGE at a page boundary
    must not fall back to comparing sibling edges — a sibling's first page
    holds KV computed at the PARENT node's depth, and offering it at the
    deeper offset would map position-mismatched rows (silently wrong
    output). The only valid boundary after a mid-edge stop is that edge's
    own next page."""
    pool, radix, take = _pool_with_pages()
    a = [1, 2, 3, 4, 5, 6, 7, 8]  # 2 pages of 4
    radix.insert(a, take(0, 2))
    b = [9, 10, 11, 12]
    radix.insert(b, take(1, 1))
    # matches a's first page, then diverges exactly at the page boundary
    # (part 0 against a's second page); b's (9, 10, ...) page must NOT be
    # offered as a boundary for rows 4-5
    hit = radix.lookup([1, 2, 3, 4, 9, 10, 99, 0])
    assert hit.rows == 4 and hit.part == 0 and hit.boundary is None


def test_fallback_boundary_child_survives_protected_eviction():
    """Review regression: the node-boundary fallback's winning child joins
    hit.path — the scheduler evicts between lookup and radix_map, and the
    page about to be mapped must not land on the free list."""
    pool, radix, take = _pool_with_pages()
    a = [1, 2, 3, 4, 5, 6, 7, 8]
    pa = take(0, 2)
    radix.insert(a, pa)
    pool.free_tail(0, 0)  # the tree is the only referent
    hit = radix.lookup([1, 2, 99])  # sub-page fallback match
    assert hit.part == 2 and hit.boundary == pa[0]
    assert radix.evict(8, protect=hit) == 0
    assert int(pool.refcount[pa[0]]) == 1  # still tree-held, mappable


def test_evict_lru_refcount_aware_and_protected():
    pool, radix, take = _pool_with_pages(n_pages=16)
    a, b, c = ([i + 1, i + 2, i + 3, i + 4] for i in (0, 10, 20))
    pa, pb, pc = take(0, 1), take(0, 1), take(0, 1)
    radix.insert(a, pa)
    radix.insert(b, pb)
    radix.insert(c, pc)
    # drop the slot's own refs: the tree is now the only referent of a/b/c
    pool.free_tail(0, 0)
    # ...except b, which a "live slot" still shares
    pool.adopt_prefix(1, pb)
    nodes = {tuple(n.tokens): n for n in radix._iter_nodes()}
    nodes[tuple(a)].last_used = 1.0   # coldest
    nodes[tuple(b)].last_used = 2.0
    nodes[tuple(c)].last_used = 3.0   # hottest
    hit_c = radix.lookup(c + [99])
    # need 2 pages: a (coldest) goes first; b would be next in LRU order but
    # frees nothing (slot 1 still references it) -> skipped, keeping the
    # cache entry; c is protected as the in-progress admission's match
    freed = radix.evict(2, protect=hit_c)
    assert freed == 1
    left = {tuple(n.tokens) for n in radix._iter_nodes()}
    assert tuple(a) not in left and tuple(b) in left and tuple(c) in left
    assert pool.audit()["ok"]
    # unprotected, with the slot ref gone, b and c are both reclaimable
    pool.free_tail(1, 0)
    assert radix.evict(8) == 2
    assert radix.stats()["nodes"] == 0 and pool.stats()["used"] == 0


def test_audit_fails_on_leaked_and_duplicate_node_refs():
    pool, radix, take = _pool_with_pages()
    toks = [1, 2, 3, 4]
    pages = take(0, 1)
    radix.insert(toks, pages)
    assert pool.audit()["ok"] and pool.audit()["radix_pages"] == 1
    # leaked node ref: the tree forgets a page without dropping its refcount
    node = next(iter(radix._iter_nodes()))
    stolen = node.pages.pop()
    node.tokens = ()
    with pytest.raises(PoolAuditError):
        pool.audit()
    node.pages.append(stolen)
    node.tokens = tuple(toks)
    assert pool.audit(raise_on_fail=False)["ok"]
    # duplicate node ref: the same page entering the tree twice is corrupt
    # even when the refcount is patched to match
    node.pages.append(stolen)
    node.tokens = tuple(toks + [9, 9, 9, 9])
    pool.refcount[stolen] += 1
    report = pool.audit(raise_on_fail=False)
    assert not report["ok"]
    assert any("radix nodes" in p for p in report["problems"])


# ------------------------------------------------------------ engine level


def _engine(radix="on", n_slots=3, kv_pages=0, spec=0):
    return BatchEngine(CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32,
                       kv_layout="paged", page_size=8, kv_pages=kv_pages,
                       spec=spec, radix_cache=radix)


def test_map_then_cow_on_divergence_inside_boundary_page():
    """A mapped partial boundary page is copy-on-written by the admission:
    the tree's page keeps its rows, and the diverged continuation matches
    an engine that never shared anything."""
    eng, solo = _engine(), _engine("off")
    prompt = list(range(1, 17))  # exactly 2 full pages
    for e in (eng, solo):
        e.add(0, prompt, temperature=0.0, seed=0)
    eng.radix_insert(0, prompt)  # adopt both pages (engine API the
    # scheduler drives at commit)
    eng.release(0)
    solo.release(0)
    assert eng.radix_stats()["pages"] == 2
    # diverge at token 12, INSIDE the tree's second page: reuse = 8 full
    # rows + 4 rows of the shared boundary page
    div = prompt[:12] + [70, 71, 72]
    rows, hit = eng.radix_lookup(div)
    assert rows == 12 and hit.part == 4
    tree_page = hit.boundary
    eng.radix_map(1, hit)
    assert int(eng.pool.refcount[tree_page]) == 2  # tree + slot 1
    eng.add(1, div[rows:], temperature=0.0, seed=1, start_pos=rows)
    # prepare_admission copy-on-wrote the shared boundary before the
    # divergent rows were scattered: the tree's page is whole again
    assert int(eng.pool.refcount[tree_page]) == 1
    assert int(eng.pool.tables[1, 1]) != tree_page
    solo.add(1, div, temperature=0.0, seed=1)
    np.testing.assert_array_equal(eng.decode(4)[:, 1], solo.decode(4)[:, 1])
    assert eng.pool.audit()["ok"]


# --------------------------------------------------------- scheduler level


def _sched(radix, overlap=True, n_slots=3, chunk=3, kv_pages=0, spec=0):
    return Scheduler(_engine(radix, n_slots=n_slots, kv_pages=kv_pages,
                             spec=spec), chunk=chunk, overlap=overlap)


_WORK: dict = {}


def _workload(radix, overlap=True, spec=0):
    """Mixed greedy/sampled/penalized workload with a shared system prompt
    and staggered submission; memoized per config (each run costs an engine
    compile inside the time-budgeted tier-1 window)."""
    key = (radix, overlap, spec)
    if key in _WORK:
        return _WORK[key]
    sched = _sched(radix, overlap=overlap, spec=spec)
    try:
        sys_p = list(range(1, 18))  # 17 tokens: 2 full pages + 1
        r1 = sched.submit(sys_p + [30], 0.0, 0.9, 10, frozenset(), seed=1)
        it1 = r1.tokens()
        head = [next(it1), next(it1)]
        r2 = sched.submit(sys_p + [40, 41], 1.1, 0.9, 8, frozenset(), seed=42)
        r3 = sched.submit(sys_p + [50], 0.9, 0.8, 8, frozenset(), seed=7,
                          presence=0.5, frequency=0.3)
        out2, out3 = list(r2.tokens()), list(r3.tokens())
        out1 = head + list(it1)
        _WORK[key] = [(out1, r1.finish_reason), (out2, r2.finish_reason),
                      (out3, r3.finish_reason)]
        return _WORK[key]
    finally:
        sched.shutdown()


def test_bitexact_on_off_mixed_batch():
    """The headline contract: greedy + sampled + penalized streams are
    BIT-IDENTICAL with the radix cache on vs off (reuse changes which rows
    are prefilled vs mapped, never their contents)."""
    assert _workload("on") == _workload("off")


def test_bitexact_on_off_overlap_off():
    assert _workload("on", overlap=False) == _workload("off", overlap=False)
    assert _workload("on", overlap=False) == _workload("on")


def test_bitexact_on_off_with_spec():
    """Spec engines draft from per-slot history; radix_map backfills the
    mapped prefix's tokens so proposals see the same history either way."""
    on = _workload("on", spec=4)
    assert on == _workload("off", spec=4)
    assert on == _workload("on", spec=0)


def test_multi_turn_saved_prefill_and_parity():
    """Turn 2 re-sends the whole conversation: the tree serves the full
    pages of turn 1's rows for free, and the stream matches a cold run."""
    sched = _sched("on", n_slots=2, chunk=4)
    try:
        turn1 = list(range(1, 14))  # 13 tokens
        r1 = sched.submit(turn1, 0.0, 0.9, 6, frozenset(), seed=0)
        gen1 = list(r1.tokens())
        turn2 = turn1 + gen1 + [7, 8]
        fed_rows = len(turn1) + len(gen1) - 1  # last token never fed back
        before = sched.engine.radix_stats()["hit_tokens"]
        r2 = sched.submit(turn2, 0.0, 0.9, 4, frozenset(), seed=0)
        warm = list(r2.tokens())
        saved = sched.engine.radix_stats()["hit_tokens"] - before
        # page-granular reuse: every FULL page of the fed rows maps free
        assert saved == (fed_rows // 8) * 8 > 0
        assert sched.reused_prefix_tokens >= saved
    finally:
        sched.shutdown()
    cold = _sched("off", n_slots=2, chunk=4)
    try:
        r = cold.submit(turn2, 0.0, 0.9, 4, frozenset(), seed=0)
        assert list(r.tokens()) == warm, "radix-mapped rows changed output"
    finally:
        cold.shutdown()


def test_eviction_under_pressure_admits_deferred_request():
    """Capacity composition: tree pages are reclaimable BEFORE a request
    defers — a prompt the free list cannot cover evicts LRU leaves and
    admits instead of parking behind a full pool."""
    sched = _sched("on", n_slots=2, chunk=3, kv_pages=8)  # 64 rows of pool
    try:
        # fill the tree: two disjoint completed prompts -> ~5-6 tree pages
        for base in (1, 40):
            r = sched.submit(list(range(base, base + 17)), 0.0, 0.9, 3,
                             frozenset(), seed=base)
            list(r.tokens())
        assert sched.engine.radix_stats()["pages"] >= 4
        assert sched.engine.pool.free_count < 5
        # 30-token prompt needs 4 pages + reserve: must evict tree leaves
        big = sched.submit(list(range(60, 90)), 0.0, 0.9, 4, frozenset(),
                           seed=9)
        out = list(big.tokens())
        assert big.finish_reason == "length" and len(out) == 4
        assert sched.engine.radix_stats()["evicted_pages"] >= 1
        assert sched.engine.pool.audit()["ok"]
    finally:
        sched.shutdown()


def test_warm_restart_drops_tree_resumes_bitexact():
    """A worker crash rebuilds pool + tree from scratch (never stale page
    refs); the tree re-fills from post-restart traffic and the interrupted
    sampled stream resumes bit-exact."""
    from dllama_tpu.utils import faults

    ref_sched = _sched("on", n_slots=2, chunk=3)
    try:
        ref = ref_sched.submit([3, 1, 4, 1, 5, 9, 2, 6, 5], 0.9, 0.9, 12,
                               frozenset(), seed=11)
        want = list(ref.tokens())
    finally:
        ref_sched.shutdown()

    sched = _sched("on", n_slots=2, chunk=3)
    sched.restart_max = 3
    sched.restart_backoff_s = 0.01
    try:
        warm = sched.submit(list(range(1, 12)), 0.0, 0.9, 4, frozenset(),
                            seed=0)
        list(warm.tokens())
        assert sched.engine.radix_stats()["nodes"] >= 1
        inserted_before = sched.engine.radix_stats()["inserted_pages"]
        r = sched.submit([3, 1, 4, 1, 5, 9, 2, 6, 5], 0.9, 0.9, 12,
                         frozenset(), seed=11)
        it = r.tokens()
        got = [next(it)]
        faults.install("scheduler.loop", "raise", times=1)
        got += list(it)
        assert got == want, "resumed stream diverged from uninterrupted run"
        assert sched.health()["restarts"] == 1
        st = sched.engine.radix_stats()
        # cumulative accounting carried across the rebuild; the tree itself
        # restarted empty and only holds post-restart insertions
        assert st["inserted_pages"] >= inserted_before
        assert sched.engine.pool.audit()["ok"]
    finally:
        faults.clear()
        sched.shutdown()


def test_release_reconciles_refcounts_and_drain_audit():
    """After every request finishes, the pool's only references are the
    tree's (slots hand every page back at release); drain's audit passes
    and clearing the tree returns the pool to empty."""
    sched = _sched("on", n_slots=3, chunk=3)
    eng = sched.engine
    try:
        for i in range(3):
            r = sched.submit(list(range(1, 14)) + [60 + i], 0.5, 0.9, 4,
                             frozenset(), seed=i)
            list(r.tokens())
        assert not eng.active.any()
        st = eng.pool.stats()
        radix_pages = eng.radix_stats()["pages"]
        assert st["used"] == radix_pages > 0  # slots empty; tree is the cache
        assert sched.drain(5.0)
    finally:
        sched.shutdown()
    assert eng.pool.audit()["ok"]
    assert eng.radix.clear() == radix_pages
    assert eng.pool.stats()["used"] == 0
