"""Fleet observability plane tests (ISSUE 19): NTP-lite clock-offset
estimation, clock-aligned Chrome-trace merging, metrics federation
(relabel + exact counter/histogram fleet rollups + staleness, checked
under the same exposition grammar as test_metrics), client-perspective
router SLO windows, distributed trace propagation through the router's
failover path, and the cross-hop postmortem join — all against the
controllable stub replicas from test_router (the real-engine e2e lives
there, next to the real mesh fixture)."""

import http.client
import json
import random
import threading
import time

import pytest

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import metrics, trace
from dllama_tpu.obs.perf import ClockOffset, SloPolicy
from dllama_tpu.serve.router import Router, federate, make_router
from tests.test_metrics import check_histogram, parse_exposition
from tests.test_router import (SHARED, StubState, make_stub, rget, rpost,
                               sse_events)


# ------------------------------------------------------ clock offset (unit)


def test_clock_offset_empty_and_basic():
    co = ClockOffset()
    assert co.estimate() is None
    # symmetric exchange: remote read at the midpoint -> exact recovery
    skew, rtt = 3.25, 0.050
    co.sample(100.0, 100.0 + rtt, (100.0 + 100.0 + rtt) / 2.0 + skew)
    est = co.estimate()
    assert est["samples"] == 1
    assert est["offset_s"] == pytest.approx(skew)
    assert est["rtt_s"] == pytest.approx(rtt)
    assert est["uncertainty_s"] == pytest.approx(rtt / 2.0)


def test_clock_offset_min_rtt_sample_wins():
    """Queue-polluted exchanges carry the worst offset error — the window
    estimate must come from the tightest round trip, and the true offset
    must sit inside its +/- rtt/2 bound."""
    skew = 4.0
    co = ClockOffset()
    # (outbound delay, inbound delay): asymmetric pairs skew the estimate
    # by (d1 - d2) / 2, always within rtt / 2
    for d1, d2 in [(0.200, 0.010), (0.002, 0.001), (0.050, 0.400)]:
        t_send = 50.0
        t_recv = t_send + d1 + d2
        co.sample(t_send, t_recv, t_send + d1 + skew)
    est = co.estimate()
    assert est["rtt_s"] == pytest.approx(0.003)  # the tight exchange
    assert abs(est["offset_s"] - skew) <= est["uncertainty_s"]
    assert est["samples"] == 3


def test_clock_offset_window_slides():
    co = ClockOffset(window=4)
    co.sample(0.0, 0.001, 0.0005 + 1.0)          # tight, offset 1.0
    for i in range(4):                            # ...evicted by 4 loose
        co.sample(10.0, 10.5, 10.25 + 2.0)
    est = co.estimate()
    assert est["samples"] == 4
    assert est["offset_s"] == pytest.approx(2.0)
    assert est["uncertainty_s"] == pytest.approx(0.25)


# --------------------------------------------------------- trace merge (unit)


def _export(track, events):
    return {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "dllama-tpu"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": track}},
    ] + events, "displayTimeUnit": "ms"}


def test_merge_chrome_relabels_shifts_and_sorts():
    a = _export("router", [
        {"ph": "X", "name": "proxy.stream", "pid": 1, "tid": 1,
         "ts": 100.0, "dur": 50.0, "args": {}},
        {"ph": "i", "name": "affinity.pick", "pid": 1, "tid": 1,
         "ts": 500.0, "s": "t", "args": {}},
    ])
    b = _export("scheduler", [
        {"ph": "X", "name": "prefill", "pid": 1, "tid": 1,
         "ts": 200.0, "dur": 10.0, "args": {}},
        {"ph": "X", "name": "request", "pid": 1, "tid": 1,
         "ts": 200.0, "dur": 90.0, "args": {}},
    ])
    merged = trace.merge_chrome([("router", a, 0.0), ("r1", b, -1100.0)])
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    # each part became its own Perfetto process, renamed to its label
    procs = {e["pid"]: e["args"]["name"] for e in meta
             if e["name"] == "process_name"}
    assert procs == {1: "router", 2: "r1"}
    # shift applied to non-meta events only; meta keeps no ts
    by = {(e["pid"], e["name"]): e for e in body}
    assert by[(2, "prefill")]["ts"] == pytest.approx(-900.0)
    assert by[(1, "proxy.stream")]["ts"] == pytest.approx(100.0)
    # global (ts, -dur) order: parent-before-child at equal start
    keyed = [(e["ts"], -e.get("dur", 0.0)) for e in body]
    assert keyed == sorted(keyed)
    assert [e["name"] for e in body[:2]] == ["request", "prefill"]


def test_merge_chrome_tolerates_empty_parts():
    merged = trace.merge_chrome([("router", {}, 0.0),
                                 ("r1", {"traceEvents": []}, 5.0)])
    assert merged["traceEvents"] == []


def test_merge_chrome_real_tracers_stay_monotone():
    t1, t2 = trace.Tracer(64), trace.Tracer(64)
    now = time.monotonic()
    t1.span_at("request", now, now + 0.01, track="requests", req_id="r1")
    t2.span_at("prefill", now, now + 0.002, track="requests", req_id="r1")
    t2.event("first_token", track="requests", req_id="r1")
    merged = trace.merge_chrome([
        ("router", t1.export_chrome(), 0.0),
        ("rep", t2.export_chrome(), (t2.epoch - t1.epoch) * 1e6),
    ])
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert len(body) == 3
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)


# --------------------------------------------------------- federation (unit)


R1_TEXT = """# HELP dllama_requests_finished_total finished requests
# TYPE dllama_requests_finished_total counter
dllama_requests_finished_total{reason="stop"} 3
dllama_requests_finished_total{reason="error"} 1
# HELP dllama_ttft_seconds time to first token
# TYPE dllama_ttft_seconds histogram
dllama_ttft_seconds_bucket{le="0.1"} 1
dllama_ttft_seconds_bucket{le="+Inf"} 2
dllama_ttft_seconds_sum 0.35
dllama_ttft_seconds_count 2
# HELP dllama_queue_depth queued requests
# TYPE dllama_queue_depth gauge
dllama_queue_depth 0
"""

R2_TEXT = """# HELP dllama_requests_finished_total finished requests
# TYPE dllama_requests_finished_total counter
dllama_requests_finished_total{reason="stop"} 4
"""

OWN_TEXT = """# HELP dllama_router_requests_total proxied requests
# TYPE dllama_router_requests_total counter
dllama_router_requests_total{outcome="ok"} 7
"""


def test_federate_relabels_and_preaggregates():
    out = federate(OWN_TEXT, [("r1", R1_TEXT), ("r2", R2_TEXT)])
    fams, samples = parse_exposition(out)  # full grammar gate
    # the router's own series stay unlabeled — it IS the scrape target
    assert samples[("dllama_router_requests_total", '{outcome="ok"}')] == 7
    # every replica series gained a LEADING replica label
    assert samples[("dllama_requests_finished_total",
                    '{replica="r1",reason="stop"}')] == 3
    assert samples[("dllama_requests_finished_total",
                    '{replica="r2",reason="stop"}')] == 4
    assert samples[("dllama_queue_depth", '{replica="r1"}')] == 0
    # one HELP/TYPE block per family, kinds preserved
    assert fams["dllama_requests_finished_total"] == "counter"
    assert fams["dllama_queue_depth"] == "gauge"
    assert fams["dllama_ttft_seconds"] == "histogram"
    # histogram invariants survive the relabel
    check_histogram(samples, "dllama_ttft_seconds")
    # counters pre-aggregated across replicas, keyed by original labels
    assert fams["dllama_fleet_requests_finished_total"] == "counter"
    assert samples[("dllama_fleet_requests_finished_total",
                    '{reason="stop"}')] == 7
    assert samples[("dllama_fleet_requests_finished_total",
                    '{reason="error"}')] == 1
    # histograms merged BUCKET-WISE into the fleet view (ISSUE 19 —
    # exact, buckets are fixed per family); only r1 exposes this one
    assert fams["dllama_fleet_ttft_seconds"] == "histogram"
    assert samples[("dllama_fleet_ttft_seconds_bucket", '{le="0.1"}')] == 1
    assert samples[("dllama_fleet_ttft_seconds_bucket", '{le="+Inf"}')] == 2
    assert samples[("dllama_fleet_ttft_seconds_sum", "")] == 0.35
    assert samples[("dllama_fleet_ttft_seconds_count", "")] == 2
    check_histogram(samples, "dllama_fleet_ttft_seconds")
    # gauges are NOT naively summed into the fleet view (a sum of queue
    # depths sampled at different instants is not a fleet queue depth)
    assert not any(n.startswith("dllama_fleet_queue_depth")
                   for n, _ in samples)


def test_federate_drops_garbage_keeps_rest():
    noisy = "garbage not a metric !!\n" + R2_TEXT + "also&bad 1\n"
    out = federate(OWN_TEXT, [("r2", noisy)])
    fams, samples = parse_exposition(out)
    assert samples[("dllama_requests_finished_total",
                    '{replica="r2",reason="stop"}')] == 4
    assert "garbage" not in out


def test_histogram_federation_equals_union_registry():
    """ISSUE 19 property test: bucket-wise merge of N scraped exposition
    texts is EXACTLY the histogram a single registry observing the union
    stream would render — same buckets, same sums, same counts, not
    approximately. Observations are dyadic rationals (k/1024) so float
    addition is exact and the equality really is ==, independent of the
    order replicas happened to see their shares of the stream."""
    buckets = (0.25, 0.5, 1.0, 2.0)
    regs = [metrics.Registry() for _ in range(3)]
    union = metrics.Registry()
    hs = [r.histogram("dllama_lat_seconds", "latency", ("kind",),
                      buckets=buckets) for r in regs]
    hu = union.histogram("dllama_lat_seconds", "latency", ("kind",),
                         buckets=buckets)
    rnd = random.Random(0xF1EE7)
    for _ in range(600):
        v = rnd.randrange(0, 4096) / 1024.0
        kind = ("prefill", "decode")[rnd.randrange(2)]
        hs[rnd.randrange(3)].labels(kind=kind).observe(v)
        hu.labels(kind=kind).observe(v)
    out = federate("", [(f"r{i}", r.render())
                        for i, r in enumerate(regs)])
    fams, samples = parse_exposition(out)
    assert fams["dllama_fleet_lat_seconds"] == "histogram"
    check_histogram(samples, "dllama_fleet_lat_seconds")
    _, want = parse_exposition(union.render())
    for (name, lbl), v in want.items():
        assert name.startswith("dllama_lat_seconds")
        fleet_key = ("dllama_fleet_" + name[len("dllama_"):], lbl)
        assert samples[fleet_key] == v, (fleet_key, samples[fleet_key], v)
    # ...and nothing beyond the union's sample set was invented
    n_fleet = sum(1 for n, _ in samples
                  if n.startswith("dllama_fleet_lat_seconds"))
    assert n_fleet == len(want)


# ----------------------------------------------------- router wiring (stubs)


@pytest.fixture
def obs_mesh():
    """Two stub replicas with SKEWED reported clocks behind a started
    router (poller inert at poll_s=30 — tests drive _poll_one directly)."""
    a, b = StubState("stub-a"), StubState("stub-b")
    a.clock_skew, b.clock_skew = 2.5, -1.25
    ha, hb = make_stub(a), make_stub(b)
    server, router = make_router(
        [f"127.0.0.1:{ha.server_address[1]}",
         f"127.0.0.1:{hb.server_address[1]}"],
        poll_s=30.0)
    router.start()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server.server_address[1], router, (a, b), (ha, hb)
    router.stop()
    server.shutdown()
    server.server_close()
    for h in (ha, hb):
        try:
            h.shutdown()
            h.server_close()
        except OSError:
            pass


def test_poller_estimates_skewed_clocks(obs_mesh):
    port, router, (a, b), _ = obs_mesh
    a.trace_epoch = 123.5
    for rep in router.replicas:
        for _ in range(3):
            router._poll_one(rep)
    # router-side replica ids are addresses, in --replica order: a then b
    ra, rb = router.replicas
    ea, eb = ra.clock.estimate(), rb.clock.estimate()
    # loopback rtt is sub-millisecond; the scripted skews dominate
    assert ea["offset_s"] == pytest.approx(2.5, abs=0.2)
    assert eb["offset_s"] == pytest.approx(-1.25, abs=0.2)
    assert ra.trace_epoch == 123.5 and rb.trace_epoch is None
    assert ins.REPLICA_CLOCK_OFFSET.labels(
        replica=ra.rid).value() == pytest.approx(ea["offset_s"])
    assert ins.REPLICA_CLOCK_UNCERTAINTY.labels(
        replica=rb.rid).value() == pytest.approx(eb["uncertainty_s"])
    # the offset rides the health snapshot into /health and /router/fleet
    st, data = rget(port, "/health")
    reps = {r["id"]: r for r in json.loads(data)["replicas"]}
    assert reps[ra.rid]["clock"]["offset_s"] == pytest.approx(
        ea["offset_s"])


def test_fleet_obs_off_disables_clock_and_tracer(obs_mesh):
    _, router, (a, b), (ha, hb) = obs_mesh
    r2 = Router([f"127.0.0.1:{ha.server_address[1]}"], poll_s=30.0,
                fleet_obs=False)
    assert r2.tracer is trace.NULL_TRACER
    r2._poll_one(r2.replicas[0])
    assert r2.replicas[0].live
    assert r2.replicas[0].clock.estimate() is None


def test_merged_trace_shifts_replica_onto_router_clock(obs_mesh):
    port, router, (a, b), _ = obs_mesh
    a.trace_epoch = 777.0
    a.trace_export = _export("scheduler", [
        {"ph": "X", "name": "request", "pid": 1, "tid": 1, "ts": 1000.0,
         "dur": 40.0, "args": {"req_id": "req-x", "trace_id": "ab" * 8}},
    ])
    # b leaves trace_export=None -> its /debug/trace 404s -> skipped
    for rep in router.replicas:
        for _ in range(3):
            router._poll_one(rep)
    # a proxied request puts the router's own spans on the merged timeline
    st, _, _ = rpost(port, "/v1/chat/completions",
                     {"messages": SHARED, "max_tokens": 4})
    assert st == 200
    st, data = rget(port, "/router/trace")
    assert st == 200
    merged = json.loads(data)
    other = merged["otherData"]
    assert other["replicas_merged"] == 1
    clk = other["clock"][router.replicas[0].rid]
    assert clk["aligned"] is True
    assert clk["trace_epoch_s"] == 777.0
    # shift = (epoch_replica - offset - epoch_router) us, offset ~ skew
    want = (777.0 - clk["offset_s"] - other["router_epoch_s"]) * 1e6
    assert clk["shift_us"] == pytest.approx(want, abs=1.0)
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    rep_ev = next(e for e in body if e["name"] == "request")
    assert rep_ev["pid"] == 2
    assert rep_ev["ts"] == pytest.approx(1000.0 + clk["shift_us"], abs=1.0)
    router_names = {e["name"] for e in body if e["pid"] == 1}
    assert {"connect", "affinity.pick"} <= router_names
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)


def test_router_metrics_endpoint_federates(obs_mesh):
    port, router, (a, b), _ = obs_mesh
    a.metrics_text = R1_TEXT
    scraped0 = (metrics.REGISTRY.sample(
        "dllama_router_federation_scrape_seconds") or {"count": 0})["count"]
    st, _, _ = rpost(port, "/v1/chat/completions",
                     {"messages": SHARED, "max_tokens": 4})
    assert st == 200
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", "/router/metrics")
    resp = conn.getresponse()
    ctype, text = resp.getheader("Content-Type"), resp.read().decode()
    conn.close()
    assert resp.status == 200 and "text/plain" in ctype
    fams, samples = parse_exposition(text)  # the scraper's grammar gate
    ra, rb = router.replicas
    # the router's own registry stays unlabeled (it IS the scrape target):
    # the process self-gauges refreshed by the federation pass, plus its
    # proxied-request counters, appear without a leading replica tag
    assert any(n.startswith("dllama_process_") and not lbl
               for n, lbl in samples)
    # both stubs relabeled into the same exposition
    assert samples[("dllama_requests_finished_total",
                    f'{{replica="{ra.rid}",reason="stop"}}')] == 3
    assert ("dllama_stub_requests_total", f'{{replica="{rb.rid}"}}') \
        in samples
    check_histogram(samples, "dllama_ttft_seconds")
    assert samples[("dllama_fleet_requests_finished_total",
                    '{reason="stop"}')] == 3
    scraped1 = metrics.REGISTRY.sample(
        "dllama_router_federation_scrape_seconds")["count"]
    assert scraped1 == scraped0 + 1


def test_federation_staleness_holds_last_scrape(obs_mesh):
    """ISSUE 19 staleness contract: a replica the scrape can't reach keeps
    federating its LAST successful exposition — a dead replica must read
    STALE (age gauge growing), never as traffic dropping to zero."""
    port, router, (a, b), (ha, hb) = obs_mesh
    a.metrics_text = R1_TEXT
    ra, rb = router.replicas
    st, text = rget(port, "/metrics")  # the default route IS the fleet view
    assert st == 200
    fams, samples = parse_exposition(text.decode())
    assert fams["dllama_fleet_scrape_age_seconds"] == "gauge"
    age_a0 = samples[("dllama_fleet_scrape_age_seconds",
                      f'{{replica="{ra.rid}"}}')]
    assert age_a0 == pytest.approx(0.0, abs=0.5)
    # kill replica a outright: its counters must HOLD last-known values
    ha.shutdown()
    ha.server_close()
    time.sleep(0.05)
    st, text = rget(port, "/metrics")
    assert st == 200
    fams, samples = parse_exposition(text.decode())
    assert samples[("dllama_requests_finished_total",
                    f'{{replica="{ra.rid}",reason="stop"}}')] == 3
    assert samples[("dllama_fleet_requests_finished_total",
                    '{reason="stop"}')] == 3
    age_a1 = samples[("dllama_fleet_scrape_age_seconds",
                      f'{{replica="{ra.rid}"}}')]
    age_b1 = samples[("dllama_fleet_scrape_age_seconds",
                      f'{{replica="{rb.rid}"}}')]
    assert age_a1 > age_a0 and age_a1 > age_b1
    assert age_b1 == pytest.approx(0.0, abs=0.5)


def test_router_client_slo_windows_and_attainment(obs_mesh):
    """Client-perspective SLO scoring is judged at the ROUTER, with its
    own targets: per-replica and fleet windows, attainment = ok/finished,
    NaN (unknown) on a drained window — never 1.0 by absence."""
    _, _, (a, b), (ha, hb) = obs_mesh
    r2 = Router([f"127.0.0.1:{ha.server_address[1]}",
                 f"127.0.0.1:{hb.server_address[1]}"], poll_s=30.0,
                slo=SloPolicy(ttft_ms=100.0, itl_ms=50.0))
    rid0, rid1 = (rep.rid for rep in r2.replicas)
    r2.observe_client(rid0, 0.050, 0.010)   # both kinds under target
    r2.observe_client(rid0, 0.250)          # TTFT blown, ITL unknowable
    r2.observe_client(rid1, None, 0.020)    # ITL-only, met
    snap = r2._client_snapshot("fleet")
    assert snap["window_finished"] == 3
    assert snap["attainment"] == pytest.approx(2 / 3)
    assert snap["ttft_ms"]["count"] == 2
    assert snap["ttft_ms"]["p95"] == pytest.approx(250.0, abs=10.0)
    assert snap["itl_ms"]["count"] == 2
    assert snap["targets"] == {"ttft_ms": 100.0, "itl_ms": 50.0}
    s0 = r2._client_snapshot(rid0)
    assert s0["window_finished"] == 2
    assert s0["attainment"] == pytest.approx(0.5)
    r2.refresh_client_gauges()
    assert ins.ROUTER_SLO_ATTAINMENT.labels(
        replica="fleet").value() == pytest.approx(2 / 3)
    # an unknown replica key is dropped, not created: the window dict is
    # pre-populated at init and never mutated (lock-free reads)
    r2.observe_client("nobody", 0.010)
    assert set(r2._client) == {"fleet", rid0, rid1}
    # a drained/empty window publishes NaN, not a perfect score
    r3 = Router(["127.0.0.1:1"], poll_s=30.0)
    r3.refresh_client_gauges()
    v = ins.ROUTER_SLO_ATTAINMENT.labels(replica="fleet").value()
    assert v != v  # NaN


def test_router_fleet_endpoint_joins_health_and_clock(obs_mesh):
    port, router, (a, b), _ = obs_mesh
    for rep in router.replicas:
        router._poll_one(rep)
    st, data = rget(port, "/router/fleet")
    assert st == 200
    fleet = json.loads(data)
    assert fleet["mesh"]["model"] == "stub-model"
    assert fleet["fleet"]["replicas"] == 2
    assert fleet["fleet"]["live"] == 2 and fleet["fleet"]["scraped"] == 2
    ra = router.replicas[0]
    reps = {r["id"]: r for r in fleet["replicas"]}
    assert reps[ra.rid]["clock"]["offset_s"] == pytest.approx(2.5, abs=0.2)
    # stubs expose no /debug/perf|kv|radix: the view degrades, not 500s
    assert reps[ra.rid]["slo"] is None and reps[ra.rid]["kv"] is None
    assert fleet["fleet"]["throughput_tok_s"] == 0.0
    assert fleet["fleet"]["slo_attainment"] is None
    # ISSUE 19 reconciliation surfaces: client-seat windows per replica
    # and fleet-wide, plus failover counters vs client-observed errors
    assert reps[ra.rid]["client"]["window_finished"] == 0
    assert fleet["fleet"]["client"]["attainment"] is None
    assert set(fleet["fleet"]["failovers"]) == {
        "retried", "resumed", "exhausted", "unresumable"}
    assert set(fleet["fleet"]["client_errors"]) == {
        "stream_error", "shed", "upstream_error"}


def _stream_with_rid(port, body, rid, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json", "X-Request-Id": rid})
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    return raw


def test_trace_propagation_and_postmortem_across_failover(obs_mesh):
    """The heart of the tentpole: the victim leg and the resumed leg ride
    ONE trace id (hop header, hop count incrementing), and the postmortem
    join reconstructs the whole story — forward died, resume succeeded —
    with each replica's own timeline attached."""
    port, router, (a, b), (ha, hb) = obs_mesh
    addrs = {a.rid: f"127.0.0.1:{ha.server_address[1]}",
             b.rid: f"127.0.0.1:{hb.server_address[1]}"}
    st, _, h1 = rpost(port, "/v1/chat/completions",
                      {"messages": SHARED, "max_tokens": 4})
    victim, survivor = (a, b) if h1["X-Replica-Id"] == "stub-a" else (b, a)
    victim.abort_after = 2
    rid = "req-obs-failover"
    victim.timelines[rid] = {"req_id": rid, "state": "died",
                             "decode_tokens": 2}
    # survivor's leg left unset -> its join degrades to {"error": ...}
    raw = _stream_with_rid(port, {"messages": SHARED, "stream": True,
                                  "max_tokens": 8}, rid)
    assert raw.rstrip().splitlines()[-1] == "data: [DONE]"
    finishes = [e["choices"][0].get("finish_reason")
                for e in sse_events(raw) if "choices" in e]
    assert [f for f in finishes if f] == ["stop"]

    # hop headers: same trace id on both legs, hop count incremented,
    # resume leg parented under the resume span
    vh = victim.header_log[-1]["x-dllama-trace"]
    sh = survivor.header_log[-1]["x-dllama-trace"]
    v_tid, v_parent, v_hop = trace.parse_hop(vh)
    s_tid, s_parent, s_hop = trace.parse_hop(sh)
    assert v_tid == s_tid and len(v_tid) == 16
    assert (v_parent, v_hop) == ("connect", 1)
    assert (s_parent, s_hop) == ("resume", 2)

    # cross-hop postmortem join
    st, data = rget(port, f"/router/requests/{rid}")
    assert st == 200
    pm = json.loads(data)
    assert pm["trace_id"] == v_tid
    rec = pm["router"]
    assert rec["stream"] is True and rec["outcome"] == "ok"
    assert rec["retries"] == 1
    kinds = [(x["kind"], x["outcome"]) for x in rec["attempts"]]
    assert ("forward", "died_mid_stream") in kinds
    assert ("resume", "ok") in kinds
    at = [x["at_ms"] for x in rec["attempts"]]
    assert at == sorted(at)
    assert pm["replicas"][addrs[victim.rid]] == {
        "req_id": rid, "state": "died", "decode_tokens": 2}
    assert pm["replicas"][addrs[survivor.rid]] == {"error": "status 404"}

    # the router's own trace shows both legs under the one trace id
    st, data = rget(port, "/router/trace")
    merged = json.loads(data)
    mine = [e for e in merged["traceEvents"] if e.get("ph") != "M"
            and e.get("args", {}).get("trace_id") == v_tid]
    names = {e["name"] for e in mine}
    assert {"connect", "proxy", "failover.attempt", "resume"} <= names
    # the journal span closes the request's router-side story
    journal = next(e for e in mine if e["name"] == "journal")
    assert journal["args"]["retries"] == 1

    # unknown ids 404, never 500
    st, data = rget(port, "/router/requests/req-nope")
    assert st == 404


def test_non_stream_and_shed_outcomes_recorded(obs_mesh):
    port, router, (a, b), _ = obs_mesh
    rid = "req-obs-plain"
    st, _, _ = rpost(port, "/v1/chat/completions",
                     {"messages": SHARED, "max_tokens": 4},
                     headers={"X-Request-Id": rid})
    assert st == 200
    st, data = rget(port, f"/router/requests/{rid}")
    pm = json.loads(data)
    assert pm["router"]["outcome"] == "ok" and pm["router"]["stream"] is False
    assert [x["kind"] for x in pm["router"]["attempts"]] == ["forward"]
    # the non-stream round trip fed the router's client-seat TTFT window
    snap = router._client_snapshot("fleet")
    assert snap["ttft_ms"]["count"] >= 1 and snap["window_finished"] >= 1

    a.saturated = b.saturated = True
    rid2 = "req-obs-shed"
    st, _, _ = rpost(port, "/v1/chat/completions",
                     {"messages": SHARED, "max_tokens": 4},
                     headers={"X-Request-Id": rid2})
    assert st == 429
    st, data = rget(port, f"/router/requests/{rid2}")
    assert json.loads(data)["router"]["outcome"] == "shed"
