"""Quantization roundtrip + format tests.

Tolerances mirror the reference kernel tests (nn-cpu-ops-test.cpp:84-89):
Q40 roundtrip eps 0.13, Q80 roundtrip eps 0.01 on U(-1,1)-scale data.
"""

import numpy as np
import pytest

from dllama_tpu.ops import quant


def test_q40_roundtrip_tolerance(rng):
    x = rng.uniform(-1, 1, size=4096).astype(np.float32)
    packed, scales = quant.quantize_q40_np(x)
    y = quant.dequantize_q40_np(packed, scales)
    assert np.max(np.abs(x - y)) < 0.13


def test_q80_roundtrip_tolerance(rng):
    x = rng.uniform(-1, 1, size=4096).astype(np.float32)
    codes, scales = quant.quantize_q80_np(x)
    y = quant.dequantize_q80_np(codes, scales)
    assert np.max(np.abs(x - y)) < 0.01


def test_q40_bytes_roundtrip(rng):
    x = rng.normal(size=2048).astype(np.float32)
    packed, scales = quant.quantize_q40_np(x)
    buf = quant.q40_to_bytes(packed, scales)
    assert len(buf) == quant.FloatType.Q40.nbytes(2048)
    p2, s2 = quant.q40_from_bytes(buf, 2048)
    np.testing.assert_array_equal(packed.reshape(-1, 16), p2)
    np.testing.assert_array_equal(scales.reshape(-1), s2)


def test_q80_bytes_roundtrip(rng):
    x = rng.normal(size=2048).astype(np.float32)
    codes, scales = quant.quantize_q80_np(x)
    buf = quant.q80_to_bytes(codes, scales)
    assert len(buf) == quant.FloatType.Q80.nbytes(2048)
    c2, s2 = quant.q80_from_bytes(buf, 2048)
    np.testing.assert_array_equal(codes.reshape(-1, 32), c2)
    np.testing.assert_array_equal(scales.reshape(-1), s2)


def test_q40_matches_reference_writer_bits():
    """Bit-exactness against the converter algorithm on a crafted block
    (incl. the -min>max tie-break and the +8.5 floor rounding of writer.py:37-41)."""
    x = np.zeros(32, dtype=np.float32)
    x[0] = -8.0  # absmax is negative -> delta = -8/-8 = 1.0
    x[1] = 7.0
    x[2] = 0.49
    x[3] = 0.51
    x[17] = -3.2
    packed, scales = quant.quantize_q40_np(x)
    assert scales[0] == np.float16(1.0)
    q = np.concatenate([packed[0] & 0xF, packed[0] >> 4])
    assert q[0] == 0  # -8 -> floor(-8+8.5)=0
    assert q[1] == 15  # 7 -> floor(15.5)=15
    assert q[2] == 8  # 0.49 -> floor(8.99)=8
    assert q[3] == 9  # 0.51 -> floor(9.01)=9
    assert q[17] == 5  # -3.2 -> floor(5.3)=5
    zero_idx = [i for i in range(32) if i not in (0, 1, 2, 3, 17)]
    assert all(q[i] == 8 for i in zero_idx)


def test_qtensor_dequant_matches_numpy(rng):
    w = rng.normal(size=(256, 128)).astype(np.float32)
    qt = quant.QTensor.quantize(w)
    assert qt.shape == (256, 128)
    got = np.asarray(qt.dequantize())
    # independently dequantize via the numpy file codec
    packed, scales = quant.quantize_q40_np(np.ascontiguousarray(w.T))
    want = quant.dequantize_q40_np(packed, scales).T
    np.testing.assert_allclose(got, want, atol=0, rtol=0)
    assert np.max(np.abs(got - w)) < 0.5  # normal data, scale ~3 sigma / 8


def test_qtensor_file_layout_roundtrip(rng):
    """QTensor.from_file_layout must agree with QTensor.quantize."""
    k, n = 128, 64
    w = rng.normal(size=(k, n)).astype(np.float32)
    qt1 = quant.QTensor.quantize(w)
    # simulate .m storage: rows are output dims -> quantize W.T rows
    packed, scales = quant.quantize_q40_np(np.ascontiguousarray(w.T))
    qt2 = quant.QTensor.from_file_layout(packed.reshape(n, -1), scales.reshape(n, -1), n, k)
    np.testing.assert_array_equal(np.asarray(qt1.packed), np.asarray(qt2.packed))
    np.testing.assert_array_equal(np.asarray(qt1.scales), np.asarray(qt2.scales))


def test_q80_jnp_matches_np(rng):
    import jax.numpy as jnp

    x = rng.normal(size=(4, 256)).astype(np.float32)
    codes, scales = quant.quantize_q80_jnp(jnp.asarray(x))
    codes_np, scales_np = quant.quantize_q80_np(x)
    np.testing.assert_array_equal(np.asarray(codes).reshape(-1, 32), codes_np.reshape(-1, 32))
    np.testing.assert_allclose(
        np.asarray(scales).reshape(-1), scales_np.reshape(-1).astype(np.float32), rtol=1e-3
    )
    y = quant.dequantize_q80_jnp(codes, scales)
    assert np.max(np.abs(np.asarray(y) - x)) < 0.05


@pytest.mark.parametrize("ft,nbytes", [("q40", 18 * 4), ("q80", 34 * 4), ("f32", 512), ("f16", 256)])
def test_float_type_sizes(ft, nbytes):
    assert quant.parse_float_type(ft).nbytes(128) == nbytes


def test_q80_weight_model_file_end_to_end(tmp_path, rng):
    """The reference converter can emit Q80-WEIGHT `.m` files
    (writer.py:55-74, 102-103); ours must write, re-read, and RUN them.
    Q80 matmul weights load as dense bf16 operands (the packed-HBM fast path
    stays Q40-only); numerics must sit inside Q80's roundtrip noise."""
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models import formats
    from dllama_tpu.models.config import LlamaConfig

    cfg = LlamaConfig(dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
                      vocab_size=128, seq_len=64, weight_type=quant.FloatType.Q80)
    tensors = {
        name: (rng.standard_normal(shape) * 0.05).astype(np.float32)
        for name, shape, _ in formats.tensor_plan(cfg)
    }
    path = str(tmp_path / "q80.m")
    formats.save_model(path, cfg, tensors)

    cfg2, hs = formats.read_header(path)
    assert cfg2.weight_type == quant.FloatType.Q80
    # per-tensor decode parity: within the reference's Q80 eps of the source
    for name, shape, ft, raw in formats.iter_tensors(path, cfg2, hs):
        got = formats.decode_dense(raw, shape, ft)
        eps = 0.01 if ft == quant.FloatType.Q80 else 1e-6
        np.testing.assert_allclose(got, tensors[name], atol=eps)

    params = formats.load_params(path, cfg2, hs, dtype=jnp.float32)
    eng = InferenceEngine(cfg2, params, cache_dtype=jnp.float32)
    logits = eng.prefill(np.array([[1, 2, 3]], np.int32))
    toks = eng.decode_greedy_n(
        np.array([[int(np.argmax(np.asarray(logits)))]]), 6
    )
    assert toks.shape == (6, 1)


def test_q8tensor_dequant_matches_numpy(rng):
    """Q8Tensor.from_file_layout + dequantize == the numpy Q80 codec."""
    import jax.numpy as jnp

    n_out, k_in = 8, 128
    w = (rng.standard_normal((n_out, k_in)) * 0.1).astype(np.float32)
    codes, scales = quant.quantize_q80_np(w.reshape(-1))
    qt = quant.Q8Tensor.from_file_layout(codes, scales, n_out, k_in)
    want = quant.dequantize_q80_np(codes, scales).reshape(n_out, k_in).T
    np.testing.assert_allclose(np.asarray(qt.dequantize(jnp.float32)), want,
                               atol=0, rtol=0)
    assert qt.shape == (k_in, n_out)
    # stacked slice_leaf
    st = quant.Q8Tensor(np.stack([np.asarray(qt.codes)] * 3),
                        np.stack([np.asarray(qt.scales)] * 3))
    sl = quant.slice_leaf(st, 1)
    np.testing.assert_array_equal(np.asarray(sl.codes), np.asarray(qt.codes))


def test_q80_packed_load_matches_dense_path(tmp_path, rng):
    """load_params(q80_packed=True) keeps Q80 weights as Q8Tensor; the
    engine's logits must match the dense-bf16 load bit-for-bit on the XLA
    path (dequantize is exact in f32)."""
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models import formats
    from dllama_tpu.models.config import LlamaConfig

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=128, seq_len=64,
                      weight_type=quant.FloatType.Q80)
    tensors = {
        name: (rng.standard_normal(shape) * 0.05).astype(np.float32)
        for name, shape, _ in formats.tensor_plan(cfg)
    }
    path = str(tmp_path / "q80p.m")
    formats.save_model(path, cfg, tensors)
    cfg2, hs = formats.read_header(path)

    dense = formats.load_params(path, cfg2, hs, dtype=jnp.float32)
    packed = formats.load_params(path, cfg2, hs, dtype=jnp.float32,
                                 q80_packed=True)
    assert isinstance(packed["wcls"], quant.Q8Tensor)
    assert isinstance(packed["layers"]["wq"], quant.Q8Tensor)
    toks = np.array([[1, 2, 3]], np.int32)
    ld = np.asarray(InferenceEngine(cfg2, dense, cache_dtype=jnp.float32,
                                    kernels="xla").prefill(toks))
    lp = np.asarray(InferenceEngine(cfg2, packed, cache_dtype=jnp.float32,
                                    kernels="xla").prefill(toks))
    np.testing.assert_allclose(lp, ld, atol=2e-5, rtol=2e-5)
